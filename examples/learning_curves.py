"""Learning-curve evidence harness (VERDICT r1 "Next round" #3).

Runs each algorithm family to a reward threshold and records the full
reward-vs-frames curve as TensorBoard events plus a machine-readable
summary — the evidence artifact the reference never produced (its IMPALA
trained to scores at runtime, ``scalerl/algorithms/impala/impala_atari.py:
403-494``, but recorded nothing).

Experiments (all CPU-runnable; the same code paths serve the TPU):

- ``impala_catch``      — fused device loop on device-native Catch: pixel
  control with a single delayed terminal reward (the smallest Pong-shaped
  task; flagship learning evidence).
- ``impala_synthetic``  — fused device loop on ``SyntheticPixelEnv``
  pixels to near-optimal policy (obs->action discrimination).
- ``impala_cartpole``   — host actor plane (SEED-style) on CartPole to a
  return threshold; also records host-path frames/sec.
- ``impala_recall_lstm`` — delayed-recall (cue -> blank frames -> act) on
  the fused device loop: to-convergence proof of the done-masked LSTM
  carry, with a feed-forward control arm pinned at chance.
- ``ppo_recall_lstm``   — recurrent PPO (LSTM + epoch reuse) on delayed
  recall via the fused loop; ~6x more sample-efficient than the IMPALA
  arm on the same task.
- ``a3c_cartpole``      — on-policy A2C runtime on CartPole.
- ``ppo_cartpole``      — PPO (fused epochs x minibatch clipped surrogate)
  on the same on-policy runtime.
- ``dqn_cartpole``      — off-policy trainer (double DQN) on CartPole,
  final greedy eval over 10 episodes.

Artifacts land in ``work_dirs/learning_curves/<name>/`` (tb events) and
``work_dirs/learning_curves/summary.json``; ``docs/LEARNING_CURVES.md``
holds the human-readable table.

Usage::

    python examples/learning_curves.py            # all experiments
    python examples/learning_curves.py impala_synthetic dqn_cartpole
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

if "--tpu" not in sys.argv:
    # Pin CPU before any backend init: under the axon tunnel JAX_PLATFORMS
    # is ignored by the plugin; the config knob is what actually pins.
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    import jax

    jax.config.update("jax_platforms", "cpu")
else:
    import jax

import numpy as np

ROOT = Path(__file__).resolve().parents[1]
OUT_DIR = ROOT / "work_dirs" / "learning_curves"


def _first_crossing(tb_dir: str, tag: str, threshold: float):
    """First logged step at which ``tag`` >= threshold (None if never)."""
    from tensorboard.backend.event_processing import event_accumulator

    ea = event_accumulator.EventAccumulator(tb_dir)
    ea.Reload()
    try:
        for ev in ea.Scalars(tag):
            if ev.value >= threshold:
                return int(ev.step)
    except KeyError:
        pass
    return None


def _tb_logger(name: str):
    from scalerl_tpu.utils.loggers import TensorboardLogger

    run_dir = OUT_DIR / name
    run_dir.mkdir(parents=True, exist_ok=True)
    return TensorboardLogger(str(run_dir), train_interval=1, update_interval=1)


# ----------------------------------------------------------------------
def _run_fused_to_threshold(
    experiment: str,
    env,
    env_label: str,
    threshold: float,
    optimal_return: float,
    max_frames: int,
    learning_rate: float,
    num_envs: int = 16,
    unroll: int = 20,
    iters_per_call: int = 5,
    seed: int = 0,
    log=None,
    use_lstm: bool = False,
    hidden_size: int = 256,
    entropy_cost: float = 0.01,
    algo_label: str = "IMPALA (fused device loop)",
):
    """Shared scaffold: fused device-loop IMPALA on a device-native env,
    trained until the windowed return crosses ``threshold``, curve logged
    to TensorBoard, summary row returned."""
    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

    args = ImpalaArguments(
        use_lstm=use_lstm,
        hidden_size=hidden_size,
        rollout_length=unroll,
        batch_size=num_envs,
        max_timesteps=0,
        learning_rate=learning_rate,
        entropy_cost=entropy_cost,
    )
    venv = JaxVecEnv(env, num_envs=num_envs)
    agent = ImpalaAgent(
        args, obs_shape=env.observation_shape, num_actions=env.num_actions
    )
    learn = agent.make_learn_fn()
    loop = DeviceActorLearnerLoop(
        agent.model, venv, learn, unroll, iters_per_call=iters_per_call
    )
    logger = log or _tb_logger(experiment)
    k_init, k_run = jax.random.split(jax.random.PRNGKey(seed))
    carry = loop.init_carry(k_init)
    frames_per_call = unroll * num_envs * iters_per_call
    t0 = time.time()

    def on_metrics(frames: int, windowed: float, m) -> None:
        logger.log_train_data(
            {
                "return_windowed": windowed,
                "total_loss": m["total_loss"],
                "fps": frames / max(time.time() - t0, 1e-8),
            },
            frames,
        )

    _, _, summary = loop.run_until(
        agent.state,
        carry,
        k_run,
        threshold=threshold,
        max_calls=max_frames // frames_per_call,
        on_metrics=on_metrics,
    )
    wall = time.time() - t0
    logger.close()
    frames = int(summary["frames"])
    return {
        "experiment": experiment,
        "env": env_label,
        "algo": algo_label,
        "threshold": round(threshold, 2),
        "optimal_return": optimal_return,
        "final_return": round(summary["windowed_return"], 3),
        "frames": frames,
        "frames_to_threshold": frames if summary["hit"] else None,
        "wall_s": round(wall, 1),
        "fps": round(frames / wall, 1),
        "passed": summary["hit"],
    }


def impala_synthetic(
    size: int = 24,
    num_states: int = 4,
    num_actions: int = 4,
    episode_length: int = 64,
    max_frames: int = 500_000,
    threshold_frac: float = 0.85,
    seed: int = 0,
    log=None,
):
    """Fused device-loop IMPALA on synthetic pixels to near-optimal return.

    Optimal return == episode_length (reward 1 per step under the correct
    obs-conditioned action); threshold is ``threshold_frac`` of optimal,
    measured over the episodes completed since the previous fused call.
    """
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv

    env = SyntheticPixelEnv(
        size=size,
        num_states=num_states,
        num_actions=num_actions,
        episode_length=episode_length,
    )
    return _run_fused_to_threshold(
        "impala_synthetic",
        env,
        f"SyntheticPixelEnv({size}x{size}x4, {num_states} states)",
        threshold=threshold_frac * episode_length,
        optimal_return=episode_length,
        max_frames=max_frames,
        learning_rate=6e-4,
        seed=seed,
        log=log,
    )


def impala_synthetic_northstar(
    max_frames: int = 30_000_000,
    sticky_prob: float = 0.25,
    threshold_frac: float = 0.85,
    num_envs: int = 256,
    seed: int = 0,
    log=None,
):
    """The exact bench configuration as a LEARNING configuration (VERDICT
    r2 #7): fused device-loop IMPALA at the full north-star shape —
    84x84x4 uint8 frames, 16 states, 6 actions, AtariNet-512 torso — with
    ALE-style sticky actions so the dynamics are stochastic and a policy
    cannot exploit determinism.

    Threshold accounting: with sticky probability p, even the optimal
    policy's chosen action is replaced by the previous action ~p of the
    time, and a repeated action is wrong at the next cell (the correct-
    action map never repeats across consecutive cells), so expected
    optimal return ~= (1-p) * episode_length.  The bar is
    ``threshold_frac`` of that; random play scores ~episode_length/6.

    Intended for accelerator runs (~tens of seconds at TPU fused-loop
    rates); on CPU this would take hours — run it when the tunnel is up.
    """
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv

    episode_length = 128
    env = SyntheticPixelEnv(
        size=84, stack=4, num_actions=6, num_states=16,
        episode_length=episode_length, sticky_prob=sticky_prob,
    )
    effective_optimal = (1.0 - sticky_prob) * episode_length
    return _run_fused_to_threshold(
        "impala_synthetic_northstar",
        env,
        f"SyntheticPixelEnv(84x84x4, 16 states, sticky={sticky_prob})",
        threshold=threshold_frac * effective_optimal,
        optimal_return=round(effective_optimal, 1),
        max_frames=max_frames,
        learning_rate=6e-4,
        num_envs=num_envs,
        hidden_size=512,
        seed=seed,
        log=log,
    )


def impala_catch(
    size: int = 24,
    max_frames: int = 600_000,
    threshold: float = 0.85,
    seed: int = 0,
    log=None,
):
    """Fused device-loop IMPALA on Catch — the flagship learning evidence:
    spatio-temporal pixel control (track a falling ball, single delayed
    terminal reward), the smallest Pong-shaped task (BASELINE.md's ALE
    north star is unavailable in this image).  Threshold 0.85 ~= 92.5%
    catch rate (returns are +-1 per episode)."""
    from scalerl_tpu.envs import JaxCatch

    return _run_fused_to_threshold(
        "impala_catch",
        JaxCatch(size=size),
        f"JaxCatch({size}x{size}, device-native)",
        threshold=threshold,
        optimal_return=1.0,
        max_frames=max_frames,
        learning_rate=1e-3,
        seed=seed,
        log=log,
    )


# ----------------------------------------------------------------------
def impala_cartpole(
    num_actors: int = 2,
    envs_per_actor: int = 8,
    max_frames: int = 400_000,
    threshold: float = 400.0,
    seed: int = 0,
):
    """Host actor plane (SEED-style central inference) to a CartPole
    return threshold; doubles as the host-path throughput measurement."""
    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs import make_vect_envs
    from scalerl_tpu.trainer.actor_learner import HostActorLearnerTrainer

    args = ImpalaArguments(
        env_id="CartPole-v1",
        rollout_length=16,
        batch_size=16,
        num_actors=num_actors,
        num_buffers=32,
        use_lstm=False,
        hidden_size=64,
        learning_rate=2e-3,
        entropy_cost=0.01,
        gamma=0.99,
        seed=seed,
        logger_backend="tensorboard",
        logger_frequency=5_000,
        work_dir=str(OUT_DIR),
        project="",
        save_model=False,
        max_timesteps=max_frames,
    )
    args.validate()
    agent = ImpalaAgent(args, obs_shape=(4,), num_actions=2, obs_dtype=np.float32)
    env_fns = [
        (
            lambda i=i: make_vect_envs(
                "CartPole-v1", num_envs=envs_per_actor, seed=seed + i, async_envs=False
            )
        )
        for i in range(num_actors)
    ]
    trainer = HostActorLearnerTrainer(args, agent, env_fns, run_name="impala_cartpole")
    t0 = time.time()
    result = trainer.train(total_frames=max_frames)
    wall = time.time() - t0
    hit_frames = _first_crossing(trainer.tb_log_dir, "train/return_mean", threshold)
    trainer.close()
    return {
        "experiment": "impala_cartpole",
        "env": "CartPole-v1",
        "algo": "IMPALA (host actor plane, central inference)",
        "threshold": threshold,
        "final_return": round(result.get("return_mean", float("nan")), 2),
        "frames": int(trainer.env_frames),
        "frames_to_threshold": hit_frames,
        "wall_s": round(wall, 1),
        "fps": round(result.get("sps", float("nan")), 1),
        "passed": hit_frames is not None,
    }


# ----------------------------------------------------------------------
def a3c_cartpole(
    num_envs: int = 8,
    max_frames: int = 300_000,
    threshold: float = 400.0,
    seed: int = 1,
):
    """On-policy A2C runtime to a CartPole eval threshold."""
    from scalerl_tpu.agents.a3c import A3CAgent
    from scalerl_tpu.config import A3CArguments
    from scalerl_tpu.envs import make_vect_envs
    from scalerl_tpu.trainer import OnPolicyTrainer

    args = A3CArguments(
        env_id="CartPole-v1",
        rollout_length=16,
        num_workers=num_envs,
        hidden_sizes="64,64",
        learning_rate=1e-3,
        entropy_coef=0.01,
        gae_lambda=0.95,
        gamma=0.99,
        seed=seed,
        max_timesteps=max_frames,
        eval_frequency=10**9,
        logger_frequency=2_000,
        logger_backend="tensorboard",
        work_dir=str(OUT_DIR),
        project="",
        save_model=False,
        normalize_obs=False,
    )
    train_envs = make_vect_envs(
        "CartPole-v1", num_envs=num_envs, seed=seed, async_envs=False
    )
    eval_envs = make_vect_envs("CartPole-v1", num_envs=4, seed=seed + 99, async_envs=False)
    agent = A3CAgent(args, obs_shape=(4,), num_actions=2, obs_dtype=np.float32)
    trainer = OnPolicyTrainer(args, agent, train_envs, eval_envs, run_name="a3c_cartpole")
    t0 = time.time()
    trainer.run()
    ev = trainer.run_evaluate_episodes(n_episodes=10)
    wall = time.time() - t0
    hit = _first_crossing(trainer.tb_log_dir, "train/return_mean", threshold)
    trainer.close()
    train_envs.close()
    eval_envs.close()
    return {
        "experiment": "a3c_cartpole",
        "env": "CartPole-v1",
        "algo": "A3C (sync-batched A2C runtime)",
        "threshold": threshold,
        "final_return": round(ev["reward_mean"], 2),
        "frames": trainer.global_step,
        "frames_to_threshold": hit,
        "wall_s": round(wall, 1),
        "fps": round(trainer.global_step / wall, 1),
        "passed": ev["reward_mean"] >= threshold,
    }


# ----------------------------------------------------------------------
def run_lagged_arm(
    force_on_policy_rhos: bool,
    pull_every: int = 5,
    iters: int = 240,
    seed: int = 0,
    on_window=None,
) -> float:
    """One arm of the off-policy-lag proof; returns the final windowed
    return.  THE shared harness — ``tests/test_offpolicy_lag.py`` asserts
    over it and ``impala_offpolicy_lag`` records it, so the calibrated
    setup cannot drift between the test and the curve.

    Behavior weights refresh only every ``pull_every`` learner steps
    through a real ``ParameterServer`` (the host planes' weight-pull
    cadence), so rollouts are collected 0..pull_every-1 updates stale.
    ``force_on_policy_rhos`` replaces the behavior logits with the target
    policy's own — log-rhos become exactly 0 (V-trace told the data is
    on-policy) and nothing else changes.  ``on_window(frames, windowed)``
    fires every 20 updates.
    """
    from scalerl_tpu.agents.impala import ImpalaAgent, make_impala_learn_fn
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs import make_jax_vec_env
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop
    from scalerl_tpu.runtime.param_server import ParameterServer

    args = ImpalaArguments(
        env_id="CartPole-v1", rollout_length=16, batch_size=16,
        use_lstm=False, hidden_size=64, logger_backend="none",
        learning_rate=1e-2, entropy_cost=0.01, gamma=0.99,
    )
    venv = make_jax_vec_env("CartPole-v1", num_envs=16)
    agent = ImpalaAgent(
        args, obs_shape=(4,), num_actions=2,
        obs_dtype=jax.numpy.float32, key=jax.random.PRNGKey(seed),
    )
    learn = jax.jit(make_impala_learn_fn(agent.model, agent.optimizer, args))
    loop = DeviceActorLearnerLoop(
        model=agent.model, venv=venv, learn_fn=learn,
        unroll_length=args.rollout_length, iters_per_call=1,
    )
    unroll = jax.jit(loop._unroll)
    model = agent.model

    @jax.jit
    def learn_rho1(state, traj):
        out, _ = model.apply(
            state.params, traj.obs, traj.action, traj.reward, traj.done,
            traj.core_state,
        )
        logits = jax.lax.stop_gradient(out.policy_logits)
        logits = logits.at[-1].set(0.0)  # row T convention: unused, zero
        return learn(state, traj.replace(logits=logits))

    server = ParameterServer()
    server.push(jax.device_get(agent.state.params))
    state = agent.state
    behavior_params = None
    key = jax.random.PRNGKey(seed + 1)
    carry = loop.init_carry(key)
    prev_sum = prev_cnt = 0.0
    windowed = 0.0
    for i in range(iters):
        if i % pull_every == 0:
            w, _v = server.pull(have_version=-1)
            behavior_params = jax.tree_util.tree_map(jax.numpy.asarray, w)
        key, sub = jax.random.split(key)
        carry, traj = unroll(behavior_params, carry, sub)
        state, _m = (
            learn_rho1(state, traj) if force_on_policy_rhos
            else learn(state, traj)
        )
        server.push(jax.device_get(state.params))
        if (i + 1) % 20 == 0:
            s = float(jax.numpy.sum(carry.return_sum))
            c = float(jax.numpy.sum(carry.episode_count))
            if c > prev_cnt:
                windowed = (s - prev_sum) / (c - prev_cnt)
                prev_sum, prev_cnt = s, c
            if on_window is not None:
                on_window((i + 1) * args.rollout_length * 16, windowed)
    return windowed


def impala_offpolicy_lag(
    pull_every: int = 5,
    iters: int = 240,
    seed: int = 0,
    log=None,
):
    """Off-policy-lag proof as a recorded curve (VERDICT r2 #4): the two
    arms of :func:`run_lagged_arm` share seeds; the gap between them is
    the measured value of V-trace.  Assertion form:
    ``tests/test_offpolicy_lag.py``."""
    logger = log or _tb_logger("impala_offpolicy_lag")
    t0 = time.time()
    threshold = 25.0  # calibrated: vtrace ~50, rho1 ~9.4 (random ~9.4)
    crossing = {"frames": None}

    def log_vtrace(f, w):
        if crossing["frames"] is None and w >= threshold:
            crossing["frames"] = f
        logger.log_train_data({"return_windowed_vtrace": w}, f)

    vtrace_ret = run_lagged_arm(
        False, pull_every, iters, seed, on_window=log_vtrace
    )
    rho1_ret = run_lagged_arm(
        True, pull_every, iters, seed,
        on_window=lambda f, w: logger.log_train_data(
            {"return_windowed_rho1": w}, f
        ),
    )
    wall = time.time() - t0
    logger.close()
    frames = 2 * iters * 16 * 16
    return {
        "experiment": "impala_offpolicy_lag",
        "env": f"CartPole-v1 (behavior weights {pull_every} steps stale)",
        "algo": "IMPALA V-trace vs rho=1 ablation",
        "threshold": threshold,
        "optimal_return": 500.0,
        "final_return": round(vtrace_ret, 1),
        "rho1_ablation_return": round(rho1_ret, 1),
        "frames": frames,
        # the vtrace arm's actual windowed-return crossing, observed by
        # the logging callback (None if the threshold was never crossed)
        "frames_to_threshold": crossing["frames"],
        "wall_s": round(wall, 1),
        "fps": round(frames / wall, 1),
        "passed": bool(vtrace_ret >= threshold and rho1_ret < vtrace_ret / 1.8),
    }


# ----------------------------------------------------------------------
def run_r2d2_recall(
    use_lstm: bool,
    frames: int = 60_000,
    seed: int = 0,
    on_log=None,
) -> dict:
    """One arm of the R2D2 memory proof; returns the trainer summary.

    THE shared harness — ``tests/test_r2d2.py`` asserts over it and
    ``r2d2_recall`` records it.  Delayed recall (flash cue, 3 blank steps,
    answer) with 2 cues: a memoryless policy is pinned at expected return
    0; the stored-state + burn-in machinery is what lets the LSTM arm
    recover the cue from its recurrent state.  Calibrated on this host:
    LSTM reaches 1.0 (perfect recall) in ~60k frames; the feed-forward
    control stays ~0.
    """
    import numpy as _np

    from scalerl_tpu.agents.r2d2 import R2D2Agent
    from scalerl_tpu.config import R2D2Arguments
    from scalerl_tpu.envs import make_vect_envs
    from scalerl_tpu.trainer.r2d2 import R2D2Trainer

    args = R2D2Arguments(
        env_id="RecallGym-v0", rollout_length=12, burn_in=2, n_steps=1,
        batch_size=16, num_actors=2, num_buffers=16, replay_capacity=512,
        warmup_sequences=32, train_intensity=2, target_update_frequency=200,
        use_lstm=use_lstm, hidden_size=64, lstm_layers=1,
        eps_base=0.3, eps_alpha=7.0,
        learning_rate=1e-3, logger_backend="none", logger_frequency=10**9,
        save_model=False, seed=seed,
    )
    agent = R2D2Agent(
        args, obs_shape=(12, 12, 1), num_actions=2, obs_dtype=_np.uint8
    )
    env_fns = [
        (
            lambda i=i: make_vect_envs(
                "RecallGym-v0", num_envs=8, seed=seed + i, async_envs=False,
                size=12, delay=3, num_cues=2,
            )
        )
        for i in range(2)
    ]
    trainer = R2D2Trainer(args, agent, env_fns)
    try:
        summary = trainer.train(total_frames=frames)
    finally:
        trainer.close()
    if on_log is not None:
        on_log(summary)
    return summary


# ----------------------------------------------------------------------
def run_sac_pendulum(
    max_timesteps: int = 24_000,
    seed: int = 0,
    use_per: bool = False,
) -> dict:
    """SAC on Pendulum-v1 to a greedy eval (shared harness: asserted in
    ``tests/test_sac.py``, recorded by ``sac_pendulum``).  Calibrated on
    this host: eval reward ~-120 after 24k steps (~45 s CPU); random play
    scores ~-1400, 'solved' is commonly taken as >= -200."""
    from scalerl_tpu.agents.sac import SACAgent
    from scalerl_tpu.config import SACArguments
    from scalerl_tpu.envs import make_vect_envs
    from scalerl_tpu.trainer import OffPolicyTrainer

    args = SACArguments(
        env_id="Pendulum-v1", num_envs=4, buffer_size=100_000, batch_size=128,
        warmup_learn_steps=1000, train_frequency=2,
        max_timesteps=max_timesteps, logger_backend="none",
        logger_frequency=10**9, save_model=False, eval_frequency=10**9,
        seed=seed, use_per=use_per,
    )
    envs = make_vect_envs("Pendulum-v1", num_envs=4, seed=seed, async_envs=False)
    eval_envs = make_vect_envs(
        "Pendulum-v1", num_envs=2, seed=seed + 1, async_envs=False
    )
    space = envs.single_action_space
    agent = SACAgent(
        args, obs_shape=(3,), action_low=space.low, action_high=space.high,
        key=jax.random.PRNGKey(seed),
    )
    trainer = OffPolicyTrainer(args, agent, envs, eval_envs)
    try:
        trainer.run()
        ev = trainer.run_evaluate_episodes(n_episodes=6)
    finally:
        trainer.close()
        envs.close()
        eval_envs.close()
    return {"eval_reward": float(ev["reward_mean"]), "steps": max_timesteps}


def run_td3_pendulum(
    max_timesteps: int = 24_000,
    seed: int = 0,
) -> dict:
    """TD3 on Pendulum-v1 (shared harness: asserted in
    ``tests/test_td3.py``, recorded by ``td3_pendulum``); same budget and
    threshold conventions as :func:`run_sac_pendulum`."""
    from scalerl_tpu.agents.td3 import TD3Agent
    from scalerl_tpu.config import TD3Arguments
    from scalerl_tpu.envs import make_vect_envs
    from scalerl_tpu.trainer import OffPolicyTrainer

    args = TD3Arguments(
        env_id="Pendulum-v1", num_envs=4, buffer_size=100_000, batch_size=128,
        warmup_learn_steps=1000, train_frequency=2,
        max_timesteps=max_timesteps, logger_backend="none",
        logger_frequency=10**9, save_model=False, eval_frequency=10**9,
        seed=seed,
    )
    envs = make_vect_envs("Pendulum-v1", num_envs=4, seed=seed, async_envs=False)
    eval_envs = make_vect_envs(
        "Pendulum-v1", num_envs=2, seed=seed + 1, async_envs=False
    )
    space = envs.single_action_space
    agent = TD3Agent(
        args, obs_shape=(3,), action_low=space.low, action_high=space.high,
        key=jax.random.PRNGKey(seed),
    )
    trainer = OffPolicyTrainer(args, agent, envs, eval_envs)
    try:
        trainer.run()
        ev = trainer.run_evaluate_episodes(n_episodes=6)
    finally:
        trainer.close()
        envs.close()
        eval_envs.close()
    return {"eval_reward": float(ev["reward_mean"]), "steps": max_timesteps}


def td3_pendulum(max_timesteps: int = 24_000, seed: int = 0, log=None):
    """TD3 continuous-control curve (companion to ``sac_pendulum``)."""
    logger = log or _tb_logger("td3_pendulum")
    t0 = time.time()
    res = run_td3_pendulum(max_timesteps, seed)
    wall = time.time() - t0
    logger.log_train_data({"eval_reward": res["eval_reward"]}, max_timesteps)
    logger.close()
    threshold = -400.0
    return {
        "experiment": "td3_pendulum",
        "env": "Pendulum-v1",
        "algo": "TD3 (delayed deterministic actor, target smoothing)",
        "threshold": threshold,
        "optimal_return": 0.0,
        "final_return": round(res["eval_reward"], 1),
        "frames": max_timesteps,
        "frames_to_threshold": None,
        "wall_s": round(wall, 1),
        "fps": round(max_timesteps / wall, 1),
        "passed": bool(res["eval_reward"] >= threshold),
    }


def sac_pendulum(max_timesteps: int = 24_000, seed: int = 0, log=None):
    """Continuous-control proof as a recorded curve: SAC (squashed
    Gaussian + twin-Q + auto temperature) solves Pendulum."""
    logger = log or _tb_logger("sac_pendulum")
    t0 = time.time()
    res = run_sac_pendulum(max_timesteps, seed)
    wall = time.time() - t0
    logger.log_train_data({"eval_reward": res["eval_reward"]}, max_timesteps)
    logger.close()
    threshold = -400.0  # calibrated: -117; random ~-1400; solved ~-150
    return {
        "experiment": "sac_pendulum",
        "env": "Pendulum-v1",
        "algo": "SAC (continuous control, auto temperature)",
        "threshold": threshold,
        "optimal_return": 0.0,
        "final_return": round(res["eval_reward"], 1),
        "frames": max_timesteps,
        "frames_to_threshold": None,
        "wall_s": round(wall, 1),
        "fps": round(max_timesteps / wall, 1),
        "passed": bool(res["eval_reward"] >= threshold),
    }


def run_r2d2_recall_device(
    use_lstm: bool,
    frames: int = 50_000,
    seed: int = 0,
) -> dict:
    """One arm of the DEVICE-plane R2D2 memory proof (shared harness:
    asserted in ``tests/test_r2d2.py``, recorded by ``r2d2_recall_device``).
    Same delayed-recall task as :func:`run_r2d2_recall`, but collection
    runs on the device-native env inside one jitted program
    (``trainer/r2d2_device.py``) — the TPU-fast R2D2 topology."""
    import numpy as _np

    from scalerl_tpu.agents.r2d2 import R2D2Agent
    from scalerl_tpu.config import R2D2Arguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.envs.jax_envs.recall import JaxRecall
    from scalerl_tpu.trainer.r2d2_device import DeviceR2D2Trainer

    args = R2D2Arguments(
        env_id="JaxRecall", rollout_length=12, burn_in=2, n_steps=1,
        batch_size=16, replay_capacity=512, warmup_sequences=32,
        train_intensity=1, target_update_frequency=200,
        use_lstm=use_lstm, hidden_size=64, lstm_layers=1, eps_base=0.05,
        learning_rate=1e-3, logger_backend="none", logger_frequency=10**9,
        save_model=False, seed=seed,
    )
    env = JaxRecall(size=12, delay=3, num_cues=2)
    venv = JaxVecEnv(env, num_envs=16)
    agent = R2D2Agent(
        args, obs_shape=env.observation_shape, num_actions=2,
        obs_dtype=_np.uint8, key=jax.random.PRNGKey(seed),
    )
    trainer = DeviceR2D2Trainer(args, agent, venv)
    try:
        summary = trainer.train(total_frames=frames)
    finally:
        trainer.close()
    return summary


def r2d2_recall_device(frames: int = 50_000, seed: int = 0, log=None):
    """Device-plane R2D2 memory proof as a recorded curve (TPU-fast
    topology; calibrated: LSTM windowed ~0.97 in ~40s CPU, ff ~0.04)."""
    logger = log or _tb_logger("r2d2_recall_device")
    t0 = time.time()
    lstm = run_r2d2_recall_device(True, frames, seed)
    ff = run_r2d2_recall_device(False, frames, seed)
    wall = time.time() - t0
    logger.log_train_data(
        {
            "return_lstm": lstm["return_windowed"],
            "return_ff": ff["return_windowed"],
        },
        frames,
    )
    logger.close()
    threshold = 0.6
    return {
        "experiment": "r2d2_recall_device",
        "env": "JaxRecall(12x12, delay 3, 2 cues, device-native)",
        "algo": "R2D2 device loop (LSTM) vs feed-forward control",
        "threshold": threshold,
        "optimal_return": 1.0,
        "final_return": round(lstm["return_windowed"], 3),
        "ff_control_return": round(ff["return_windowed"], 3),
        "frames": int(lstm["env_frames"] + ff["env_frames"]),
        "frames_to_threshold": None,
        "wall_s": round(wall, 1),
        "fps": round((lstm["env_frames"] + ff["env_frames"]) / wall, 1),
        "passed": bool(
            lstm["return_windowed"] >= threshold
            and ff["return_windowed"] < threshold / 2
        ),
    }


def r2d2_recall(frames: int = 60_000, seed: int = 0, log=None):
    """R2D2 memory proof as a recorded curve: the LSTM arm must recall the
    cue across the delay; the feed-forward control arm is the falsifier
    (same seeds, same budget, no recurrence)."""
    logger = log or _tb_logger("r2d2_recall")
    t0 = time.time()
    lstm = run_r2d2_recall(True, frames, seed)
    ff = run_r2d2_recall(False, frames, seed)
    wall = time.time() - t0
    logger.log_train_data(
        {"return_lstm": lstm["return_mean"], "return_ff": ff["return_mean"]},
        frames,
    )
    logger.close()
    threshold = 0.6  # calibrated: lstm 1.0, ff 0.04, chance 0.0, optimal 1.0
    return {
        "experiment": "r2d2_recall",
        "env": "RecallGym-v0 (12x12, delay 3, 2 cues)",
        "algo": "R2D2 (LSTM) vs feed-forward control",
        "threshold": threshold,
        "optimal_return": 1.0,
        "final_return": round(lstm["return_mean"], 3),
        "ff_control_return": round(ff["return_mean"], 3),
        "frames": int(lstm["env_frames"] + ff["env_frames"]),
        "frames_to_threshold": None,
        "wall_s": round(wall, 1),
        "fps": round((lstm["env_frames"] + ff["env_frames"]) / wall, 1),
        "passed": bool(
            lstm["return_mean"] >= threshold
            and ff["return_mean"] < threshold / 2
        ),
    }


# ----------------------------------------------------------------------
def impala_recall_lstm(
    size: int = 16,
    delay: int = 6,
    max_frames: int = 400_000,
    threshold: float = 0.8,
    seed: int = 0,
):
    """Recurrent learning evidence: delayed-recall on the fused device loop.

    The cue flashes in frame 0 only and the rewarded action happens
    ``delay`` blank frames later, so a memoryless policy is pinned at
    ``2/num_actions - 1 = -0.5`` expected return — crossing ``threshold``
    proves the done-masked LSTM carry learns end to end (the Catch /
    Synthetic curves use feed-forward torsos and cannot show this).  A
    feed-forward control arm runs the same config at the LSTM arm's frame
    budget; its ceiling-at-chance return lands in the summary row.
    """
    from scalerl_tpu.envs import JaxRecall

    env = JaxRecall(size=size, delay=delay, num_cues=4)
    label = f"JaxRecall({size}x{size}, delay={delay}, device-native)"
    common = dict(
        threshold=threshold, optimal_return=1.0, learning_rate=1e-3,
        num_envs=32, unroll=8, iters_per_call=5, seed=seed,
        hidden_size=64, entropy_cost=0.02,
    )
    row = _run_fused_to_threshold(
        "impala_recall_lstm", env, label, max_frames=max_frames,
        use_lstm=True,
        algo_label="IMPALA conv+LSTM (fused device loop); FF control at chance",
        **common,
    )
    # control: same config, no memory, matched to the LSTM arm's budget
    ff = _run_fused_to_threshold(
        "impala_recall_ff_control", env, label, max_frames=row["frames"],
        use_lstm=False, algo_label="FF control", **common,
    )
    row["ff_control_return"] = ff["final_return"]
    row["passed"] = bool(row["passed"] and ff["final_return"] < 0.0)
    return row


# ----------------------------------------------------------------------
def ppo_recall_lstm(
    size: int = 16,
    delay: int = 6,
    max_frames: int = 200_000,
    threshold: float = 0.8,
    seed: int = 0,
):
    """Recurrent PPO to convergence: the PPO learn fn inside the fused
    device loop (Anakin/Brax shape) with an LSTM torso on delayed recall.

    Complements ``impala_recall_lstm``: same memory-required task, second
    algorithm family — and PPO's epoch reuse is markedly more
    sample-efficient here (the recorded run crosses the threshold in ~19k
    frames vs IMPALA's ~120k)."""
    from scalerl_tpu.agents.ppo import PPOAgent
    from scalerl_tpu.envs import JaxRecall
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

    from scalerl_tpu.config import PPOArguments

    env = JaxRecall(size=size, delay=delay, num_cues=4)
    B, T, I = 32, 8, 2
    args = PPOArguments(
        use_lstm=True, hidden_size=64, rollout_length=T, num_workers=B,
        num_minibatches=2, ppo_epochs=2, max_timesteps=0,
        learning_rate=1e-3, entropy_coef=0.02, gae_lambda=0.95,
    )
    venv = JaxVecEnv(env, B)
    agent = PPOAgent(
        args, obs_shape=env.observation_shape, num_actions=env.num_actions,
        obs_dtype=jax.numpy.uint8,
    )
    loop = DeviceActorLearnerLoop(
        agent.model, venv, agent.make_learn_fn(), T, iters_per_call=I
    )
    logger = _tb_logger("ppo_recall_lstm")
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    carry = loop.init_carry(k1)
    t0 = time.time()

    def on_metrics(frames, windowed, m):
        logger.log_train_data(
            {"return_windowed": windowed, "total_loss": m["total_loss"]}, frames
        )

    _, _, summary = loop.run_until(
        agent.state, carry, k2, threshold=threshold,
        max_calls=max_frames // (B * T * I), on_metrics=on_metrics,
    )
    wall = time.time() - t0
    logger.close()
    frames = int(summary["frames"])
    return {
        "experiment": "ppo_recall_lstm",
        "env": f"JaxRecall({size}x{size}, delay={delay}, device-native)",
        "algo": "PPO conv+LSTM (fused device loop, epoch reuse)",
        "threshold": threshold,
        "final_return": round(summary["windowed_return"], 3),
        "frames": frames,
        "frames_to_threshold": frames if summary["hit"] else None,
        "wall_s": round(wall, 1),
        "fps": round(frames / max(wall, 1e-8), 1),
        "passed": bool(summary["hit"]),
    }


# ----------------------------------------------------------------------
def ppo_cartpole(
    num_envs: int = 8,
    max_frames: int = 300_000,
    threshold: float = 400.0,
    seed: int = 5,
):
    """PPO (fused epochs x minibatch clipped surrogate) on the same
    on-policy runtime as A3C, to a CartPole eval threshold."""
    from scalerl_tpu.agents.ppo import PPOAgent
    from scalerl_tpu.config import PPOArguments
    from scalerl_tpu.envs import make_vect_envs
    from scalerl_tpu.trainer import OnPolicyTrainer

    args = PPOArguments(
        env_id="CartPole-v1",
        rollout_length=32,
        num_workers=num_envs,
        num_minibatches=4,
        ppo_epochs=4,
        hidden_sizes="64,64",
        learning_rate=3e-4,
        entropy_coef=0.01,
        gae_lambda=0.95,
        gamma=0.99,
        seed=seed,
        max_timesteps=max_frames,
        eval_frequency=10**9,
        logger_frequency=2_000,
        logger_backend="tensorboard",
        work_dir=str(OUT_DIR),
        project="",
        save_model=False,
        normalize_obs=False,
    )
    train_envs = make_vect_envs(
        "CartPole-v1", num_envs=num_envs, seed=seed, async_envs=False
    )
    eval_envs = make_vect_envs("CartPole-v1", num_envs=4, seed=seed + 99, async_envs=False)
    agent = PPOAgent(args, obs_shape=(4,), num_actions=2, obs_dtype=np.float32)
    trainer = OnPolicyTrainer(args, agent, train_envs, eval_envs, run_name="ppo_cartpole")
    t0 = time.time()
    trainer.run()
    ev = trainer.run_evaluate_episodes(n_episodes=10)
    wall = time.time() - t0
    hit = _first_crossing(trainer.tb_log_dir, "train/return_mean", threshold)
    trainer.close()
    train_envs.close()
    eval_envs.close()
    return {
        "experiment": "ppo_cartpole",
        "env": "CartPole-v1",
        "algo": "PPO (fused minibatch epochs, on-policy runtime)",
        "threshold": threshold,
        "final_return": round(ev["reward_mean"], 2),
        "frames": trainer.global_step,
        "frames_to_threshold": hit,
        "wall_s": round(wall, 1),
        "fps": round(trainer.global_step / wall, 1),
        "passed": ev["reward_mean"] >= threshold,
    }


# ----------------------------------------------------------------------
def dqn_cartpole(
    num_envs: int = 4,
    max_frames: int = 300_000,
    threshold: float = 450.0,
    seed: int = 3,
):
    """Double+dueling+3-step DQN through the off-policy trainer; final
    greedy eval over 10 episodes must beat the threshold (CartPole-v1
    'solved' is 475).  Hard target updates every 500 learn steps: per-step
    soft updates let the target chase the online net and CartPole DQN then
    collapses from ~250 into a ~135 plateau (observed with tau=0.005)."""
    from scalerl_tpu.agents import DQNAgent
    from scalerl_tpu.config import DQNArguments
    from scalerl_tpu.envs import make_vect_envs
    from scalerl_tpu.trainer import OffPolicyTrainer

    args = DQNArguments(
        env_id="CartPole-v1",
        num_envs=num_envs,
        buffer_size=50_000,
        batch_size=128,
        max_timesteps=max_frames,
        warmup_learn_steps=1_000,
        train_frequency=4,
        learning_rate=5e-4,
        double_dqn=True,
        dueling_dqn=True,
        n_steps=3,
        use_soft_update=False,
        target_update_frequency=500,
        lr_scheduler="linear",
        min_learning_rate=5e-5,
        exploration_fraction=0.25,
        eps_greedy_end=0.02,
        eval_frequency=25_000,
        eval_episodes=5,
        logger_frequency=2_000,
        save_frequency=10**9,
        seed=seed,
        work_dir=str(OUT_DIR),
        project="",
        logger_backend="tensorboard",
        save_model=False,
    )
    args.validate()
    train_envs = make_vect_envs(args.env_id, num_envs=num_envs, seed=seed, async_envs=False)
    eval_envs = make_vect_envs(args.env_id, num_envs=4, seed=seed + 99, async_envs=False)
    agent = DQNAgent(
        args,
        obs_shape=train_envs.single_observation_space.shape,
        action_dim=train_envs.single_action_space.n,
    )
    trainer = OffPolicyTrainer(args, agent, train_envs, eval_envs, run_name="dqn_cartpole")
    t0 = time.time()
    trainer.run()
    ev = trainer.run_evaluate_episodes(n_episodes=10)
    wall = time.time() - t0
    hit = _first_crossing(trainer.tb_log_dir, "train/return_mean", threshold)
    trainer.close()
    train_envs.close()
    eval_envs.close()
    return {
        "experiment": "dqn_cartpole",
        "env": "CartPole-v1",
        "algo": "double+dueling 3-step DQN (off-policy trainer)",
        "threshold": threshold,
        "final_return": round(ev["reward_mean"], 2),
        "frames": trainer.global_step,
        "frames_to_threshold": hit,
        "wall_s": round(wall, 1),
        "fps": round(trainer.global_step / wall, 1),
        "passed": ev["reward_mean"] >= threshold,
    }


EXPERIMENTS = {
    "impala_synthetic": impala_synthetic,
    "impala_synthetic_northstar": impala_synthetic_northstar,
    "impala_catch": impala_catch,
    "impala_cartpole": impala_cartpole,
    "impala_offpolicy_lag": impala_offpolicy_lag,
    "impala_recall_lstm": impala_recall_lstm,
    "ppo_recall_lstm": ppo_recall_lstm,
    "r2d2_recall": r2d2_recall,
    "r2d2_recall_device": r2d2_recall_device,
    "sac_pendulum": sac_pendulum,
    "td3_pendulum": td3_pendulum,
    "a3c_cartpole": a3c_cartpole,
    "ppo_cartpole": ppo_cartpole,
    "dqn_cartpole": dqn_cartpole,
}


def _write_markdown(results) -> None:
    lines = [
        "# Learning curves",
        "",
        "Recorded to-threshold training runs (VERDICT r1 #3). Curves: TensorBoard",
        "event files under `work_dirs/learning_curves/` — `impala_synthetic/` directly,",
        "trainer-based runs at `CartPole-v1/<algo>/<experiment>/tb_log/`; summary JSON in",
        "`work_dirs/learning_curves/summary.json`. All runs CPU-only (the TPU-tunnel",
        "backend was unreachable; the identical code paths serve the TPU) via",
        "`python examples/learning_curves.py`.",
        "",
        "| experiment | env | algo | threshold | final return | frames | frames→threshold | wall s | fps | passed |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in results:
        lines.append(
            "| {experiment} | {env} | {algo} | {threshold} | {final_return} | "
            "{frames} | {frames_to_threshold} | {wall_s} | {fps} | {passed} |".format(**r)
        )
    lag = next(
        (r for r in results if r["experiment"] == "impala_offpolicy_lag"), None
    )
    if lag is not None:
        lines += [
            "",
            "`impala_offpolicy_lag` is the V-trace value proof: behavior weights",
            "refresh only every 5 learner steps (ParameterServer pull cadence), and",
            "the identically-seeded rho=1 ablation (behavior logits overwritten by",
            f"the target policy's) finished at {lag['rho1_ablation_return']} — "
            "the random-policy level —",
            f"while the V-trace arm reached {lag['final_return']}.  "
            "See `tests/test_offpolicy_lag.py`.",
        ]
    r2d2 = next((r for r in results if r["experiment"] == "r2d2_recall"), None)
    if r2d2 is not None:
        lines += [
            "",
            "`r2d2_recall` is the recurrent OFF-POLICY proof: R2D2's",
            "stored-state + burn-in machinery recalls the cue across the delay",
            f"to {r2d2['final_return']} (optimal 1.0), while the identically-"
            f"budgeted feed-forward control finished at "
            f"{r2d2['ff_control_return']} (chance 0.0).",
            "See `tests/test_r2d2.py` for the assertion form.",
        ]
    if any(r["experiment"] == "impala_recall_lstm" for r in results):
        lines += [
            "",
            "`impala_recall_lstm` is the recurrent-learning proof: a memoryless",
            "policy is pinned at expected return -0.5 on delayed recall, and the",
            "feed-forward control arm recorded in `summary.json`",
            "(`ff_control_return`) indeed stays at chance while the LSTM arm",
            "crosses the threshold.",
        ]
    lines += [
        "",
        "North-star note (BASELINE.md): wall-clock-to-Pong-18 needs ALE ROMs, absent",
        "from this image. The exact recipe once ROMs are available:",
        "`python examples/train_impala.py --env_id ALE/Pong-v5 --total_steps 30000000",
        "--num_actors 8 --batch_size 32 --rollout_length 20 --use_lstm True` —",
        "the `impala_synthetic` run above exercises the identical pixel pipeline",
        "(conv torso, V-trace, fused loop) to a provably-optimal policy instead.",
        "",
    ]
    (ROOT / "docs" / "LEARNING_CURVES.md").write_text("\n".join(lines))


def main() -> None:
    names = [a for a in sys.argv[1:] if not a.startswith("-")]
    if not names:
        names = list(EXPERIMENTS)
        if jax.default_backend() == "cpu":
            # accelerator-scale run (~hours on CPU): request explicitly, or
            # run with --tpu when the tunnel is up
            names.remove("impala_synthetic_northstar")
    OUT_DIR.mkdir(parents=True, exist_ok=True)
    summary_path = OUT_DIR / "summary.json"
    results = []
    if summary_path.exists():
        results = [
            r for r in json.loads(summary_path.read_text()) if r["experiment"] not in names
        ]
    for name in names:
        print(f"=== {name} ===", flush=True)
        r = EXPERIMENTS[name]()
        print(json.dumps(r), flush=True)
        results.append(r)
        results.sort(key=lambda r: r["experiment"])
        summary_path.write_text(json.dumps(results, indent=2))
        _write_markdown(results)


if __name__ == "__main__":
    main()
