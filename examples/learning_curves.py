"""Learning-curve evidence harness (VERDICT r1 "Next round" #3).

Runs each algorithm family to a reward threshold and records the full
reward-vs-frames curve as TensorBoard events plus a machine-readable
summary — the evidence artifact the reference never produced (its IMPALA
trained to scores at runtime, ``scalerl/algorithms/impala/impala_atari.py:
403-494``, but recorded nothing).

The experiments live in ``examples/curves/`` (one module per algorithm
family; see ``curves/__init__.py`` for the registry).  This entry point
only pins the backend, resolves names, and writes the artifacts:

Artifacts land in ``work_dirs/learning_curves/<name>/`` (tb events) and
``work_dirs/learning_curves/summary.json``; ``docs/LEARNING_CURVES.md``
holds the human-readable table.

Usage::

    python examples/learning_curves.py            # all experiments
    python examples/learning_curves.py impala_synthetic dqn_cartpole
    python examples/learning_curves.py impala_synthetic_northstar --tpu
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
sys.path.insert(0, str(Path(__file__).resolve().parent))

if "--tpu" not in sys.argv:
    # Pin CPU before any backend init: under the axon tunnel JAX_PLATFORMS
    # is ignored by the plugin; the config knob is what actually pins.
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    import jax

    jax.config.update("jax_platforms", "cpu")
else:
    import jax

from curves import EXPERIMENTS  # noqa: E402
from curves.common import OUT_DIR  # noqa: E402
from curves.report import _write_markdown  # noqa: E402

# Shared harnesses re-exported at their historical location: the regression
# tests (tests/test_offpolicy_lag.py, test_r2d2.py, test_sac.py, test_td3.py)
# assert over the SAME calibrated setups the recorded curves use, importing
# them from here.
from curves.continuous import run_sac_pendulum, run_td3_pendulum  # noqa: E402,F401
from curves.impala import run_lagged_arm  # noqa: E402,F401
from curves.r2d2 import run_r2d2_recall, run_r2d2_recall_device  # noqa: E402,F401


def main() -> None:
    names = [a for a in sys.argv[1:] if not a.startswith("-")]
    if not names:
        names = list(EXPERIMENTS)
        if jax.default_backend() == "cpu":
            # accelerator-scale runs (~hours on CPU at these shapes):
            # request explicitly, or run with --tpu when the tunnel is up
            names.remove("impala_synthetic_northstar")
            names.remove("impala_breakout_84")
    OUT_DIR.mkdir(parents=True, exist_ok=True)
    summary_path = OUT_DIR / "summary.json"
    results = []
    if summary_path.exists():
        results = [
            r for r in json.loads(summary_path.read_text()) if r["experiment"] not in names
        ]
    for name in names:
        print(f"=== {name} ===", flush=True)
        r = EXPERIMENTS[name]()
        print(json.dumps(r), flush=True)
        results.append(r)
        results.sort(key=lambda r: r["experiment"])
        summary_path.write_text(json.dumps(results, indent=2))
        _write_markdown(results)


if __name__ == "__main__":
    main()
