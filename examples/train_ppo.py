"""PPO on CartPole (beyond-parity family; see ``scalerl_tpu/agents/ppo.py``).

Runs on the same on-policy runtime as A3C (``trainer/on_policy.py``): a
vector-env actor fleet with central batched inference feeding fused
epochs x minibatch clipped-surrogate updates.  DD-PPO over a mesh:
``--mesh-shape "dp=8"`` data-parallels the learner with per-minibatch
gradient all-reduce.

Usage::

    python examples/train_ppo.py --env-id CartPole-v1 --max-timesteps 100000
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalerl_tpu.agents import PPOAgent
from scalerl_tpu.config import PPOArguments, parse_args
from scalerl_tpu.envs import make_vect_envs
from scalerl_tpu.trainer import OnPolicyTrainer


def main() -> None:
    args = parse_args(PPOArguments)
    from scalerl_tpu.utils.platform import setup_platform

    print("backend:", setup_platform(args.platform))
    train_envs = make_vect_envs(
        args.env_id,
        num_envs=args.num_workers,
        seed=args.seed,
        normalize_obs=args.normalize_obs,
    )
    eval_envs = make_vect_envs(
        args.env_id,
        num_envs=2,
        seed=args.seed + 1,
        async_envs=False,
        normalize_obs=args.normalize_obs,
    )
    agent = PPOAgent(
        args,
        obs_shape=train_envs.single_observation_space.shape,
        num_actions=train_envs.single_action_space.n,
    )
    if args.mesh_shape:
        agent.enable_mesh(args.mesh_shape)
    trainer = OnPolicyTrainer(args, agent, train_envs, eval_envs)
    try:
        summary = trainer.run()
        print("final:", summary)
        final_eval = trainer.run_evaluate_episodes()
        print("eval:", final_eval)
    finally:
        trainer.close()
        train_envs.close()
        eval_envs.close()


if __name__ == "__main__":
    main()
