"""IMPACT training entry point: the sharded big-model learner's companion.

IMPACT (arxiv 1912.00167) on the host actor-learner plane: clipped
target-network surrogate + circular replay of every trajectory chunk
``--replay-times`` times — the sample-efficiency counterweight that keeps
a heavy (mp-sharded transformer/MoE) learner step busy while async actors
lag.  The dp×mp mesh resolves from the args alone; no mesh code here.

Usage (8 virtual devices, transformer policy sharded dp=4 × mp=2)::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/train_impact.py --env-id CartPole-v1 \
        --policy-arch transformer --mp-size 2 --d-model 256 \
        --replay-times 2 --max-timesteps 100000
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp

from scalerl_tpu.agents.impact import ImpactAgent
from scalerl_tpu.config import ImpactArguments, parse_args
from scalerl_tpu.envs import make_vect_envs


def main() -> None:
    args = parse_args(ImpactArguments)
    from scalerl_tpu.utils.platform import setup_platform

    print("backend:", setup_platform(args.platform))

    from scalerl_tpu.trainer.actor_learner import HostActorLearnerTrainer

    envs_per_actor = max(args.num_envs // args.num_actors, 1)
    atari = args.env_id.startswith("ALE/") or "NoFrameskip" in args.env_id
    env_fns = [
        (
            lambda i=i: make_vect_envs(
                args.env_id,
                num_envs=envs_per_actor,
                seed=args.seed + i,
                async_envs=envs_per_actor > 1,
                atari=atari,
            )
        )
        for i in range(args.num_actors)
    ]
    from scalerl_tpu.envs import make_gym_env

    probe = make_gym_env(args.env_id, seed=args.seed, atari=atari)()
    obs_shape = probe.observation_space.shape
    num_actions = probe.action_space.n
    probe.close()
    agent = ImpactAgent(
        args,
        obs_shape=obs_shape,
        num_actions=num_actions,
        obs_dtype=jnp.uint8 if len(obs_shape) == 3 else jnp.float32,
    )
    # mesh (mesh_shape / dp_size×mp_size) is resolved by the trainer via
    # maybe_enable_mesh_from_args — same wiring as IMPALA/PPO
    trainer = HostActorLearnerTrainer(args, agent, env_fns)
    try:
        result = trainer.train(total_frames=args.total_steps)
        print("final:", {k: round(float(v), 3) for k, v in result.items()})
        print("surrogate buffer:", agent.surrogate.stats())
        if args.save_model and not args.disable_checkpoint:
            path = agent.save_checkpoint(
                os.path.join(trainer.model_save_dir, "ckpt_final")
            )
            print("checkpoint:", path)
    finally:
        trainer.close()


if __name__ == "__main__":
    main()
