"""Parallel DQN entry: actor processes + shm ring + central TPU learner.

The working equivalent of the reference's ``ParallelDQNv2`` architecture
(``scalerl/algorithms/dqn/parallel_dqn.py``; the reference had no example
entry for it).  Actors are OS processes doing numpy CPU inference on
versioned weight snapshots; transitions flow through the lock-free C++
shared-memory ring; the learner trains double-DQN on device.

Usage:
    python examples/train_parallel_dqn.py --max-timesteps 20000 --num-actors 4
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import gymnasium as gym

    from scalerl_tpu.agents.dqn import DQNAgent
    from scalerl_tpu.config import DQNArguments, parse_args
    from scalerl_tpu.trainer.parallel_dqn import ParallelDQNTrainer

    args = parse_args(DQNArguments)
    from scalerl_tpu.utils.platform import setup_platform

    print("backend:", setup_platform(args.platform))
    probe = gym.make(args.env_id)
    obs_shape = probe.observation_space.shape
    action_dim = probe.action_space.n
    probe.close()
    agent = DQNAgent(
        args, obs_shape=obs_shape, action_dim=action_dim, donate_state=False
    )
    trainer = ParallelDQNTrainer(
        args,
        agent,
        env_id=args.env_id,
        obs_shape=obs_shape,
        num_actors=args.num_actors,
    )
    result = trainer.train()
    print("final:", {k: round(v, 2) for k, v in result.items()})


if __name__ == "__main__":
    main()
