"""Distributed DQN over the actor fleet: the Gorila/HandyRL topology, live.

The capability the reference vendored but never wired
(``scalerl/hpc/worker.py`` + ``parameter_server.py`` — import-broken as
shipped, SURVEY.md §2.1): a central learner hands out rollout tasks, a
worker fleet (local pipes here; ``RemoteCluster`` from other hosts) runs
eps-greedy episodes with CPU numpy inference on versioned weight snapshots,
and episode transitions stream back — batched + compressed — into the
device-side replay the TPU learner samples from.  Weights republish every
``publish_every`` learn steps.

Usage:
    python examples/train_fleet_dqn.py --episodes 200 --num-workers 4
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

ENV_ID = "CartPole-v1"
OBS_DIM, NUM_ACTIONS = 4, 2


def numpy_qnet(weights, obs: np.ndarray) -> np.ndarray:
    """CPU forward of the plain (non-dueling) QNet MLP param pytree."""
    x = obs.astype(np.float32)
    layers = sorted(weights["params"].keys(), key=lambda k: int(k.split("_")[1]))
    for i, name in enumerate(layers):
        layer = weights["params"][name]
        x = x @ layer["kernel"] + layer["bias"]
        if i < len(layers) - 1:
            x = np.maximum(x, 0.0)
    return x


def episode_runner(task, weights, worker_id):
    """One eps-greedy CartPole episode on the fleet worker's CPU."""
    import gymnasium as gym

    env = gym.make(ENV_ID)
    seed = int(task["seed"])
    rng = np.random.default_rng(seed)
    eps = float(task.get("eps", 0.1))
    obs, _ = env.reset(seed=seed)
    obs_l, act_l, rew_l, next_l, done_l = [], [], [], [], []
    done = False
    while not done and len(act_l) < 500:
        if weights is None or rng.random() < eps:
            a = int(rng.integers(NUM_ACTIONS))
        else:
            a = int(np.argmax(numpy_qnet(weights, obs[None])[0]))
        nxt, r, term, trunc, _ = env.step(a)
        obs_l.append(obs)
        act_l.append(a)
        rew_l.append(float(r))
        next_l.append(nxt)
        done_l.append(bool(term))
        obs = nxt
        done = term or trunc
    env.close()
    return {
        "obs": np.asarray(obs_l, np.float32),
        "action": np.asarray(act_l, np.int32),
        "reward": np.asarray(rew_l, np.float32),
        "next_obs": np.asarray(next_l, np.float32),
        "done": np.asarray(done_l, np.bool_),
        "episode_return": float(np.sum(rew_l)),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--episodes", type=int, default=200)
    parser.add_argument("--num-workers", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--publish-every", type=int, default=10)
    parser.add_argument("--eps", type=float, default=0.2)
    # "cpu" pins the learner off the TPU plugin (under the axon tunnel a
    # wedged backend hangs the first jax call indefinitely); pass "auto"
    # to put the learner on the accelerator
    parser.add_argument("--platform", default="cpu")
    args = parser.parse_args()

    from scalerl_tpu.utils.platform import setup_platform

    setup_platform(args.platform)

    import jax

    from scalerl_tpu.agents.dqn import DQNAgent
    from scalerl_tpu.config import DQNArguments
    from scalerl_tpu.data.replay import ReplayBuffer
    from scalerl_tpu.fleet import FleetConfig, LocalCluster, WorkerServer

    agent = DQNAgent(
        DQNArguments(hidden_sizes=(128, 128), learning_rate=1e-3),
        obs_shape=(OBS_DIM,),
        action_dim=NUM_ACTIONS,
    )
    replay = ReplayBuffer(obs_shape=(OBS_DIM,), capacity=50_000, num_envs=1)

    lock = threading.Lock()
    counter = {"i": 0}
    server_box = {}

    def task_source():
        with lock:
            if counter["i"] >= args.episodes:
                return None
            counter["i"] += 1
            return {
                "role": "rollout",
                "seed": counter["i"],
                "eps": args.eps,
                "param_version": server_box["s"].params.version,
            }

    config = FleetConfig(
        num_workers=args.num_workers, workers_per_gather=4, upload_batch=2
    )
    server = WorkerServer(config, task_source)
    server_box["s"] = server
    server.publish(jax.tree_util.tree_map(np.asarray, agent.get_weights()))
    server.start()
    cluster = LocalCluster(server, config, episode_runner)
    cluster.start()

    episodes = 0
    learn_steps = 0
    returns = []
    metrics = {}
    # host staging: insert fixed-size chunks so the device add compiles once
    CHUNK = 64
    pending = {k: [] for k in ("obs", "action", "reward", "next_obs", "done")}

    def flush_pending() -> None:
        while len(pending["action"]) >= CHUNK:
            chunk = {k: np.asarray(v[:CHUNK]) for k, v in pending.items()}
            for k in pending:
                del pending[k][:CHUNK]
            replay.save_chunk(**chunk)

    t0 = time.time()
    while episodes < args.episodes:
        result = server.get_result(timeout=1.0)
        if result is None:
            continue
        episodes += 1
        returns.append(result["episode_return"])
        for k in pending:
            pending[k].extend(list(result[k]))
        flush_pending()
        if len(replay) >= args.batch_size:
            for _ in range(2):
                metrics = agent.learn(replay.sample(args.batch_size))
                learn_steps += 1
            if learn_steps % args.publish_every < 2:
                server.publish(
                    jax.tree_util.tree_map(np.asarray, agent.get_weights())
                )
        if episodes % 20 == 0:
            recent = float(np.mean(returns[-20:]))
            print(
                f"episodes {episodes} | return(20) {recent:.1f} | "
                f"learn_steps {learn_steps} | weight v{server.params.version} | "
                f"loss {metrics.get('loss', float('nan')):.4f}",
                flush=True,
            )

    cluster.join()
    server.stop()
    dt = time.time() - t0
    print(
        f"done: {episodes} episodes in {dt:.1f}s | "
        f"final return(20) {np.mean(returns[-20:]):.1f} | "
        f"first return(20) {np.mean(returns[:20]):.1f}"
    )


if __name__ == "__main__":
    main()
