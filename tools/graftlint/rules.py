"""graftlint rules JG001-JG005: the dispatch/transfer discipline this repo
learned the hard way.

Each rule encodes one class of silent performance/correctness bug that a
previous PR root-caused at runtime (faulthandler dumps, fps regressions) and
that nothing previously prevented from being reintroduced:

- **JG001 blocking-transfer-in-loop** — per-key host syncs (``float()``,
  ``.item()``, ``np.asarray()``, per-iteration ``jax.device_get``) on jax
  values in the hot packages serialize the host against the device and
  defeat async dispatch (the PR 1 class).  Metric reads must go through
  ``runtime.dispatch.get_metrics`` / one batched ``device_get`` per chunk.
- **JG002 unguarded-mesh-dispatch** — multi-device (pjit/meshed) programs
  dispatched concurrently from actor threads and the learner enqueue in
  different per-device orders and deadlock the XLA client (the PR 2
  ``test_apex_sharded_replay_mesh_e2e`` hang).  Every dispatch site in a
  threaded + meshed module must sit behind the mesh dispatch lock.
- **JG003 retrace-hazard** — a ``static_argnums`` slot fed a value that
  varies per loop iteration recompiles every call; a jitted function that
  reads host state (``time.time``, ``np.random``, ``os.environ``) bakes it
  in at trace time.
- **JG004 tracer-leak** — assigning to ``self.*``/globals inside jitted
  code leaks tracers (or silently freezes a side effect at trace time).
- **JG005 donation-misuse** — reusing an argument after it was donated
  (``donate_argnums``) reads a deleted buffer.

Rules are deliberately heuristic: high-precision syntactic + local-taint
checks, with inline suppressions and the checked-in baseline absorbing the
deliberate exceptions (see ``docs/LINTING.md``).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from tools.graftlint.engine import (
    Finding,
    ModuleContext,
    assign_target_paths,
    attr_path,
    root_name,
)

# packages whose loops are device hot paths (relative path segments)
HOT_DIRS = {"runtime", "trainer", "agents", "serving", "genrl"}

# jax module aliases whose call results live on device
JAX_ROOTS = {"jax", "jnp"}

# method names that dispatch jitted/meshed device programs in this codebase
DISPATCH_METHODS = {
    "learn",
    "learn_device",
    "learn_sequences",
    "act",
    "predict",
    "get_action",
    "_act",
    "_act_greedy",
    "_priority",
    "sample",
    "add",
    "add_with_priorities",
    "update_priorities",
}

# receivers those methods count on (dotted-path segments)
DISPATCH_RECEIVERS = {"agent", "policy", "buffer", "replay", "sampler", "_sharded_replay"}

# module-level jitted data-plane entry points (defined with @partial(jax.jit)
# in scalerl_tpu.data.*) and their donated positions
KNOWN_JITTED_FNS: Dict[str, Tuple[int, ...]] = {
    "seq_add": (0,),
    "seq_sample": (),
    "seq_update_priorities": (0,),
    "seq_update_priorities_keep_empty": (0,),
    "per_add_with_priorities": (0,),
}

# JG001 allowlist: cold-path recovery handlers where ONE blocking readback
# is the point.  The divergence-rollback handler restores params from the
# last good checkpoint and reads them back once to assert finiteness before
# training resumes — it runs at most once per divergence event, never in
# the steady state, so the host sync is sanctioned by design (the same
# contract as the explicit float(jax.device_get(x)) idiom).
JG001_COLD_FUNCS = {"_divergence_rollback"}

# host-state calls that must not be captured inside jitted code
IMPURE_CALLS = {
    "time.time",
    "time.perf_counter",
    "time.monotonic",
    "os.environ.get",
    "os.getenv",
}
IMPURE_ROOT_PREFIXES = ("np.random.", "numpy.random.", "random.")


def _is_hot_path(relpath: str) -> bool:
    return any(part in HOT_DIRS for part in relpath.split("/")[:-1])


def _jit_wrapper_info(call: ast.Call) -> Optional[Dict]:
    """If ``call`` is jax.jit/pjit/shard_map(...), return its metadata."""
    path = attr_path(call.func)
    if path is None:
        return None
    name = path.split(".")[-1]
    if name not in {"jit", "pjit", "shard_map"}:
        return None
    info: Dict = {"kind": name, "static": set(), "static_names": set(), "donate": ()}
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnames", "donate_argnums"):
            vals: List = []
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant):
                    vals.append(e.value)
            if kw.arg == "static_argnums":
                info["static"] = {v for v in vals if isinstance(v, int)}
            elif kw.arg == "static_argnames":
                info["static_names"] = {v for v in vals if isinstance(v, str)}
            else:
                info["donate"] = tuple(v for v in vals if isinstance(v, int))
    return info


class _JitIndex:
    """Module-wide map of jit-wrapped callables.

    ``wrapped``: assigned name / attribute name -> jit info (e.g.
    ``self._priority = jax.jit(...)`` registers ``_priority``).
    ``impl_funcs``: names of local functions handed to jax.jit/shard_map
    (``jax.jit(self._fused_iter_impl)`` registers ``_fused_iter_impl``) —
    their *bodies* are traced, so JG003/JG004 inspect them.
    """

    def __init__(self, ctx: ModuleContext) -> None:
        self.wrapped: Dict[str, Dict] = {}
        self.impl_funcs: Set[str] = set()
        self.decorated: Dict[str, Dict] = {}
        for node in ctx.walk():
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                info = self._info_through_partial(node.value)
                if info is None:
                    continue
                for path in assign_target_paths(node):
                    self.wrapped[path.split(".")[-1]] = info
                self._collect_impls(node.value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    info = None
                    if isinstance(dec, ast.Call):
                        dec_path = attr_path(dec.func) or ""
                        if dec_path.split(".")[-1] == "partial" and dec.args:
                            inner = attr_path(dec.args[0]) or ""
                            if inner.split(".")[-1] in {"jit", "pjit"}:
                                info = _jit_wrapper_info(
                                    ast.Call(
                                        func=dec.args[0],
                                        args=[],
                                        keywords=dec.keywords,
                                    )
                                )
                        else:
                            info = _jit_wrapper_info(dec)
                    elif (attr_path(dec) or "").split(".")[-1] in {"jit", "pjit"}:
                        info = {"kind": "jit", "static": set(), "static_names": set(), "donate": ()}
                    if info is not None:
                        self.decorated[node.name] = info
                        self.impl_funcs.add(node.name)
                        break

    def _info_through_partial(self, call: ast.Call) -> Optional[Dict]:
        return _jit_wrapper_info(call)

    def _collect_impls(self, call: ast.Call) -> None:
        """Record local function/method names traced by this wrapper —
        including through nested partial()/shard_map() calls."""
        stack: List[ast.AST] = list(call.args)
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                stack.extend(node.args)
            else:
                path = attr_path(node)
                if path is not None:
                    self.impl_funcs.add(path.split(".")[-1])


def _jitted_defs(ctx: ModuleContext, index: _JitIndex) -> List[ast.FunctionDef]:
    out = []
    for node in ctx.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in index.impl_funcs or node.name in index.decorated:
                out.append(node)
    return out


# ---------------------------------------------------------------------------
# JG001 — blocking transfer in hot-path loops


def _tainted_names(ctx: ModuleContext, func: Optional[ast.AST]) -> Set[str]:
    """Names bound (within ``func``, or at module level) to values produced
    by jnp./jax. expressions — a local, two-pass taint."""
    body_owner = func if func is not None else ctx.tree
    tainted: Set[str] = set()
    assigns: List[Tuple[List[str], ast.AST]] = []
    for node in ast.walk(body_owner):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if ctx.enclosing_function(node) is not (
                func if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)) else None
            ):
                continue
            value = node.value
            if value is None:
                continue
            names = [p for p in assign_target_paths(node) if "." not in p]
            if names:
                assigns.append((names, value))
    for _ in range(2):  # two passes: one hop of name-to-name propagation
        for names, value in assigns:
            root = root_name(value)
            if root in JAX_ROOTS or root in tainted:
                tainted.update(names)
            elif isinstance(value, ast.BinOp):
                for side in (value.left, value.right):
                    r = root_name(side)
                    if r in JAX_ROOTS or r in tainted:
                        tainted.update(names)
    return tainted


def _is_jax_valued(node: ast.AST, tainted: Set[str]) -> bool:
    if isinstance(node, ast.Call):
        # jax.device_get IS the sanctioned explicit transfer: its result is
        # host memory, so float(jax.device_get(x)) at a cold path is the
        # idiom the rule steers code toward, not a violation
        if attr_path(node.func) == "jax.device_get":
            return False
        return root_name(node) in JAX_ROOTS
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, (ast.Subscript, ast.Attribute)):
        return root_name(node) in JAX_ROOTS
    return False


def rule_jg001(ctx: ModuleContext) -> Iterator[Finding]:
    if not _is_hot_path(ctx.relpath):
        return
    taint_cache: Dict[Optional[ast.AST], Set[str]] = {}

    def tainted_for(node: ast.AST) -> Set[str]:
        func = ctx.enclosing_function(node)
        if func not in taint_cache:
            taint_cache[func] = _tainted_names(ctx, func)
        return taint_cache[func]

    hint = (
        "route metric/scalar reads through runtime.dispatch.get_metrics (one "
        "batched device->host transfer per chunk) or hoist the read out of "
        "the loop; keep running reductions on device"
    )
    for node in ctx.walk():
        if not isinstance(node, ast.Call):
            continue
        enclosing = ctx.enclosing_function(node)
        if enclosing is not None and enclosing.name in JG001_COLD_FUNCS:
            continue  # sanctioned cold-path recovery handler
        in_loop = ctx.enclosing_loop(node) is not None
        where = " inside a loop body" if in_loop else ""
        # float(X) / int(X) on a jax value
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int")
            and len(node.args) == 1
            and not node.keywords
            and _is_jax_valued(node.args[0], tainted_for(node))
        ):
            yield ctx.finding(
                node,
                "JG001",
                f"blocking host sync: {node.func.id}() on a jax value{where}",
                hint,
            )
            continue
        # np.asarray/np.array on a jax value
        fpath = attr_path(node.func)
        if (
            fpath in ("np.asarray", "numpy.asarray", "np.array", "numpy.array")
            and node.args
            and _is_jax_valued(node.args[0], tainted_for(node))
        ):
            yield ctx.finding(
                node,
                "JG001",
                f"blocking host sync: {fpath}() on a jax value{where}",
                hint,
            )
            continue
        # .item() — the canonical scalar sync
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
            and (in_loop or _is_jax_valued(node.func.value, tainted_for(node)))
        ):
            yield ctx.finding(
                node, "JG001", f".item() host sync{where}", hint
            )
            continue
        # per-iteration jax.device_get
        if fpath == "jax.device_get" and in_loop:
            yield ctx.finding(
                node,
                "JG001",
                "jax.device_get inside a loop body (per-key/per-iteration "
                "transfer)",
                "batch the whole pytree into ONE device_get per chunk "
                "(runtime.dispatch.get_metrics does this)",
            )


# ---------------------------------------------------------------------------
# JG002 — unguarded mesh dispatch in threaded modules


def _guarded(ctx: ModuleContext, node: ast.AST) -> bool:
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                seg = ctx.segment(item.context_expr)
                if "_dispatch_guard" in seg or "_mesh_lock" in seg:
                    return True
    return False


def _dispatch_site(node: ast.Call, jit_names: Set[str]) -> Optional[str]:
    """Return a short label if ``node`` dispatches a device program."""
    func = node.func
    if isinstance(func, ast.Name):
        if func.id in KNOWN_JITTED_FNS or func.id in jit_names:
            return func.id
        return None
    if isinstance(func, ast.Attribute):
        if func.attr in jit_names:
            return attr_path(func) or func.attr
        if func.attr in DISPATCH_METHODS:
            recv = attr_path(func.value)
            if recv is not None and any(
                seg in DISPATCH_RECEIVERS for seg in recv.split(".")
            ):
                return f"{recv}.{func.attr}"
    return None


def rule_jg002(ctx: ModuleContext) -> Iterator[Finding]:
    # trigger: the module both runs threads and touches a mesh — the only
    # combination where concurrent multi-device dispatch can interleave
    if "threading" not in ctx.source or "mesh" not in ctx.source:
        return
    index = ctx.jit_index()
    jit_names = set(index.wrapped)
    for node in ctx.walk():
        if not isinstance(node, ast.Call):
            continue
        label = _dispatch_site(node, jit_names)
        if label is None:
            continue
        func = ctx.enclosing_function(node)
        if func is not None and func.name == "__init__":
            continue  # construction happens before any thread starts
        if _guarded(ctx, node):
            continue
        yield ctx.finding(
            node,
            "JG002",
            f"meshed/jitted dispatch `{label}` outside the mesh dispatch "
            "lock in a threaded module",
            "wrap the call in `with self._dispatch_guard():` — concurrent "
            "multi-device programs enqueued in different per-device orders "
            "deadlock the XLA client (the apex mesh e2e hang)",
        )


# ---------------------------------------------------------------------------
# JG003 — retrace hazards


def _loop_bound_names(loop: ast.AST) -> Set[str]:
    bound: Set[str] = set()
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        stack = [loop.target]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.Tuple, ast.List)):
                stack.extend(cur.elts)
            elif isinstance(cur, ast.Name):
                bound.add(cur.id)
    for node in ast.walk(loop):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            bound.update(p for p in assign_target_paths(node) if "." not in p)
        elif isinstance(node, ast.NamedExpr) and isinstance(node.target, ast.Name):
            bound.add(node.target.id)
    return bound


def _references(expr: ast.AST, names: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in names:
            return True
    return False


def rule_jg003(ctx: ModuleContext) -> Iterator[Finding]:
    index = ctx.jit_index()
    static_callables: Dict[str, Dict] = {
        name: info
        for name, info in {**index.wrapped, **index.decorated}.items()
        if info["static"] or info["static_names"]
    }
    # (a) per-call-varying value fed to a static slot inside a loop
    for node in ctx.walk():
        if not isinstance(node, ast.Call):
            continue
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        info = static_callables.get(name or "")
        if info is None:
            continue
        loop = ctx.enclosing_loop(node)
        if loop is None:
            continue
        varying = _loop_bound_names(loop)
        static_args: List[Tuple[str, ast.AST]] = []
        for pos in sorted(info["static"]):
            if pos < len(node.args):
                static_args.append((f"positional {pos}", node.args[pos]))
        for kw in node.keywords:
            if kw.arg in info["static_names"]:
                static_args.append((f"`{kw.arg}=`", kw.value))
        for slot, expr in static_args:
            if _references(expr, varying) and not isinstance(expr, ast.Constant):
                yield ctx.finding(
                    node,
                    "JG003",
                    f"static argument {slot} of `{name}` varies per loop "
                    "iteration — every call retraces and recompiles",
                    "pass per-call-varying values as traced (device) "
                    "arguments, or hoist the value out of the loop",
                )
    # (b) jitted body capturing mutable host state
    for fn in _jitted_defs(ctx, index):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            path = attr_path(node.func) or ""
            if path in IMPURE_CALLS or path.startswith(IMPURE_ROOT_PREFIXES):
                yield ctx.finding(
                    node,
                    "JG003",
                    f"jitted function `{fn.name}` calls `{path}` — the value "
                    "is baked in at trace time and never refreshed",
                    "compute host state outside the jitted function and pass "
                    "it in as an argument (traced, or static if trace-stable)",
                )
        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript):
                if (attr_path(node.value) or "") == "os.environ":
                    yield ctx.finding(
                        node,
                        "JG003",
                        f"jitted function `{fn.name}` reads os.environ — "
                        "baked in at trace time",
                        "resolve environment knobs at construction time "
                        "(see pallas_per.resolve_sample_method)",
                    )


# ---------------------------------------------------------------------------
# JG004 — tracer leaks out of jitted code


def rule_jg004(ctx: ModuleContext) -> Iterator[Finding]:
    index = ctx.jit_index()
    for fn in _jitted_defs(ctx, index):
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for path in assign_target_paths(node):
                    if "." in path and path.split(".")[0] == "self":
                        yield ctx.finding(
                            node,
                            "JG004",
                            f"jitted function `{fn.name}` assigns to "
                            f"`{path}` — tracer leak / side effect frozen at "
                            "trace time",
                            "return the value from the jitted function and "
                            "assign it on the host side",
                        )
            elif isinstance(node, ast.Global):
                yield ctx.finding(
                    node,
                    "JG004",
                    f"jitted function `{fn.name}` writes module globals — "
                    "tracer leak / trace-time side effect",
                    "thread state through the function's inputs/outputs",
                )


# ---------------------------------------------------------------------------
# JG005 — use after donation


def _donating_callables(index: _JitIndex) -> Dict[str, Tuple[int, ...]]:
    out: Dict[str, Tuple[int, ...]] = {
        name: donate for name, donate in KNOWN_JITTED_FNS.items() if donate
    }
    for name, info in {**index.wrapped, **index.decorated}.items():
        if info["donate"]:
            out[name] = info["donate"]
    return out


def rule_jg005(ctx: ModuleContext) -> Iterator[Finding]:
    index = ctx.jit_index()
    donating = _donating_callables(index)
    if not donating:
        return
    for node in ctx.walk():
        if not isinstance(node, ast.Call):
            continue
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        donate = donating.get(name or "")
        if not donate:
            continue
        stmt = ctx.enclosing_statement(node)
        rebinds = set(assign_target_paths(stmt))
        func = ctx.enclosing_function(node)
        scope = func if func is not None else ctx.tree
        for pos in donate:
            if pos >= len(node.args):
                continue
            path = attr_path(node.args[pos])
            if path is None or path in rebinds:
                continue
            # linear scan of the enclosing scope for a read of the donated
            # binding after the call, before any rebind (source order —
            # good enough for a linter, suppressions cover the rest)
            end = getattr(stmt, "end_lineno", stmt.lineno)
            events: List[Tuple[int, str]] = []
            for n in ast.walk(scope):
                p = attr_path(n) if isinstance(n, (ast.Name, ast.Attribute)) else None
                if p != path:
                    continue
                lineno = getattr(n, "lineno", 0)
                if lineno <= end:
                    continue
                is_store = isinstance(getattr(n, "ctx", None), (ast.Store, ast.Del))
                events.append((lineno, "store" if is_store else "load"))
            for lineno, kind in sorted(events):
                if kind == "store":
                    break
                yield ctx.finding(
                    node,
                    "JG005",
                    f"`{path}` is donated to `{name}` (donate_argnums "
                    f"position {pos}) but read again at line {lineno} — "
                    "use of a deleted buffer",
                    "rebind the result over the donated name "
                    "(`x = fn(x, ...)`) or copy before donating",
                )
                break


RULES = [
    ("JG001", "blocking-transfer-in-loop", rule_jg001),
    ("JG002", "unguarded-mesh-dispatch", rule_jg002),
    ("JG003", "retrace-hazard", rule_jg003),
    ("JG004", "tracer-leak", rule_jg004),
    ("JG005", "donation-misuse", rule_jg005),
]
