"""Phase-1 fact harvest for graftlint v2's whole-program rules.

One pass over a :class:`~tools.graftlint.engine.ModuleContext` distills the
module into a :class:`ModuleFacts` record — the only thing phase 2 (the
cross-file join in ``tools/graftlint/xrules.py``) ever sees.  Facts are
deliberately lossy: each captures just enough structure for its rule.

Fact families (one per v2 rule):

- **locks** (JG006): lock attributes defined via ``threading.Lock()`` et
  al., ``with``-acquisition edges from lexical nesting (holding A, acquire
  B), the lock set each method acquires at its top, and method calls made
  while a lock is held (resolved one hop in phase 2).
- **wire kinds** (JG007): every dict-literal frame carrying a ``"kind"``
  key (a *send*) and every comparison/membership test against
  ``msg["kind"]`` / ``msg.get("kind")`` or a local alias of one (a
  *handle*).  Values resolve through module-level string constants; names
  that stay unresolved locally are carried as refs for the phase-2 global
  constant table.  Only modules under :data:`WIRE_DIRS` contribute — the
  codec-v2 wire lives in the host plane, and dict literals elsewhere (the
  linter's own rule tables, say) are not frames.
- **lifecycle** (JG008): ``threading.Thread(...)`` creations with daemon
  status, whether the module calls ``.start()`` / ``.join()`` at all,
  ``ThreadPoolExecutor``/``ProcessPoolExecutor`` constructions (with
  ``with``-managed ones marked — the context manager is their shutdown)
  and whether the module calls ``.shutdown()`` at all, per-class
  ``PageAllocator`` acquire/release tallies plus acquire-inside-
  ``try``-without-exception-path-release sites, and ``start_span`` results
  that are discarded or never read again.
- **telemetry** (JG009): ``MetricsRegistry`` instrument creations
  (``reg.counter("a.b")``, f-string families as constant prefixes) and
  ``reg.bind(...)`` names, with dynamic names recorded as such rather
  than guessed at.

A module may declare wire kinds that are sent (or dispatched) on purpose
without a static peer via ``# graftlint: wire-ignore=kind1,kind2``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.graftlint.engine import ModuleContext, attr_path, root_name

#: directories whose modules speak the codec-v2 wire; dict literals with a
#: "kind" key outside these are not frames and never enter JG007's join.
WIRE_DIRS = {"fleet", "serving", "genrl", "runtime", "trainer"}

#: hot host-plane dirs for the JG008 thread sub-rule (mirrors rules.HOT_DIRS).
HOT_DIRS = {"runtime", "trainer", "agents", "serving", "genrl"}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
#: executor constructors the JG008 pool sub-rule tracks (shutdown() is the
#: executor's join()).
_POOL_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_LOCK_SUFFIXES = ("_lock", "_guard", "_mutex")
_LOCK_NAMES = {"lock", "mutex", "guard"}

_ALLOC_ACQUIRE = {"alloc", "try_reserve", "share"}
_ALLOC_RELEASE = {"free", "release"}

_REG_RECEIVERS = {"reg", "_reg", "registry", "_registry"}
_INSTRUMENT_APIS = {"counter", "gauge", "histogram", "meter"}

_WIRE_IGNORE_RE = re.compile(r"#\s*graftlint:\s*wire-ignore=([A-Za-z0-9_.,\- ]+)")

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


@dataclass
class KindSite:
    """One wire-kind occurrence: a resolved literal, or an unresolved name
    ref for the phase-2 global constant table, or dynamic (both None)."""

    kind: Optional[str]
    ref: Optional[str]
    line: int


@dataclass
class ThreadFact:
    line: int
    daemonic: bool


@dataclass
class PoolFact:
    """One ThreadPoolExecutor/ProcessPoolExecutor construction.  ``managed``
    means it was built as a ``with`` context expression — the context
    manager IS the shutdown, so only unmanaged pools need a reachable
    ``shutdown()`` (the executor twin of the Thread ``join`` rule)."""

    line: int
    managed: bool


@dataclass
class AllocFact:
    owner: str  # enclosing class name, or "<module>"
    acquire_lines: List[int] = field(default_factory=list)
    releases: int = 0


@dataclass
class InstrumentFact:
    api: str  # counter / gauge / histogram / meter / bind
    name: Optional[str]  # exact string name
    prefix: Optional[str]  # constant prefix of an f-string family
    line: int


@dataclass
class ModuleFacts:
    relpath: str
    module_id: str  # file stem, qualifies module-level lock names
    is_wire: bool
    is_hot: bool
    consts: Dict[str, str] = field(default_factory=dict)
    # locks
    lock_defs: Dict[str, int] = field(default_factory=dict)
    lock_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    method_locks: Dict[str, Set[Tuple[str, FrozenSet[str]]]] = field(
        default_factory=dict
    )
    held_calls: List[Tuple[str, str, int]] = field(default_factory=list)
    # wire
    sends: List[KindSite] = field(default_factory=list)
    handles: List[KindSite] = field(default_factory=list)
    wire_ignored: Set[str] = field(default_factory=set)
    # lifecycle
    threads: List[ThreadFact] = field(default_factory=list)
    has_start: bool = False
    has_join: bool = False
    pools: List[PoolFact] = field(default_factory=list)
    has_pool_shutdown: bool = False
    allocs: Dict[str, AllocFact] = field(default_factory=dict)
    alloc_leaks: List[int] = field(default_factory=list)
    unended_spans: List[Tuple[int, str]] = field(default_factory=list)
    # telemetry
    instruments: List[InstrumentFact] = field(default_factory=list)
    binds: List[InstrumentFact] = field(default_factory=list)
    dynamic_bind: bool = False
    # suppressions, retained so phase-2 findings honor their anchor file's
    # inline/file-wide disables
    suppress_lines: Dict[int, Set[str]] = field(default_factory=dict)
    suppress_file: Set[str] = field(default_factory=set)


def _path_dirs(relpath: str) -> Set[str]:
    return set(relpath.split("/")[:-1])


def _enclosing_class(ctx: ModuleContext, node: ast.AST) -> Optional[str]:
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc.name
    return None


def _const_str(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    return None


def _fstring_prefix(expr: ast.AST) -> Optional[str]:
    """Constant leading text of an f-string (or ``"lit" + x`` concat)."""
    if isinstance(expr, ast.JoinedStr):
        prefix = ""
        for part in expr.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix += part.value
            else:
                break
        return prefix or None
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _const_str(expr.left)
    return None


# ---------------------------------------------------------------------------
# locks


def _lock_id(
    ctx: ModuleContext, expr: ast.AST, cls: Optional[str], module_id: str
) -> Tuple[Optional[str], Optional[str]]:
    """(graph node id, last path segment) for a with-item expression."""
    e = expr
    if isinstance(e, ast.Call):  # e.g. ``with lock_timeout(self._lock):``
        if e.args:
            e = e.args[0]
        else:
            e = e.func
    if isinstance(e, ast.Name):
        return f"{module_id}.{e.id}", e.id
    path = attr_path(e)
    if path is None:
        return None, None
    parts = path.split(".")
    if parts[0] == "self":
        if len(parts) == 2 and cls:
            return f"{cls}.{parts[1]}", parts[1]
        return ".".join(parts[1:]), parts[-1]
    return path, parts[-1]


def _lockish(facts: ModuleFacts, lock_id: Optional[str], last: Optional[str]) -> bool:
    if not lock_id or not last:
        return False
    if lock_id in facts.lock_defs:
        return True
    return last.endswith(_LOCK_SUFFIXES) or last in _LOCK_NAMES


def _harvest_locks_in_function(
    ctx: ModuleContext, func: ast.AST, facts: ModuleFacts
) -> None:
    cls = _enclosing_class(ctx, func)
    top_locks: Set[str] = set()

    def visit(node: ast.AST, held: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPES):
                continue  # nested scopes don't run under this lock
            nxt = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in child.items:
                    lid, last = _lock_id(ctx, item.context_expr, cls, facts.module_id)
                    if _lockish(facts, lid, last):
                        acquired.append(lid)  # type: ignore[arg-type]
                if acquired:
                    for lid in acquired:
                        if held:
                            facts.lock_edges.append((held[-1], lid, child.lineno))
                        else:
                            top_locks.add(lid)
                    nxt = held + acquired
            elif isinstance(child, ast.Call) and held:
                if isinstance(child.func, ast.Attribute):
                    facts.held_calls.append(
                        (held[-1], child.func.attr, child.lineno)
                    )
            visit(child, nxt)

    visit(func, [])
    if top_locks:
        name = getattr(func, "name", "<lambda>")
        facts.method_locks.setdefault(name, set()).add(
            (cls or "", frozenset(top_locks))
        )


# ---------------------------------------------------------------------------
# wire kinds


def _kind_value_site(expr: ast.AST, consts: Dict[str, str]) -> KindSite:
    line = getattr(expr, "lineno", 1)
    s = _const_str(expr)
    if s is not None:
        return KindSite(kind=s, ref=None, line=line)
    if isinstance(expr, ast.Name):
        if expr.id in consts:
            return KindSite(kind=consts[expr.id], ref=None, line=line)
        return KindSite(kind=None, ref=expr.id, line=line)
    if isinstance(expr, ast.Attribute):
        return KindSite(kind=None, ref=expr.attr, line=line)
    return KindSite(kind=None, ref=None, line=line)


def _is_kind_read(expr: ast.AST, aliases: Set[str]) -> bool:
    """True for ``X["kind"]``, ``X.get("kind"[, d])``, or a local alias."""
    if isinstance(expr, ast.Subscript):
        return _const_str(expr.slice) == "kind"
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "get"
        and expr.args
    ):
        return _const_str(expr.args[0]) == "kind"
    if isinstance(expr, ast.Name):
        return expr.id in aliases
    return False


def _harvest_handles_in_function(
    ctx: ModuleContext, func: ast.AST, facts: ModuleFacts
) -> None:
    nodes = [n for n in _scope_walk(func)]
    aliases: Set[str] = set()
    for n in nodes:  # pass 1: ``kind = msg.get("kind")`` aliases
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t = n.targets[0]
            if isinstance(t, ast.Name) and _is_kind_read(n.value, set()):
                aliases.add(t.id)
    for n in nodes:  # pass 2: comparisons / membership tests
        if not isinstance(n, ast.Compare):
            continue
        sides = [n.left] + list(n.comparators)
        if not any(_is_kind_read(s, aliases) for s in sides):
            continue
        for op, comp in zip(n.ops, n.comparators):
            exprs: List[ast.AST]
            if isinstance(op, (ast.In, ast.NotIn)) and isinstance(
                comp, (ast.Tuple, ast.List, ast.Set)
            ):
                exprs = list(comp.elts)
            elif isinstance(op, (ast.Eq, ast.NotEq)):
                exprs = [comp, n.left]
            else:
                continue
            for e in exprs:
                if _is_kind_read(e, aliases):
                    continue
                site = _kind_value_site(e, facts.consts)
                if site.kind is not None or site.ref is not None:
                    facts.handles.append(site)


def _scope_walk(func: ast.AST):
    """Walk a function body without descending into nested scopes."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, _SCOPES):
            stack.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# lifecycle


def _alloc_receiver(call: ast.Call) -> Optional[str]:
    if not isinstance(call.func, ast.Attribute):
        return None
    recv = attr_path(call.func.value) or root_name(call.func.value) or ""
    return recv if "alloc" in recv else None


def _harvest_alloc_call(
    ctx: ModuleContext, call: ast.Call, facts: ModuleFacts
) -> None:
    recv = _alloc_receiver(call)
    if recv is None:
        return
    method = call.func.attr  # type: ignore[union-attr]
    owner = _enclosing_class(ctx, call) or "<module>"
    af = facts.allocs.setdefault(owner, AllocFact(owner=owner))
    if method in _ALLOC_RELEASE:
        af.releases += 1
        return
    if method not in _ALLOC_ACQUIRE:
        return
    af.acquire_lines.append(call.lineno)
    # acquire inside a try whose handlers/finally never release, while the
    # function does release later: the exception path leaks the pages
    anc = list(ctx.ancestors(call))
    for i, a in enumerate(anc):
        if isinstance(a, _SCOPES):
            break
        if isinstance(a, ast.Try):
            child = anc[i - 1] if i else call
            if child not in a.body and not any(
                child is s or _contains(s, child) for s in a.body
            ):
                continue
            cleanup = list(a.finalbody)
            for h in a.handlers:
                cleanup.extend(h.body)
            if any(_has_release(s) for s in cleanup):
                break
            func = ctx.enclosing_function(call)
            if func is not None and any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _ALLOC_RELEASE
                and _alloc_receiver(n)
                and n.lineno > max(call.lineno, getattr(a, "end_lineno", 0) or 0)
                for n in _scope_walk(func)
            ):
                facts.alloc_leaks.append(call.lineno)
            break


def _contains(tree: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(tree))


def _has_release(stmt: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr in _ALLOC_RELEASE
        and _alloc_receiver(n)
        for n in ast.walk(stmt)
    )


def _harvest_spans_in_function(
    ctx: ModuleContext, func: ast.AST, facts: ModuleFacts
) -> None:
    nodes = list(_scope_walk(func))
    for n in nodes:
        if not (
            isinstance(n, ast.Assign)
            and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)
            and isinstance(n.value, ast.Call)
        ):
            continue
        callee = n.value.func
        last = callee.attr if isinstance(callee, ast.Attribute) else (
            callee.id if isinstance(callee, ast.Name) else None
        )
        if last != "start_span":
            continue
        name = n.targets[0].id
        used = any(
            isinstance(m, ast.Name)
            and m.id == name
            and isinstance(m.ctx, ast.Load)
            and m.lineno >= n.lineno
            for m in nodes
        )
        if not used:
            facts.unended_spans.append((n.lineno, name))


# ---------------------------------------------------------------------------
# telemetry


def _is_registry_receiver(recv: ast.AST) -> bool:
    path = attr_path(recv)
    if path is not None and path.split(".")[-1] in _REG_RECEIVERS:
        return True
    if isinstance(recv, ast.Name) and recv.id in _REG_RECEIVERS:
        return True
    if root_name(recv) == "telemetry":
        return True
    for n in ast.walk(recv):
        if isinstance(n, ast.Call):
            f = n.func
            tail = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None
            )
            if tail == "get_registry":
                return True
    return False


def _harvest_telemetry_call(call: ast.Call, facts: ModuleFacts) -> None:
    if not isinstance(call.func, ast.Attribute):
        return
    api = call.func.attr
    if api not in _INSTRUMENT_APIS and api != "bind":
        return
    if not call.args:
        return
    if not _is_registry_receiver(call.func.value):
        return
    arg = call.args[0]
    name = _const_str(arg)
    prefix = None if name is not None else _fstring_prefix(arg)
    if api == "bind":
        if name is None and prefix is None:
            facts.dynamic_bind = True
            return
        facts.binds.append(InstrumentFact("bind", name, prefix, call.lineno))
        return
    if name is None and prefix is None:
        return  # fully dynamic instrument name: nothing to check statically
    facts.instruments.append(InstrumentFact(api, name, prefix, call.lineno))


# ---------------------------------------------------------------------------
# entry point


def harvest(
    ctx: ModuleContext,
    suppress_lines: Optional[Dict[int, Set[str]]] = None,
    suppress_file: Optional[Set[str]] = None,
) -> ModuleFacts:
    """Distill ``ctx`` into the facts phase 2 joins across the program."""
    dirs = _path_dirs(ctx.relpath)
    module_id = ctx.relpath.rsplit("/", 1)[-1].rsplit(".", 1)[0]
    facts = ModuleFacts(
        relpath=ctx.relpath,
        module_id=module_id,
        is_wire=bool(dirs & WIRE_DIRS),
        is_hot=bool(dirs & HOT_DIRS),
        suppress_lines=dict(suppress_lines or {}),
        suppress_file=set(suppress_file or ()),
    )

    for m in _WIRE_IGNORE_RE.finditer(ctx.source):
        facts.wire_ignored |= {k.strip() for k in m.group(1).split(",") if k.strip()}

    # module-level string constants (wire vocabularies: PING = "ping", ...)
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            t, v = stmt.targets[0], _const_str(stmt.value)
            if isinstance(t, ast.Name) and v is not None:
                facts.consts[t.id] = v
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            v = _const_str(stmt.value)
            if isinstance(stmt.target, ast.Name) and v is not None:
                facts.consts[stmt.target.id] = v

    nodes = ctx.walk()

    # lock attribute definitions first — _lockish consults them
    for n in nodes:
        if not (isinstance(n, ast.Assign) and isinstance(n.value, ast.Call)):
            continue
        callee = n.value.func
        ctor = callee.attr if isinstance(callee, ast.Attribute) else (
            callee.id if isinstance(callee, ast.Name) else None
        )
        if ctor not in _LOCK_CTORS:
            continue
        for t in n.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                cls = _enclosing_class(ctx, n)
                if cls:
                    facts.lock_defs[f"{cls}.{t.attr}"] = n.lineno
            elif isinstance(t, ast.Name):
                tgt_cls = _enclosing_class(ctx, n)
                owner = tgt_cls if (
                    tgt_cls and ctx.enclosing_function(n) is None
                ) else module_id
                facts.lock_defs[f"{owner}.{t.id}"] = n.lineno

    # with-managed executor constructions: the With node's context_expr is
    # the pool Call itself, and the context manager shuts it down
    managed_ctx_calls = {
        id(item.context_expr)
        for n in nodes
        if isinstance(n, (ast.With, ast.AsyncWith))
        for item in n.items
        if isinstance(item.context_expr, ast.Call)
    }

    daemon_assigned = any(
        isinstance(n, ast.Assign)
        and any(
            isinstance(t, ast.Attribute) and t.attr == "daemon" for t in n.targets
        )
        and isinstance(n.value, ast.Constant)
        and n.value.value
        for n in nodes
    )

    for n in nodes:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _harvest_locks_in_function(ctx, n, facts)
            _harvest_spans_in_function(ctx, n, facts)
            if facts.is_wire:
                _harvest_handles_in_function(ctx, n, facts)
            continue
        if isinstance(n, ast.Dict) and facts.is_wire:
            for k, v in zip(n.keys, n.values):
                if k is not None and _const_str(k) == "kind":
                    facts.sends.append(_kind_value_site(v, facts.consts))
            continue
        if not isinstance(n, ast.Call):
            continue
        callee = n.func
        tail = callee.attr if isinstance(callee, ast.Attribute) else (
            callee.id if isinstance(callee, ast.Name) else None
        )
        if tail == "Thread":
            rn = root_name(callee)
            if rn in ("threading", "Thread") or tail == rn:
                daemonic = daemon_assigned or any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and bool(kw.value.value)
                    for kw in n.keywords
                )
                facts.threads.append(ThreadFact(line=n.lineno, daemonic=daemonic))
        elif tail in _POOL_CTORS:
            rn = root_name(callee)
            if rn in ("concurrent", "futures") or tail == rn:
                facts.pools.append(
                    PoolFact(
                        line=n.lineno, managed=id(n) in managed_ctx_calls
                    )
                )
        elif tail == "dict" and facts.is_wire:
            for kw in n.keywords:
                if kw.arg == "kind":
                    facts.sends.append(_kind_value_site(kw.value, facts.consts))
        elif tail == "start" and isinstance(callee, ast.Attribute):
            facts.has_start = True
        elif tail == "join" and isinstance(callee, ast.Attribute):
            if not isinstance(callee.value, ast.Constant):  # skip ", ".join
                facts.has_join = True
        elif tail == "shutdown" and isinstance(callee, ast.Attribute):
            facts.has_pool_shutdown = True
        if isinstance(callee, ast.Attribute):
            _harvest_alloc_call(ctx, n, facts)
            _harvest_telemetry_call(n, facts)

    return facts
