"""graftlint: jax-free static analyzer for this repo's dispatch/transfer
discipline (per-file rules JG001-JG005), the whole-program host-plane
rules (JG006-JG009: lock order, wire-kind exhaustiveness, thread/resource
lifecycle, telemetry-catalog drift), and the baseline/suppression gate.

Run: ``python -m tools.graftlint scalerl_tpu``
Programmatic: :func:`gate` returns (all_findings, new_findings) — the
in-process entry the tier-1 ``tests/test_lint_gate.py`` uses.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from tools.graftlint.engine import (
    Finding,
    lint_paths,
    lint_source,
    lint_sources,
    load_baseline,
    partition_new,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def gate(
    paths: Sequence[str],
    baseline_path: Optional[str] = None,
    repo_root: Optional[str] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Lint ``paths`` and split findings against the baseline.

    Returns ``(all_findings, new_findings)``; a clean gate is
    ``new_findings == []``.  ``baseline_path=None`` uses the checked-in
    default; pass ``""`` to gate with no baseline at all.
    """
    if baseline_path is None:
        baseline_path = DEFAULT_BASELINE
    findings = lint_paths(paths, repo_root=repo_root)
    baseline: Dict[str, int] = {}
    if baseline_path and os.path.exists(baseline_path):
        baseline = load_baseline(baseline_path)
    _old, new = partition_new(findings, baseline)
    return findings, new


__all__ = [
    "Finding",
    "DEFAULT_BASELINE",
    "gate",
    "lint_paths",
    "lint_source",
    "lint_sources",
    "load_baseline",
    "partition_new",
    "write_baseline",
]
