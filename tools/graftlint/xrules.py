"""Phase-2 whole-program rules (JG006-JG009): the cross-file join.

Each rule is ``fn(program) -> Iterator[Finding]`` over a :class:`Program`
holding every module's :class:`~tools.graftlint.facts.ModuleFacts`.  The
engine runs these after the per-file rules, then applies the anchor file's
inline/file-wide suppressions exactly as for per-file findings.

Join semantics, per rule:

- **JG006 lock-order-inversion** — build a directed lock-acquisition graph:
  lexical edges (holding A, ``with B:``) union one-hop call edges (holding
  A, call ``self.x.m()`` where ``m`` resolves to exactly one method in the
  whole program that acquires lock set S -> edges A->s for s in S; an
  ambiguous method name contributes nothing).  Any simple cycle is a
  potential ABBA deadlock and is reported once, anchored at its first edge.
- **JG007 wire-kind-exhaustiveness** — union all send sites and all handle
  sites, resolving named constants through a program-wide table (a name
  bound to conflicting strings resolves to nothing).  Sent-but-unhandled
  and handled-but-never-sent kinds flag unless declared via
  ``# graftlint: wire-ignore=...`` in any wire module.  Runs only on
  *complete* programs (the whole ``scalerl_tpu`` tree) — linting one file
  in isolation must not report its peers' kinds as missing.
- **JG008 thread-resource-lifecycle** — per-module: non-daemon thread
  created in a HOT dir whose module starts threads but never joins any;
  a class that acquires allocator pages and never releases; an acquire
  inside ``try`` with no release on the exception path; a ``start_span``
  result discarded or never read (``record_span`` and spans that escape
  into stores/returns are fine).
- **JG009 telemetry-catalog-drift** — instruments and binds in code vs.
  the OBSERVABILITY.md "Instrument catalog" table, both directions.
  Wildcard rows (``chaos.<fault_kind>``) and star rows (``fleet.*``) cover
  whole families; only exact rows are checked for staleness, and bind
  rows are satisfied by a covering ``reg.bind`` root.  The doc->code
  direction also needs a complete program.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.graftlint.engine import Finding
from tools.graftlint.facts import ModuleFacts

CATALOG_RELPATH = "docs/OBSERVABILITY.md"
_CATALOG_HEADING = "instrument catalog"
_BACKTICK_RE = re.compile(r"`([^`]+)`")


# ---------------------------------------------------------------------------
# the OBSERVABILITY.md instrument-catalog table


@dataclass
class CatalogEntry:
    name: str  # exact name, or literal prefix for wildcard/star entries
    line: int
    kind_cell: str
    style: str  # "exact" | "wildcard" | "star"

    @property
    def is_bind(self) -> bool:
        return "bind" in self.kind_cell.lower()


@dataclass
class Catalog:
    entries: List[CatalogEntry] = field(default_factory=list)

    @property
    def exacts(self) -> List[CatalogEntry]:
        return [e for e in self.entries if e.style == "exact"]

    @property
    def family_prefixes(self) -> List[str]:
        return [e.name for e in self.entries if e.style != "exact"]

    def covers_exact(self, name: str) -> bool:
        for e in self.exacts:
            if name == e.name or name.startswith(e.name + "."):
                return True
        return any(p and name.startswith(p) for p in self.family_prefixes)

    def covers_prefix(self, prefix: str) -> bool:
        for e in self.exacts:
            if e.name.startswith(prefix) or prefix.startswith(e.name + "."):
                return True
        return any(
            p and (p.startswith(prefix) or prefix.startswith(p))
            for p in self.family_prefixes
        )

    def covers_bind(self, name: str) -> bool:
        if any(name == e.name for e in self.exacts):
            return True
        return self.covers_prefix(name + ".")


def parse_catalog(text: str) -> Catalog:
    """Extract instrument names from the ``### Instrument catalog`` table.

    Names live backticked in the first cell; a dotless follow-on token in
    the same cell inherits the previous token's dotted prefix (so
    ``| `server.total_results` / `duplicate_results` |`` yields both fully
    qualified names).  ``<placeholder>`` tokens become family prefixes, as
    do ``foo.*`` rows.  Non-backticked (italic, report-time) rows
    contribute nothing.
    """
    cat = Catalog()
    in_section = False
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if line.startswith("#"):
            in_section = _CATALOG_HEADING in line.lower()
            continue
        if not in_section or not line.startswith("|"):
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if not cells or set(cells[0]) <= {"-", ":", " "}:
            continue  # separator row
        first, kind_cell = cells[0], cells[1] if len(cells) > 1 else ""
        if first.lower() in ("name", "instrument"):
            continue  # header row
        last_prefix = ""
        for i, tok in enumerate(_BACKTICK_RE.findall(first)):
            tok = tok.strip()
            if not tok:
                continue
            if i > 0 and "." not in tok and last_prefix:
                tok = last_prefix + tok
            if "." in tok:
                last_prefix = tok.rsplit(".", 1)[0] + "."
            if "<" in tok:
                cat.entries.append(
                    CatalogEntry(tok.split("<", 1)[0], lineno, kind_cell, "wildcard")
                )
            elif tok.endswith("*"):
                cat.entries.append(
                    CatalogEntry(tok.rstrip("*"), lineno, kind_cell, "star")
                )
            else:
                cat.entries.append(CatalogEntry(tok, lineno, kind_cell, "exact"))
    return cat


# ---------------------------------------------------------------------------
# program: what a phase-2 rule sees


@dataclass
class Program:
    modules: List[ModuleFacts]
    complete: bool = False
    catalog: Optional[Catalog] = None
    catalog_relpath: str = CATALOG_RELPATH
    lines: Dict[str, List[str]] = field(default_factory=dict)

    def finding(
        self, relpath: str, line: int, rule: str, message: str, hint: str = ""
    ) -> Finding:
        text = self.lines.get(relpath, [])
        snippet = text[line - 1].strip() if 1 <= line <= len(text) else ""
        return Finding(
            file=relpath, line=line, rule=rule, message=message, hint=hint,
            snippet=snippet,
        )


# ---------------------------------------------------------------------------
# JG006


def _simple_cycles(
    edges: Dict[str, Dict[str, Tuple[str, int]]], cap: int = 25
) -> List[Tuple[str, ...]]:
    nodes = sorted(set(edges) | {b for outs in edges.values() for b in outs})
    out: List[Tuple[str, ...]] = []
    for start in nodes:
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        while stack and len(out) < cap:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, {})):
                if nxt == start:
                    out.append(tuple(path))
                elif nxt > start and nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return out


def xrule_jg006(prog: Program) -> Iterator[Finding]:
    """Cycles in the cross-module lock-acquisition graph (ABBA deadlock)."""
    edges: Dict[str, Dict[str, Tuple[str, int]]] = {}

    def add(a: str, b: str, site: Tuple[str, int]) -> None:
        if a != b:
            edges.setdefault(a, {}).setdefault(b, site)

    for m in prog.modules:
        for a, b, ln in m.lock_edges:
            add(a, b, (m.relpath, ln))

    method_locks: Dict[str, Set[Tuple[str, frozenset]]] = {}
    for m in prog.modules:
        for name, entries in m.method_locks.items():
            method_locks.setdefault(name, set()).update(entries)

    for m in prog.modules:
        for held, meth, ln in m.held_calls:
            candidates = method_locks.get(meth, set())
            if len(candidates) != 1:
                continue  # unknown or ambiguous method: no edge
            (_cls, locks), = candidates
            for b in locks:
                add(held, b, (m.relpath, ln))

    for cyc in _simple_cycles(edges):
        hops = []
        ring = list(cyc) + [cyc[0]]
        for a, b in zip(ring, ring[1:]):
            f, ln = edges[a][b]
            hops.append(f"{a} -> {b} at {f}:{ln}")
        anchor_file, anchor_line = edges[ring[0]][ring[1]]
        yield prog.finding(
            anchor_file,
            anchor_line,
            "JG006",
            "lock-order inversion: " + " -> ".join(ring)
            + " (" + "; ".join(hops) + ")",
            hint="pick one global acquisition order, or move the cross-object "
            "call outside the held section",
        )


# ---------------------------------------------------------------------------
# JG007


def xrule_jg007(prog: Program) -> Iterator[Finding]:
    """Every sent wire kind must be dispatched somewhere (and vice versa)."""
    if not prog.complete:
        return

    gconsts: Dict[str, str] = {}
    conflicted: Set[str] = set()
    for m in prog.modules:
        for name, value in m.consts.items():
            if name in gconsts and gconsts[name] != value:
                conflicted.add(name)
            else:
                gconsts.setdefault(name, value)

    def resolve(site) -> Optional[str]:
        if site.kind is not None:
            return site.kind
        if site.ref is not None and site.ref not in conflicted:
            return gconsts.get(site.ref)
        return None

    sent: Dict[str, Tuple[str, int]] = {}
    handled: Dict[str, Tuple[str, int]] = {}
    ignored: Set[str] = set()
    for m in prog.modules:
        ignored |= m.wire_ignored
        for s in m.sends:
            k = resolve(s)
            if k is not None:
                sent.setdefault(k, (m.relpath, s.line))
        for h in m.handles:
            k = resolve(h)
            if k is not None:
                handled.setdefault(k, (m.relpath, h.line))

    for kind in sorted(set(sent) - set(handled) - ignored):
        f, ln = sent[kind]
        yield prog.finding(
            f, ln, "JG007",
            f"frame kind '{kind}' is sent here but no recv pump dispatches "
            "on it anywhere in the program",
            hint="handle the kind on the receiving pump, or declare it with "
            f"'# graftlint: wire-ignore={kind}'",
        )
    for kind in sorted(set(handled) - set(sent) - ignored):
        f, ln = handled[kind]
        yield prog.finding(
            f, ln, "JG007",
            f"frame kind '{kind}' is dispatched on here but never sent "
            "anywhere in the program (dead kind)",
            hint="delete the dead dispatch arm, or declare it with "
            f"'# graftlint: wire-ignore={kind}'",
        )


# ---------------------------------------------------------------------------
# JG008


def xrule_jg008(prog: Program) -> Iterator[Finding]:
    """Thread, executor-pool, allocator-page, and span lifecycle hygiene."""
    for m in prog.modules:
        if m.is_hot and m.has_start and not m.has_join:
            for t in m.threads:
                if not t.daemonic:
                    yield prog.finding(
                        m.relpath, t.line, "JG008",
                        "non-daemon thread created in a hot dir and started "
                        "without any reachable join() in this module",
                        hint="pass daemon=True, or join the thread on the "
                        "shutdown path",
                    )
        if m.is_hot and not m.has_pool_shutdown:
            # the executor twin of the thread rule: shutdown() is the
            # pool's join(); a with-managed pool shuts down at scope exit
            for p in m.pools:
                if not p.managed:
                    yield prog.finding(
                        m.relpath, p.line, "JG008",
                        "executor pool created in a hot dir without any "
                        "reachable shutdown() in this module (and not "
                        "with-managed)",
                        hint="use the pool as a context manager, or call "
                        "shutdown(wait=...) on the teardown path",
                    )
        for owner in sorted(m.allocs):
            af = m.allocs[owner]
            if af.acquire_lines and af.releases == 0:
                yield prog.finding(
                    m.relpath, af.acquire_lines[0], "JG008",
                    f"{owner} acquires allocator pages (alloc/try_reserve/"
                    "share) but never releases any (free/release)",
                    hint="release or free the pages on every exit path",
                )
        for ln in m.alloc_leaks:
            yield prog.finding(
                m.relpath, ln, "JG008",
                "allocator pages acquired inside try, but no handler or "
                "finally releases them: the exception path leaks the pages",
                hint="release in a finally (or in every except) so the "
                "exception path returns the pages",
            )
        for ln, name in m.unended_spans:
            yield prog.finding(
                m.relpath, ln, "JG008",
                f"span '{name}' = start_span(...) is never read again: it "
                "is neither ended nor handed off, so the trace dangles",
                hint="call span.end(...), use the span as a context manager, "
                "or use tracing.record_span for retroactive spans",
            )


# ---------------------------------------------------------------------------
# JG009


def xrule_jg009(prog: Program) -> Iterator[Finding]:
    """Instruments in code vs. the OBSERVABILITY.md catalog, both ways."""
    cat = prog.catalog
    if cat is None:
        return

    code_exact: Set[str] = set()
    code_prefixes: Set[str] = set()
    bind_names: Set[str] = set()
    bind_prefixes: Set[str] = set()
    any_dynamic_bind = False

    for m in prog.modules:
        any_dynamic_bind = any_dynamic_bind or m.dynamic_bind
        for inst in m.instruments:
            if inst.name is not None:
                code_exact.add(inst.name)
                if not cat.covers_exact(inst.name):
                    yield prog.finding(
                        m.relpath, inst.line, "JG009",
                        f"{inst.api} '{inst.name}' is not in the "
                        "OBSERVABILITY.md instrument catalog",
                        hint="add a catalog row (name | kind | source) to "
                        "docs/OBSERVABILITY.md",
                    )
            elif inst.prefix:
                code_prefixes.add(inst.prefix)
                if not cat.covers_prefix(inst.prefix):
                    yield prog.finding(
                        m.relpath, inst.line, "JG009",
                        f"{inst.api} family '{inst.prefix}<...>' is not in "
                        "the OBSERVABILITY.md instrument catalog",
                        hint="add a wildcard catalog row like "
                        f"`{inst.prefix}<name>` to docs/OBSERVABILITY.md",
                    )
        for b in m.binds:
            if b.name is not None:
                bind_names.add(b.name)
                if not cat.covers_bind(b.name):
                    yield prog.finding(
                        m.relpath, b.line, "JG009",
                        f"bind '{b.name}' is not in the OBSERVABILITY.md "
                        "instrument catalog",
                        hint="add a catalog row for the bound scalar family",
                    )
            elif b.prefix:
                bind_prefixes.add(b.prefix)
                if not cat.covers_prefix(b.prefix):
                    yield prog.finding(
                        m.relpath, b.line, "JG009",
                        f"bind family '{b.prefix}<...>' is not in the "
                        "OBSERVABILITY.md instrument catalog",
                        hint="add a wildcard catalog row like "
                        f"`{b.prefix}<name>`",
                    )

    if not prog.complete:
        return

    for e in cat.exacts:
        name = e.name
        covered = name in code_exact or any(
            name.startswith(p) for p in code_prefixes
        )
        if not covered and e.is_bind:
            covered = (
                name in bind_names
                or any(name == b or name.startswith(b + ".") for b in bind_names)
                or any(name.startswith(p) for p in bind_prefixes)
                or any_dynamic_bind
            )
        if not covered:
            yield prog.finding(
                prog.catalog_relpath, e.line, "JG009",
                f"catalog row '{name}' has no matching instrument or bind "
                "in code (stale row)",
                hint="delete the stale row, or re-add the instrument",
            )


XRULES = [
    ("JG006", "lock-order-inversion", xrule_jg006),
    ("JG007", "wire-kind-exhaustiveness", xrule_jg007),
    ("JG008", "thread-resource-lifecycle", xrule_jg008),
    ("JG009", "telemetry-catalog-drift", xrule_jg009),
]
