"""graftlint engine: findings, suppressions, baseline, file walking.

jax-free on purpose — the linter runs anywhere (CI boxes without the TPU
tunnel, pre-commit hooks) in milliseconds, using only stdlib ``ast``.  The
rules themselves live in ``tools/graftlint/rules.py``; this module owns the
plumbing they share:

- :class:`Finding` — one diagnosis (``file:line``, rule id, message, fix
  hint) keyed for baselining by ``file::rule::<normalized source line>`` so
  entries survive unrelated line-number drift.
- inline suppressions — ``# graftlint: disable=JG001[,JG002]`` trailing on
  the offending line, ``# graftlint: disable-next-line=JG001`` on the line
  above it, or ``# graftlint: disable-file=JG001`` anywhere in the file.
- the checked-in baseline (``tools/graftlint/baseline.json``): pre-existing
  findings are explicit and counted; only *new* findings (a key appearing
  more often than the baseline records) fail the run.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9,\s]+)")
_SUPPRESS_NEXT_RE = re.compile(r"#\s*graftlint:\s*disable-next-line=([A-Z0-9,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*graftlint:\s*disable-file=([A-Z0-9,\s]+)")


@dataclass(frozen=True)
class Finding:
    """One diagnosis.  ``snippet`` is the stripped source line — part of the
    baseline key so baselined findings track the code, not the line number."""

    file: str  # repo-relative posix path
    line: int
    rule: str  # "JG001"
    message: str
    hint: str = ""
    snippet: str = ""

    @property
    def key(self) -> str:
        return f"{self.file}::{self.rule}::{self.snippet}"

    def render(self) -> str:
        out = f"{self.file}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class ModuleContext:
    """Shared per-file analysis state handed to every rule."""

    def __init__(self, relpath: str, source: str) -> None:
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self._parents: Dict[ast.AST, ast.AST] = {}
        # cache the full node list while building the parent map: every rule
        # iterates it via walk(), so the tree is traversed once per file
        # instead of once per rule
        self._nodes: List[ast.AST] = [self.tree]
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
                self._nodes.append(child)
        self._jit_index = None

    def walk(self) -> List[ast.AST]:
        """Every node in the tree (ast.walk order) — the shared-walk path."""
        return self._nodes

    def jit_index(self):
        """The module's jit-wrapper index, built once and shared by every
        rule that needs it (JG002-JG005 each used to rebuild it)."""
        if self._jit_index is None:
            from tools.graftlint.rules import _JitIndex

            self._jit_index = _JitIndex(self)
        return self._jit_index

    # -- tree navigation ------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_loop(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest For/While ancestor within the same function scope."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
            if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
                return anc
        return None

    def enclosing_statement(self, node: ast.AST) -> ast.AST:
        stmt = node
        for anc in self.ancestors(node):
            if isinstance(anc, ast.stmt):
                stmt = anc
                break
        return stmt

    def segment(self, node: ast.AST) -> str:
        try:
            return ast.get_source_segment(self.source, node) or ""
        except Exception:  # pragma: no cover - malformed position info
            return ""

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(
        self, node: ast.AST, rule: str, message: str, hint: str = ""
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            file=self.relpath,
            line=line,
            rule=rule,
            message=message,
            hint=hint,
            snippet=self.line_text(line),
        )


# ---------------------------------------------------------------------------
# small AST helpers rules share


def attr_path(node: ast.AST) -> Optional[str]:
    """Dotted path for Name/Attribute chains ("self.agent.learn"); None if
    the chain passes through calls/subscripts/etc."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def root_name(node: ast.AST) -> Optional[str]:
    """Leftmost identifier of an expression (descends calls/attrs/subscripts)."""
    cur = node
    while True:
        if isinstance(cur, ast.Call):
            cur = cur.func
        elif isinstance(cur, (ast.Attribute, ast.Subscript, ast.Starred)):
            cur = cur.value
        elif isinstance(cur, ast.Name):
            return cur.id
        else:
            return None


def assign_target_paths(stmt: ast.AST) -> List[str]:
    """Dotted paths of every assignment target in a statement (tuple
    targets flattened); empty for non-assignments."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    out: List[str] = []
    for t in targets:
        stack = [t]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.Tuple, ast.List)):
                stack.extend(cur.elts)
            elif isinstance(cur, ast.Starred):
                stack.append(cur.value)
            else:
                p = attr_path(cur)
                if p is not None:
                    out.append(p)
    return out


# ---------------------------------------------------------------------------
# suppressions


def _parse_rules(blob: str) -> Set[str]:
    return {r.strip() for r in blob.split(",") if r.strip()}


def collect_suppressions(lines: Sequence[str]) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """Map line -> suppressed rule ids, plus file-wide suppressed rules."""
    by_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            file_wide |= _parse_rules(m.group(1))
            continue
        m = _SUPPRESS_NEXT_RE.search(text)
        if m:
            by_line.setdefault(i + 1, set()).update(_parse_rules(m.group(1)))
            continue
        m = _SUPPRESS_RE.search(text)
        if m:
            by_line.setdefault(i, set()).update(_parse_rules(m.group(1)))
    return by_line, file_wide


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r") as f:
        data = json.load(f)
    entries = data.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    payload = {
        "version": 1,
        "generated_by": "python -m tools.graftlint --write-baseline",
        "entries": dict(sorted(counts.items())),
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


def partition_new(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (baselined, new).  A key occurring more often
    than the baseline records spills the excess into ``new`` — adding a
    second violation on an already-baselined line still fails the gate."""
    budget = dict(baseline)
    old: List[Finding] = []
    new: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.file, f.line)):
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            old.append(f)
        else:
            new.append(f)
    return old, new


# ---------------------------------------------------------------------------
# running


def lint_source(source: str, relpath: str) -> List[Finding]:
    """Lint one file's source with the per-file rules (JG001-JG005) only;
    the whole-program rules need the full tree — see :func:`lint_sources`."""
    from tools.graftlint.rules import RULES

    ctx = ModuleContext(relpath, source)
    by_line, file_wide = collect_suppressions(ctx.lines)
    findings: List[Finding] = []
    for rule_id, _title, fn in RULES:
        if rule_id in file_wide:
            continue
        for f in fn(ctx):
            if f.rule in by_line.get(f.line, ()):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings


#: relpath suffix that marks a lint run as covering the whole program: the
#: telemetry registry is the host plane's innermost module, so a run that
#: includes it is linting the full tree and the global joins (JG007 both
#: directions, JG009 doc->code) are sound.  Single-file runs skip them.
_COMPLETE_SENTINEL = "runtime/telemetry.py"


def lint_sources(
    items: Sequence[Tuple[str, str]],
    catalog_text: Optional[str] = None,
    complete: Optional[bool] = None,
    stats_out: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Two-phase lint over ``(relpath, source)`` pairs.

    Phase 1 runs the per-file rules and harvests each module's facts off
    the same parsed AST; phase 2 joins the facts across files and runs the
    whole-program rules (JG006-JG009).  Phase-2 findings honor the anchor
    file's inline/file-wide suppressions just like per-file findings.

    ``catalog_text`` is docs/OBSERVABILITY.md for JG009 (None skips it).
    ``complete`` marks the item set as the whole program; None auto-detects
    via :data:`_COMPLETE_SENTINEL`.  ``stats_out`` receives wall-clock
    seconds per stage when provided.
    """
    import time as _time

    from tools.graftlint.facts import harvest
    from tools.graftlint.rules import RULES
    from tools.graftlint.xrules import XRULES, Program, parse_catalog

    findings: List[Finding] = []
    all_facts = []
    lines_by_file: Dict[str, List[str]] = {}
    t_parse = t_rules = t_facts = 0.0

    for relpath, source in items:
        rel = relpath.replace(os.sep, "/")
        t0 = _time.perf_counter()
        try:
            ctx = ModuleContext(rel, source)
        except SyntaxError as e:
            t_parse += _time.perf_counter() - t0
            findings.append(
                Finding(
                    file=rel,
                    line=e.lineno or 1,
                    rule="JG000",
                    message=f"file does not parse: {e.msg}",
                    snippet="",
                )
            )
            continue
        t_parse += _time.perf_counter() - t0
        lines_by_file[rel] = ctx.lines
        by_line, file_wide = collect_suppressions(ctx.lines)

        t0 = _time.perf_counter()
        for rule_id, _title, fn in RULES:
            if rule_id in file_wide:
                continue
            for f in fn(ctx):
                if f.rule in by_line.get(f.line, ()):
                    continue
                findings.append(f)
        t_rules += _time.perf_counter() - t0

        t0 = _time.perf_counter()
        all_facts.append(harvest(ctx, by_line, file_wide))
        t_facts += _time.perf_counter() - t0

    t0 = _time.perf_counter()
    if complete is None:
        complete = any(m.relpath.endswith(_COMPLETE_SENTINEL) for m in all_facts)
    catalog = parse_catalog(catalog_text) if catalog_text is not None else None
    prog = Program(
        modules=all_facts,
        complete=complete,
        catalog=catalog,
        lines=lines_by_file,
    )
    if catalog_text is not None:
        prog.lines[prog.catalog_relpath] = catalog_text.splitlines()
    suppress = {m.relpath: (m.suppress_lines, m.suppress_file) for m in all_facts}
    for rule_id, _title, fn in XRULES:
        for f in fn(prog):
            by_line, file_wide = suppress.get(f.file, ({}, set()))
            if f.rule in file_wide or f.rule in by_line.get(f.line, ()):
                continue
            findings.append(f)
    if stats_out is not None:
        stats_out["join"] = _time.perf_counter() - t0
        stats_out["parse"] = t_parse
        stats_out["rules"] = t_rules
        stats_out["facts"] = t_facts
        stats_out["files"] = float(len(items))

    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        out.append(os.path.join(dirpath, name))
    return sorted(set(out))


def lint_paths(
    paths: Sequence[str],
    repo_root: Optional[str] = None,
    stats_out: Optional[Dict[str, float]] = None,
) -> List[Finding]:
    """Two-phase lint of every .py under ``paths``; files that fail to
    parse yield a single parse-error finding instead of crashing the run.
    Picks up docs/OBSERVABILITY.md from ``repo_root`` for JG009 when it
    exists."""
    repo_root = repo_root or os.getcwd()
    items: List[Tuple[str, str]] = []
    for path in iter_python_files(paths):
        rel = os.path.relpath(os.path.abspath(path), repo_root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            items.append((rel, f.read()))
    catalog_text: Optional[str] = None
    catalog_path = os.path.join(repo_root, "docs", "OBSERVABILITY.md")
    if os.path.exists(catalog_path):
        with open(catalog_path, "r", encoding="utf-8") as f:
            catalog_text = f.read()
    return lint_sources(items, catalog_text=catalog_text, stats_out=stats_out)
