"""CLI: ``python -m tools.graftlint scalerl_tpu [paths...]``.

Exit 0 when every finding is baselined/suppressed; exit 1 when new
findings exist (the CI gate); exit 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.graftlint import DEFAULT_BASELINE, write_baseline
from tools.graftlint.engine import lint_paths, load_baseline, partition_new
from tools.graftlint.rules import RULES
from tools.graftlint.xrules import XRULES


def _findings_payload(findings, new_keys):
    return [
        {
            "file": f.file,
            "line": f.line,
            "rule": f.rule,
            "message": f.message,
            "hint": f.hint,
            "snippet": f.snippet,
            "new": id(f) in new_keys,
        }
        for f in findings
    ]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="static analyzer: per-file JAX dispatch/transfer rules "
        "(JG001-JG005) plus whole-program host-plane rules (JG006-JG009)",
    )
    parser.add_argument("paths", nargs="*", default=["scalerl_tpu"],
                        help="files/packages to lint (default: scalerl_tpu)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (default: tools/graftlint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, ignoring the baseline")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept all current findings into the baseline")
    parser.add_argument("--show-baselined", action="store_true",
                        help="also print findings the baseline absorbs")
    parser.add_argument("--format", choices=("text", "json"), default="text",
                        help="finding output format (default: text)")
    parser.add_argument("--json-out", metavar="PATH", default=None,
                        help="also write the JSON findings payload to PATH "
                        "(the CI artifact), independent of --format")
    parser.add_argument("--stats", action="store_true",
                        help="print a per-stage wall-clock timing line")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id, title, fn in list(RULES) + list(XRULES):
            doc = (fn.__doc__ or "").strip().splitlines()
            print(f"{rule_id}  {title}" + (f" — {doc[0]}" if doc else ""))
        return 0

    paths = args.paths or ["scalerl_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"graftlint: no such path: {', '.join(missing)}", file=sys.stderr)
        return 2

    stats = {} if args.stats else None
    findings = lint_paths(paths, stats_out=stats)
    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(
            f"graftlint: wrote {len(findings)} finding(s) to {args.baseline}"
        )
        return 0

    baseline = {}
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = load_baseline(args.baseline)
    old, new = partition_new(findings, baseline)

    n_files = len({f.file for f in findings})
    shown = findings if args.no_baseline else new
    payload = {
        "findings": _findings_payload(findings, {id(f) for f in new}),
        "summary": {
            "total": len(findings),
            "files_with_findings": n_files,
            "baselined": len(old),
            "new": len(new),
        },
    }
    if stats is not None:
        payload["stats"] = stats
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(payload, fh, indent=2)
            fh.write("\n")

    if args.format == "json":
        print(json.dumps(payload, indent=2))
    else:
        if args.show_baselined and not args.no_baseline:
            for f in old:
                print(f"[baselined] {f.render()}")
        for f in shown:
            print(f.render())
        print(
            f"graftlint: {len(findings)} finding(s) across {n_files} file(s): "
            f"{len(old)} baselined, {len(new)} new"
        )
        if stats is not None:
            print(
                "graftlint: stats: {files:.0f} files, parse {parse:.3f}s, "
                "per-file rules {rules:.3f}s, fact harvest {facts:.3f}s, "
                "cross-file join {join:.3f}s".format(**stats)
            )

    if args.no_baseline:
        return 1 if findings else 0
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
