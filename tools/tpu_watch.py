"""TPU tunnel watcher (round 5).

The axon TPU tunnel is intermittent (rounds 1-4 saw minutes of uptime
total; round 5 landed the first witnessed bench in one such window). This
watcher probes the backend once a minute and writes every attempt -
timestamp, outcome, latency - to the committed probe log
``TPU_PROBELOG.md`` so the round artifact proves tunnel state rather than
asserts it.

On contact (re-armed up to 3 times, 30 min apart) it runs, in order:
  1. ``bench.py --fast`` (micro-witness banked within ~60 s),
  2. ``bench.py`` (fused-loop fps + MFU; appends to ``BENCH_TPU.md``),
  2b. ``bench.py --mesh dp=N`` when the tunnel exposes >1 chip,
  2c. ``bench.py --mode sharded`` (dp×mp pjit transformer train step:
      MFU + params-per-chip, perf-gated like-for-like per mesh shape),
  2d. ``bench.py --mode serving`` (centralized inference plane: act
      requests/sec + latency SLO quantiles + batch occupancy),
  3. ``bench.py --learn`` (train-step-only MFU at the north-star shape),
  4. ``pytest tests_tpu`` (compiled Pallas kernels + shard_map legality),
  5. ``examples/profile_fused_loop.py`` (idle fraction),
  6. the ``impala_breakout_84`` wall-clock-to-score curve,
then commits the artifacts immediately.

Run: ``nohup python tools/tpu_watch.py >/tmp/tpu_watch_r5.out 2>&1 &``
"""

import json
import os
import re
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBELOG = os.path.join(REPO, "TPU_PROBELOG.md")
PAYLOG = "/tmp/tpu_autobench_r5.log"
# machine-readable twin of the lint step's log output: the full findings
# payload ({findings, summary, stats}) lands here on every run, pass or
# fail, so a red lint step can be triaged without re-running the linter
LINT_JSON = "/tmp/tpu_autobench_r5_lint.json"
TELEM_ROOT = "/tmp/tpu_watch_telemetry"

# registry counters whose nonzero final value flags a step as suspect even
# when its exit code was 0: the integrity layer detected (and absorbed)
# corruption, or the numerical guard skipped updates — worth a human look
INTEGRITY_FLAT_KEYS = (
    "hub.protocol_errors",
    "ring.torn_reads",
    "server.duplicate_results",
    "train.skipped_steps",
    "train.nonfinite_grads",
    "queue.actor_errors",
)

PROBE = (
    "import jax; print('backend:', jax.default_backend());"
    " print('kind:', jax.devices()[0].device_kind);"
    " print('n:', jax.device_count())"
)


def log_probe(line: str) -> None:
    with open(PROBELOG, "a", buffering=1) as f:
        f.write(line + "\n")


def ensure_header() -> None:
    if not os.path.exists(PROBELOG) or os.path.getsize(PROBELOG) == 0:
        with open(PROBELOG, "w") as f:
            f.write(
                "# TPU tunnel probe log\n\n"
                "One line per probe attempt by `tools/tpu_watch.py`: UTC time, "
                "outcome, latency. A `backend: tpu` line means contact; the "
                "watcher then runs the full bench payload and commits. "
                "Timeout lines are the committed evidence that the axon "
                "tunnel was down during this round (VERDICT r3 item #1).\n\n"
                "```\n"
            )


def _watchdog_dump_marker(bl, start_offset: int) -> str:
    """Scan the step's log segment for supervision-layer stall evidence.

    Returns ``"+stall-dump"`` when the segment contains a StallWatchdog
    report (``runtime/supervisor.py``) or a pytest/faulthandler timeout
    dump — the per-step summary then records that the hang was *diagnosed*
    (stacks + queue depths are in the payload log), not just killed.
    """
    try:
        bl.flush()
        with open(bl.name, "r", errors="replace") as f:
            f.seek(start_offset)
            segment = f.read()
        if "StallWatchdog" in segment or "Timeout (" in segment:
            return "+stall-dump"
    except Exception:  # noqa: BLE001 - diagnosis must not fail the watcher
        pass
    return ""


def _flatten_snapshot(tree, prefix="") -> dict:
    flat = {}
    for k, v in (tree or {}).items():
        name = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
        if isinstance(v, dict):
            flat.update(_flatten_snapshot(v, name))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            flat[name] = float(v)
    return flat


def _telemetry_marker(telem_dir: str, bl) -> str:
    """Attach the step's final telemetry snapshot to the step summary.

    Every payload step runs with SCALERL_TELEMETRY_DIR pointed at its own
    dir; the runtime's atexit hook (runtime/telemetry.py) writes
    ``final_snapshot.json`` there.  The flat counter view is appended to
    the payload log, and the returned marker is ``+telem`` — plus
    ``!integrity(<keys>)`` when any protocol_errors/torn_reads/nonfinite
    counter ended nonzero (the step *absorbed* corruption; the summary
    must say so even on rc=0).
    """
    path = os.path.join(telem_dir, "final_snapshot.json")
    try:
        if not os.path.exists(path):
            return ""
        with open(path) as f:
            payload = json.load(f)
        flat = _flatten_snapshot(payload.get("snapshot") or {})
        bl.write(
            "[watcher] final telemetry snapshot "
            f"({len(flat)} series): "
            + json.dumps({k: flat[k] for k in sorted(flat)[:80]})
            + "\n"
        )
        bad = [
            k.rsplit(".", 1)[0]
            for k in flat
            for key in INTEGRITY_FLAT_KEYS
            if (k == key or k.startswith(key + ".")) and flat[k] > 0
        ]
        if bad:
            return "+telem!integrity(" + ",".join(sorted(set(bad))[:4]) + ")"
        return "+telem"
    except Exception as e:  # noqa: BLE001 - diagnosis must not fail the watcher
        bl.write(f"[watcher] telemetry attach failed: {e}\n")
        return ""


def _elastic_marker(bl, start_offset: int, flap_per_min: float = 10.0) -> str:
    """Gate the elastic-soak step on its JSON verdict line.

    ``tools/elastic_soak.py`` prints one ``{"metric": "elastic_soak", ...}``
    line: lost episodes, consumer-visible duplicates, and the autoscaler's
    decisions/min.  Lost/duplicated episodes or a flapping fleet
    (> ``flap_per_min`` scale actions/min) mark the outcome
    ``!elastic(...)`` — the step absorbed a preemption wave *wrong* even if
    its exit code said otherwise.  A clean wave marks ``+elastic``.
    """
    try:
        bl.flush()
        with open(bl.name, "r", errors="replace") as f:
            f.seek(start_offset)
            segment = f.read()
        verdict = None
        for line in segment.splitlines():
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("metric") == "elastic_soak":
                verdict = obj
        if not verdict:
            return ""
        lost = int(verdict.get("lost", 0))
        dups = int(verdict.get("duplicates", 0))
        flap = float(verdict.get("decisions_per_min", 0.0))
        bad = []
        if lost > 0:
            bad.append(f"lost={lost}")
        if dups > 0:
            bad.append(f"dup={dups}")
        if flap > flap_per_min:
            bad.append(f"flap={flap}/min")
        if bad:
            bl.write(f"[watcher] ELASTIC GATE: {','.join(bad)} — flagging\n")
            return "!elastic(" + ",".join(bad) + ")"
        return "+elastic"
    except Exception as e:  # noqa: BLE001 - diagnosis must not fail the watcher
        bl.write(f"[watcher] elastic gate failed: {e}\n")
        return ""


def _disagg_marker(bl, start_offset: int) -> str:
    """Gate the disagg-soak step on its JSON verdict line.

    ``tools/disagg_soak.py`` prints one ``{"metric": "disagg_soak", ...}``
    line: lost/duplicated sequences, payload mismatches, and the
    autoscaler's backfill count after a seeded mid-decode preemption wave.
    Any loss, consumer-visible duplicate, corrupt payload, or missing
    backfill marks the outcome ``!disagg(...)``; a clean wave marks
    ``+disagg``.
    """
    try:
        bl.flush()
        with open(bl.name, "r", errors="replace") as f:
            f.seek(start_offset)
            segment = f.read()
        verdict = None
        for line in segment.splitlines():
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("metric") == "disagg_soak":
                verdict = obj
        if not verdict:
            return ""
        bad = []
        if int(verdict.get("lost", 0)) > 0:
            bad.append(f"lost={verdict['lost']}")
        if int(verdict.get("duplicates", 0)) > 0:
            bad.append(f"dup={verdict['duplicates']}")
        if int(verdict.get("payload_mismatches", 0)) > 0:
            bad.append(f"corrupt={verdict['payload_mismatches']}")
        if int(verdict.get("scale_ups", 0)) < 1:
            bad.append("no-backfill")
        if bad:
            bl.write(f"[watcher] DISAGG GATE: {','.join(bad)} — flagging\n")
            return "!disagg(" + ",".join(bad) + ")"
        return "+disagg"
    except Exception as e:  # noqa: BLE001 - diagnosis must not fail the watcher
        bl.write(f"[watcher] disagg gate failed: {e}\n")
        return ""


def _preempt_marker(bl, start_offset: int) -> str:
    """Gate the preempt-soak step on its JSON verdict line.

    ``tools/preempt_soak.py`` prints one ``{"metric": "preempt_soak", ...}``
    line after SIGTERMing the learner mid-decode and restarting it from the
    durable ledger.  The gate is EXACT accounting across the restart: any
    lost sequence, consumer-visible duplicate, corrupt payload, orphaned
    lease, or a learner that came back without bumping its epoch marks the
    outcome ``!ledger(...)``; a cleanly-closed ledger marks ``+preempt``.
    """
    try:
        bl.flush()
        with open(bl.name, "r", errors="replace") as f:
            f.seek(start_offset)
            segment = f.read()
        verdict = None
        for line in segment.splitlines():
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("metric") == "preempt_soak":
                verdict = obj
        if not verdict:
            return ""
        bad = []
        if int(verdict.get("lost", 0)) > 0:
            bad.append(f"lost={verdict['lost']}")
        if int(verdict.get("duplicates", 0)) > 0:
            bad.append(f"dup={verdict['duplicates']}")
        if int(verdict.get("payload_mismatches", 0)) > 0:
            bad.append(f"corrupt={verdict['payload_mismatches']}")
        if int(verdict.get("orphaned_leases", 0)) > 0:
            bad.append(f"orphans={verdict['orphaned_leases']}")
        if not verdict.get("epoch_bumped", False):
            bad.append("no-epoch-bump")
        if int(verdict.get("resume_events", 0)) < 1:
            bad.append("no-resume")
        if bad:
            bl.write(f"[watcher] PREEMPT GATE: {','.join(bad)} — flagging\n")
            return "!ledger(" + ",".join(bad) + ")"
        return "+preempt"
    except Exception as e:  # noqa: BLE001 - diagnosis must not fail the watcher
        bl.write(f"[watcher] preempt gate failed: {e}\n")
        return ""


def _trace_marker(bl, start_offset: int) -> str:
    """Gate the trace-soak step on the trace_report verdict line.

    The trace soak is the disagg soak re-run with ``SCALERL_TRACE_SAMPLE=
    1.0`` + per-host span export; ``tools/trace_report.py`` merges the
    span files and prints one ``{"metric": "trace_report", ...}`` line.
    Completeness is the gate: every soaked sequence must yield a single
    root-to-learn-step trace — incomplete lifecycles or orphan spans
    (a span whose parent never made it into the merge) mark the outcome
    ``!trace(...)``; a fully-stitched run marks ``+trace``.
    """
    try:
        bl.flush()
        with open(bl.name, "r", errors="replace") as f:
            f.seek(start_offset)
            segment = f.read()
        verdict = None
        for line in segment.splitlines():
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("metric") == "trace_report":
                verdict = obj
        if not verdict:
            return ""
        bad = []
        if int(verdict.get("sequence_traces", 0)) < 1:
            bad.append("no-traces")
        if int(verdict.get("incomplete", 0)) > 0:
            bad.append(f"incomplete={verdict['incomplete']}")
        if int(verdict.get("orphan_spans", 0)) > 0:
            bad.append(f"orphans={verdict['orphan_spans']}")
        if bad:
            bl.write(f"[watcher] TRACE GATE: {','.join(bad)} — flagging\n")
            return "!trace(" + ",".join(bad) + ")"
        return "+trace"
    except Exception as e:  # noqa: BLE001 - diagnosis must not fail the watcher
        bl.write(f"[watcher] trace gate failed: {e}\n")
        return ""


def _traffic_marker(bl, start_offset: int) -> str:
    """Gate the traffic-replay step on the traffic_replay verdict line.

    ``tools/traffic_replay.py`` drives the router with diurnal open-loop
    socket traffic while the streaming tier attribution decomposes every
    sampled request; the verdict carries four acceptance facts and this
    marker gates on all of them: exact router accounting
    (admitted == answered + shed + orphaned), attribution completeness
    (every sampled root decomposed, zero orphaned traces), the digest's
    p99 within its relative-error bound of the exact percentile, and a
    non-empty ``bottleneck_tier``.  Failures mark
    ``!traffic(orphans=N,unattributed=X,...)``; a clean soak marks
    ``+traffic(<bottleneck_tier>)``.
    """
    try:
        bl.flush()
        with open(bl.name, "r", errors="replace") as f:
            f.seek(start_offset)
            segment = f.read()
        verdict = None
        for line in segment.splitlines():
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("metric") == "traffic_replay":
                verdict = obj
        if not verdict:
            return ""
        attr = verdict.get("attribution") or {}
        digest = verdict.get("digest_check") or {}
        bad = []
        if not verdict.get("accounting_balanced"):
            bad.append("unbalanced")
        if int(attr.get("orphans", 0)) > 0:
            bad.append(f"orphans={attr['orphans']}")
        unattributed = int(attr.get("sampled", 0)) - int(
            attr.get("decomposed", 0)
        )
        if unattributed != 0:
            bad.append(f"unattributed={unattributed}")
        if not digest.get("ok", False):
            bad.append(f"digest_err={digest.get('rel_err')}")
        if not verdict.get("bottleneck_tier"):
            bad.append("no-bottleneck")
        if bad:
            bl.write(f"[watcher] TRAFFIC GATE: {','.join(bad)} — flagging\n")
            return "!traffic(" + ",".join(bad) + ")"
        return f"+traffic({verdict['bottleneck_tier']})"
    except Exception as e:  # noqa: BLE001 - diagnosis must not fail the watcher
        bl.write(f"[watcher] traffic gate failed: {e}\n")
        return ""


def perf_gate_verdict(
    new_value: float, prior_values, threshold: float = 0.2
):
    """The perf-regression gate: fail on a >``threshold`` drop vs history.

    ``prior_values``: fps/chip numbers from the committed ``BENCH_r0N.json``
    history (zeros/missing rounds already filtered).  Returns
    ``(ok, median)`` — ``ok`` is True when there is no usable history or
    the new value is within ``threshold`` of the median.  A slowdown fails
    the payload step the same way a lint finding does (ISSUE 6 satellite).
    """
    vals = sorted(v for v in prior_values if v and v > 0)
    if not vals:
        return True, None
    median = vals[len(vals) // 2]
    return new_value >= (1.0 - threshold) * median, median


def _bench_history_values(
    metric: str, mode=None, mesh=None, group=None, field: str = "value"
):
    """fps values from the committed bench history, LIKE-FOR-LIKE: only
    rows with the same metric AND the same ``mode`` (anakin/sharded vs
    default) AND the same ``mesh`` shape AND the same ``group`` shape
    (BENCH_GENRL_GROUP fan-out; absent = ungrouped) gate each other — a
    dp=8 number must never fail a dp=4,mp=2 run, and a grouped n=8 decode
    rate must never gate the ungrouped workload (prefix sharing changes
    the prefill mix by design; the artifact schema records all three so
    the comparison stays honest)."""
    sys.path.insert(0, REPO)
    try:
        from bench import load_bench_history
    finally:
        sys.path.remove(REPO)
    return [
        float(h.get(field) or 0.0)
        for h in load_bench_history(REPO)
        if h.get("metric") == metric
        and h.get("mode") == mode
        and h.get("mesh") == mesh
        and h.get("group") == group
    ]


# sub-metrics gated off artifact FIELDS (the bench orchestrator's
# one-json-line contract keeps them from being their own metric lines):
# per headline metric, the extra fields whose like-for-like history must
# not regress >20% either.  token_ppo_learn_tokens_per_sec_per_chip is
# the ISSUE 15 packed-learner rate (real, non-pad tokens/s);
# genrl_spec_accepted_tokens_per_sec is the ISSUE 16 speculative-decode
# rate (accepted tokens over whole-round wall clock, spec-on side of the
# same-shape A/B).
GATED_FIELDS = {
    "genrl_decode_tokens_per_sec_per_chip": (
        "token_ppo_learn_tokens_per_sec_per_chip",
        "genrl_spec_accepted_tokens_per_sec",
    ),
}


def _perf_gate_marker(bl, start_offset: int) -> str:
    """Gate a bench step's result against the BENCH_r0N history.

    Scans the step's log segment for its JSON result line; when the
    fps/chip metric dropped >20% below the median of the committed prior
    rounds, returns a ``+perf-drop(...)`` marker — ``run_payload`` turns
    that into a FAILED outcome (excluded from the witness quorum), so a
    perf regression blocks the payload step exactly like a lint finding.
    """
    try:
        bl.flush()
        with open(bl.name, "r", errors="replace") as f:
            f.seek(start_offset)
            segment = f.read()
        gated_metrics = {
            "impala_atari_env_frames_per_sec_per_chip",
            "sharded_train_step_frames_per_sec",
            "serving_requests_per_sec",
            "traffic_goodput_rps",
            "genrl_decode_tokens_per_sec_per_chip",
            "disagg_sequences_per_sec",
        }
        result = None
        for line in segment.splitlines():
            line = line.strip()
            if not (line.startswith("{") and line.endswith("}")):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if obj.get("metric") in gated_metrics:
                result = obj
        if not result or not result.get("value"):
            return ""
        markers = []
        checks = [("value", float(result["value"]))]
        for field in GATED_FIELDS.get(result["metric"], ()):
            if result.get(field):
                checks.append((field, float(result[field])))
        for field, value in checks:
            ok, median = perf_gate_verdict(
                value,
                # like-for-like: same metric, same mode (anakin/sharded/
                # default), same mesh shape, same gated field — cross-shape
                # comparisons never gate
                _bench_history_values(
                    result["metric"], result.get("mode"),
                    result.get("mesh"), result.get("group"), field=field,
                ),
            )
            if ok or median is None:
                continue
            label = "" if field == "value" else f"{field}:"
            bl.write(
                f"[watcher] PERF GATE: {label}{value} is >20% below "
                f"the committed like-for-like history median {median} — "
                "failing the step\n"
            )
            markers.append(f"+perf-drop({label}{value}<0.8x{median})")
        return "".join(markers)
    except Exception as e:  # noqa: BLE001 - diagnosis must not fail the watcher
        bl.write(f"[watcher] perf gate failed: {e}\n")
        return ""


def _run_step(cmd, env, bl, timeout_s: float) -> str:
    """Run one payload step; on timeout SIGTERM first (bench.py's handler
    prints its banked JSON and reaps its JAX children — a straight SIGKILL
    would orphan a TPU-holding grandchild that then starves the next step).

    Returns the step outcome: ``"ok"`` (exit 0), ``"rc=N"``, or
    ``"timeout"`` — plus a ``+stall-dump`` suffix when the step's log
    carries a watchdog/faulthandler stack dump (the supervision layer
    diagnosed the stall) — the per-step evidence the witness commit
    summarizes.
    """
    start_offset = bl.tell()
    p = subprocess.Popen(cmd, env=env, stdout=bl, stderr=bl, cwd=REPO)
    try:
        p.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        p.terminate()
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        bl.write(f"[watcher] step timed out after {timeout_s:.0f}s\n")
        return "timeout" + _watchdog_dump_marker(bl, start_offset)
    if p.returncode == 0:
        return "ok"
    return f"rc={p.returncode}" + _watchdog_dump_marker(bl, start_offset)


def run_payload(n_devices: int = 1) -> None:
    env = dict(os.environ, BENCH_BUDGET_S="900")
    # fast step gets its own small budget: its wall-clock cap must exceed
    # its bench budget (+ the give-up grace) or a flap gets it killed
    # mid-probe instead of falling back cleanly
    fast_env = dict(os.environ, BENCH_BUDGET_S="120")
    steps = [
        # rule-registry smoke before the real lint: --list-rules imports
        # the whole rule table (JG001-JG009, per-file and whole-program),
        # so a broken rule module fails loudly here instead of silently
        # shrinking the set of rules the gating step below actually runs
        ("lint-rules",
         [sys.executable, "-m", "tools.graftlint", "--list-rules"],
         60, env),
        # lint second: jax-free and ~instant, so a dispatch-discipline
        # regression (graftlint JG001-JG005) or a cross-file finding
        # (JG006-JG009, docs/LINTING.md) is recorded in the step summary
        # even if the tunnel drops before any bench.  Any finding fails
        # the step (the baseline is empty by contract); the JSON artifact
        # is written alongside the step log either way
        ("lint",
         [sys.executable, "-m", "tools.graftlint", "scalerl_tpu",
          "--stats", "--json-out", LINT_JSON],
         120, env),
        # chaos soak second: seeded fault injection over the data plane
        # (frame corruption, torn shm slots, partial checkpoints, NaN
        # bursts — tests/test_chaos.py -m chaos).  CPU-pinned and bounded,
        # so like lint it records integrity regressions even when the
        # tunnel flaps — and like lint it doesn't count toward the
        # witness-commit quorum (no TPU was exercised)
        ("chaos-soak",
         [sys.executable, "-m", "pytest", "tests/test_chaos.py", "-q",
          "-m", "chaos"],
         900, dict(env, JAX_PLATFORMS="cpu")),
        # elastic soak third: a seeded mass_kill preemption wave against a
        # live pipe fleet with the autoscaler backfilling
        # (tools/elastic_soak.py).  jax-free and bounded; like lint and the
        # chaos soak it records elasticity regressions even tunnel-down and
        # does not count toward the witness quorum.  The verdict JSON is
        # gated by _elastic_marker: lost/duplicated episodes or a flapping
        # fleet mark the outcome !elastic(...)
        ("elastic-soak", [sys.executable, "tools/elastic_soak.py"],
         600, dict(env, JAX_PLATFORMS="cpu")),
        # disagg soak: a jax-free pipe fleet of 2 generation hosts + the
        # sequence learner under a seeded mid-decode mass_kill wave
        # (tools/disagg_soak.py).  Like the elastic soak it is bounded,
        # runs tunnel-down, does not count toward the witness quorum, and
        # its JSON verdict is gated by _disagg_marker: lost/duplicated/
        # corrupt sequences or a missing backfill mark !disagg(...)
        ("disagg-soak", [sys.executable, "tools/disagg_soak.py"],
         600, dict(env, JAX_PLATFORMS="cpu")),
        # preempt soak: SIGTERM the LEARNER mid-decode (the guard's seeded
        # preempt draw), restart it from the durable ledger, and close the
        # accounting exactly (tools/preempt_soak.py).  jax-free thread
        # fleet, bounded, runs tunnel-down, non-quorum like the other
        # soaks; _preempt_marker gates on the ledger identity — lost/
        # duplicate/orphaned work or a missing epoch bump marks !ledger(...)
        ("preempt-soak", [sys.executable, "tools/preempt_soak.py"],
         600, dict(env, JAX_PLATFORMS="cpu")),
        # trace soak: the disagg soak with SCALERL_TRACE_SAMPLE=1.0 and
        # per-host span export — tools/trace_report.py merges the files
        # into Chrome trace_event JSON + a critical-path breakdown, and
        # _trace_marker gates on completeness: every soaked sequence must
        # yield ONE root-to-learn-step trace with zero orphan spans.
        # jax-free, bounded, runs tunnel-down, non-quorum like the other
        # soaks
        ("trace-soak",
         [sys.executable, "tools/disagg_soak.py", "--trace-dir",
          "/tmp/tpu_watch_trace", "--leases", "48"],
         600, dict(env, JAX_PLATFORMS="cpu")),
        # traffic replay soak: diurnal x Poisson open-loop arrivals (plus
        # burst overlays and one seeded replica kill) through the router's
        # REAL listening socket from 1k RemotePolicyClients, with the
        # streaming tier attribution decomposing every request online.
        # _traffic_marker gates on exact accounting, attribution
        # completeness (zero orphans, every sampled root decomposed), the
        # digest error bound, and a named bottleneck tier.  jax-free
        # scripted replicas, bounded, runs tunnel-down, non-quorum
        ("traffic-replay",
         [sys.executable, "tools/traffic_replay.py", "--clients", "1000",
          "--duration-s", "20", "--base-rps", "300",
          "--kill-replica-at", "8", "--rollout-at", "14"],
         600, dict(env, JAX_PLATFORMS="cpu")),
        # genrl soak: the hermetic token-PPO e2e (generate -> score
        # -> learn on the synthetic recall task, scan/unroll decode parity,
        # reward-improvement threshold).  CPU-pinned and ~1 min (measured
        # well under the step budget — the ISSUE 10 admission condition),
        # so like the other soaks it records sequence-RL regressions even
        # tunnel-down and does not count toward the witness quorum
        ("genrl-soak",
         [sys.executable, "-m", "pytest", "tests/test_genrl.py", "-q",
          "-k", "e2e"],
         600, dict(env, JAX_PLATFORMS="cpu")),
        # --fast first: banks a BENCH_TPU.md artifact within ~60 s of
        # contact, before the long steps gamble on the tunnel staying up
        ("bench-fast", [sys.executable, "bench.py", "--fast"], 450, fast_env),
        # bench-fast above already banked the micro row: later bench
        # steps skip the micro phase and spend their post-ack window on
        # their own measurement (BENCH_SKIP_MICRO; process-local dedup)
        ("bench", [sys.executable, "bench.py"], 1500,
         dict(env, BENCH_SKIP_MICRO="1")),
        # batch sweep: the 98k fps witness used B=512; if the tunnel holds,
        # try more lanes (banked to BENCH_TPU.md like any TPU success)
        ("bench-B1024", [sys.executable, "bench.py"], 1500,
         dict(env, BENCH_B="1024", BENCH_SKIP_MICRO="1")),
        # Anakin whole-run fusion: one dispatch covers a super-chunk of
        # rollout+learn chunks with the transfer guard armed; reports its
        # own MFU from the super-chunk executable's cost analysis
        ("bench-anakin", [sys.executable, "bench.py", "--mode", "anakin"],
         1500, dict(env, BENCH_SKIP_MICRO="1")),
        # dp×mp sharded learner: the pjit transformer train step with
        # heads/mlp/vocab over mp — reports MFU + params-per-chip and is
        # perf-gated like-for-like against history at the same mesh shape
        ("bench-sharded", [sys.executable, "bench.py", "--mode", "sharded"],
         1500, dict(env, BENCH_SKIP_MICRO="1")),
        # centralized inference plane: act requests/sec through the
        # InferenceServer's dynamic batcher + the latency SLO quantiles
        # (p50/p95/p99) and batch occupancy; perf-gated like-for-like
        # against serving-mode history exactly like the other bench steps
        ("bench-serving", [sys.executable, "bench.py", "--mode", "serving"],
         1500, dict(env, BENCH_SKIP_MICRO="1")),
        # serving front door: open-loop (Poisson + bursty) traffic through
        # the multi-replica router — goodput under the latency SLO
        # (traffic_goodput_rps), perf-gated like-for-like against
        # traffic-mode history; the artifact also carries the exact-
        # accounting verdict (accounting_balanced) from the router ledger
        # plus the streaming tier attribution's bottleneck_tier — sampling
        # must be armed here or every traffic.request is head-sampled out
        # and the tier verdict rides empty
        ("bench-traffic", [sys.executable, "bench.py", "--mode", "traffic"],
         1500, dict(env, BENCH_SKIP_MICRO="1", SCALERL_TRACE_SAMPLE="1.0")),
        # token-level sequence-RL plane: prefill/decode tokens/s/chip
        # through the KV-cached generation engine + token-PPO learn
        # steps/s; perf-gated like-for-like against genrl-mode history and
        # counted toward the witness quorum like the other bench steps
        ("bench-genrl", [sys.executable, "bench.py", "--mode", "genrl"],
         1500, dict(env, BENCH_SKIP_MICRO="1")),
        # continuous-batching decode plane: the paged-KV lane pool under
        # Poisson arrivals vs the fixed-cohort engine, like-for-like in
        # one artifact (mode "genrl-continuous" keeps its own perf-gate
        # history; the speedup_vs_cohort field is the ISSUE 11 acceptance
        # comparison, measured fresh every round)
        ("bench-genrl-cont",
         [sys.executable, "bench.py", "--mode", "genrl", "--continuous"],
         1500, dict(env, BENCH_SKIP_MICRO="1")),
        # the same continuous plane at GROUP shape n=8 (ISSUE 14: GRPO
        # group sampling through submit_group — shared-prefix CoW fork +
        # pipelined admission).  The artifact carries group=8, so the
        # perf gate compares like-for-like at the same group shape and
        # never cross-gates the ungrouped bench-genrl-cont history; its
        # prefill_tokens_saved_ratio field is the ISSUE 14 acceptance
        # number (>= 0.8 of full-page prefix tokens at n=8)
        ("bench-genrl-group",
         [sys.executable, "bench.py", "--mode", "genrl", "--continuous"],
         1500, dict(env, BENCH_SKIP_MICRO="1", BENCH_GENRL_GROUP="8")),
        # disaggregated dataflow: end-to-end sequences/s through the full
        # generation-host -> wire -> learner path plus snapshot-push
        # latency for the int8 wire format; perf-gated like-for-like
        # against disagg-mode history (metric disagg_sequences_per_sec)
        ("bench-disagg", [sys.executable, "bench.py", "--mode", "disagg"],
         1500, dict(env, BENCH_SKIP_MICRO="1")),
        # learner-step-only MFU at the north-star shape (the fused loop's
        # MFU is env-bound by design; this is the train-step number)
        ("bench-learn", [sys.executable, "bench.py", "--learn"], 1500, env),
        ("tests_tpu", [sys.executable, "-m", "pytest", "tests_tpu", "-q"], 1800, env),
        ("profile", [sys.executable, "examples/profile_fused_loop.py"], 1200, env),
        # the ALE-scale flagship curve: ~4M frames is under a minute at the
        # witnessed single-chip rate, so a held tunnel records the
        # wall-clock-to-score protocol at the north-star pixel shape
        ("breakout84", [sys.executable, "examples/learning_curves.py",
                        "impala_breakout_84", "--tpu"], 1800, env),
    ]
    if n_devices > 1:  # aggregate north-star shape, only when multi-chip
        steps.insert(
            1,
            (
                "bench-mesh",
                [sys.executable, "bench.py", "--mesh", f"dp={n_devices}"],
                1500,
                env,
            ),
        )
    outcomes: list = []
    with open(PAYLOG, "a", buffering=1) as bl:
        for name, cmd, tmo, step_env in steps:
            bl.write(f"=== {name} {time.strftime('%H:%M:%S')} ===\n")
            # per-step telemetry dir: the runtime's exit hook drops a final
            # registry snapshot there, attached to this step's summary
            telem_dir = os.path.join(TELEM_ROOT, name)
            shutil.rmtree(telem_dir, ignore_errors=True)
            os.makedirs(telem_dir, exist_ok=True)
            step_env = dict(step_env, SCALERL_TELEMETRY_DIR=telem_dir)
            try:
                step_start = bl.tell()
                status = _run_step(cmd, step_env, bl, tmo)
                if name.startswith("bench") and status == "ok":
                    # perf-regression gate: a >20% fps/chip drop vs the
                    # committed BENCH history fails the step like a lint
                    # finding (and drops it from the witness quorum)
                    gate = _perf_gate_marker(bl, step_start)
                    if gate:
                        status = "FAILED" + gate
                if name == "elastic-soak":
                    status += _elastic_marker(bl, step_start)
                if name == "disagg-soak":
                    status += _disagg_marker(bl, step_start)
                if name == "preempt-soak":
                    status += _preempt_marker(bl, step_start)
                if name == "trace-soak":
                    status += _disagg_marker(bl, step_start)
                    status += _trace_marker(bl, step_start)
                if name == "traffic-replay":
                    status += _traffic_marker(bl, step_start)
                outcomes.append((name, status + _telemetry_marker(telem_dir, bl)))
            except Exception as e:  # noqa: BLE001 - watcher must survive anything
                bl.write(f"[watcher] {name} failed: {e}\n")
                outcomes.append((name, "error"))
    summary = " ".join(f"{name}:{status}" for name, status in outcomes)
    log_probe(
        f"{time.strftime('%Y-%m-%d %H:%M:%S')} payload done [{summary}] "
        "(see BENCH_TPU.md)"
    )
    if not any(
        status.startswith("ok")
        for name, status in outcomes
        if name not in (
            "lint-rules", "lint", "chaos-soak", "elastic-soak",
            "disagg-soak", "preempt-soak", "trace-soak", "traffic-replay",
            "genrl-soak",
        )
    ):
        # nothing TPU-witnessed succeeded (lint, the chaos soak, the
        # elastic soak, and the genrl soak are CPU-only and pass
        # tunnel-down, so they do not count): there is no artifact to
        # record — a commit here would just stamp noise over the probe log
        log_probe("[watcher] no payload step succeeded; skipping witness commit")
        return
    try:
        subprocess.run(
            # summary.json lives under gitignored work_dirs/ but is
            # force-tracked (the docs table is generated from it — the two
            # committed artifacts must stay in step)
            ["git", "add", "-f", "BENCH_TPU.md", "TPU_PROBELOG.md",
             "docs/LEARNING_CURVES.md",
             "work_dirs/learning_curves/summary.json"],
            cwd=REPO,
        )
        subprocess.run(
            ["git", "commit", "-m",
             f"Record witnessed TPU bench artifacts\n\nsteps: {summary}"],
            cwd=REPO,
        )
    except Exception as e:  # noqa: BLE001
        log_probe(f"[watcher] auto-commit failed: {e}")


def main() -> None:
    ensure_header()
    # re-arm: the tunnel flaps, and a payload cut short mid-suite (round 5
    # saw tests_tpu die to a drop minutes after the bench landed) deserves
    # another shot on the next contact — up to 3 runs, 30 min apart
    payload_runs = 0
    last_payload_t = 0.0
    while True:
        t0 = time.time()
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        try:
            p = subprocess.run(
                [sys.executable, "-c", PROBE], timeout=300, capture_output=True, text=True
            )
            dt = time.time() - t0
            out = (p.stdout or "").strip().replace("\n", " | ")
            log_probe(f"{stamp} rc={p.returncode} dt={dt:.0f}s [{out}]")
            if (
                "backend: tpu" in out
                and payload_runs < 3
                and time.time() - last_payload_t > 1800
            ):
                payload_runs += 1
                log_probe(f"{stamp} TPU CONTACT - running payload ({payload_runs}/3)")
                m = re.search(r"n: (\d+)", out)
                run_payload(int(m.group(1)) if m else 1)
                # stamp AFTER the (blocking, possibly hour-long) payload:
                # stamping before it would mean the cooldown had already
                # elapsed on return, re-running a fully successful suite
                last_payload_t = time.time()
        except subprocess.TimeoutExpired:
            log_probe(f"{stamp} TIMEOUT after {time.time() - t0:.0f}s")
        except Exception as e:  # noqa: BLE001
            log_probe(f"{stamp} watcher error: {e}")
        time.sleep(60)


if __name__ == "__main__":
    main()
