"""TPU tunnel watcher (round 4).

The axon TPU tunnel is intermittent (rounds 1-3: it answered once in round
1, then hung ``jax.devices()`` for entire driver windows). This watcher
probes the backend once a minute and writes every attempt - timestamp,
outcome, latency - to the committed probe log ``TPU_PROBELOG.md`` so the
round artifact proves the tunnel was down rather than asserts it
(VERDICT r3, next-round item #1a).

On first contact it runs, in order (VERDICT r3 #1b):
  1. ``bench.py`` (bf16 headline + MFU; appends TPU successes to
     ``BENCH_TPU.md`` itself),
  2. ``bench.py --mesh dp=8`` if the tunnel exposes >1 chip (aggregate
     north-star shape),
  3. ``pytest tests_tpu`` (compiled Pallas-kernel legality),
  4. ``examples/profile_fused_loop.py`` (idle fraction),
then commits the artifacts immediately.

Run: ``nohup python tools/tpu_watch.py >/tmp/tpu_watch_r4.out 2>&1 &``
"""

import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBELOG = os.path.join(REPO, "TPU_PROBELOG.md")
PAYLOG = "/tmp/tpu_autobench_r4.log"

PROBE = (
    "import jax; print('backend:', jax.default_backend());"
    " print('kind:', jax.devices()[0].device_kind);"
    " print('n:', jax.device_count())"
)


def log_probe(line: str) -> None:
    with open(PROBELOG, "a", buffering=1) as f:
        f.write(line + "\n")


def ensure_header() -> None:
    if not os.path.exists(PROBELOG) or os.path.getsize(PROBELOG) == 0:
        with open(PROBELOG, "w") as f:
            f.write(
                "# TPU tunnel probe log (round 4)\n\n"
                "One line per probe attempt by `tools/tpu_watch.py`: UTC time, "
                "outcome, latency. A `backend: tpu` line means contact; the "
                "watcher then runs the full bench payload and commits. "
                "Timeout lines are the committed evidence that the axon "
                "tunnel was down during this round (VERDICT r3 item #1).\n\n"
                "```\n"
            )


def run_payload(n_devices: int = 1) -> None:
    env = dict(os.environ, BENCH_BUDGET_S="900")
    steps = [
        ("bench", [sys.executable, "bench.py"], 1500),
        ("tests_tpu", [sys.executable, "-m", "pytest", "tests_tpu", "-q"], 1800),
        ("profile", [sys.executable, "examples/profile_fused_loop.py"], 1200),
    ]
    if n_devices > 1:  # aggregate north-star shape, only when multi-chip
        steps.insert(
            1,
            (
                "bench-mesh",
                [sys.executable, "bench.py", "--mesh", f"dp={n_devices}"],
                1500,
            ),
        )
    with open(PAYLOG, "a", buffering=1) as bl:
        for name, cmd, tmo in steps:
            bl.write(f"=== {name} {time.strftime('%H:%M:%S')} ===\n")
            try:
                subprocess.run(cmd, env=env, stdout=bl, stderr=bl, timeout=tmo, cwd=REPO)
            except Exception as e:  # noqa: BLE001 - watcher must survive anything
                bl.write(f"[watcher] {name} failed: {e}\n")
    log_probe(f"{time.strftime('%Y-%m-%d %H:%M:%S')} payload done (see BENCH_TPU.md)")
    try:
        subprocess.run(["git", "add", "BENCH_TPU.md", "TPU_PROBELOG.md"], cwd=REPO)
        subprocess.run(
            ["git", "commit", "-m", "Record witnessed TPU bench artifacts"], cwd=REPO
        )
    except Exception as e:  # noqa: BLE001
        log_probe(f"[watcher] auto-commit failed: {e}")


def main() -> None:
    ensure_header()
    ran_payload = False
    while True:
        t0 = time.time()
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        try:
            p = subprocess.run(
                [sys.executable, "-c", PROBE], timeout=300, capture_output=True, text=True
            )
            dt = time.time() - t0
            out = (p.stdout or "").strip().replace("\n", " | ")
            log_probe(f"{stamp} rc={p.returncode} dt={dt:.0f}s [{out}]")
            if "backend: tpu" in out and not ran_payload:
                ran_payload = True
                log_probe(f"{stamp} TPU CONTACT - running payload")
                m = re.search(r"n: (\d+)", out)
                run_payload(int(m.group(1)) if m else 1)
        except subprocess.TimeoutExpired:
            log_probe(f"{stamp} TIMEOUT after {time.time() - t0:.0f}s")
        except Exception as e:  # noqa: BLE001
            log_probe(f"{stamp} watcher error: {e}")
        time.sleep(60)


if __name__ == "__main__":
    main()
