"""Diurnal traffic replay over real sockets: the front door's soak harness.

Drives the :class:`~scalerl_tpu.serving.router.ServingRouter` with an
OPEN-LOOP arrival process shaped like real traffic — a diurnal sinusoid
modulating a Poisson stream, with periodic burst overlays — through
thousands of :class:`RemotePolicyClient` instances dialing the router's
REAL listening socket (not in-process pipes: the codec framing, the
accept path, and the ``route_sock`` chaos site are all on the wire).
Replicas are jax-free SCRIPTED servers (seeded service-time
distribution, serial worker queue) so the harness measures the
*traffic plane* — routing, queueing, failover — not model math, and runs
in CI without an accelerator.

While the replay runs, the streaming tier attribution
(:class:`~scalerl_tpu.runtime.attribution.TierLedger`) decomposes every
sampled request into named tier edges ONLINE — per-edge durations sum to
the end-to-end latency exactly — and the final verdict names the
``bottleneck_tier`` (largest p95 share of the critical path).  The last
stdout line is a one-line JSON verdict (``{"metric": "traffic_replay",
...}``) that ``tools/tpu_watch.py`` gates its ``traffic-replay`` soak
step on:

- **exact accounting**: ``admitted == answered + shed + orphaned`` at
  quiesce (the chaos e2e's equation);
- **attribution completeness**: every sampled root decomposed, zero
  orphaned traces, ``max_sum_err`` at float-noise level;
- **digest honesty**: the log-bucket digest's p99 within its configured
  relative-error bound of the exact percentile over the SAME samples.

Fault sites: ``--kill-replica-at`` closes one scripted replica's link
mid-run (death verdict -> eject -> re-dispatch), ``--rollout-at`` runs a
rolling weight rollout mid-run (drain/push/readmit phase events land in
the flight recorder), and the links carry chaos sites
(``route_sock`` on client sockets, ``replay_replica`` on replica pipes)
so the chaos injector's env knobs compose with the replay unchanged.

Arrivals: ``rate(t) = base_rps * (1 + depth * sin(2*pi*t / period))``
thinned from a max-rate Poisson stream (Lewis-Shedler), plus ``burst_n``
back-to-back requests every ``burst_every_s``; ``--trace-file`` replays
recorded arrival offsets (one float seconds-from-start per line)
instead.  Latency is measured from the SCHEDULED arrival, so schedule
slip counts against the tier.  Everything is seeded (``--seed``).

jax-free: imports serving submodules directly (the package __init__
pulls the jitted server).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalerl_tpu.fleet.transport import PipeConnection, connect_socket
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.runtime.attribution import TierLedger
from scalerl_tpu.runtime.supervisor import is_heartbeat, make_pong
from scalerl_tpu.serving.client import RemotePolicyClient
from scalerl_tpu.serving.router import (
    ReplicaHandle,
    RouterConfig,
    ServingRouter,
)

# the replay's observation shape: tiny on purpose — the codec cost per
# frame should be wire overhead, not payload serialization
LANES, OBS_DIM, NUM_ACTIONS = 1, 8, 4

PHASE_NAMES = ("rise", "peak", "fall", "trough")


def replica_pair() -> Tuple[PipeConnection, PipeConnection]:
    """A duplex pipe pair for the router<->scripted-replica link, under
    its own chaos site so the injector can fault replica links without
    touching the client sockets."""
    import multiprocessing as mp

    a, b = mp.Pipe(duplex=True)
    return (
        PipeConnection(a, chaos_site="replay_replica"),
        PipeConnection(b, chaos_site="replay_replica"),
    )


class ScriptedReplica:
    """A jax-free stand-in for ``InferenceServer`` behind the router.

    A reader thread enqueues act frames with their arrival stamp; ONE
    serial worker pops them, sleeps a seeded lognormal service time, and
    replies — so queueing under bursts is real, and the replica records
    the same ``serve.queue_wait`` / ``serve.flush`` spans the real server
    stamps (the tier ledger cannot tell them apart).  Speaks the router's
    control frames (``router_hello``, ``health``, ping/pong) and exposes
    ``push_params`` so rolling rollouts exercise the drain protocol.
    """

    def __init__(
        self,
        name: str,
        conn: PipeConnection,
        service_ms: float = 2.0,
        jitter: float = 0.25,
        seed: int = 0,
    ) -> None:
        self.name = name
        self.conn = conn
        self.service_s = service_ms / 1e3
        self.jitter = jitter
        self.gen = 0
        self.served = 0
        self.killed = False
        self._rng = np.random.default_rng(seed)
        self._queue: "List[Tuple[Dict[str, Any], float]]" = []
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._send_lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._read_loop, daemon=True,
                             name=f"{name}-reader"),
            threading.Thread(target=self._work_loop, daemon=True,
                             name=f"{name}-worker"),
        ]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001 — teardown
            pass
        for t in self._threads:
            t.join(timeout=3.0)

    def kill(self) -> None:
        """The seeded fault: drop the link mid-run.  The router's reader
        sees the dead pipe, ejects, and re-dispatches the in-flight."""
        self.killed = True
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001 — the fault IS the close
            pass

    def push_params(self, params: Any, learner_step: Optional[int] = None) -> int:
        self.gen += 1
        return self.gen

    def _send(self, msg: Dict[str, Any]) -> None:
        with self._send_lock:
            self.conn.send(msg)

    def _read_loop(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self.conn.recv(timeout=0.2)
            except TimeoutError:
                continue
            except (ConnectionError, EOFError, OSError, ValueError):
                return
            if not isinstance(msg, dict):
                continue
            if is_heartbeat(msg):
                if msg.get("kind") == "ping":
                    try:
                        self._send(make_pong(msg))
                    except (ConnectionError, OSError):
                        return
                continue
            kind = msg.get("kind")
            try:
                if kind == "router_hello":
                    self._send({"kind": "router_hello", "req": msg.get("req"),
                                "host": self.name, "gen": self.gen})
                elif kind == "health":
                    self._send({
                        "kind": "health_result", "req": msg.get("req"),
                        "p95_ms": self.service_s * 1e3, "shed_total": 0,
                        "pending": len(self._queue), "gen": self.gen,
                        "host": self.name,
                    })
                elif kind == "core_init":
                    self._send({"kind": "core_init", "req": msg.get("req"),
                                "core": (), "gen": self.gen})
                elif kind == "act":
                    with self._cv:
                        self._queue.append((msg, time.monotonic()))
                        self._cv.notify()
            except (ConnectionError, OSError):
                return

    def _work_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self._cv.wait(timeout=0.2)
                if self._stop.is_set():
                    return
                msg, t_enq = self._queue.pop(0)
            t_flush0 = time.monotonic()
            # seeded lognormal-ish service time: mean service_s, a real tail
            dt = self.service_s * float(
                self._rng.lognormal(mean=0.0, sigma=self.jitter)
            )
            time.sleep(dt)
            t_done = time.monotonic()
            ctx = tracing.extract(msg)
            if ctx is not None:
                # the same two spans the real server stamps per request
                tracing.record_span(
                    "serve.queue_wait", parent=ctx, t_start=t_enq,
                    t_end=t_flush0, kind="serving", replica=self.name,
                )
                tracing.record_span(
                    "serve.flush", parent=ctx, t_start=t_flush0,
                    t_end=t_done, kind="serving", replica=self.name, batch=1,
                )
            batch = int(np.asarray(msg["obs"]).shape[0]) or 1
            try:
                self._send({
                    "kind": "act_result", "req": msg["req"],
                    "action": np.zeros(batch, np.int32),
                    "logits": np.zeros((batch, NUM_ACTIONS), np.float32),
                    "core": (), "gen": self.gen,
                })
                self.served += 1
            except (ConnectionError, OSError):
                return


def _raise_nofile(need: int) -> None:
    """Each socket client costs two fds (client + router side); lift the
    soft RLIMIT_NOFILE toward the hard cap before dialing thousands."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = min(max(need, soft), hard)
        if want > soft:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
    except (ImportError, ValueError, OSError):
        pass


def diurnal_rate(t: float, base: float, depth: float, period: float) -> float:
    return base * (1.0 + depth * math.sin(2.0 * math.pi * t / period))


def phase_of(t: float, period: float) -> str:
    return PHASE_NAMES[int(4.0 * ((t % period) / period)) % 4]


def make_schedule(
    duration_s: float,
    base_rps: float,
    depth: float,
    period_s: float,
    burst_every_s: float,
    burst_n: int,
    seed: int,
    trace_file: Optional[str] = None,
) -> np.ndarray:
    """The full arrival schedule, seconds from start, sorted.  Diurnal x
    Poisson by Lewis-Shedler thinning (draw at the peak rate, accept with
    probability rate(t)/peak), plus burst overlays — or the replayed
    offsets from ``trace_file``."""
    if trace_file:
        offs = []
        with open(trace_file) as f:
            for line in f:
                line = line.strip()
                if line:
                    offs.append(float(line))
        return np.sort(np.asarray(offs, dtype=np.float64))
    rng = np.random.default_rng(seed)
    peak = base_rps * (1.0 + abs(depth))
    arrivals: List[float] = []
    t = 0.0
    while t < duration_s:
        t += float(rng.exponential(1.0 / peak))
        if t >= duration_s:
            break
        if rng.random() * peak <= diurnal_rate(t, base_rps, depth, period_s):
            arrivals.append(t)
    if burst_every_s > 0 and burst_n > 0:
        tb = burst_every_s
        while tb < duration_s:
            arrivals.extend([tb] * burst_n)
            tb += burst_every_s
    return np.sort(np.asarray(arrivals, dtype=np.float64))


class Harvest:
    """One request's outcome, recorded at reply-poll time."""

    __slots__ = ("t_sched", "lat_s", "outcome")

    def __init__(self, t_sched: float, lat_s: float, outcome: str) -> None:
        self.t_sched = t_sched
        self.lat_s = lat_s
        self.outcome = outcome


def run_replay(args: argparse.Namespace) -> Dict[str, Any]:
    _raise_nofile(2 * args.clients + 256)
    tracer = tracing.get_tracer()
    tracer.sample_rate = args.trace_sample
    ledger = TierLedger(
        relative_error=args.relative_error,
        max_pending=max(8192, 4 * args.clients),
        registry=telemetry.get_registry(),
    ).attach(tracer)

    # -- topology: scripted replicas behind a socket-listening router ----
    replicas: List[ScriptedReplica] = []
    handles: List[ReplicaHandle] = []
    for i in range(args.replicas):
        router_end, replica_end = replica_pair()
        rep = ScriptedReplica(
            f"replica{i}", replica_end, service_ms=args.service_ms,
            seed=args.seed + 100 + i,
        )
        rep.start()
        replicas.append(rep)
        handles.append(ReplicaHandle(rep.name, router_end, server=rep))
    router = ServingRouter(
        handles,
        RouterConfig(hedge_budget=2, probe_backoff_s=0.05,
                     drain_timeout_s=2.0, hub_maxsize=4096,
                     seed=args.seed),
    )
    router.start(listen_port=args.listen_port)
    port = router._listen_sock.getsockname()[1]
    print(f"router listening on :{port}; dialing {args.clients} socket "
          f"clients ...", flush=True)

    clients = [
        RemotePolicyClient(
            connect=lambda: connect_socket("127.0.0.1", port, retries=10),
            request_timeout_s=60.0,
        )
        for _ in range(args.clients)
    ]

    # -- the open-loop drive ---------------------------------------------
    schedule = make_schedule(
        args.duration_s, args.base_rps, args.diurnal_depth,
        args.diurnal_period_s, args.burst_every_s, args.burst_n,
        args.seed, args.trace_file,
    )
    duration = float(schedule[-1]) + 0.5 if schedule.size else args.duration_s
    shards = max(1, min(args.shards, args.clients))
    shard_sched = [schedule[i::shards] for i in range(shards)]
    shard_clients = [
        [c for j, c in enumerate(clients) if j % shards == i]
        for i in range(shards)
    ]
    results: List[List[Harvest]] = [[] for _ in range(shards)]
    fired = [0] * shards
    sampled = [0] * shards
    unharvested = [0] * shards
    la = np.zeros(LANES, np.int32)
    rew = np.zeros(LANES, np.float32)
    done_arr = np.zeros(LANES, bool)
    go = threading.Event()
    abort = threading.Event()

    def shard_loop(i: int) -> None:
        local = np.random.default_rng(args.seed + 500 + i)
        mine, sched = shard_clients[i], shard_sched[i]
        inflight: List[Tuple[Any, float, Any]] = []
        go.wait()
        t0 = time.perf_counter()
        k = 0

        def sweep(final: bool = False) -> None:
            deadline = time.perf_counter() + (args.drain_timeout_s if final
                                              else 0.0)
            while True:
                still: List[Tuple[Any, float, Any]] = []
                for pending, t_sched, span in inflight:
                    if not pending.done():
                        still.append((pending, t_sched, span))
                        continue
                    t_done = time.perf_counter()
                    try:
                        reply = pending.result(timeout=0)
                    except (TimeoutError, ConnectionError):
                        span.end(outcome="lost")
                        results[i].append(Harvest(t_sched, 0.0, "lost"))
                        continue
                    if reply.get("shed"):
                        span.end(outcome="shed")
                        results[i].append(Harvest(t_sched, 0.0, "shed"))
                    else:
                        span.end(outcome="ok")
                        results[i].append(
                            Harvest(t_sched, t_done - (t0 + t_sched), "ok")
                        )
                inflight[:] = still
                if not final or not inflight or time.perf_counter() > deadline:
                    break
                time.sleep(0.005)
            if final:
                # anything still pending never came back: end the span so
                # the trace decomposes (never an attribution orphan), and
                # count it against the harness, not the router ledger
                for pending, t_sched, span in inflight:
                    span.end(outcome="lost")
                    results[i].append(Harvest(t_sched, 0.0, "lost"))
                    unharvested[i] += 1
                inflight.clear()

        while k < sched.size and not abort.is_set():
            now = time.perf_counter() - t0
            while k < sched.size and float(sched[k]) <= now:
                t_sched = float(sched[k])
                c = mine[k % len(mine)]
                span = tracing.start_span("traffic.request", kind="serving",
                                          phase=phase_of(
                                              t_sched, args.diurnal_period_s))
                msg = c._act_msg(
                    local.normal(size=(LANES, OBS_DIM)).astype(np.float32),
                    la, rew, done_arr, (),
                )
                tracing.inject(msg, span)
                try:
                    inflight.append((c._submit(msg), t_sched, span))
                except ConnectionError:
                    span.end(outcome="dial_lost")
                    results[i].append(Harvest(t_sched, 0.0, "lost"))
                fired[i] += 1
                if span.sampled:
                    sampled[i] += 1
                k += 1
            sweep()
            nxt = float(sched[k]) if k < sched.size else now
            time.sleep(min(0.002, max(nxt - (time.perf_counter() - t0), 0.0)))
        sweep(final=True)

    threads = [
        threading.Thread(target=shard_loop, args=(i,), daemon=True,
                         name=f"replay-shard{i}")
        for i in range(shards)
    ]
    for t in threads:
        t.start()

    killer: Optional[threading.Thread] = None
    if args.kill_replica_at > 0:
        victim = replicas[args.kill_replica % len(replicas)]

        def kill() -> None:
            go.wait()
            time.sleep(args.kill_replica_at)
            print(f"[fault] killing {victim.name} at t={args.kill_replica_at:g}s",
                  flush=True)
            victim.kill()

        killer = threading.Thread(target=kill, daemon=True, name="replay-kill")
        killer.start()

    roller: Optional[threading.Thread] = None
    if args.rollout_at > 0:

        def roll() -> None:
            go.wait()
            time.sleep(args.rollout_at)
            print(f"[rollout] rolling weights at t={args.rollout_at:g}s",
                  flush=True)
            router.rollout(params=None, learner_step=1)

        roller = threading.Thread(target=roll, daemon=True, name="replay-roll")
        roller.start()

    t_start = time.perf_counter()
    go.set()
    for t in threads:
        t.join(timeout=duration + 120.0)
        if t.is_alive():
            abort.set()
    elapsed = time.perf_counter() - t_start
    if killer is not None:
        killer.join(timeout=5.0)
    if roller is not None:
        roller.join(timeout=30.0)

    # quiesce the router before reading the accounting ledger
    deadline = time.monotonic() + 10.0
    while router.stats()["inflight"] > 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    stats = router.stats()
    ledger.drain()

    # -- verdict assembly ------------------------------------------------
    all_h = [h for shard in results for h in shard]
    ok_lat = np.sort(np.asarray(
        [h.lat_s for h in all_h if h.outcome == "ok"], dtype=np.float64))
    answered = int(ok_lat.size)
    shed_total = sum(1 for h in all_h if h.outcome == "shed")
    lost_total = sum(1 for h in all_h if h.outcome == "lost")
    good = int(np.searchsorted(ok_lat, args.slo_ms / 1e3, side="right"))

    def _q(arr: np.ndarray, q: float) -> float:
        if not arr.size:
            return 0.0
        return float(arr[min(int(q * (arr.size - 1)), arr.size - 1)])

    # digest honesty check: the SAME samples through the sketch vs exact.
    # The sketch guarantees |est - exact| <= relerr * exact at any count
    # (exact = the lower-rank order statistic the bucket walk targets)
    from scalerl_tpu.runtime.attribution import LatencyDigest

    check = LatencyDigest(relative_error=args.relative_error)
    check.observe_array(ok_lat)
    p99_exact = _q(ok_lat, 0.99)
    p99_digest = check.quantile(0.99)
    digest_rel_err = (abs(p99_digest - p99_exact) / p99_exact
                      if p99_exact > 0 else 0.0)
    digest_ok = digest_rel_err <= args.relative_error + 1e-9

    # per-phase goodput/SLO accounting (diurnal quadrants)
    phases: Dict[str, Dict[str, Any]] = {}
    period = args.diurnal_period_s
    phase_time: Dict[str, float] = {p: 0.0 for p in PHASE_NAMES}
    grid = np.arange(0.0, duration, 1e-2)
    for tt in grid:
        phase_time[phase_of(float(tt), period)] += 1e-2
    for h in all_h:
        p = phases.setdefault(phase_of(h.t_sched, period), {
            "offered": 0, "answered": 0, "good": 0, "shed": 0, "lost": 0,
        })
        p["offered"] += 1
        if h.outcome == "ok":
            p["answered"] += 1
            if h.lat_s <= args.slo_ms / 1e3:
                p["good"] += 1
        elif h.outcome == "shed":
            p["shed"] += 1
        else:
            p["lost"] += 1
    for name, p in phases.items():
        secs = phase_time.get(name, 0.0) or 1.0
        p["goodput_rps"] = round(p["good"] / secs, 1)
        p["offered_rps"] = round(p["offered"] / secs, 1)

    total_fired = sum(fired)
    total_sampled = sum(sampled)
    balanced = (stats["answered"] + stats["shed"] + stats["orphaned"]
                == stats["admitted"])
    bn = ledger.bottleneck()
    attribution_complete = (
        bn["decomposed"] == total_sampled and bn["orphans"] == 0
    )

    verdict: Dict[str, Any] = {
        "metric": "traffic_replay",
        "clients": args.clients,
        "replicas": args.replicas,
        "shards": shards,
        "duration_s": round(elapsed, 2),
        "base_rps": args.base_rps,
        "diurnal_depth": args.diurnal_depth,
        "diurnal_period_s": args.diurnal_period_s,
        "seed": args.seed,
        "fired": total_fired,
        "answered": answered,
        "good": good,
        "shed": shed_total,
        "lost": lost_total,
        "unharvested": sum(unharvested),
        "goodput_rps": round(good / elapsed, 1) if elapsed else 0.0,
        "offered_rps": round(total_fired / elapsed, 1) if elapsed else 0.0,
        "slo_ms": args.slo_ms,
        "p50_ms": round(_q(ok_lat, 0.50) * 1e3, 3),
        "p95_ms": round(_q(ok_lat, 0.95) * 1e3, 3),
        "p99_ms": round(_q(ok_lat, 0.99) * 1e3, 3),
        "router": {
            "admitted": stats["admitted"],
            "answered": stats["answered"],
            "shed": stats["shed"],
            "orphaned": stats["orphaned"],
            "retries": stats["retries"],
            "redispatches": stats["redispatches"],
            "duplicate_replies": stats["duplicate_replies"],
            "ejections": stats["ejections"],
            "readmissions": stats["readmissions"],
            "rollouts": stats["rollouts"],
            "breaker": stats["breaker"],
        },
        "accounting_balanced": balanced,
        "bottleneck_tier": bn["bottleneck_tier"],
        "tiers": bn["tiers"],
        "attribution": {
            "sampled": total_sampled,
            "decomposed": bn["decomposed"],
            "orphans": bn["orphans"],
            "late_spans": bn["late_spans"],
            "max_sum_err_s": bn["max_sum_err_s"],
            "complete": attribution_complete,
        },
        "digest_check": {
            "p99_exact_ms": round(p99_exact * 1e3, 3),
            "p99_digest_ms": round(p99_digest * 1e3, 3),
            "rel_err": round(digest_rel_err, 5),
            "bound": args.relative_error,
            "ok": digest_ok,
        },
        "phases": phases,
        "fault": (
            {"kill_replica": replicas[args.kill_replica % len(replicas)].name,
             "at_s": args.kill_replica_at}
            if args.kill_replica_at > 0 else None
        ),
    }

    # teardown
    for c in clients:
        c.close()
    router.stop()
    for rep in replicas:
        rep.stop()
    ledger.detach(tracer)
    return verdict


def print_verdict(v: Dict[str, Any], out=sys.stdout) -> None:
    print(
        f"traffic replay: {v['fired']} fired over {v['duration_s']}s "
        f"({v['offered_rps']} rps offered) -> {v['answered']} answered, "
        f"{v['shed']} shed, {v['lost']} lost; goodput "
        f"{v['goodput_rps']} rps within {v['slo_ms']:g}ms SLO "
        f"(p50={v['p50_ms']}ms p95={v['p95_ms']}ms p99={v['p99_ms']}ms)",
        file=out,
    )
    r = v["router"]
    print(
        f"router ledger: admitted={r['admitted']} answered={r['answered']} "
        f"shed={r['shed']} orphaned={r['orphaned']} "
        f"(balanced={v['accounting_balanced']}) retries={r['retries']} "
        f"redispatches={r['redispatches']} dup={r['duplicate_replies']} "
        f"ejections={r['ejections']} readmissions={r['readmissions']}",
        file=out,
    )
    a = v["attribution"]
    print(
        f"attribution: {a['decomposed']}/{a['sampled']} sampled traces "
        f"decomposed, {a['orphans']} orphans, {a['late_spans']} late spans, "
        f"max sum error {a['max_sum_err_s'] * 1e6:.3f}us",
        file=out,
    )
    for tier, row in sorted(
        v["tiers"].items(), key=lambda kv: -kv[1]["share"]
    ):
        print(
            f"  {tier:<16} {100 * row['share']:5.1f}%  "
            f"p50={row['p50_ms']:.2f}ms p95={row['p95_ms']:.2f}ms "
            f"p99={row['p99_ms']:.2f}ms  (n={row['count']})",
            file=out,
        )
    d = v["digest_check"]
    print(
        f"digest check: p99 exact={d['p99_exact_ms']}ms "
        f"digest={d['p99_digest_ms']}ms rel_err={d['rel_err']} "
        f"(bound {d['bound']}, ok={d['ok']})",
        file=out,
    )
    for name in PHASE_NAMES:
        p = v["phases"].get(name)
        if p:
            print(
                f"  phase {name:<7} offered={p['offered_rps']}rps "
                f"goodput={p['goodput_rps']}rps good={p['good']}/"
                f"{p['answered']} shed={p['shed']}",
                file=out,
            )
    print(f"bottleneck tier: {v['bottleneck_tier']}", file=out)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--clients", type=int, default=1000)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--shards", type=int, default=16,
                   help="firing threads; each drives clients/shards clients")
    p.add_argument("--duration-s", type=float, default=20.0)
    p.add_argument("--base-rps", type=float, default=300.0)
    p.add_argument("--diurnal-period-s", type=float, default=8.0,
                   help="one compressed 'day' of the sinusoid")
    p.add_argument("--diurnal-depth", type=float, default=0.6)
    p.add_argument("--burst-every-s", type=float, default=2.5)
    p.add_argument("--burst-n", type=int, default=40)
    p.add_argument("--trace-file", default=None,
                   help="replay recorded arrival offsets instead of the "
                   "synthetic diurnal process (one float per line)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--slo-ms", type=float, default=250.0)
    p.add_argument("--service-ms", type=float, default=2.0,
                   help="scripted replica mean service time")
    p.add_argument("--kill-replica-at", type=float, default=0.0,
                   help="seconds into the run to kill one replica (0 = off)")
    p.add_argument("--kill-replica", type=int, default=0)
    p.add_argument("--rollout-at", type=float, default=0.0,
                   help="seconds into the run to trigger a rolling weight "
                   "rollout (0 = off)")
    p.add_argument("--listen-port", type=int, default=0,
                   help="router listening port (0 = ephemeral)")
    p.add_argument("--trace-sample", type=float, default=1.0)
    p.add_argument("--relative-error", type=float, default=0.01)
    p.add_argument("--drain-timeout-s", type=float, default=30.0)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    verdict = run_replay(args)
    print_verdict(verdict)
    # the gate line LAST: tpu_watch scans for the newest matching object
    print(json.dumps(verdict), flush=True)
    ok = (
        verdict["accounting_balanced"]
        and verdict["attribution"]["complete"]
        and verdict["digest_check"]["ok"]
        and bool(verdict["bottleneck_tier"])
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
