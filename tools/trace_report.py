"""Trace merge + critical-path analyzer for the distributed tracer.

Input: a directory of per-host span files (``spans_<host>_<pid>.jsonl``)
written by ``scalerl_tpu/runtime/tracing.py`` — one JSON object per line:
span records, one ``meta`` line per file, and optional ``skew`` lines
carrying the writer's per-peer clock offsets (estimated off heartbeat
ping/pong RTTs).  Output, in one pass:

1. **merged trace trees** — spans grouped by trace id, skew-corrected onto
   the observer's clock, roots identified, orphans counted (a span whose
   parent id is absent from its trace — the completeness failure mode a
   lost host file produces);
2. **Chrome/Perfetto ``trace_event`` JSON** (``--chrome``, default
   ``<dir>/trace_events.json``) — one ``ph: "X"`` complete event per span,
   ``pid`` = host, ``tid`` = trace, so chrome://tracing renders each
   sequence lifecycle as one row spanning generation host -> learner;
3. a **critical-path breakdown** — top traces by duration with per-edge
   attribution, plus the aggregate % of traced wall-clock spent on
   queue-wait vs compute vs wire.  Attribution walks each trace's
   timeline from root start to last span end, charging every interval to
   the span covering it (ties: the later-starting span) or to
   ``untracked`` — so per-edge durations sum to the end-to-end latency
   EXACTLY, and the report can never double-count overlap.

The last stdout line is a one-line JSON verdict
(``{"metric": "trace_report", ...}``) that ``tools/tpu_watch.py`` gates
its trace-soak step on: ``sequence_traces`` vs ``complete_sequences``
(root -> learn_step present) and ``orphan_spans``.

``--traffic`` additionally runs the tier-attribution walk
(``scalerl_tpu.runtime.attribution``) over every traffic trace
(``traffic.request`` / ``serve.request`` roots), prints the per-tier
latency table, and emits a second verdict line
(``{"metric": "traffic_report", "bottleneck_tier": ...}``) — the offline
twin of the streaming ``TierLedger`` that multi-host runs use, since the
ledger can only see spans recorded through the local tracer.

jax-free: the trace-tree grouping and the exact-sum attribution walk
live in ``scalerl_tpu.runtime.attribution`` (shared with the online
ledger) and are re-exported here for compatibility.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalerl_tpu.runtime.attribution import (  # noqa: F401  (re-exports)
    TRAFFIC_ROOTS,
    LatencyDigest,
    attribute_edges,
    attribute_tiers,
    build_traces,
)

# edge-name -> cost class for the queue/compute/wire rollup
EDGE_CLASSES = {
    "seq.queue_wait": "queue",
    "seq.replay_wait": "queue",
    "serve.queue_wait": "queue",
    "seq.decode": "compute",
    "seq.seq_add": "compute",
    "seq.learn_step": "compute",
    "serve.flush": "compute",
    "task.episode": "compute",
    "genrl.macro_step": "compute",
    "genrl.generate_round": "compute",
    "round.generate": "compute",
    "round.seq_add": "compute",
    "round.learn": "compute",
    "seq.upload": "wire",
    "snapshot.fetch": "wire",
    "snapshot_publish": "wire",
    "serve.request": "wire",
}

# roots whose traces the completeness verdict inspects, and the leaf edge
# that must be present for the lifecycle to count as complete
COMPLETENESS = {"sequence": "seq.learn_step"}


def classify(name: str) -> str:
    return EDGE_CLASSES.get(name, "other")


def load_dir(trace_dir: str) -> Tuple[List[Dict], Dict[str, float]]:
    """All span records in ``trace_dir``, skew-corrected.

    Skew lines carry ``offsets[peer] = peer_wall - observer_wall`` as
    measured by the writing host; the host with the most measured peers
    (the learner — it pings everyone) becomes the reference, and every
    measured peer's spans shift by ``-offset`` onto its clock.  Files
    without skew data pass through untouched (same-machine soaks).
    """
    spans: List[Dict] = []
    skew_by_observer: Dict[str, Dict[str, float]] = {}
    for path in sorted(glob.glob(os.path.join(trace_dir, "spans_*.jsonl"))):
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue  # a torn last line from a SIGTERM'd host
                if "span" in obj:
                    spans.append(obj)
                elif obj.get("kind") == "skew":
                    skew_by_observer.setdefault(
                        str(obj.get("host")), {}
                    ).update(obj.get("offsets") or {})
    offsets: Dict[str, float] = {}
    if skew_by_observer:
        reference = max(
            skew_by_observer, key=lambda h: len(skew_by_observer[h])
        )
        offsets = dict(skew_by_observer[reference])
        offsets.pop(reference, None)
    for s in spans:
        off = offsets.get(str(s.get("host")))
        if off:
            s["t0"] = float(s["t0"]) - off
    return spans, offsets


def build_traffic_report(
    traces: Dict[str, Dict[str, Any]], relative_error: float = 0.01
) -> Dict[str, Any]:
    """Per-tier latency table + bottleneck verdict over the traffic
    traces (``TRAFFIC_ROOTS``-rooted) in an already-built trace set."""
    tier_digests: Dict[str, LatencyDigest] = {}
    tier_totals: Dict[str, float] = {}
    e2e_digest = LatencyDigest(relative_error=relative_error)
    n = 0
    max_sum_err = 0.0
    for t in traces.values():
        root = t["root"]
        if root is None or root["name"] not in TRAFFIC_ROOTS:
            continue
        n += 1
        tiers = attribute_tiers(t)
        max_sum_err = max(
            max_sum_err, abs(sum(tiers.values()) - t["e2e"])
        )
        e2e_digest.observe(t["e2e"])
        for tier, dur in tiers.items():
            tier_totals[tier] = tier_totals.get(tier, 0.0) + dur
            tier_digests.setdefault(
                tier, LatencyDigest(relative_error=relative_error)
            ).observe(dur)
    total = sum(tier_totals.values()) or 1.0
    table = {
        tier: {
            "share": round(tier_totals[tier] / total, 4),
            "total_s": round(tier_totals[tier], 6),
            "p50_ms": round(d.quantile(0.50) * 1e3, 3),
            "p95_ms": round(d.quantile(0.95) * 1e3, 3),
            "p99_ms": round(d.quantile(0.99) * 1e3, 3),
            "count": d.count,
        }
        for tier, d in tier_digests.items()
    }
    bottleneck = (
        max(table, key=lambda k: table[k]["p95_ms"]) if table else None
    )
    return {
        "metric": "traffic_report",
        "traffic_traces": n,
        "bottleneck_tier": bottleneck,
        "tiers": table,
        "max_sum_err_s": max_sum_err,
        "e2e_p50_ms": round(e2e_digest.quantile(0.50) * 1e3, 3),
        "e2e_p95_ms": round(e2e_digest.quantile(0.95) * 1e3, 3),
        "e2e_p99_ms": round(e2e_digest.quantile(0.99) * 1e3, 3),
        "relative_error": relative_error,
    }


def print_traffic_report(tr: Dict[str, Any], out=sys.stdout) -> None:
    print(
        f"traffic tiers ({tr['traffic_traces']} traces, max attribution "
        f"error {tr['max_sum_err_s'] * 1e6:.3f}us):",
        file=out,
    )
    for tier, row in sorted(
        tr["tiers"].items(), key=lambda kv: -kv[1]["share"]
    ):
        print(
            f"  {tier:<16} {100 * row['share']:5.1f}%  "
            f"p50={row['p50_ms']:.2f}ms p95={row['p95_ms']:.2f}ms "
            f"p99={row['p99_ms']:.2f}ms  (n={row['count']})",
            file=out,
        )
    print(f"bottleneck tier: {tr['bottleneck_tier']}", file=out)


def build_report(trace_dir: str, top: int = 5) -> Dict[str, Any]:
    spans, offsets = load_dir(trace_dir)
    traces = build_traces(spans)
    orphan_spans = sum(len(t["orphans"]) for t in traces.values())
    # completeness: every root-named lifecycle must reach its leaf edge
    seq_traces = incomplete = 0
    for t in traces.values():
        root = t["root"]
        leaf = root is not None and COMPLETENESS.get(root["name"])
        if not leaf:
            continue
        seq_traces += 1
        if not any(s["name"] == leaf for s in t["spans"]):
            incomplete += 1
    # per-trace edge attribution + the queue/compute/wire rollup
    per_trace: List[Dict[str, Any]] = []
    agg_edges: Dict[str, float] = {}
    agg_classes: Dict[str, float] = {}
    for tid, t in traces.items():
        edges = attribute_edges(t)
        for name, dur in edges.items():
            agg_edges[name] = agg_edges.get(name, 0.0) + dur
            cls = "untracked" if name == "untracked" else classify(name)
            agg_classes[cls] = agg_classes.get(cls, 0.0) + dur
        per_trace.append(
            {
                "trace": tid,
                "name": t["root"]["name"] if t["root"] else "<orphaned>",
                "e2e_ms": t["e2e"] * 1e3,
                "edges": edges,
                "edge_sum_ms": sum(edges.values()) * 1e3,
            }
        )
    per_trace.sort(key=lambda r: r["e2e_ms"], reverse=True)
    total = sum(agg_classes.values()) or 1.0
    e2es = sorted(t["e2e"] for t in traces.values())
    return {
        "dir": trace_dir,
        "spans": len(spans),
        "traces": traces,
        "top_traces": per_trace[:top],
        "agg_edges": agg_edges,
        "agg_classes": agg_classes,
        "class_fractions": {
            k: v / total for k, v in sorted(agg_classes.items())
        },
        "skew_offsets": offsets,
        "verdict": {
            "metric": "trace_report",
            "spans": len(spans),
            "traces": len(traces),
            "sequence_traces": seq_traces,
            "complete_sequences": seq_traces - incomplete,
            "incomplete": incomplete,
            "orphan_spans": orphan_spans,
            "tracked_fraction": round(
                1.0 - agg_classes.get("untracked", 0.0) / total, 4
            ),
            "p50_e2e_ms": round(e2es[len(e2es) // 2] * 1e3, 3)
            if e2es
            else 0.0,
            "max_e2e_ms": round(e2es[-1] * 1e3, 3) if e2es else 0.0,
        },
    }


def write_chrome(report: Dict[str, Any], path: str) -> str:
    """Chrome/Perfetto ``trace_event`` JSON: complete ("X") events, host as
    pid, trace as tid — load in chrome://tracing or ui.perfetto.dev."""
    t_base = min(
        (t["t0"] for t in report["traces"].values()), default=0.0
    )
    events = []
    for tid, t in report["traces"].items():
        for s in t["spans"]:
            events.append(
                {
                    "ph": "X",
                    "name": s["name"],
                    "cat": s.get("kind") or "span",
                    "pid": str(s.get("host", "?")),
                    "tid": tid,
                    "ts": round((float(s["t0"]) - t_base) * 1e6, 1),
                    "dur": round(float(s["dur"]) * 1e6, 1),
                    "args": dict(s.get("attrs") or {}, span=s["span"]),
                }
            )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


def print_report(report: Dict[str, Any], out=sys.stdout) -> None:
    v = report["verdict"]
    print(
        f"trace report: {v['spans']} spans, {v['traces']} traces "
        f"({v['sequence_traces']} sequence lifecycles, "
        f"{v['complete_sequences']} complete, {v['orphan_spans']} orphan "
        "spans)",
        file=out,
    )
    if report["skew_offsets"]:
        print(
            "clock-skew correction applied: "
            + ", ".join(
                f"{h}={o * 1e3:+.3f}ms"
                for h, o in sorted(report["skew_offsets"].items())
            ),
            file=out,
        )
    print("wall-clock attribution (all traces):", file=out)
    for cls, frac in sorted(
        report["class_fractions"].items(), key=lambda kv: -kv[1]
    ):
        print(
            f"  {cls:<10} {100 * frac:5.1f}%  "
            f"({report['agg_classes'][cls] * 1e3:.1f} ms)",
            file=out,
        )
    print("top traces by end-to-end latency:", file=out)
    for r in report["top_traces"]:
        edges = "  ".join(
            f"{name}={dur * 1e3:.1f}ms"
            for name, dur in sorted(
                r["edges"].items(), key=lambda kv: -kv[1]
            )
        )
        print(
            f"  {r['name']}[{r['trace'][:8]}] e2e={r['e2e_ms']:.1f}ms "
            f"(edges sum {r['edge_sum_ms']:.1f}ms): {edges}",
            file=out,
        )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("trace_dir", help="directory of spans_*.jsonl files")
    parser.add_argument(
        "--chrome",
        default=None,
        help="trace_event JSON output path (default <dir>/trace_events.json)",
    )
    parser.add_argument("--top", type=int, default=5)
    parser.add_argument(
        "--traffic",
        action="store_true",
        help="also run the tier-attribution walk over traffic traces and "
        "emit a traffic_report verdict line",
    )
    parser.add_argument(
        "--relative-error",
        type=float,
        default=0.01,
        help="digest quantile relative-error bound for --traffic",
    )
    args = parser.parse_args(argv)

    report = build_report(args.trace_dir, top=args.top)
    chrome = args.chrome or os.path.join(args.trace_dir, "trace_events.json")
    report["verdict"]["chrome"] = write_chrome(report, chrome)
    print_report(report)
    if args.traffic:
        traffic = build_traffic_report(
            report["traces"], relative_error=args.relative_error
        )
        print_traffic_report(traffic)
        print(json.dumps(traffic), flush=True)
    # the gate line LAST: tpu_watch scans for the newest matching object
    print(json.dumps(report["verdict"]), flush=True)
    ok = (
        report["verdict"]["orphan_spans"] == 0
        and report["verdict"]["incomplete"] == 0
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
