"""Trace merge + critical-path analyzer for the distributed tracer.

Input: a directory of per-host span files (``spans_<host>_<pid>.jsonl``)
written by ``scalerl_tpu/runtime/tracing.py`` — one JSON object per line:
span records, one ``meta`` line per file, and optional ``skew`` lines
carrying the writer's per-peer clock offsets (estimated off heartbeat
ping/pong RTTs).  Output, in one pass:

1. **merged trace trees** — spans grouped by trace id, skew-corrected onto
   the observer's clock, roots identified, orphans counted (a span whose
   parent id is absent from its trace — the completeness failure mode a
   lost host file produces);
2. **Chrome/Perfetto ``trace_event`` JSON** (``--chrome``, default
   ``<dir>/trace_events.json``) — one ``ph: "X"`` complete event per span,
   ``pid`` = host, ``tid`` = trace, so chrome://tracing renders each
   sequence lifecycle as one row spanning generation host -> learner;
3. a **critical-path breakdown** — top traces by duration with per-edge
   attribution, plus the aggregate % of traced wall-clock spent on
   queue-wait vs compute vs wire.  Attribution walks each trace's
   timeline from root start to last span end, charging every interval to
   the span covering it (ties: the later-starting span) or to
   ``untracked`` — so per-edge durations sum to the end-to-end latency
   EXACTLY, and the report can never double-count overlap.

The last stdout line is a one-line JSON verdict
(``{"metric": "trace_report", ...}``) that ``tools/tpu_watch.py`` gates
its trace-soak step on: ``sequence_traces`` vs ``complete_sequences``
(root -> learn_step present) and ``orphan_spans``.

jax-free, stdlib-only: runs anywhere the soak ran.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# edge-name -> cost class for the queue/compute/wire rollup
EDGE_CLASSES = {
    "seq.queue_wait": "queue",
    "seq.replay_wait": "queue",
    "serve.queue_wait": "queue",
    "seq.decode": "compute",
    "seq.seq_add": "compute",
    "seq.learn_step": "compute",
    "serve.flush": "compute",
    "task.episode": "compute",
    "genrl.macro_step": "compute",
    "genrl.generate_round": "compute",
    "round.generate": "compute",
    "round.seq_add": "compute",
    "round.learn": "compute",
    "seq.upload": "wire",
    "snapshot.fetch": "wire",
    "snapshot_publish": "wire",
    "serve.request": "wire",
}

# roots whose traces the completeness verdict inspects, and the leaf edge
# that must be present for the lifecycle to count as complete
COMPLETENESS = {"sequence": "seq.learn_step"}


def classify(name: str) -> str:
    return EDGE_CLASSES.get(name, "other")


def load_dir(trace_dir: str) -> Tuple[List[Dict], Dict[str, float]]:
    """All span records in ``trace_dir``, skew-corrected.

    Skew lines carry ``offsets[peer] = peer_wall - observer_wall`` as
    measured by the writing host; the host with the most measured peers
    (the learner — it pings everyone) becomes the reference, and every
    measured peer's spans shift by ``-offset`` onto its clock.  Files
    without skew data pass through untouched (same-machine soaks).
    """
    spans: List[Dict] = []
    skew_by_observer: Dict[str, Dict[str, float]] = {}
    for path in sorted(glob.glob(os.path.join(trace_dir, "spans_*.jsonl"))):
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue  # a torn last line from a SIGTERM'd host
                if "span" in obj:
                    spans.append(obj)
                elif obj.get("kind") == "skew":
                    skew_by_observer.setdefault(
                        str(obj.get("host")), {}
                    ).update(obj.get("offsets") or {})
    offsets: Dict[str, float] = {}
    if skew_by_observer:
        reference = max(
            skew_by_observer, key=lambda h: len(skew_by_observer[h])
        )
        offsets = dict(skew_by_observer[reference])
        offsets.pop(reference, None)
    for s in spans:
        off = offsets.get(str(s.get("host")))
        if off:
            s["t0"] = float(s["t0"]) - off
    return spans, offsets


def build_traces(spans: List[Dict]) -> Dict[str, Dict[str, Any]]:
    """Group spans by trace id; identify each trace's root and orphans."""
    traces: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        traces.setdefault(s["trace"], {"spans": []})["spans"].append(s)
    for t in traces.values():
        ids = {s["span"] for s in t["spans"]}
        t["root"] = next(
            (s for s in t["spans"] if not s.get("parent")), None
        )
        t["orphans"] = [
            s for s in t["spans"]
            if s.get("parent") and s["parent"] not in ids
        ]
        t0 = min(float(s["t0"]) for s in t["spans"])
        t1 = max(float(s["t0"]) + float(s["dur"]) for s in t["spans"])
        if t["root"] is not None:
            t0 = min(t0, float(t["root"]["t0"]))
        t["t0"], t["t1"] = t0, t1
        t["e2e"] = max(t1 - t0, 0.0)
    return traces


def attribute_edges(trace: Dict[str, Any]) -> Dict[str, float]:
    """Charge every interval of [trace start, trace end] to exactly one
    edge (or ``untracked``): walk the child spans in start order, clip to
    the un-attributed suffix, fill holes with ``untracked``.  The values
    sum to ``e2e`` by construction."""
    edges: Dict[str, float] = {}
    start, end = trace["t0"], trace["t1"]
    root = trace["root"]
    children = sorted(
        (
            s for s in trace["spans"]
            if root is None or s["span"] != root["span"]
        ),
        key=lambda s: float(s["t0"]),
    )
    cursor = start
    for s in children:
        s0 = max(float(s["t0"]), cursor)
        s1 = min(float(s["t0"]) + float(s["dur"]), end)
        if s0 > cursor:
            edges["untracked"] = edges.get("untracked", 0.0) + (s0 - cursor)
            cursor = s0
        if s1 > cursor:
            edges[s["name"]] = edges.get(s["name"], 0.0) + (s1 - cursor)
            cursor = s1
    if end > cursor:
        edges["untracked"] = edges.get("untracked", 0.0) + (end - cursor)
    return edges


def build_report(trace_dir: str, top: int = 5) -> Dict[str, Any]:
    spans, offsets = load_dir(trace_dir)
    traces = build_traces(spans)
    orphan_spans = sum(len(t["orphans"]) for t in traces.values())
    # completeness: every root-named lifecycle must reach its leaf edge
    seq_traces = incomplete = 0
    for t in traces.values():
        root = t["root"]
        leaf = root is not None and COMPLETENESS.get(root["name"])
        if not leaf:
            continue
        seq_traces += 1
        if not any(s["name"] == leaf for s in t["spans"]):
            incomplete += 1
    # per-trace edge attribution + the queue/compute/wire rollup
    per_trace: List[Dict[str, Any]] = []
    agg_edges: Dict[str, float] = {}
    agg_classes: Dict[str, float] = {}
    for tid, t in traces.items():
        edges = attribute_edges(t)
        for name, dur in edges.items():
            agg_edges[name] = agg_edges.get(name, 0.0) + dur
            cls = "untracked" if name == "untracked" else classify(name)
            agg_classes[cls] = agg_classes.get(cls, 0.0) + dur
        per_trace.append(
            {
                "trace": tid,
                "name": t["root"]["name"] if t["root"] else "<orphaned>",
                "e2e_ms": t["e2e"] * 1e3,
                "edges": edges,
                "edge_sum_ms": sum(edges.values()) * 1e3,
            }
        )
    per_trace.sort(key=lambda r: r["e2e_ms"], reverse=True)
    total = sum(agg_classes.values()) or 1.0
    e2es = sorted(t["e2e"] for t in traces.values())
    return {
        "dir": trace_dir,
        "spans": len(spans),
        "traces": traces,
        "top_traces": per_trace[:top],
        "agg_edges": agg_edges,
        "agg_classes": agg_classes,
        "class_fractions": {
            k: v / total for k, v in sorted(agg_classes.items())
        },
        "skew_offsets": offsets,
        "verdict": {
            "metric": "trace_report",
            "spans": len(spans),
            "traces": len(traces),
            "sequence_traces": seq_traces,
            "complete_sequences": seq_traces - incomplete,
            "incomplete": incomplete,
            "orphan_spans": orphan_spans,
            "tracked_fraction": round(
                1.0 - agg_classes.get("untracked", 0.0) / total, 4
            ),
            "p50_e2e_ms": round(e2es[len(e2es) // 2] * 1e3, 3)
            if e2es
            else 0.0,
            "max_e2e_ms": round(e2es[-1] * 1e3, 3) if e2es else 0.0,
        },
    }


def write_chrome(report: Dict[str, Any], path: str) -> str:
    """Chrome/Perfetto ``trace_event`` JSON: complete ("X") events, host as
    pid, trace as tid — load in chrome://tracing or ui.perfetto.dev."""
    t_base = min(
        (t["t0"] for t in report["traces"].values()), default=0.0
    )
    events = []
    for tid, t in report["traces"].items():
        for s in t["spans"]:
            events.append(
                {
                    "ph": "X",
                    "name": s["name"],
                    "cat": s.get("kind") or "span",
                    "pid": str(s.get("host", "?")),
                    "tid": tid,
                    "ts": round((float(s["t0"]) - t_base) * 1e6, 1),
                    "dur": round(float(s["dur"]) * 1e6, 1),
                    "args": dict(s.get("attrs") or {}, span=s["span"]),
                }
            )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


def print_report(report: Dict[str, Any], out=sys.stdout) -> None:
    v = report["verdict"]
    print(
        f"trace report: {v['spans']} spans, {v['traces']} traces "
        f"({v['sequence_traces']} sequence lifecycles, "
        f"{v['complete_sequences']} complete, {v['orphan_spans']} orphan "
        "spans)",
        file=out,
    )
    if report["skew_offsets"]:
        print(
            "clock-skew correction applied: "
            + ", ".join(
                f"{h}={o * 1e3:+.3f}ms"
                for h, o in sorted(report["skew_offsets"].items())
            ),
            file=out,
        )
    print("wall-clock attribution (all traces):", file=out)
    for cls, frac in sorted(
        report["class_fractions"].items(), key=lambda kv: -kv[1]
    ):
        print(
            f"  {cls:<10} {100 * frac:5.1f}%  "
            f"({report['agg_classes'][cls] * 1e3:.1f} ms)",
            file=out,
        )
    print("top traces by end-to-end latency:", file=out)
    for r in report["top_traces"]:
        edges = "  ".join(
            f"{name}={dur * 1e3:.1f}ms"
            for name, dur in sorted(
                r["edges"].items(), key=lambda kv: -kv[1]
            )
        )
        print(
            f"  {r['name']}[{r['trace'][:8]}] e2e={r['e2e_ms']:.1f}ms "
            f"(edges sum {r['edge_sum_ms']:.1f}ms): {edges}",
            file=out,
        )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("trace_dir", help="directory of spans_*.jsonl files")
    parser.add_argument(
        "--chrome",
        default=None,
        help="trace_event JSON output path (default <dir>/trace_events.json)",
    )
    parser.add_argument("--top", type=int, default=5)
    args = parser.parse_args(argv)

    report = build_report(args.trace_dir, top=args.top)
    chrome = args.chrome or os.path.join(args.trace_dir, "trace_events.json")
    report["verdict"]["chrome"] = write_chrome(report, chrome)
    print_report(report)
    # the gate line LAST: tpu_watch scans for the newest matching object
    print(json.dumps(report["verdict"]), flush=True)
    ok = (
        report["verdict"]["orphan_spans"] == 0
        and report["verdict"]["incomplete"] == 0
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
