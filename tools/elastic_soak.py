"""Elastic-fleet soak: a seeded preemption wave against a live fleet.

The tpu_watch ``elastic-soak`` payload step (non-quorum, like the chaos
soak): run a short pipe fleet through a seeded ``mass_kill`` wave with the
autoscaler backfilling, then emit a one-line JSON verdict the watcher gates
on — ``lost`` episodes (exact unique accounting over the PR 4 dedup keys +
task-level requeue) and ``decisions_per_min`` (autoscaler flap rate).

jax-free on purpose: the driver exercises the fleet/autoscaler planes only,
so gathers fork cheaply and the soak stays bounded (~1 min) even on a
tunnel-down CI host.

Run: ``python tools/elastic_soak.py`` (options below).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scalerl_tpu.fleet import ClusterExecutor, FleetConfig, LocalCluster, WorkerServer
from scalerl_tpu.runtime import chaos, telemetry
from scalerl_tpu.runtime.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    fleet_signal_source,
)


def _soak_runner(task, weights, worker_id):
    """Module-level (spawn/fork-picklable): a short fake episode whose
    payload is just its seed — uniqueness accounting needs nothing more."""
    time.sleep(0.2)
    return {"seed": int(task.get("seed", 0))}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tasks", type=int, default=96)
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--workers-per-gather", type=int, default=1)
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument("--kills", type=int, default=0,
                        help="victims per wave (0 = half the gathers)")
    parser.add_argument("--deadline-s", type=float, default=240.0)
    args = parser.parse_args()

    # seeded wave: ~30% chance per supervisor poll (0.5 s cadence), capped at
    # one wave — it lands a couple of seconds into the run, mid-stream
    os.environ.setdefault(
        chaos.ENV_VAR, f"{args.seed}:mass_kill=0.5@1,kills={args.kills}"
    )
    chaos.clear()

    n_tasks = args.tasks
    counter = {"i": 0}
    lock = threading.Lock()

    def source():
        with lock:
            if counter["i"] >= n_tasks:
                return None
            counter["i"] += 1
            return {"role": "rollout", "seed": counter["i"]}

    config = FleetConfig(
        num_workers=args.workers,
        workers_per_gather=args.workers_per_gather,
        upload_batch=1,
        heartbeat_interval_s=0.5,
    )
    server = WorkerServer(config, source)
    server.start(listen=False)
    # max_restarts=0: the AUTOSCALER (floor rule), not the respawn budget,
    # must backfill the wave — that is the property this soak certifies.
    # spawn, not fork: the parent is heavily threaded (hub pumps, autoscaler,
    # supervisor) and forked children inherit held locks and every live pipe
    # fd — a SIGTERMed gather's workers then never see EOF and linger as
    # orphans on the CI host
    cluster = LocalCluster(server, config, _soak_runner, mp_context="spawn",
                           max_restarts=0)
    cluster.start()
    autoscaler = Autoscaler(
        AutoscalerConfig(
            min_workers=args.workers,
            max_workers=2 * args.workers,
            interval_s=0.25,
            cooldown_s=1.0,
            up_hysteresis=1,
            down_hysteresis=2,
            # floor backfill is the property under test: disable the
            # starved rule (a drain-to-verdict consumer keeps occupancy at
            # 0 permanently, which would just push the fleet to max)
            low_occupancy=-1.0,
        ),
        executor=ClusterExecutor(server, cluster),
        signal_source=fleet_signal_source(server),
    ).start()

    t0 = time.monotonic()
    results = []
    try:
        deadline = t0 + args.deadline_s
        while len(results) < n_tasks and time.monotonic() < deadline:
            r = server.get_result(timeout=0.2)
            if r is not None:
                results.append(r)
    finally:
        autoscaler.stop()
        cluster.join()
        server.stop()

    elapsed = time.monotonic() - t0
    seeds = [r.get("seed") for r in results]
    unique = len(set(seeds))
    mass_kills = telemetry.get_recorder().events("mass_kill")
    killed = sum(len(e.get("victims", [])) for e in mass_kills)
    actions = autoscaler.scale_ups + autoscaler.scale_downs
    # rate over at least a minute: a 10 s run with one backfill is not a
    # "6/min" flap, it is one action
    rate_window_min = max(elapsed, 60.0) / 60.0
    verdict = {
        "metric": "elastic_soak",
        "expected": n_tasks,
        "received": len(results),
        "unique": unique,
        "lost": n_tasks - unique,
        # duplicates that REACHED the consumer (must be 0: the dedup layers
        # absorb redelivery); absorbed ones are the dedup working as designed
        "duplicates": len(results) - unique,
        "absorbed_duplicates": server.duplicate_results + server.duplicate_tasks,
        "requeued_tasks": server.requeued_tasks,
        "gathers_killed": killed,
        "waves": len(mass_kills),
        "scale_ups": autoscaler.scale_ups,
        "scale_downs": autoscaler.scale_downs,
        "decisions_per_min": round(actions / rate_window_min, 2),
        "elapsed_s": round(elapsed, 1),
        "chaos": os.environ.get(chaos.ENV_VAR, ""),
    }
    print(json.dumps(verdict), flush=True)
    # the soak proves nothing unless the wave landed AND no episode was lost
    ok = verdict["lost"] == 0 and killed > 0 and autoscaler.scale_ups >= 1
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
