"""Disaggregated-dataflow soak: a seeded preemption wave mid-decode.

The tpu_watch ``disagg-soak`` payload step (non-quorum, like the chaos and
elastic soaks): a jax-free pipe fleet of 2 generation hosts (scripted
engines — deterministic payloads, so bit-exactness is checkable) streams
sequences into a :class:`SequenceLearner`; a seeded ``mass_kill`` wave
SIGTERMs half the hosts while lanes are mid-decode, and the autoscaler's
floor rule backfills.  One JSON verdict line gates the step: ``lost``
sequences (exact unique accounting over the lease ids + the
(host, epoch, seq) dedup keys), consumer-visible ``duplicates``, and
``payload_mismatches`` (every accepted byte re-derived from the lease seed).

jax-free on purpose: the generation hosts are spawn children that never
import jax, so the soak stays bounded (~1 min) even on a tunnel-down CI
host while still exercising the full wire/lease/ack/drain machinery.

Run: ``python tools/disagg_soak.py`` (options below).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from scalerl_tpu.genrl.disagg import (
    DisaggConfig,
    GenerationTierExecutor,
    LocalGenerationFleet,
    ScriptedEngineFactory,
    SequenceLearner,
    disagg_signal_source,
    scripted_sequence_payload,
)
from scalerl_tpu.genrl.disagg import record_consumption_trace
from scalerl_tpu.runtime import chaos, telemetry, tracing
from scalerl_tpu.runtime.autoscaler import Autoscaler, AutoscalerConfig

RESPONSE_LEN = 8
VOCAB = 32
LEARN_BATCH = 8  # pseudo learn-round size for the traced consumption loop


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--leases", type=int, default=96)
    parser.add_argument("--hosts", type=int, default=2)
    parser.add_argument("--lanes", type=int, default=2)
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument("--kills", type=int, default=0,
                        help="victims per wave (0 = half the hosts)")
    parser.add_argument("--warmup", type=int, default=6,
                        help="sequences collected before the wave lands")
    parser.add_argument("--deadline-s", type=float, default=240.0)
    parser.add_argument(
        "--trace-dir", default="",
        help="arm SCALERL_TRACE_SAMPLE=1.0 + per-host span export, then "
        "run tools/trace_report.py over the merged files (the tpu_watch "
        "trace-soak step): every completed sequence must yield one "
        "root-to-learn-step trace with zero orphan spans",
    )
    args = parser.parse_args()

    # the wave fires on the FIRST chaos_poll draw (rate 1.0@1) — the soak
    # lands it deliberately after warmup, so the kill is provably
    # mid-decode rather than mid-boot
    os.environ.setdefault(
        chaos.ENV_VAR, f"{args.seed}:mass_kill=1.0@1,kills={args.kills}"
    )
    chaos.clear()

    if args.trace_dir:
        # spawn children inherit the env, so every generation host samples
        # at 1.0 and appends spans to its own file as they finish (a
        # SIGTERM'd host loses at most the line in flight)
        os.makedirs(args.trace_dir, exist_ok=True)
        for stale in os.listdir(args.trace_dir):
            if stale.startswith("spans_") or stale == "trace_events.json":
                os.unlink(os.path.join(args.trace_dir, stale))
        os.environ[tracing.ENV_SAMPLE] = "1.0"
        os.environ[tracing.ENV_DIR] = args.trace_dir
        tracing.reset()

    n = args.leases
    counter = {"i": 0}
    lock = threading.Lock()

    def source():
        with lock:
            if counter["i"] >= n:
                return None
            counter["i"] += 1
            return {"seed": counter["i"], "length": 4}

    cfg = DisaggConfig(
        num_hosts=args.hosts,
        lanes_per_host=args.lanes,
        upload_batch=1,
        heartbeat_interval_s=0.5,
    )
    learner = SequenceLearner(cfg, source)
    learner.start()
    rng = np.random.default_rng(0)
    weights = {"w": rng.standard_normal((32, 32)).astype(np.float32)}
    learner.publish(weights, learner_step=0)
    # slow scripted decode (one token per step + a sleep) so sequences are
    # genuinely in flight when the wave lands.  spawn, not fork: a
    # SIGTERMed fork child inherits live pipe fds and lingers (the
    # elastic_soak verdict); spawn children boot in well under a second
    # because the shells never import jax.
    fleet = LocalGenerationFleet(
        learner,
        cfg,
        ScriptedEngineFactory(
            lanes=args.lanes,
            response_len=RESPONSE_LEN,
            tokens_per_step=1,
            step_sleep_s=0.02,
            vocab=VOCAB,
        ),
        mp_context="spawn",
        auto_chaos=False,  # the soak times the wave itself (post-warmup)
    )
    fleet.start()
    # max restarts are nobody's job here: the AUTOSCALER's floor rule must
    # backfill the wave — that is the property this soak certifies
    autoscaler = Autoscaler(
        AutoscalerConfig(
            min_workers=args.hosts,
            max_workers=2 * args.hosts,
            interval_s=0.25,
            cooldown_s=1.0,
            up_hysteresis=1,
            down_hysteresis=2,
            low_occupancy=-1.0,  # floor backfill only (see elastic_soak)
        ),
        executor=GenerationTierExecutor(learner, fleet),
        signal_source=disagg_signal_source(learner),
    ).start()

    t0 = time.monotonic()
    seqs = []
    killed = []
    pending_learn = []
    learn_steps = 0

    def pseudo_learn(batch) -> None:
        # the soak is jax-free, so the "learn step" is a stamp-only twin of
        # DisaggSequenceRLTrainer's: the same record_consumption_trace call
        # with monotonic stamps around the (trivial) consumption work —
        # every accepted sequence's trace still ends in seq.learn_step
        nonlocal learn_steps
        learn_steps += 1
        now = time.monotonic()
        record_consumption_trace(
            batch, now, now, now, now, time.monotonic(), learn_steps
        )

    try:
        deadline = t0 + args.deadline_s
        while len(seqs) < n and time.monotonic() < deadline:
            s = learner.get_sequence(timeout=0.2)
            if s is not None:
                seqs.append(s)
                if args.trace_dir:
                    pending_learn.append(s)
                    if len(pending_learn) >= LEARN_BATCH:
                        pseudo_learn(pending_learn)
                        pending_learn = []
            if not killed and len(seqs) >= args.warmup:
                # the seeded wave: half the generation hosts, mid-decode
                killed = fleet.chaos_poll()
        if args.trace_dir and pending_learn:
            pseudo_learn(pending_learn)
    finally:
        autoscaler.stop()
        learner.stop()
        fleet.join()

    elapsed = time.monotonic() - t0
    lease_ids = [s.get("lease_id") for s in seqs]
    unique = len(set(lease_ids))
    mismatches = 0
    for s in seqs:
        expect = scripted_sequence_payload(
            s["seed"], RESPONSE_LEN, VOCAB, s["generation"]
        )
        for key in ("prompt", "response_tokens", "behavior_logp", "values"):
            if not np.array_equal(s[key], expect[key]):
                mismatches += 1
                break
    waves = telemetry.get_recorder().events("mass_kill")
    verdict = {
        "metric": "disagg_soak",
        "expected": n,
        "received": len(seqs),
        "unique": unique,
        "lost": n - unique,
        # duplicates that REACHED the consumer (must be 0: the dedup
        # layers absorb redelivery); absorbed ones are the design working
        "duplicates": len(seqs) - unique,
        "payload_mismatches": mismatches,
        "absorbed_duplicates": learner.duplicate_sequences
        + learner.duplicate_leases,
        "requeued_leases": learner.requeued_leases,
        "hosts_killed": len(killed),
        "waves": len(waves),
        "scale_ups": autoscaler.scale_ups,
        "scale_downs": autoscaler.scale_downs,
        "snapshot_wire_bytes": learner.snapshot_wire_bytes,
        "elapsed_s": round(elapsed, 1),
        "chaos": os.environ.get(chaos.ENV_VAR, ""),
    }
    print(json.dumps(verdict), flush=True)
    ok = (
        verdict["lost"] == 0
        and verdict["duplicates"] == 0
        and verdict["payload_mismatches"] == 0
        and len(killed) > 0
        and autoscaler.scale_ups >= 1
    )
    if args.trace_dir:
        # merge the per-host span files and gate on trace completeness:
        # every accepted sequence must have one root-to-learn-step trace
        # with zero orphan spans (the tpu_watch !trace(...) marker reads
        # the trace_report verdict line printed here)
        tracing.export_skew()
        from tools.trace_report import build_report, print_report, write_chrome

        report = build_report(args.trace_dir)
        tv = report["verdict"]
        tv["chrome"] = write_chrome(
            report, os.path.join(args.trace_dir, "trace_events.json")
        )
        tv["expected_sequences"] = len(seqs)
        print_report(report)
        print(json.dumps(tv), flush=True)
        ok = ok and (
            tv["orphan_spans"] == 0
            and tv["incomplete"] == 0
            and tv["sequence_traces"] >= len(seqs)
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
