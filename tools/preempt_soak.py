"""Preemption soak: kill the learner mid-decode, restart it, close the ledger.

The tpu_watch ``preempt-soak`` payload step (non-quorum, like the chaos and
disagg soaks): a jax-free THREAD fleet of generation hosts (scripted
engines — deterministic payloads, so bit-exactness is checkable) streams
sequences into a :class:`SequenceLearner` backed by a durable ledger.  A
seeded ``preempt`` draw (the :class:`PreemptionGuard` chaos hook — the same
code path the trainer's learn loop polls) trips mid-consume; the soak runs
the save-and-exit protocol (stop serving, ``save_ledger``), boots a SECOND
learner from the ledger (epoch + 1), and points the fleet's reconnect seam
at it.  Surviving hosts park their in-flight work, redial with capped
backoff, re-handshake via ``gen_welcome``, and resend retained uploads into
the restored dedup tables.

One JSON verdict line gates the step: the ledger must close EXACTLY —
``lost == 0`` (every issued lease's sequence reached the consumer once),
``duplicates == 0`` (consumer-visible; absorbed redelivery is the design
working), ``payload_mismatches == 0`` (every accepted byte re-derived from
the lease seed), ``orphaned_leases == 0`` after the drain, and the restarted
learner's epoch is the predecessor's + 1.

jax-free on purpose: thread-mode hosts never touch jax, so the soak stays
bounded (~1 min) even on a tunnel-down CI host while still exercising the
full ledger/epoch/reconnect machinery.

Run: ``python tools/preempt_soak.py`` (options below).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from scalerl_tpu.genrl.disagg import (
    DisaggConfig,
    LocalGenerationFleet,
    ScriptedEngineFactory,
    SequenceLearner,
    scripted_sequence_payload,
)
from scalerl_tpu.runtime import chaos, telemetry
from scalerl_tpu.runtime.supervisor import PreemptionGuard

RESPONSE_LEN = 8
VOCAB = 32


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--leases", type=int, default=72)
    parser.add_argument("--hosts", type=int, default=2)
    parser.add_argument("--lanes", type=int, default=2)
    parser.add_argument("--seed", type=int, default=1234)
    parser.add_argument("--warmup", type=int, default=10,
                        help="sequences consumed before the guard may trip")
    parser.add_argument("--deadline-s", type=float, default=240.0)
    parser.add_argument("--ledger-dir", default="",
                        help="ledger directory (default: a fresh tempdir)")
    args = parser.parse_args()

    # the preempt draw fires on the FIRST guard poll (rate 1.0@1) — the
    # soak polls deliberately after warmup, so the kill is provably
    # mid-decode (open leases, queued sequences) rather than mid-boot
    os.environ.setdefault(chaos.ENV_VAR, f"{args.seed}:preempt=1.0@1")
    chaos.clear()

    scratch = args.ledger_dir or tempfile.mkdtemp(prefix="preempt_soak_")
    ledger_path = os.path.join(scratch, "learner_ledger")

    n = args.leases
    counter = {"i": 0}
    lock = threading.Lock()

    def source():
        with lock:
            if counter["i"] >= n:
                return None
            counter["i"] += 1
            return {"seed": counter["i"], "length": 4}

    cfg = DisaggConfig(
        num_hosts=args.hosts,
        lanes_per_host=args.lanes,
        upload_batch=1,
        heartbeat_interval_s=0.5,
    )
    learner = SequenceLearner(cfg, source, ledger_path=ledger_path)
    learner.start()
    rng = np.random.default_rng(0)
    weights = {"w": rng.standard_normal((32, 32)).astype(np.float32)}
    learner.publish(weights, learner_step=0)
    # slow scripted decode (one token per step + a sleep) so leases are
    # genuinely open when the preemption lands.  Thread-mode hosts: the
    # reconnect seam (fleet._dial) is how survivors re-join the restarted
    # learner — the exact elastic-membership path the docs diagram.
    fleet = LocalGenerationFleet(
        learner,
        cfg,
        ScriptedEngineFactory(
            lanes=args.lanes,
            response_len=RESPONSE_LEN,
            tokens_per_step=1,
            step_sleep_s=0.02,
            vocab=VOCAB,
        ),
        use_threads=True,
        auto_chaos=False,  # the guard poll times the kill itself
    )
    fleet.start()

    guard = PreemptionGuard()  # not installed: threads simulate the signal
    t0 = time.monotonic()
    seqs = []
    preempted_at = -1
    epoch_before = learner.learner_epoch
    restarted = None

    try:
        deadline = t0 + args.deadline_s
        while len(seqs) < n and time.monotonic() < deadline:
            active = restarted if restarted is not None else learner
            s = active.get_sequence(timeout=0.2)
            if s is not None:
                seqs.append(s)
            if restarted is None and len(seqs) >= args.warmup:
                if guard.poll_chaos("learner"):
                    # save-and-exit, exactly the trainer's protocol: stop
                    # serving (hosts lose their uplink and start parking),
                    # persist the full plane, boot the successor from the
                    # ledger, then hand the reconnect seam the new learner
                    preempted_at = len(seqs)
                    learner.stop()
                    learner.save_ledger()
                    restarted = SequenceLearner(
                        cfg, source, ledger_path=ledger_path
                    )
                    restarted.start()
                    fleet.adopt_learner(restarted)
    finally:
        for ln in (learner, restarted):
            if ln is not None:
                ln.stop()
        fleet.join()

    elapsed = time.monotonic() - t0
    lease_ids = [s.get("lease_id") for s in seqs]
    unique = len(set(lease_ids))
    mismatches = 0
    for s in seqs:
        expect = scripted_sequence_payload(
            s["seed"], RESPONSE_LEN, VOCAB, s["generation"]
        )
        for key in ("prompt", "response_tokens", "behavior_logp", "values"):
            if not np.array_equal(s[key], expect[key]):
                mismatches += 1
                break
    post = restarted if restarted is not None else learner
    orphaned = len(post._outstanding)
    resumes = telemetry.get_recorder().events("preemption_resume")
    verdict = {
        "metric": "preempt_soak",
        "expected": n,
        "received": len(seqs),
        "unique": unique,
        "lost": n - unique,
        # duplicates that REACHED the consumer (must be 0: the restored
        # dedup watermarks + completed-lease table absorb redelivery)
        "duplicates": len(seqs) - unique,
        "payload_mismatches": mismatches,
        "orphaned_leases": orphaned,
        "preempted_at": preempted_at,
        "reissued": post.resumed_sequences_reissued,
        "resume_duplicates_dropped": post.resumed_duplicates_dropped,
        "absorbed_duplicates": post.duplicate_sequences
        + post.duplicate_leases,
        "epoch": post.learner_epoch,
        "epoch_bumped": post.learner_epoch == epoch_before + 1,
        "resume_events": len(resumes),
        "ledger_balanced": (
            n - unique == 0 and len(seqs) - unique == 0 and orphaned == 0
        ),
        "elapsed_s": round(elapsed, 1),
        "chaos": os.environ.get(chaos.ENV_VAR, ""),
    }
    print(json.dumps(verdict), flush=True)
    if not args.ledger_dir:
        shutil.rmtree(scratch, ignore_errors=True)
    ok = (
        verdict["ledger_balanced"]
        and verdict["payload_mismatches"] == 0
        and verdict["epoch_bumped"]
        and restarted is not None
        and verdict["resume_events"] >= 1
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
