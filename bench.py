"""Headline benchmark: IMPALA Atari-shaped env-frames/sec on one chip.

Runs the flagship path — the fully-fused on-device actor-learner loop
(``scalerl_tpu/runtime/device_loop.py``: env step + AtariNet forward +
action sample + V-trace learner update, all one XLA program) — on the
synthetic Atari-shaped pixel env at real frame shapes ``[84, 84, 4]``.

Baseline: the driver target (BASELINE.json north star) of >=100k
env-frames/sec aggregate on a v5e-16, i.e. 6,250 frames/sec/chip;
``vs_baseline`` is measured frames/sec/chip over that number.

Prints exactly one JSON line, **always** — the orchestrator in ``main()``
runs the measurement in a subprocess so a hanging or crashing TPU backend
init (round 1 failure mode: the axon tunnel either raised UNAVAILABLE or
hung past the driver timeout) can neither kill nor stall this process.
On persistent TPU failure it falls back to a CPU-pinned run and reports
the TPU error in an ``"error"`` field alongside the CPU number.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

BASELINE_FPS_PER_CHIP = 100_000 / 16  # v5e-16 north star, per chip

PROBE_TIMEOUT_S = 90
TPU_ATTEMPT_TIMEOUT_S = 420
CPU_ATTEMPT_TIMEOUT_S = 420


def _run_measurement() -> None:
    """Child mode: do the actual measurement and print the JSON line."""
    import jax
    import jax.numpy as jnp  # noqa: F401

    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

    from scalerl_tpu.utils.platform import setup_platform

    # backend already pinned by __main__ when --cpu; "auto" here just turns
    # on the persistent compilation cache (warm relaunches skip the 20-40 s
    # TPU compile of the fused loop)
    platform = setup_platform("auto")
    # batch/unroll sized for one chip (swept: B=512/iters=5 beats B=128/10
    # by ~21% — bigger batches keep the MXU busy between infeed boundaries);
    # CPU fallback shrinks to stay quick
    on_accel = platform in ("tpu", "gpu")
    B = 512 if on_accel else 8
    T = 20
    iters_per_call = 5 if on_accel else 1

    args = ImpalaArguments(
        use_lstm=False,
        hidden_size=512,
        rollout_length=T,
        batch_size=B,
        max_timesteps=0,
        # mixed precision on accelerators: conv/dense torso in bfloat16 feeds
        # the MXU at full rate; params, V-trace, and the optimizer stay f32
        # (standard IMPALA mixed-precision recipe, tested in
        # tests/test_impala.py::test_impala_bfloat16_compute_dtype)
        compute_dtype="bfloat16" if on_accel else "float32",
    )
    env = SyntheticPixelEnv()
    venv = JaxVecEnv(env, num_envs=B)
    agent = ImpalaAgent(args, obs_shape=env.observation_shape, num_actions=env.num_actions)
    learn = agent.make_learn_fn()
    loop = DeviceActorLearnerLoop(
        model=agent.model,
        venv=venv,
        learn_fn=learn,
        unroll_length=T,
        iters_per_call=iters_per_call,
    )

    key = jax.random.PRNGKey(0)
    carry = loop.init_carry(key)
    state = agent.state
    frames_per_call = T * B * iters_per_call

    # warmup: compile + one full call.  Synchronize by *fetching a scalar*:
    # under the axon tunnel block_until_ready can return before the program
    # finishes, but a host transfer of an output cannot.
    state, carry, m = loop._train_many(state, carry, jax.random.PRNGKey(1))
    float(m["total_loss"])

    target_s = 20.0 if on_accel else 4.0
    frames = 0
    t0 = time.perf_counter()
    i = 0
    while True:
        key, sub = jax.random.split(key)
        state, carry, metrics = loop._train_many(state, carry, sub)
        i += 1
        frames += frames_per_call
        float(metrics["total_loss"])
        if time.perf_counter() - t0 >= target_s and i >= 3:
            break
    elapsed = time.perf_counter() - t0

    fps = frames / elapsed
    print(
        json.dumps(
            {
                "metric": "impala_atari_env_frames_per_sec_per_chip",
                "value": round(fps, 1),
                "unit": f"frames/sec/chip ({platform})",
                "vs_baseline": round(fps / BASELINE_FPS_PER_CHIP, 3),
            }
        )
    )


def _probe_backend(timeout_s: float):
    """Cheap liveness check of the default backend in a subprocess.

    Returns ``(backend_name, None)`` or ``(None, err)``.  Round-1/2 failure
    mode: the axon TPU tunnel hangs ``jax.devices()`` indefinitely — without
    this probe each full attempt burns its whole ``TPU_ATTEMPT_TIMEOUT_S``
    before the CPU fallback runs, flirting with the driver's overall budget.
    """
    cmd = [sys.executable, str(Path(__file__).resolve()), "--probe"]
    try:
        proc = subprocess.run(cmd, timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return None, f"probe timeout after {timeout_s:.0f}s"
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("backend:"):
            return line.split(":", 1)[1].strip(), None
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-2:]
    return None, f"probe rc={proc.returncode}: " + " | ".join(tail)[-200:]


def _attempt(cpu: bool, timeout_s: float):
    """Run the measurement in a subprocess; return (json_line | None, err)."""
    env = dict(os.environ)
    cmd = [sys.executable, str(Path(__file__).resolve()), "--run"]
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=1").strip()
        cmd.append("--cpu")
    try:
        proc = subprocess.run(
            cmd, env=env, timeout=timeout_s, capture_output=True, text=True
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s:.0f}s"
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                json.loads(line)
            except ValueError:
                continue
            return line, None
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
    return None, f"rc={proc.returncode}: " + " | ".join(tail)[-400:]


def main() -> None:
    errors = []
    backend, probe_err = _probe_backend(PROBE_TIMEOUT_S)
    if backend == "cpu":
        # healthy CPU-only host: the default backend IS cpu — measure it and
        # report clean (no "error" field; nothing failed)
        line, err = _attempt(cpu=True, timeout_s=CPU_ATTEMPT_TIMEOUT_S)
        if line is not None:
            print(line)
            return
        errors.append(f"cpu-default: {err}")
    elif backend is None and "probe timeout" in (probe_err or ""):
        # a hung tunnel: skip the full attempts — they would hang just the
        # same and burn TPU_ATTEMPT_TIMEOUT_S each before the CPU fallback
        errors.append(probe_err)
    else:
        # healthy accelerator, or a fast probe failure (e.g. transient
        # UNAVAILABLE, the round-1 mode): full attempts with one retry
        if probe_err:
            errors.append(probe_err)
        for i in range(2):
            line, err = _attempt(cpu=False, timeout_s=TPU_ATTEMPT_TIMEOUT_S)
            if line is not None:
                print(line)
                return
            errors.append(f"attempt{i + 1}: {err}")
            if "timeout" in err:
                break
    # CPU fallback: still a real number, annotated with the TPU error.
    line, err = _attempt(cpu=True, timeout_s=CPU_ATTEMPT_TIMEOUT_S)
    if line is not None:
        obj = json.loads(line)
        obj["error"] = "default backend failed, CPU fallback: " + "; ".join(errors)
        print(json.dumps(obj))
        return
    errors.append(f"cpu: {err}")
    print(
        json.dumps(
            {
                "metric": "impala_atari_env_frames_per_sec_per_chip",
                "value": 0.0,
                "unit": "frames/sec/chip (unavailable)",
                "vs_baseline": 0.0,
                "error": "; ".join(errors)[-800:],
            }
        )
    )


if __name__ == "__main__":
    if "--probe" in sys.argv[1:]:
        import jax

        print("backend:", jax.default_backend(), flush=True)
    elif "--run" in sys.argv[1:]:
        if "--cpu" in sys.argv[1:]:
            import jax

            jax.config.update("jax_platforms", "cpu")
        try:
            _run_measurement()
        except Exception:  # noqa: BLE001 — parent needs the traceback on stderr
            import traceback

            traceback.print_exc()
            sys.exit(1)
    else:
        try:
            main()
        except Exception as e:  # noqa: BLE001 — must always print one JSON line
            print(
                json.dumps(
                    {
                        "metric": "impala_atari_env_frames_per_sec_per_chip",
                        "value": 0.0,
                        "unit": "frames/sec/chip (unavailable)",
                        "vs_baseline": 0.0,
                        "error": f"orchestrator: {type(e).__name__}: {e}"[:800],
                    }
                )
            )
