"""Headline benchmark: IMPALA Atari-shaped env-frames/sec on one chip.

Runs the flagship path — the fully-fused on-device actor-learner loop
(``scalerl_tpu/runtime/device_loop.py``: env step + AtariNet forward +
action sample + V-trace learner update, all one XLA program) — on the
synthetic Atari-shaped pixel env at real frame shapes ``[84, 84, 4]``.

Baseline: the driver target (BASELINE.json north star) of >=100k
env-frames/sec aggregate on a v5e-16, i.e. 6,250 frames/sec/chip;
``vs_baseline`` is measured frames/sec/chip over that number.  The JSON
line also reports ``mfu`` (achieved FLOPs/s over the chip's peak bf16
FLOPs/s, from XLA's own cost analysis of the compiled program).

Prints exactly one JSON line, **always**.

Probe policy (round 3): the round-2 design gave the TPU one 90 s probe
and then surrendered to CPU for the whole bench window — under the axon
tunnel (which hangs ``jax.devices()`` for minutes and then recovers) that
budget was never going to land a number.  Now:

- ONE child process both probes and measures: it prints ``backend: X`` as
  soon as the backend answers, then keeps going straight into the
  measurement — no second process re-paying tunnel init.
- The CPU fallback measurement starts in parallel at entry (pinned
  ``JAX_PLATFORMS=cpu``, so it never touches the tunnel); its result is
  ready the moment we give up on the TPU, costing zero extra wall time.
- Probe patience escalates across the whole window (60 s, 180 s, then
  300 s repeatedly) until ``BENCH_BUDGET_S`` (default 1500 s) runs out,
  instead of one shot.  A hung child is killed and retried — the tunnel
  is intermittent, so later probes genuinely can succeed where the first
  timed out.
- Every successful TPU measurement is appended (with a timestamp and the
  raw JSON) to ``BENCH_TPU.md`` so in-session successes leave a committed
  artifact even if the driver's own run later misses the tunnel.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

BASELINE_FPS_PER_CHIP = 100_000 / 16  # v5e-16 north star, per chip

BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1200"))
PROBE_SCHEDULE_S = (60.0, 180.0, 300.0)  # then 300 s repeatedly
MEASURE_TIMEOUT_S = 420.0  # beyond backend-ack: compile (20-40 s) + run
CPU_ATTEMPT_TIMEOUT_S = 420.0

# Peak dense bf16 FLOPs/s per chip by device kind (public spec sheets);
# used only to turn achieved FLOPs/s into an MFU fraction.
_PEAK_BF16_FLOPS = (
    ("v6", 918e12),  # v6e / Trillium
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for tag, peak in _PEAK_BF16_FLOPS:
        if tag in kind:
            return peak
    return None


def load_bench_history(repo_dir=None) -> list:
    """Parse every committed ``BENCH_r0N.json`` driver artifact.

    Each file holds concatenated ``{"n": ..., "parsed": {...}}`` objects
    (no separators); returns the ``parsed`` dicts in round order, skipping
    rounds that produced no measurement.  Shared by the measured-window
    drift warning below and the tpu_watch perf-regression gate.
    """
    import re

    repo = Path(repo_dir) if repo_dir else Path(__file__).resolve().parent
    out = []
    for path in sorted(repo.glob("BENCH_r[0-9]*.json")):
        try:
            text = path.read_text()
        except OSError:
            continue
        # the driver concatenates JSON objects back to back; split on the
        # "}{"  boundaries between top-level objects
        for chunk in re.split(r"(?<=\})\s*(?=\{)", text.strip()):
            try:
                obj = json.loads(chunk)
            except ValueError:
                continue
            parsed = obj.get("parsed")
            if isinstance(parsed, dict) and parsed.get("value"):
                out.append(parsed)
    return out


def _measured_drift(result: dict) -> None:
    """Flag a measured-window drift against the committed bench history.

    r05's CPU fallback measured 75 s where r02-r04 measured ~38 s at the
    identical batch/unroll — the window length is ``max(target_s,
    min_iters x chunk_time)``, so a chunk-cost change silently doubles the
    window and the runs stop being comparable.  Compare this run's
    ``measured_s`` against the median of prior same-shape runs and attach a
    warning field when it drifts by more than 50% either way; the fps
    number itself stays untouched (it is already time-normalized).
    """
    try:
        prior = [
            float(h["measured_s"])
            for h in load_bench_history()
            if h.get("metric") == result.get("metric")
            and h.get("batch") == result.get("batch")
            and h.get("unroll") == result.get("unroll")
            and h.get("device_kind") == result.get("device_kind")
            and h.get("measured_s")
        ]
        if not prior:
            return
        prior.sort()
        median = prior[len(prior) // 2]
        ratio = float(result["measured_s"]) / max(median, 1e-9)
        if ratio > 1.5 or ratio < 1 / 1.5:
            result["measured_s_drift"] = {
                "prior_median_s": round(median, 1),
                "ratio": round(ratio, 2),
                "warning": "measured window drifted >50% vs history at the "
                "same batch/unroll — chunk cost changed; runs are "
                "time-normalized but check min_iters domination",
            }
    except Exception:  # noqa: BLE001 — the drift check must never kill a bench
        pass


def _cost_analysis_flops(compiled) -> float | None:
    """Per-call FLOPs from XLA's cost analysis; None if unavailable."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend-dependent API
        return None
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    flops = ca.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


def _micro_witness(device_kind: str, platform: str) -> None:
    """~30-second TPU witness: chained bf16 matmuls, analytic FLOPs.

    The full fused bench needs the tunnel to stay up through a 20-40 s
    XLA compile plus a 20 s measurement; rounds 1-4 showed windows can be
    shorter than that.  This program compiles in a few seconds (one
    ``fori_loop`` of ``n``×``n`` bf16 matmuls — the MXU primitive), runs
    ~3 s, and prints its own JSON line so the parent can bank a
    timestamped artifact in ``BENCH_TPU.md`` before the escalation to the
    full bench even starts (VERDICT r4 next-round item #1b).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    # 8.8 TFLOP/call: ~45 ms on a v5e, minutes on one CPU core — shrink
    # off-accelerator (that path only exists for plumbing tests)
    on_accel = platform in ("tpu", "gpu")
    n, k_loop = (4096, 64) if on_accel else (256, 4)

    def chain(x, w):
        return lax.fori_loop(0, k_loop, lambda _, y: (y @ w) * 0.02, x)

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, n), dtype=jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (n, n), dtype=jnp.bfloat16)
    f = jax.jit(chain)
    f(x, w).block_until_ready()  # compile + warmup
    flops_per_call = 2.0 * n * n * n * k_loop
    t0 = time.perf_counter()
    calls = 0
    while time.perf_counter() - t0 < 3.0 or calls < 2:
        f(x, w).block_until_ready()
        calls += 1
    elapsed = time.perf_counter() - t0
    achieved = flops_per_call * calls / elapsed
    result = {
        "metric": "tpu_micro_witness_tflops",
        "value": round(achieved / 1e12, 2),
        "unit": f"TFLOP/s bf16 matmul ({platform})",
        "device_kind": device_kind,
        "matmul_n": n,
        "measured_s": round(elapsed, 2),
    }
    peak = _peak_flops(device_kind)
    if peak is not None:
        result["mfu"] = round(achieved / peak, 4)
    print(json.dumps(result), flush=True)


def _run_learn_measurement() -> None:
    """Learner-step-only benchmark: MFU of the IMPALA training update.

    The fused-loop MFU (~0.9% witnessed) is env-step/HBM-bound by design
    — most of its wall-clock is the pixel env scan, not matmuls.  This
    mode isolates the LEARN step (AtariNet forward + V-trace + backward +
    RMSProp over a [T+1, B] trajectory at the north-star shape, bf16
    torso) and reports ITS throughput and MFU — the number comparable to
    supervised-training MFU figures.
    """
    import jax
    import jax.numpy as jnp

    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.data.trajectory import Trajectory
    from scalerl_tpu.utils.platform import setup_platform

    platform = setup_platform("auto")
    print("backend:", platform, flush=True)
    device_kind = jax.devices()[0].device_kind
    on_accel = platform in ("tpu", "gpu")

    T = 20
    B = 256 if on_accel else 8
    args = ImpalaArguments(
        use_lstm=False, hidden_size=512, rollout_length=T, batch_size=B,
        max_timesteps=0,
        compute_dtype="bfloat16" if on_accel else "float32",
    )
    agent = ImpalaAgent(args, obs_shape=(84, 84, 4), num_actions=6)
    learn = agent.make_learn_fn()
    key = jax.random.PRNGKey(0)
    traj = Trajectory(
        obs=jax.random.randint(key, (T + 1, B, 84, 84, 4), 0, 255, jnp.uint8),
        action=jax.random.randint(key, (T + 1, B), 0, 6, jnp.int32),
        reward=jax.random.normal(key, (T + 1, B), jnp.float32),
        done=jnp.zeros((T + 1, B), jnp.bool_),
        logits=jax.random.normal(key, (T + 1, B, 6), jnp.float32),
        core_state=agent.initial_state(B),
    )
    flops_per_step = None
    run_fn = jax.jit(learn)
    try:
        compiled = jax.jit(learn).lower(agent.state, traj).compile()
        # keep the executable BEFORE attempting cost analysis: a failing
        # cost_analysis must not discard the compile and force a second
        # full compile inside a possibly-short tunnel window
        run_fn = compiled
        flops_per_step = _cost_analysis_flops(compiled)
    except Exception:  # noqa: BLE001 — whatever run_fn holds still works
        pass
    from scalerl_tpu.runtime.dispatch import MetricsPipeline

    state, m = run_fn(agent.state, traj)
    float(m["total_loss"])  # sync through a host fetch (tunnel-safe)
    target_s = 15.0 if on_accel else 4.0
    # pipelined driver: 2 steps in flight, ONE batched metric read per step
    # (lagged — the read blocks on a step the device already finished);
    # drain() is the final host-fetch sync before the clock stops
    pipe = MetricsPipeline(depth=2)
    t0 = time.perf_counter()
    steps = 0
    while time.perf_counter() - t0 < target_s or steps < 2:
        state, m = run_fn(state, traj)
        steps += 1
        pipe.push(steps, m)
    pipe.drain()
    elapsed = time.perf_counter() - t0
    frames = steps * T * B
    result = {
        "metric": "impala_learn_step_frames_per_sec",
        "value": round(frames / elapsed, 1),
        "unit": f"train frames/sec ({platform})",
        "device_kind": device_kind,
        "batch": B,
        "unroll": T,
        "steps_per_sec": round(steps / elapsed, 2),
        "measured_s": round(elapsed, 1),
    }
    if flops_per_step is not None:
        achieved = flops_per_step * steps / elapsed
        result["achieved_tflops_per_s"] = round(achieved / 1e12, 2)
        peak = _peak_flops(device_kind)
        if peak is not None:
            result["mfu"] = round(achieved / peak, 4)
    print(json.dumps(result), flush=True)


def _run_sharded_measurement(mesh_spec: str | None) -> None:
    """``--mode sharded``: the dp×mp pjit train step on the transformer
    policy — the big-model learner plane's headline number.

    Builds an IMPALA learn step over ``TransformerPolicyNet`` with the
    policy's heads/mlp/vocab dims sharded over the named ``mp`` axis
    (``parallel/logical.py`` rules), activations constrained batch-over-dp,
    and the state donated; measures train frames/sec and MFU from the
    pjit executable's own cost analysis.  The artifact carries
    ``params_total`` / ``params_per_chip`` / ``mesh`` so the tpu_watch
    perf gate compares like-for-like across mesh shapes: a dp=8 number
    never gates a dp=4,mp=2 run.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.data.trajectory import Trajectory
    from scalerl_tpu.utils.platform import setup_platform

    platform = setup_platform("auto")
    print("backend:", platform, flush=True)
    device_kind = jax.devices()[0].device_kind
    on_accel = platform in ("tpu", "gpu")
    n_dev = len(jax.devices())

    spec = mesh_spec or os.environ.get("BENCH_SHARD_MESH")
    if not spec:
        mp = 2 if n_dev % 2 == 0 and n_dev >= 2 else 1
        spec = f"dp={n_dev // mp},mp={mp}" if mp > 1 else f"dp={n_dev}"
    dp = _mesh_axis(spec, "dp")

    # model sized to make the matmuls the story on accelerators; the CPU
    # fallback proves the code path at toy scale
    if on_accel:
        T, B_chip = 16, 8
        d_model, n_layers, n_heads = 1024, 8, 16
    else:
        T, B_chip = 8, 2
        d_model, n_layers, n_heads = 64, 2, 4
    B = B_chip * dp
    args = ImpalaArguments(
        policy_arch="transformer",
        d_model=d_model, n_layers=n_layers, n_heads=n_heads,
        bf16_params=on_accel,
        rollout_length=T, batch_size=B, use_lstm=False, max_timesteps=0,
        num_actors=1, num_buffers=2,
    )
    obs_dim = 64
    agent = ImpalaAgent(
        args, obs_shape=(obs_dim,), num_actions=16, obs_dtype=jnp.float32
    )
    agent.enable_mesh(spec)

    key = jax.random.PRNGKey(0)
    traj = agent._shard_batch(Trajectory(
        obs=jax.random.normal(key, (T + 1, B, obs_dim), jnp.float32),
        action=jax.random.randint(key, (T + 1, B), 0, 16, jnp.int32),
        reward=jax.random.normal(key, (T + 1, B), jnp.float32),
        done=jnp.zeros((T + 1, B), jnp.bool_),
        logits=jax.random.normal(key, (T + 1, B, 16), jnp.float32),
        core_state=(),
    ))

    def _leaf_elems(x):
        return int(np.prod(x.shape)) if hasattr(x, "shape") else 0

    def _leaf_local_elems(x):
        if not hasattr(x, "sharding"):
            return _leaf_elems(x)
        return int(np.prod(x.sharding.shard_shape(x.shape)))

    p_leaves = jax.tree_util.tree_leaves(agent.state.params)
    params_total = sum(_leaf_elems(x) for x in p_leaves)
    params_per_chip = sum(_leaf_local_elems(x) for x in p_leaves)

    flops_per_step = None
    run_fn = agent._learn
    try:
        compiled = agent._learn.lower(agent.state, traj).compile()
        run_fn = compiled
        flops_per_step = _cost_analysis_flops(compiled)
    except Exception:  # noqa: BLE001 — jit path still measures, no MFU
        pass

    state, m = run_fn(agent.state, traj)
    float(m["total_loss"])  # host-fetch sync (tunnel-safe warmup barrier)

    from scalerl_tpu.runtime.dispatch import MetricsPipeline

    target_s = 15.0 if on_accel else 4.0
    pipe = MetricsPipeline(depth=2)
    t0 = time.perf_counter()
    steps = 0
    while time.perf_counter() - t0 < target_s or steps < 2:
        state, m = run_fn(state, traj)
        steps += 1
        pipe.push(steps, m)
    pipe.drain()
    elapsed = time.perf_counter() - t0
    frames = steps * T * B
    result = {
        "metric": "sharded_train_step_frames_per_sec",
        "mode": "sharded",
        "value": round(frames / elapsed, 1),
        "unit": f"train frames/sec ({platform}, mesh {spec})",
        "mesh": spec,
        "device_kind": device_kind,
        "batch": B,
        "unroll": T,
        "d_model": d_model,
        "num_layers": n_layers,
        "params_total": params_total,
        "params_per_chip": params_per_chip,
        "steps_per_sec": round(steps / elapsed, 2),
        "measured_s": round(elapsed, 1),
    }
    if flops_per_step is not None:
        achieved = flops_per_step * steps / elapsed
        result["achieved_tflops_per_s"] = round(achieved / 1e12, 2)
        peak = _peak_flops(device_kind)
        if peak is not None:
            # fleet MFU: achieved FLOPs/s over the peak of ALL chips in the
            # mesh — the per-chip utilization figure for the sharded step
            result["mfu"] = round(achieved / (peak * n_dev), 4)
    print(json.dumps(result))


def _run_serving_measurement() -> None:
    """``--mode serving``: the centralized inference plane's headline
    numbers — act requests/sec through the InferenceServer's dynamic
    batcher, the latency SLO quantiles (p50/p95/p99) from the serving
    histogram, and mean batch occupancy.

    Hermetic in-process shape: N client threads over codec pipe pairs
    hammer a small MLP policy — every byte flows through the same framing/
    batching/flush path remote env-shell hosts use over sockets, so the
    number measures the serving machinery (admission, bucketing, one
    upload + one read per flush), not env dynamics.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.serving import (
        InferenceServer,
        RemotePolicyClient,
        ServingConfig,
        local_pair,
    )
    from scalerl_tpu.utils.platform import setup_platform

    platform = setup_platform("auto")
    print("backend:", platform, flush=True)
    device_kind = jax.devices()[0].device_kind
    on_accel = platform in ("tpu", "gpu")
    obs_dim, num_actions = 64, 16
    if on_accel:
        n_clients, lanes, max_batch, target_s = 16, 16, 256, 10.0
    else:
        n_clients, lanes, max_batch = 4, 4, 32
        target_s = float(os.environ.get("BENCH_SERVING_TARGET_S", "4.0"))

    args = ImpalaArguments(
        use_lstm=False, hidden_size=256, rollout_length=8, batch_size=4,
        num_actors=1, num_buffers=2, max_timesteps=0, logger_backend="none",
    )
    agent = ImpalaAgent(
        args, obs_shape=(obs_dim,), num_actions=num_actions,
        obs_dtype=jnp.float32,
    )
    server = InferenceServer(
        agent, ServingConfig(max_batch=max_batch, max_wait_s=0.002)
    )
    server.start()
    clients = []
    for _ in range(n_clients):
        c_end, s_end = local_pair()
        server.add_connection(s_end)
        clients.append(RemotePolicyClient(conn=c_end, request_timeout_s=60.0))

    rng = np.random.default_rng(0)
    obs = rng.normal(size=(lanes, obs_dim)).astype(np.float32)
    la = np.zeros(lanes, np.int32)
    rew = np.zeros(lanes, np.float32)
    done = np.zeros(lanes, bool)

    # warmup: every client round-trips once so the flush buckets compile
    # before the measured window (the steady-state guard arms after this)
    for c in clients:
        c.act(obs, la, rew, done, ())

    stop = threading.Event()
    counts = [0] * n_clients

    def hammer(i: int) -> None:
        c = clients[i]
        while not stop.is_set():
            c.act(obs, la, rew, done, ())
            counts[i] += 1

    threads = [
        threading.Thread(target=hammer, args=(i,), daemon=True)
        for i in range(n_clients)
    ]
    flushes0 = server.flushes
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(target_s)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    elapsed = time.perf_counter() - t0
    requests = sum(counts)
    slo = server.slo()
    occ = slo["batch_occupancy_mean"]
    result = {
        "metric": "serving_requests_per_sec",
        "mode": "serving",
        "value": round(requests / elapsed, 1),
        "unit": f"act requests/sec ({platform}, {n_clients} clients x "
                f"{lanes} lanes)",
        "lane_steps_per_sec": round(requests * lanes / elapsed, 1),
        "p50_ms": round(slo["p50_ms"], 3),
        "p95_ms": round(slo["p95_ms"], 3),
        "p99_ms": round(slo["p99_ms"], 3),
        "batch_occupancy": round(occ, 4),
        "flushes": server.flushes - flushes0,
        "shed_total": server.batcher.shed_total,
        "n_clients": n_clients,
        "lanes": lanes,
        "max_batch": max_batch,
        "device_kind": device_kind,
        "measured_s": round(elapsed, 1),
    }
    for c in clients:
        c.close()
    server.stop()
    print(json.dumps(result))


def _run_traffic_measurement() -> None:
    """``--mode traffic``: the serving front door's headline number —
    goodput under SLO (requests answered within ``BENCH_TRAFFIC_SLO_MS``
    per second) through the :class:`ServingRouter` over N in-process
    replicas, under OPEN-LOOP arrivals.

    Open-loop is the honest load model for a front door: each client fires
    on a Poisson schedule (plus periodic bursts) regardless of whether the
    previous reply came back, so queueing delay compounds the way real
    traffic makes it compound — a closed loop would self-throttle and hide
    exactly the latency the SLO gate exists to catch.  Latency is measured
    from the request's SCHEDULED arrival, so schedule slip (the client
    thread falling behind) counts against the tier, and every request
    carries a head-sampled trace (the PR 13 context keys), so the router's
    ``router.route`` spans land under each ``traffic.request`` root.

    Exact accounting is asserted before the verdict line: admitted ==
    answered + shed + orphaned at quiesce, the same equation the chaos e2e
    gates on.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.runtime import tracing
    from scalerl_tpu.runtime.attribution import TierLedger
    from scalerl_tpu.serving import (
        InferenceServer,
        RemotePolicyClient,
        RouterConfig,
        ServingConfig,
        ServingRouter,
        connect_replica,
        local_pair,
    )
    from scalerl_tpu.utils.platform import setup_platform

    platform = setup_platform("auto")
    print("backend:", platform, flush=True)
    device_kind = jax.devices()[0].device_kind
    on_accel = platform in ("tpu", "gpu")
    obs_dim, num_actions, lanes = 64, 16, 4
    if on_accel:
        n_replicas, n_clients, rps, target_s, slo_ms = 3, 16, 200.0, 10.0, 100.0
    else:
        n_replicas = int(os.environ.get("BENCH_TRAFFIC_REPLICAS", "3"))
        n_clients = int(os.environ.get("BENCH_TRAFFIC_CLIENTS", "4"))
        rps = float(os.environ.get("BENCH_TRAFFIC_RPS", "60"))
        target_s = float(os.environ.get("BENCH_TRAFFIC_TARGET_S", "4.0"))
        slo_ms = float(os.environ.get("BENCH_TRAFFIC_SLO_MS", "250"))

    args = ImpalaArguments(
        use_lstm=False, hidden_size=256, rollout_length=8, batch_size=4,
        num_actors=1, num_buffers=2, max_timesteps=0, logger_backend="none",
    )
    agent = ImpalaAgent(
        args, obs_shape=(obs_dim,), num_actions=num_actions,
        obs_dtype=jnp.float32,
    )
    servers = [
        InferenceServer(agent, ServingConfig(max_batch=32, max_wait_s=0.002))
        for _ in range(n_replicas)
    ]
    for s in servers:
        s.start()
    router = ServingRouter(
        [connect_replica(s, f"replica{i}") for i, s in enumerate(servers)],
        RouterConfig(hedge_budget=2, probe_backoff_s=0.05, seed=0),
    )
    router.start()
    # streaming tier attribution: every sampled traffic.request decomposes
    # online into named tier edges (exact sum), so the goodput verdict can
    # also NAME the bottleneck tier — zero extra round-trips, the spans
    # already flow
    ledger = TierLedger().attach(tracing.get_tracer())
    clients = []
    for _ in range(n_clients):
        c_end, r_end = local_pair()
        router.add_client(r_end)
        clients.append(RemotePolicyClient(conn=c_end, request_timeout_s=60.0))

    rng = np.random.default_rng(0)
    la = np.zeros(lanes, np.int32)
    rew = np.zeros(lanes, np.float32)
    done = np.zeros(lanes, bool)

    # warmup: keep acting until EVERY replica has flushed at least once —
    # affinity routing can pin early traffic to one replica, and a replica
    # that first compiles inside the window torches the latency tail
    warm_deadline = time.monotonic() + 120.0
    while (any(s.flushes == 0 for s in servers)
           and time.monotonic() < warm_deadline):
        for c in clients:
            c.act(rng.normal(size=(lanes, obs_dim)).astype(np.float32),
                  la, rew, done, ())

    per_client_rps = rps / n_clients
    burst_every_s, burst_n = 1.0, max(2, int(per_client_rps // 4))
    stop = threading.Event()
    lat_s: list[list[float]] = [[] for _ in range(n_clients)]
    sheds = [0] * n_clients

    import queue as queue_mod

    def open_loop(i: int) -> None:
        local = np.random.default_rng(1000 + i)
        c = clients[i]
        inflight: queue_mod.Queue = queue_mod.Queue()

        # companion drain: harvests replies AS THEY LAND (per-client reply
        # streams are FIFO-demuxed), so t_done is delivery time, not the
        # end of the window — blocking result() on the oldest first
        def drain() -> None:
            while True:
                item = inflight.get()
                if item is None:
                    return
                pending, t_sched, span = item
                try:
                    reply = pending.result(timeout=30.0)
                except (TimeoutError, ConnectionError):
                    span.end(outcome="lost")
                    continue
                t_done = time.perf_counter()
                if reply.get("shed"):
                    sheds[i] += 1
                    span.end(outcome="shed")
                else:
                    lat_s[i].append(t_done - t_sched)
                    span.end(outcome="ok")

        drainer = threading.Thread(target=drain, daemon=True)
        drainer.start()

        def fire(t_sched: float) -> None:
            span = tracing.start_span("traffic.request", kind="serving")
            msg = c._act_msg(
                local.normal(size=(lanes, obs_dim)).astype(np.float32),
                la, rew, done, (),
            )
            tracing.inject(msg, span)
            inflight.put((c._submit(msg), t_sched, span))

        t0 = time.perf_counter()
        next_poisson = t0 + local.exponential(1.0 / per_client_rps)
        next_burst = t0 + burst_every_s
        while not stop.is_set():
            now = time.perf_counter()
            # fire everything the schedule owes us — open loop never waits
            # on a reply to advance the clock
            while next_poisson <= now:
                fire(next_poisson)
                next_poisson += local.exponential(1.0 / per_client_rps)
            if next_burst <= now:
                for _ in range(burst_n):
                    fire(next_burst)
                next_burst += burst_every_s
            time.sleep(min(0.002, max(next_poisson - now, 0.0)))
        inflight.put(None)
        drainer.join(timeout=60.0)

    threads = [
        threading.Thread(target=open_loop, args=(i,), daemon=True)
        for i in range(n_clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(target_s)
    stop.set()
    for t in threads:
        t.join(timeout=90.0)
    elapsed = time.perf_counter() - t0

    # quiesce, then assert the chaos e2e's accounting equation
    deadline = time.monotonic() + 10.0
    while router.stats()["inflight"] > 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    stats = router.stats()
    balanced = (
        stats["answered"] + stats["shed"] + stats["orphaned"]
        == stats["admitted"]
    )

    ledger.drain()
    ledger.detach(tracing.get_tracer())
    bn = ledger.bottleneck()

    lat = np.sort(np.concatenate([np.asarray(v) for v in lat_s])
                  if any(lat_s) else np.zeros(0))
    answered = int(lat.size)
    good = int(np.searchsorted(lat, slo_ms / 1e3, side="right"))
    shed_total = sum(sheds)

    def _q(q: float) -> float:
        return float(lat[min(int(q * lat.size), lat.size - 1)]) * 1e3 if lat.size else 0.0

    result = {
        "metric": "traffic_goodput_rps",
        "mode": "traffic",
        "value": round(good / elapsed, 1),
        "unit": f"requests answered within {slo_ms:g} ms SLO per sec "
                f"({platform}, {n_replicas} replicas)",
        "offered_rps": round((answered + shed_total) / elapsed, 1),
        "answered": answered,
        "good": good,
        "shed": shed_total,
        "slo_ms": slo_ms,
        "p50_ms": round(_q(0.50), 3),
        "p95_ms": round(_q(0.95), 3),
        "p99_ms": round(_q(0.99), 3),
        "retries": stats["retries"],
        "ejections": stats["ejections"],
        "accounting_balanced": balanced,
        "n_replicas": n_replicas,
        "n_clients": n_clients,
        "lanes": lanes,
        "device_kind": device_kind,
        "measured_s": round(elapsed, 1),
        # the tier verdict (empty when tracing is head-sampled out —
        # SCALERL_TRACE_SAMPLE gates how many requests decompose)
        "bottleneck_tier": bn["bottleneck_tier"],
        "tiers": bn["tiers"],
        "attribution": {
            "decomposed": bn["decomposed"],
            "orphans": bn["orphans"],
            "late_spans": bn["late_spans"],
            "max_sum_err_s": bn["max_sum_err_s"],
        },
    }
    for c in clients:
        c.close()
    router.stop()
    for s in servers:
        s.stop()
    print(json.dumps(result))


def _run_genrl_continuous_measurement() -> None:
    """``--mode genrl --continuous``: the continuous-batching decode plane
    vs the fixed-cohort engine, like-for-like (same model, same params,
    same mixed-length prompt distribution, same EOS geometry), in ONE
    artifact — the ISSUE 11 acceptance comparison.

    Workload shape: mixed-length prompts and an EOS token the policy
    actually samples, so response lengths vary — the regime continuous
    batching exists for.  The cohort engine pays the full response bucket
    for every lane regardless (its decode loop is one fused program);
    the continuous engine backfills freed lanes from a Poisson arrival
    queue, so its decode steps stay near-full occupancy of LIVE lanes.
    Decode tokens/s counts REAL (mask=1) tokens for both engines over
    whole-phase wall clock — an honest end-to-end rate, not a
    padding-subtracted estimate.

    ``BENCH_GENRL_GROUP=n`` (ISSUE 14) switches arrivals to GROUP shape:
    every Poisson arrival is one prompt submitted via ``submit_group`` for
    ``n`` completions (the GRPO workload the prefix-CoW fork exists for) —
    the artifact then carries ``group: n`` so the perf gate compares
    like-for-like at the same group shape, and the
    ``prefill_tokens_saved_ratio`` / ``prefix_hit_rate`` fields report how
    much full-page prefix prefill the cache + CoW sharing skipped.
    """
    import jax
    import numpy as np

    from scalerl_tpu.genrl.continuous import (
        ContinuousConfig,
        ContinuousEngine,
    )
    from scalerl_tpu.genrl.engine import GenerationConfig, GenerationEngine
    from scalerl_tpu.models.transformer import TransformerPolicy
    from scalerl_tpu.runtime import telemetry
    from scalerl_tpu.utils.platform import setup_platform

    platform = setup_platform("auto")
    print("backend:", platform, flush=True)
    device_kind = jax.devices()[0].device_kind
    on_accel = platform in ("tpu", "gpu")

    # the regime continuous batching exists for: a LONG response budget
    # with a real EOS rate (small vocab => the random-init policy actually
    # samples EOS), so response lengths land well short of the budget —
    # the cohort engine still pays every budget step, the continuous
    # engine backfills the freed lanes
    if on_accel:
        V, d_model, n_layers, n_heads = 32, 256, 4, 8
        P_max, R, lanes = 128, 256, 256
        page_size, macro_steps, min_free = 16, 16, 32
        target_s = 10.0
    else:
        V, d_model, n_layers, n_heads = 8, 64, 1, 4
        P_max, R, lanes = 16, 64, 64
        page_size, macro_steps, min_free = 8, 4, 8
        # schema tests shrink the window (and optionally the lane pool) to
        # stay cheap on the tier-1 clock; the real CPU shape is the default
        target_s = float(os.environ.get("BENCH_GENRL_TARGET_S", "3.0"))
        lanes = int(os.environ.get("BENCH_GENRL_LANES", lanes))
        R = int(os.environ.get("BENCH_GENRL_RESPONSE", R))
    # group-arrival mode: n completions per arriving prompt (1 = the
    # ungrouped workload; its artifact carries no "group" key, so the two
    # shapes never gate each other)
    group = max(int(os.environ.get("BENCH_GENRL_GROUP", "1")), 1)

    base = dict(
        vocab_size=V, max_prompt_len=P_max, max_new_tokens=R,
        temperature=1.0, eos_token=1, seed=0,
    )
    model = TransformerPolicy(
        num_actions=V, vocab_size=V, d_model=d_model, num_heads=n_heads,
        num_layers=n_layers, max_len=2 * (P_max + R),
    )
    params = model.init(
        jax.random.PRNGKey(0),
        jax.numpy.zeros((1, 2), jax.numpy.int32),
    )
    rng = np.random.default_rng(0)

    def sample_prompts(n):
        lengths = rng.integers(2, P_max + 1, size=n).astype(np.int32)
        prompts = rng.integers(2, V, size=(n, P_max)).astype(np.int32)
        return prompts, lengths

    def sample_prompt_batch(n):
        """Group mode tiles each distinct prompt ``group`` times — the
        cohort twin of submit_group, so both phases see the SAME prompt
        distribution at the same group shape."""
        if group > 1:
            k = max(n // group, 1)
            prompts, lengths = sample_prompts(k)
            reps = -(-n // k)
            prompts = np.repeat(prompts, reps, axis=0)[:n]
            lengths = np.repeat(lengths, reps, axis=0)[:n]
            return prompts, lengths
        return sample_prompts(n)

    # phase 1: fixed-cohort rounds at the same lane count
    cohort = GenerationEngine(model, params, GenerationConfig(**base))
    prompts, lengths = sample_prompt_batch(lanes)
    cohort.generate(prompts, lengths)  # warm/compile
    t0 = time.perf_counter()
    cohort_tokens = 0
    cohort_rounds = 0
    while time.perf_counter() - t0 < target_s or cohort_rounds < 2:
        prompts, lengths = sample_prompt_batch(lanes)
        result = cohort.generate(prompts, lengths)
        cohort_tokens += result.decode_tokens
        cohort_rounds += 1
    cohort_elapsed = time.perf_counter() - t0
    cohort_tps = cohort_tokens / cohort_elapsed
    cohort_seq_per_s = cohort_rounds * lanes / cohort_elapsed

    # phase 2: the continuous engine under Poisson prompt arrivals at
    # ~2x the cohort completion rate (saturating: the queue stays fed,
    # admission latency is the congestion signal in the artifact)
    engine = ContinuousEngine(
        model, params,
        ContinuousConfig(
            lanes=lanes, page_size=page_size, steps_per_macro=macro_steps,
            min_free_lanes=min_free,
            # ONE admission prompt bucket: a prefill dispatch per group
            # per bucket is the dominant overhead at CPU shapes, and the
            # pad waste of the collapsed ladder is far cheaper (measured)
            prompt_buckets=(P_max,),
            **base,
        ),
    )
    # arrivals in SEQUENCES stay at ~2x the cohort completion rate; in
    # group mode each Poisson arrival is one prompt fanned into `group`
    # lanes via submit_group (the GRPO shape the prefix-CoW fork serves)
    rate = 2.0 * cohort_seq_per_s / group
    # warm: churn several lane-fills through so the decode program AND the
    # admission (prompt, admit) bucket programs all compile off the clock
    n_warm = max(6 * lanes // group, 2)
    prompts, lengths = sample_prompts(n_warm)
    for i in range(n_warm):
        engine.submit_group(prompts[i], group, lengths[i])
    while engine.live_lanes or engine.pending or engine._inflight:
        engine.step()
    t0 = time.perf_counter()
    next_arrival = rng.exponential(1.0 / rate)
    cont_tokens = 0
    completed = 0
    occ0, macro0 = engine._occupancy_sum, engine.macro_steps
    while time.perf_counter() - t0 < target_s or completed < 2:
        now = time.perf_counter() - t0
        n_new = 0
        while next_arrival <= now:
            n_new += 1
            next_arrival += rng.exponential(1.0 / rate)
        if n_new:
            prompts, lengths = sample_prompts(n_new)
            for i in range(n_new):
                engine.submit_group(prompts[i], group, lengths[i])
        if engine.live_lanes == 0 and engine.pending == 0:
            continue  # idle until the next arrival lands
        done = engine.step()
        completed += len(done)
        cont_tokens += sum(len(c.response_tokens) for c in done)
    cont_elapsed = time.perf_counter() - t0
    cont_tps = cont_tokens / cont_elapsed
    # ratios (not rates): computed over the engine's whole lifetime —
    # warmup included, which runs the same group shape — so a short
    # measured window can never report an empty 0/0 sample
    saved = engine.prefix_tokens_saved
    total = engine.prefix_tokens_total
    hit_num = hit_den = 0
    if engine._prefix_cache is not None:
        hit_num = engine._prefix_cache.hits
        hit_den = hit_num + engine._prefix_cache.misses
    admit_hist = telemetry.get_registry().histogram(
        "genrl.admission_latency_s"
    )

    result_obj = {
        "metric": "genrl_decode_tokens_per_sec_per_chip",
        "mode": "genrl-continuous",
        "value": round(cont_tps, 1),
        "unit": f"decode tokens/sec/chip ({platform}, continuous)",
        "decode_tokens_per_sec": round(cont_tps, 1),
        "cohort_decode_tokens_per_sec": round(cohort_tps, 1),
        "speedup_vs_cohort": round(cont_tps / max(cohort_tps, 1e-9), 3),
        "lane_occupancy_mean": round(
            (engine._occupancy_sum - occ0)
            / max(engine.macro_steps - macro0, 1),
            4,
        ),
        "admission_latency_p50_ms": round(
            admit_hist.quantile(0.50) * 1e3, 3
        ),
        "admission_latency_p95_ms": round(
            admit_hist.quantile(0.95) * 1e3, 3
        ),
        "admission_latency_p99_ms": round(
            admit_hist.quantile(0.99) * 1e3, 3
        ),
        "completed_sequences": completed,
        "arrival_rate_per_s": round(rate, 2),
        "shed_total": engine._batcher.shed_total,
        # shared-prefix reuse (ISSUE 14): fraction of admitted full-page
        # prefix tokens whose prefill was skipped (cache hits + CoW group
        # shares), and the admission-level cache hit rate
        "prefill_tokens_saved_ratio": round(saved / max(total, 1), 4),
        "prefix_hit_rate": round(hit_num / max(hit_den, 1), 4),
        "steps_in_flight": engine.config.steps_in_flight,
        "lanes": lanes,
        "page_size": page_size,
        "macro_steps": macro_steps,
        "pages_capacity": engine.allocator.capacity,
        "vocab": V,
        "d_model": d_model,
        "num_layers": n_layers,
        "prompt_max": P_max,
        "response_budget": R,
        "iter_mode": engine.iter_mode,
        "device_kind": device_kind,
        "measured_s": round(cohort_elapsed + cont_elapsed, 1),
    }
    if group > 1:
        # the group shape keys its own like-for-like perf-gate history
        result_obj["group"] = group
    # packed-learner A/B fields (ISSUE 15) ride this artifact too — the
    # continuous plane feeds the same learner, so its artifact reports
    # the learn-side pad economics alongside the decode ones (the
    # token_ppo_learn_tokens_per_sec_per_chip field is gated in
    # tpu_watch).  BENCH_SKIP_LEARN_AB=1 drops the phase for callers that
    # only exercise the decode planes (the group-shape schema test).
    if not os.environ.get("BENCH_SKIP_LEARN_AB"):
        result_obj.update(_packed_learn_phase(on_accel))
    print(json.dumps(result_obj))


def _run_disagg_measurement() -> None:
    """``--mode disagg``: the disaggregated dataflow's headline numbers —
    end-to-end sequences/s through the full wire path (generation hosts
    behind jax-free shells -> codec-v2 pipe frames -> lease/ack/dedup ->
    the learner's accepted-sequence queue) and snapshot-push latency
    (``SequenceLearner.publish`` of an int8-quantized wire snapshot ->
    first accepted sequence decoded under the new generation).

    Hosts run as in-process threads with REAL fixed-cohort engines: the
    wire, lease accounting, and quantized snapshot adoption all flow
    exactly as in the process topology, without charging the bench two
    jax process spin-ups.
    """
    import threading as _threading

    import jax
    import numpy as np

    from scalerl_tpu.config import GenRLArguments
    from scalerl_tpu.genrl.disagg import (
        DisaggConfig,
        LocalGenerationFleet,
        SequenceLearner,
    )
    from scalerl_tpu.genrl.task import TokenRecallTask
    from scalerl_tpu.trainer.sequence_rl import (
        _CohortShellFactory,
        build_genrl_model,
    )
    from scalerl_tpu.utils.platform import setup_platform

    platform = setup_platform("auto")
    print("backend:", platform, flush=True)
    device_kind = jax.devices()[0].device_kind
    on_accel = platform in ("tpu", "gpu")

    if on_accel:
        V, d_model, n_layers, n_heads = 1024, 256, 4, 8
        P, R, lanes = 128, 128, 32
        target_s = 10.0
    else:
        V, d_model, n_layers, n_heads = 32, 32, 1, 4
        P, R, lanes = 8, 4, 4
        target_s = float(os.environ.get("BENCH_DISAGG_TARGET_S", "3.0"))

    args = GenRLArguments(
        vocab_size=V, prompt_len=P, max_new_tokens=R,
        d_model=d_model, n_layers=n_layers, n_heads=n_heads,
        telemetry_interval_s=0.0, logger_backend="none",
    )
    task = TokenRecallTask(vocab_size=V, prompt_len=P, response_len=R)
    model = build_genrl_model(args)
    params = model.init(
        jax.random.PRNGKey(0), jax.numpy.zeros((1, 2), jax.numpy.int32)
    )
    host_weights = jax.device_get(params)

    rng = np.random.default_rng(0)
    lease_lock = _threading.Lock()
    lease_seq = {"i": 0}

    def source():
        with lease_lock:
            lease_seq["i"] += 1
            prompts, lengths = task.sample_prompts(1, rng)
        n = int(lengths[0])
        return {
            "seed": lease_seq["i"],
            "prompt": prompts[0, :n].astype(np.int32),
            "length": n,
        }

    cfg = DisaggConfig(
        num_hosts=2, lanes_per_host=lanes, upload_batch=lanes,
        snapshot_quantize="int8", seq_maxsize=16 * lanes,
    )
    learner = SequenceLearner(cfg, source)
    learner.start()
    t_pub0 = time.perf_counter()
    learner.publish(host_weights, learner_step=0)
    quantize_ms = (time.perf_counter() - t_pub0) * 1e3
    fleet = LocalGenerationFleet(
        learner, cfg, _CohortShellFactory(args, lanes), use_threads=True
    )
    fleet.start()

    def drain_one(timeout=0.2):
        return learner.get_sequence(timeout=timeout)

    # warmup: both hosts compile their round program off the clock
    warm = 0
    warm_deadline = time.monotonic() + 300
    while warm < 4 * lanes and time.monotonic() < warm_deadline:
        if drain_one() is not None:
            warm += 1

    # measured window: accepted sequences over wall clock, with snapshot
    # pushes fired at quarter-window marks to measure publish->adoption
    t0 = time.perf_counter()
    accepted = 0
    push_lat_ms = []
    next_push = t0 + target_s / 4
    pending_push = None  # (generation, t_pub)
    step_count = 0
    while time.perf_counter() - t0 < target_s or accepted < 2:
        s = drain_one()
        now = time.perf_counter()
        if s is not None:
            accepted += 1
            if pending_push is not None and s["generation"] >= pending_push[0]:
                push_lat_ms.append((now - pending_push[1]) * 1e3)
                pending_push = None
        if pending_push is None and now >= next_push:
            step_count += 1
            gen = learner.publish(host_weights, learner_step=step_count)
            pending_push = (gen, time.perf_counter())
            next_push = now + target_s / 4
    elapsed = time.perf_counter() - t0
    learner.stop()
    fleet.join()

    result_obj = {
        "metric": "disagg_sequences_per_sec",
        "mode": "disagg",
        "value": round(accepted / elapsed, 2),
        "unit": f"end-to-end sequences/sec ({platform}, 2 hosts over the "
        "pipe wire)",
        "sequences_per_sec": round(accepted / elapsed, 2),
        "snapshot_push_latency_ms_p50": round(
            float(np.median(push_lat_ms)), 2
        )
        if push_lat_ms
        else None,
        # real tail quantiles (exact percentile over every sample, not the
        # reservoir max standing in for one)
        "snapshot_push_latency_ms_p95": round(
            float(np.percentile(push_lat_ms, 95)), 2
        )
        if push_lat_ms
        else None,
        "snapshot_push_latency_ms_p99": round(
            float(np.percentile(push_lat_ms, 99)), 2
        )
        if push_lat_ms
        else None,
        "snapshot_push_latency_ms_max": round(max(push_lat_ms), 2)
        if push_lat_ms
        else None,
        "snapshot_quantize_ms": round(quantize_ms, 2),
        "snapshot_wire_bytes": learner.snapshot_wire_bytes,
        "snapshot_pushes": step_count,
        "accepted_sequences": accepted,
        "duplicates_absorbed": learner.duplicate_sequences
        + learner.duplicate_leases,
        "dropped_stale": learner.dropped_sequences,
        # preemption plane (ISSUE 19): a fresh bench learner sits at
        # epoch 1 with zero resume traffic — the fields exist so a bench
        # run that ever rides a restored ledger is distinguishable
        "learner_epoch": learner.learner_epoch,
        "resumed_sequences_reissued": learner.resumed_sequences_reissued,
        "resumed_duplicates_dropped": learner.resumed_duplicates_dropped,
        "hosts": cfg.num_hosts,
        "lanes_per_host": lanes,
        "vocab": V,
        "d_model": d_model,
        "num_layers": n_layers,
        "prompt_bucket": P,
        "response_bucket": R,
        "device_kind": device_kind,
        "measured_s": round(elapsed, 1),
    }
    print(json.dumps(result_obj))


def _packed_learn_phase(on_accel: bool) -> dict:
    """Packed-vs-padded token-PPO learn A/B (ISSUE 15) on a MIXED-length
    workload (mean true length <= half the bucket — the regime the
    bin-packer exists for).

    The same agent runs both layouts: its learn fn dispatches on the
    batch's ``segment_ids`` key, so the A/B holds params, optimizer, and
    metric discipline constant and varies ONLY the input layout.  Both
    rates count REAL (response, mask=1) tokens over wall clock — the
    padded path is penalized exactly by the pad FLOPs it burns, which is
    the honest comparison.  Returns the artifact fields; the headline
    ``token_ppo_learn_tokens_per_sec_per_chip`` (the PACKED rate) also
    rides its own metric line gated like-for-like in tpu_watch.
    """
    import jax
    import numpy as np

    from scalerl_tpu.agents.token_ppo import TokenPPOAgent
    from scalerl_tpu.config import GenRLArguments
    from scalerl_tpu.genrl.rollout import pack_learner_batch
    from scalerl_tpu.runtime.dispatch import MetricsPipeline
    from scalerl_tpu.trainer.sequence_rl import build_genrl_model
    from scalerl_tpu.utils.buckets import bucket_for, default_buckets

    if on_accel:
        V, d_model, n_layers, n_heads = 1024, 256, 4, 8
        P = R = 128
        B = 64
        target_s = 5.0
    else:
        V, d_model, n_layers, n_heads = 32, 32, 1, 4
        P = R = 32
        B = 16
        target_s = 0.75
    target_s = float(os.environ.get("BENCH_LEARN_TARGET_S", target_s))

    args = GenRLArguments(
        vocab_size=V, prompt_len=P, max_new_tokens=R,
        d_model=d_model, n_layers=n_layers, n_heads=n_heads,
        genrl_batch=B, genrl_sample_batch=B,
        genrl_buffer_sequences=2 * B, learner_packing=True,
        telemetry_interval_s=0.0, logger_backend="none",
    )
    agent = TokenPPOAgent(args, build_genrl_model(args))
    S = P + R
    rng = np.random.default_rng(0)
    # mixed lengths, mean <= half the bucket on both axes
    plens = rng.integers(1, P // 2 + 1, B)
    rlens = rng.integers(1, R // 2 + 1, B)
    prompts = [rng.integers(1, V, n).astype(np.int32) for n in plens]
    resps = [rng.integers(1, V, n).astype(np.int32) for n in rlens]
    logps = [
        np.log(rng.uniform(0.05, 0.5, n)).astype(np.float32)
        for n in rlens
    ]
    vals = [rng.normal(0, 0.1, n).astype(np.float32) for n in rlens]
    rewards = rng.uniform(0, 1, B).astype(np.float32)
    gens = np.zeros(B, np.int32)

    # padded bucket-pair layout (the parity twin)
    tokens = np.zeros((B, S), np.int32)
    blogp = np.zeros((B, R), np.float32)
    bval = np.zeros((B, R), np.float32)
    mask = np.zeros((B, R), np.float32)
    for i in range(B):
        n, r = int(plens[i]), int(rlens[i])
        tokens[i, P - n : P] = prompts[i]
        tokens[i, P : P + r] = resps[i]
        blogp[i, :r] = logps[i]
        bval[i, :r] = vals[i]
        mask[i, :r] = 1.0
    padded = jax.device_put({
        "tokens": tokens, "behavior_logp": blogp, "value": bval,
        "mask": mask, "reward": rewards,
        "prompt_len": plens.astype(np.int32), "generation": gens,
    })
    pk = pack_learner_batch(
        prompts, resps, logps, vals, rewards, gens, pack_len=S
    )
    pk = pk.bucketed(bucket_for(max(pk.rows, 1), default_buckets(B)))
    fields, _prio = pk.fields()
    packed = jax.device_put(fields)
    real_tokens = int(mask.sum())

    def _measure(batch):
        m = agent.learn_device(batch)
        float(jax.device_get(m["total_loss"]))  # compile + sync
        pipe = MetricsPipeline(depth=2)
        t0 = time.perf_counter()
        steps = 0
        while time.perf_counter() - t0 < target_s or steps < 2:
            m = agent.learn_device(batch)
            steps += 1
            pipe.push(steps, m)
        pipe.drain()
        return steps * real_tokens / (time.perf_counter() - t0)

    padded_tps = _measure(padded)
    packed_tps = _measure(packed)
    return {
        "token_ppo_learn_tokens_per_sec_per_chip": round(packed_tps, 1),
        "padded_learn_tokens_per_sec": round(padded_tps, 1),
        "learn_speedup_vs_padded": round(
            packed_tps / max(padded_tps, 1e-9), 3
        ),
        # pad fraction of the PADDED layout on this workload — what the
        # packed path stops paying for (the OBSERVABILITY.md math)
        "learn_pad_ratio": round(
            1.0 - (int(plens.sum()) + real_tokens) / (B * S), 4
        ),
        "learn_packed_pad_ratio": round(pk.pad_ratio, 4),
        "learn_packed_rows": pk.rows,
        "learn_pack_len": S,
        "learn_batch_sequences": B,
    }


def _spec_decode_phase(on_accel: bool) -> dict:
    """Speculative-decode A/B (ISSUE 16): the continuous engine at the
    SAME shape/model/params/prompt distribution, speculation off vs on,
    in one artifact.

    Workload: token-recall prompts decoded greedily with a fixed response
    budget, so both engines emit the SAME tokens per round (greedy is
    deterministic and both see identical prompts) and the rate ratio is a
    pure speed ratio.  Greedy decode of the bench policy settles into
    repetitive continuations — exactly the structure the n-gram
    self-drafter exploits — so the reported ``spec_acceptance_rate``
    shows the regime where speculation pays; on incompressible output it
    degrades toward 1 token/pass (the docs/SEQUENCE_RL.md
    acceptance-rate table).

    Measurement design, tuned for a noisy CPU substrate:

    - **interleaved rounds** — each measured round runs through the OFF
      engine then the ON engine back-to-back, so host-load drift hits
      both sides equally instead of whichever phase ran second;
    - **long responses** — every lane occupancy re-pays the drafter's
      cold ramp (the AIMD cap regrows 1 -> 2 -> 4 -> ... -> k through
      the verify ladder's narrow buckets), a fixed per-occupancy cost
      that only amortizes when the steady full-``k`` stretch dominates.
      At the default response budget the spec side clears >1.2x on CPU;
      at short budgets the ramp eats the win — which is itself the
      honest answer the A/B exists to report.

    The headline ``genrl_spec_accepted_tokens_per_sec`` counts accepted
    (real) tokens over whole-round wall clock and is perf-gated
    like-for-like in tpu_watch alongside the decode headline."""
    import jax
    import numpy as np

    from scalerl_tpu.genrl.continuous import (
        ContinuousConfig,
        ContinuousEngine,
    )
    from scalerl_tpu.genrl.task import TokenRecallTask
    from scalerl_tpu.models.transformer import TransformerPolicy

    R = int(os.environ.get("BENCH_SPEC_RESPONSE", "512"))
    k = int(os.environ.get("BENCH_SPEC_K", "24"))
    if on_accel:
        V, d_model, n_layers, n_heads = 64, 256, 4, 8
        P, lanes, ps = 32, 64, 16
        target_s = 8.0
    else:
        V, d_model, n_layers, n_heads = 8, 32, 1, 4
        P, lanes, ps = 8, 8, 8
        target_s = float(os.environ.get("BENCH_SPEC_TARGET_S", "2.0"))
    task = TokenRecallTask(vocab_size=V, prompt_len=P, response_len=R)
    model = TransformerPolicy(
        num_actions=V, vocab_size=V, d_model=d_model, num_heads=n_heads,
        num_layers=n_layers, max_len=2 * (P + R),
    )
    params = model.init(
        jax.random.PRNGKey(2),
        jax.numpy.zeros((1, 2), jax.numpy.int32),
    )
    base = dict(
        vocab_size=V, max_prompt_len=P, max_new_tokens=R,
        temperature=0.0, eos_token=-1, seed=0,
        lanes=lanes, page_size=ps, steps_per_macro=8,
        prompt_buckets=(P,),
    )

    def make(spec_k):
        return ContinuousEngine(
            model, params, ContinuousConfig(spec_k=spec_k, **base)
        )

    def round_once(engine, prompts, lengths):
        for i in range(lanes):
            engine.submit(prompts[i], int(lengths[i]))
        done = tokens = 0
        while done < lanes:
            cs = engine.step()
            done += len(cs)
            tokens += sum(len(c.response_tokens) for c in cs)
        return tokens

    engines = (make(0), make(k))
    rng = np.random.default_rng(0)
    # warm until the verify ladder stops compiling new buckets for TWO
    # consecutive round pairs: a first pass through an unseen
    # draft-length bucket traces (~1s on CPU), and one stray compile
    # inside a measured round would swamp the signal the interleaving
    # exists to protect.  Rare buckets (a pass whose longest draft is 0
    # or 1 tokens) can surface several rounds in, hence the hysteresis.
    stable = 0
    while stable < 2:
        traces = engines[1]._verify_traces
        warm = task.sample_prompts(lanes, rng)
        for engine in engines:
            round_once(engine, *warm)
        stable = stable + 1 if engines[1]._verify_traces == traces else 0
    times = [0.0, 0.0]
    toks = [0, 0]
    rounds = 0
    while sum(times) < target_s or rounds < 2:
        prompts, lengths = task.sample_prompts(lanes, rng)
        for i, engine in enumerate(engines):
            t0 = time.perf_counter()
            toks[i] += round_once(engine, prompts, lengths)
            times[i] += time.perf_counter() - t0
        rounds += 1
    off_tps = toks[0] / times[0]
    on_tps = toks[1] / times[1]
    eng = engines[1]
    return {
        "genrl_spec_accepted_tokens_per_sec": round(on_tps, 1),
        "spec_off_tokens_per_sec": round(off_tps, 1),
        "spec_speedup": round(on_tps / max(off_tps, 1e-9), 3),
        "spec_acceptance_rate": round(eng.spec_acceptance_rate, 4),
        "spec_k": k,
        "spec_response_budget": R,
        "spec_rollback_pages": eng.spec_rollback_pages_total,
    }


def _run_genrl_measurement() -> None:
    """``--mode genrl``: the token-level sequence-RL plane's headline
    numbers — prefill tokens/s/chip and decode tokens/s/chip through the
    KV-cached generation engine, plus token-PPO learn steps/s.

    Three timed phases over the same model/params, all shape-stable:

    1. **prefill** — the jitted prefill-only program (one full-prompt
       forward filling the KV cache) driven through a 2-deep
       MetricsPipeline, ONE batched metric read per call;
    2. **decode** — whole generation rounds through
       ``GenerationEngine.generate`` (prefill + the fused decode loop in
       one dispatch, one batched read per round — the steady-state guard
       armed after the first round); decode tokens/s counts response
       tokens only, against the full round wall-clock, so the number is
       an honest end-to-end generation rate, not a prefill-subtracted
       estimate;
    3. **learn** — token-PPO steps on a packed batch, pipelined like the
       other learn benches.
    """
    import jax
    import numpy as np

    from scalerl_tpu.agents.token_ppo import TokenPPOAgent
    from scalerl_tpu.config import GenRLArguments
    from scalerl_tpu.genrl.rollout import pack_sequences
    from scalerl_tpu.genrl.task import TokenRecallTask
    from scalerl_tpu.runtime.dispatch import MetricsPipeline
    from scalerl_tpu.trainer.sequence_rl import SequenceRLTrainer
    from scalerl_tpu.utils.platform import setup_platform

    platform = setup_platform("auto")
    print("backend:", platform, flush=True)
    device_kind = jax.devices()[0].device_kind
    on_accel = platform in ("tpu", "gpu")

    if on_accel:
        V, d_model, n_layers, n_heads = 1024, 256, 4, 8
        P, R, B = 128, 128, 64
        target_s = 10.0
    else:
        V, d_model, n_layers, n_heads = 32, 32, 1, 4
        P, R, B = 8, 4, 4
        target_s = 1.5

    args = GenRLArguments(
        vocab_size=V, prompt_len=P, max_new_tokens=R,
        d_model=d_model, n_layers=n_layers, n_heads=n_heads,
        genrl_batch=B, genrl_sample_batch=B,
        genrl_buffer_sequences=2 * B,
        telemetry_interval_s=0.0, logger_backend="none",
    )
    task = TokenRecallTask(vocab_size=V, prompt_len=P, response_len=R)
    trainer = SequenceRLTrainer(args, task=task)
    engine, agent = trainer.engine, trainer.agent
    rng = np.random.default_rng(0)
    prompts, lengths = task.sample_prompts(B, rng)

    # phase 1: prefill-only tokens/s (pipelined, one batched read/call)
    pre = engine.prefill_program(P, R)
    aligned = engine._align_prompts(prompts, lengths, P)
    dev_tokens, dev_lengths = jax.device_put((aligned, lengths))
    params, _gen = engine._snapshot_params()
    logits0, value0, _cache = pre(params, dev_tokens, dev_lengths)
    float(value0[0])  # compile + host-fetch sync (tunnel-safe warmup)
    pipe = MetricsPipeline(depth=2)
    t0 = time.perf_counter()
    pre_calls = 0
    while time.perf_counter() - t0 < target_s / 2 or pre_calls < 2:
        logits0, value0, _cache = pre(params, dev_tokens, dev_lengths)
        pre_calls += 1
        pipe.push(pre_calls, value0[0])
    pipe.drain()
    pre_elapsed = time.perf_counter() - t0
    prefill_tps = pre_calls * B * P / pre_elapsed

    # phase 2: whole generation rounds (the engine's own one-read round)
    engine.generate(prompts, lengths)  # warm: compile the fused program
    t0 = time.perf_counter()
    rounds = 0
    decode_tokens = 0
    while time.perf_counter() - t0 < target_s or rounds < 2:
        result = engine.generate(prompts, lengths)
        rounds += 1
        decode_tokens += result.decode_tokens
    gen_elapsed = time.perf_counter() - t0
    decode_tps = decode_tokens / gen_elapsed

    # phase 3: token-PPO learn steps/s (pipelined batched metric reads)
    rewards = task.score(
        prompts, lengths, result.response_tokens, result.response_len
    )
    fields, _prio = pack_sequences(result, rewards)
    batch = jax.device_put(fields)
    m = agent.learn_device(batch)
    float(jax.device_get(m["total_loss"]))  # warmup sync
    pipe = MetricsPipeline(depth=2)
    t0 = time.perf_counter()
    steps = 0
    while time.perf_counter() - t0 < target_s / 2 or steps < 2:
        m = agent.learn_device(batch)
        steps += 1
        pipe.push(steps, m)
    pipe.drain()
    learn_elapsed = time.perf_counter() - t0

    result_obj = {
        "metric": "genrl_decode_tokens_per_sec_per_chip",
        "mode": "genrl",
        "value": round(decode_tps, 1),
        "unit": f"decode tokens/sec/chip ({platform})",
        "prefill_tokens_per_sec": round(prefill_tps, 1),
        "decode_tokens_per_sec": round(decode_tps, 1),
        "learn_steps_per_sec": round(steps / learn_elapsed, 2),
        "rounds_per_sec": round(rounds / gen_elapsed, 2),
        "vocab": V,
        "d_model": d_model,
        "num_layers": n_layers,
        "prompt_bucket": P,
        "response_bucket": R,
        "batch": B,
        "iter_mode": engine.iter_mode,
        "device_kind": device_kind,
        "measured_s": round(pre_elapsed + gen_elapsed + learn_elapsed, 1),
    }
    # phase 4 (ISSUE 15): packed-vs-padded learn A/B on a mixed-length
    # workload — the token_ppo_learn_tokens_per_sec_per_chip field is
    # perf-gated like-for-like in tpu_watch alongside the headline value
    # (the artifact stays ONE json line, the orchestrator's contract)
    result_obj.update(_packed_learn_phase(on_accel))
    # phase 5 (ISSUE 16): speculative-decode A/B on the continuous engine
    # at one shape — spec off vs on in the same artifact, with the
    # accepted-tokens/s headline gated like-for-like in tpu_watch
    result_obj.update(_spec_decode_phase(on_accel))
    print(json.dumps(result_obj))


def _mesh_axis(mesh_spec: str, axis: str) -> int:
    import re as _re

    m = _re.search(rf"{axis}=(\d+)", mesh_spec or "")
    return int(m.group(1)) if m else 1


def _run_measurement(
    mesh_spec: str | None = None, fast: str | None = None,
    mode: str | None = None,
) -> None:
    """Child mode: probe + measure in one process.

    Prints ``backend: X`` the moment the backend answers (the parent's
    probe deadline watches for this line), then runs the measurement and
    prints the JSON line.

    ``mesh_spec`` (e.g. ``"dp=8"``): run the fused loop data-parallel over
    a device mesh (the Anakin dp scaling the 8-device dryrun validates) and
    report AGGREGATE env-frames/sec plus per-chip — the north-star-shaped
    number for the day multi-chip hardware answers (BASELINE v5e-16 row).
    Per-chip batch is held constant, so this measures weak scaling.

    ``mode="anakin"``: drive the measurement through
    ``DeviceActorLearnerLoop.run_anakin`` — ONE host dispatch (a single
    jitted scan/unroll over env step -> policy -> V-trace learn) covers a
    whole super-chunk of chunks, with the steady-state transfer guard
    armed and ONE batched metric read per super-chunk.  Reports the same
    fps/chip shape plus MFU from the super-chunk executable's own XLA cost
    analysis.
    """
    import jax
    import jax.numpy as jnp  # noqa: F401

    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop
    from scalerl_tpu.utils.platform import setup_platform

    if mode == "sharded":
        # its own program entirely (dp×mp pjit train step on the
        # transformer policy); prints backend + one JSON line itself
        _run_sharded_measurement(mesh_spec)
        return
    if mode == "serving":
        # the centralized inference plane: requests/sec + latency SLO
        _run_serving_measurement()
        return
    if mode == "traffic":
        # the serving front door: open-loop goodput under SLO through the
        # multi-replica router
        _run_traffic_measurement()
        return
    if mode == "genrl":
        # the token-level sequence-RL plane: prefill/decode tokens/s +
        # token-PPO learn steps/s through the KV-cached engine
        _run_genrl_measurement()
        return
    if mode == "genrl-continuous":
        # the continuous-batching decode plane: paged-KV lane pool under
        # Poisson arrivals, like-for-like vs the fixed-cohort engine
        _run_genrl_continuous_measurement()
        return
    if mode == "disagg":
        # the disaggregated dataflow: generation hosts -> wire -> learner
        _run_disagg_measurement()
        return

    # backend already pinned by __main__ when --cpu; "auto" here just turns
    # on the persistent compilation cache (warm relaunches skip the 20-40 s
    # TPU compile of the fused loop)
    platform = setup_platform("auto")
    print("backend:", platform, flush=True)  # parent's probe watches this
    device_kind = jax.devices()[0].device_kind

    # Micro-witness first on accelerators: a durable artifact lands within
    # ~30 s of backend ack, so a tunnel window too short for the full
    # fused bench still leaves a timestamped TPU number (VERDICT r4 #1b).
    on_accel_now = platform in ("tpu", "gpu")
    if fast == "only" or (fast == "first" and on_accel_now and mesh_spec is None):
        _micro_witness(device_kind, platform)
        if fast == "only":
            return

    # batch/unroll sized for one chip (swept: B=512/iters=5 beats B=128/10
    # by ~21% — bigger batches keep the MXU busy between infeed boundaries);
    # CPU fallback shrinks to stay quick
    on_accel = platform in ("tpu", "gpu")
    mesh = None
    n_dev = 1
    if mesh_spec:
        from scalerl_tpu.parallel import make_mesh

        mesh = make_mesh(mesh_spec)
        n_dev = mesh.devices.size
        if mesh.shape["dp"] != n_dev:
            raise ValueError(
                f"--mesh {mesh_spec!r}: the fused loop shards env lanes over "
                "dp only; use a pure-dp spec (dp=N)"
            )
    # CPU-fallback mesh runs exist to prove the code path, not to measure
    # (8 virtual devices on one core): shrink so they finish in the
    # parent's give-up window.  BENCH_B overrides the accelerator batch
    # (the watcher sweeps it on tunnel contact: the 98k fps witness used
    # 512; more lanes may amortize the env scan further)
    if on_accel:
        try:
            B_chip = int(os.environ.get("BENCH_B", "512"))
        except ValueError:
            # a malformed override must degrade to the known-good batch,
            # not crash every post-ack attempt and forfeit the window
            B_chip = 512
    else:
        B_chip = 8 if mesh is None else 4
    B = B_chip * (n_dev if mesh is not None else 1)
    T = 20
    iters_per_call = 5 if on_accel else 1
    min_iters = 3 if (on_accel or mesh is None) else 1

    args = ImpalaArguments(
        use_lstm=False,
        hidden_size=512,
        rollout_length=T,
        batch_size=B,
        max_timesteps=0,
        # mixed precision on accelerators: conv/dense torso in bfloat16 feeds
        # the MXU at full rate; params, V-trace, and the optimizer stay f32
        # (standard IMPALA mixed-precision recipe, tested in
        # tests/test_impala.py::test_impala_bfloat16_compute_dtype)
        compute_dtype="bfloat16" if on_accel else "float32",
    )
    env = SyntheticPixelEnv()
    venv = JaxVecEnv(env, num_envs=B)
    agent = ImpalaAgent(args, obs_shape=env.observation_shape, num_actions=env.num_actions)
    learn = agent.make_learn_fn(grad_axis="dp" if mesh is not None else None)
    loop = DeviceActorLearnerLoop(
        model=agent.model,
        venv=venv,
        learn_fn=learn,
        unroll_length=T,
        iters_per_call=iters_per_call,
        mesh=mesh,
    )

    key = jax.random.PRNGKey(0)
    carry = loop.init_carry(key)
    state = agent.state
    frames_per_call = T * B * iters_per_call

    if mode == "anakin":
        _run_anakin_measurement(
            loop, state, carry, key, platform, device_kind,
            frames_per_call, on_accel,
        )
        return

    # AOT-compile the fused program ONCE and run the measurement through the
    # executable: the same compile yields XLA's FLOPs estimate (the MFU
    # numerator) and the jit dispatch path is never hit, so there is no
    # second compile of an identical program eating the attempt window.
    flops_per_call = None
    run_fn = loop._train_many
    if mesh is None:
        try:
            compiled = loop._train_many.lower(
                state, carry, jax.random.PRNGKey(1)
            ).compile()
            flops_per_call = _cost_analysis_flops(compiled)
            run_fn = compiled
        except Exception:  # noqa: BLE001 — fall back to the jit path, no MFU
            pass
    # mesh mode: _train_many builds its shard_map program lazily on first
    # call; MFU comes from the single-chip bench, this mode measures scaling

    # warmup: one full call.  Synchronize by *fetching a scalar*: under the
    # axon tunnel block_until_ready can return before the program finishes,
    # but a host transfer of an output cannot.
    state, carry, m = run_fn(state, carry, jax.random.PRNGKey(1))
    float(m["total_loss"])

    from scalerl_tpu.runtime.dispatch import MetricsPipeline

    target_s = 20.0 if on_accel else 4.0
    frames = 0
    # pipelined driver: 2 chunks in flight, ONE batched metric read per
    # chunk (lagged a chunk behind the device, so the host never stalls
    # it); drain() is the final host-fetch sync before the clock stops —
    # still a host transfer, which under the axon tunnel is the only
    # trustworthy completion signal (block_until_ready is not)
    pipe = MetricsPipeline(depth=2)
    # --profile-dir / BENCH_PROFILE_DIR: capture a device+host trace of the
    # measured window with one step_marker per fused chunk so the trace
    # viewer lines chunks up against the telemetry spans (a no-op when
    # unset; tracing perturbs the measurement, so profile runs are for
    # understanding the number, not reporting it)
    from scalerl_tpu.utils.profiling import maybe_trace, step_marker

    profile_dir = os.environ.get("BENCH_PROFILE_DIR") or None
    t0 = time.perf_counter()
    i = 0
    with maybe_trace(profile_dir):
        while True:
            key, sub = jax.random.split(key)
            with step_marker(i):
                state, carry, metrics = run_fn(state, carry, sub)
            i += 1
            frames += frames_per_call
            pipe.push(i, metrics)
            if time.perf_counter() - t0 >= target_s and i >= min_iters:
                break
        pipe.drain()
    elapsed = time.perf_counter() - t0

    fps = frames / elapsed
    if mesh is not None:
        # aggregate number, shaped like the BASELINE north star (>=100k
        # aggregate env-frames/sec on a v5e-16)
        result = {
            "metric": "impala_atari_env_frames_per_sec_aggregate",
            "value": round(fps, 1),
            "unit": f"frames/sec aggregate ({platform} x{n_dev})",
            "vs_baseline": round(fps / 100_000, 3),
            "per_chip": round(fps / n_dev, 1),
            "mesh": mesh_spec,
            "device_kind": device_kind,
            "batch": B,
            "unroll": T,
            "measured_s": round(elapsed, 1),
        }
        print(json.dumps(result))
        return
    result = {
        "metric": "impala_atari_env_frames_per_sec_per_chip",
        "value": round(fps, 1),
        "unit": f"frames/sec/chip ({platform})",
        "vs_baseline": round(fps / BASELINE_FPS_PER_CHIP, 3),
        "device_kind": device_kind,
        "batch": B,
        "unroll": T,
        "measured_s": round(elapsed, 1),
    }
    if flops_per_call is not None:
        achieved = flops_per_call * i / elapsed
        result["flops_per_frame"] = round(flops_per_call / frames_per_call)
        result["achieved_tflops_per_s"] = round(achieved / 1e12, 2)
        peak = _peak_flops(device_kind)
        if peak is not None:
            result["mfu"] = round(achieved / peak, 4)
    _measured_drift(result)
    print(json.dumps(result))


def _run_anakin_measurement(
    loop, state, carry, key, platform, device_kind, frames_per_call, on_accel
) -> None:
    """``--mode anakin``: the whole-run single-dispatch fused path.

    Each measured dispatch is one super-chunk — ``SC`` chunks of (env
    unroll -> policy -> V-trace learn) inside ONE jitted program, with the
    steady-state transfer guard armed and ONE batched metric read covering
    all of them.  MFU comes from the super-chunk executable's own cost
    analysis, exactly like the default mode.
    """
    import jax

    from scalerl_tpu.runtime import dispatch
    from scalerl_tpu.runtime.dispatch import get_metrics

    SC = int(os.environ.get("BENCH_SUPERCHUNK", "10" if on_accel else "4"))
    from functools import partial as _partial

    flops_per_super = None
    run_fn = None
    try:
        compiled = jax.jit(
            _partial(loop._superchunk_impl, num_chunks=SC),
            donate_argnums=(0, 1),
        ).lower(state, carry, jax.random.PRNGKey(1)).compile()
        flops_per_super = _cost_analysis_flops(compiled)
        run_fn = compiled
    except Exception:  # noqa: BLE001 — fall back to the jit cache, no MFU
        run_fn = lambda s, c, k: loop.train_superchunk(s, c, k, SC)  # noqa: E731

    # warmup (compile + constants); sync via host fetch like the main mode
    state, carry, m = run_fn(state, carry, jax.random.PRNGKey(1))
    float(get_metrics(m)["total_loss"][0])

    target_s = 20.0 if on_accel else 4.0
    min_iters = 1
    frames = 0
    t0 = time.perf_counter()
    i = 0
    while True:
        key, sub = jax.random.split(key)
        # steady state: one dispatch + one batched read per super-chunk,
        # with implicit host transfers hard-disallowed
        with dispatch.steady_state_guard():
            state, carry, m = run_fn(state, carry, sub)
            host = get_metrics(m)
        i += 1
        frames += frames_per_call * SC
        if time.perf_counter() - t0 >= target_s and i >= min_iters:
            break
    elapsed = time.perf_counter() - t0
    fps = frames / elapsed
    result = {
        "metric": "impala_atari_env_frames_per_sec_per_chip",
        "mode": "anakin",
        "value": round(fps, 1),
        "unit": f"frames/sec/chip ({platform}, anakin x{SC})",
        "vs_baseline": round(fps / BASELINE_FPS_PER_CHIP, 3),
        "device_kind": device_kind,
        "batch": loop.venv.num_envs,
        "unroll": loop.unroll_length,
        "superchunk": SC,
        "dispatches": i,
        "loss_last": round(float(host["total_loss"][-1]), 4),
        "measured_s": round(elapsed, 1),
    }
    if flops_per_super is not None:
        achieved = flops_per_super * i / elapsed
        result["flops_per_frame"] = round(flops_per_super / (frames_per_call * SC))
        result["achieved_tflops_per_s"] = round(achieved / 1e12, 2)
        peak = _peak_flops(device_kind)
        if peak is not None:
            result["mfu"] = round(achieved / peak, 4)
    print(json.dumps(result))


def _mesh_device_total(mesh_spec: str) -> int:
    import re as _re

    total = 1
    for n in _re.findall(r"\d+", mesh_spec):
        total *= int(n)
    return max(total, 1)


class _Child:
    """A supervised measurement subprocess with line-buffered stdout."""

    def __init__(
        self,
        cpu: bool,
        mesh_spec: str | None = None,
        fast: str | None = None,
        learn: bool = False,
        mode: str | None = None,
    ) -> None:
        env = dict(os.environ)
        cmd = [sys.executable, str(Path(__file__).resolve()), "--run"]
        if mesh_spec:
            cmd += ["--mesh", mesh_spec]
        if fast:
            cmd += ["--fast-mode", fast]
        if learn:
            cmd += ["--learn-run"]
        if mode:
            cmd += ["--bench-mode", mode]
        if cpu:
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                if mesh_spec:
                    n = _mesh_device_total(mesh_spec)
                elif mode == "sharded":
                    n = 8  # default dp=4,mp=2 virtual mesh for the CPU path
                else:
                    n = 1
                env["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={n}"
                ).strip()
            cmd.append("--cpu")
        self.proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True
        )
        self.lines: list[str] = []
        self._err_tail: list[str] = []  # bounded; drained concurrently
        self._cond = threading.Condition()
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()
        # stderr must be drained WHILE the child runs: jax/libtpu log there,
        # and an undrained 64 KB pipe would block the child mid-measurement
        # (then the parent would kill a healthy child as "hung")
        self._err_reader = threading.Thread(target=self._read_err, daemon=True)
        self._err_reader.start()

    def _read(self) -> None:
        assert self.proc.stdout is not None
        for line in self.proc.stdout:
            with self._cond:
                self.lines.append(line.strip())
                self._cond.notify_all()
        with self._cond:
            self._cond.notify_all()

    def _read_err(self) -> None:
        assert self.proc.stderr is not None
        for line in self.proc.stderr:
            self._err_tail.append(line.rstrip())
            if len(self._err_tail) > 50:
                del self._err_tail[:-20]

    def wait_for(self, pred, timeout_s: float):
        """First stdout line matching ``pred`` within ``timeout_s``, else None."""
        deadline = time.monotonic() + timeout_s
        seen = 0
        with self._cond:
            while True:
                for line in self.lines[seen:]:
                    if pred(line):
                        return line
                seen = len(self.lines)
                # "dead" means the READER finished (EOF seen): proc.poll()
                # can flip before the reader drains the final buffered
                # lines, which would discard a completed measurement
                if not self._reader.is_alive() and seen == len(self.lines):
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(timeout=min(remaining, 1.0))

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass

    def error_tail(self) -> str:
        self._err_reader.join(timeout=2.0)
        return " | ".join(self._err_tail[-3:])[-400:]


def _is_json(line: str) -> bool:
    if not (line.startswith("{") and line.endswith("}")):
        return False
    try:
        json.loads(line)
    except ValueError:
        return False
    return True


def _log_tpu_success(line: str) -> None:
    """Append a timestamped artifact for every witnessed TPU number."""
    try:
        path = Path(__file__).resolve().parent / "BENCH_TPU.md"
        stamp = time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())
        with path.open("a") as f:
            f.write(f"- `{stamp}` `{line}`\n")
    except OSError:
        pass


def _is_micro(line: str) -> bool:
    return _is_json(line) and json.loads(line).get("metric") == "tpu_micro_witness_tflops"


def main(
    mesh_spec: str | None = None,
    fast_only: bool = False,
    learn: bool = False,
    mode: str | None = None,
) -> None:
    deadline = time.monotonic() + BUDGET_S
    errors: list[str] = []
    # failure artifacts must carry the metric of the mode that FAILED —
    # a dead --learn run labeled as the fused env-fps bench would record
    # a bogus zero datapoint under the flagship metric
    fail_metric = (
        "impala_learn_step_frames_per_sec" if learn
        else "sharded_train_step_frames_per_sec" if mode == "sharded"
        else "serving_requests_per_sec" if mode == "serving"
        else "traffic_goodput_rps" if mode == "traffic"
        else "genrl_decode_tokens_per_sec_per_chip"
        if mode in ("genrl", "genrl-continuous")
        else "disagg_sequences_per_sec" if mode == "disagg"
        else "impala_atari_env_frames_per_sec_aggregate" if mesh_spec
        else "impala_atari_env_frames_per_sec_per_chip"
    )

    # CPU fallback starts now, in parallel — pinned to cpu so it never
    # touches the tunnel; result is banked for the give-up path.  In
    # --fast mode the fallback is the quick micro witness too: the whole
    # point of the flag is an artifact in seconds, not the full fused
    # CPU bench.
    cpu_child = _Child(
        cpu=True, mesh_spec=mesh_spec,
        fast="only" if fast_only else None, learn=learn, mode=mode,
    )

    # If the DRIVER's own timeout kills this process before the budget
    # elapses, still emit the one promised JSON line: print whatever the
    # CPU child has banked (or an error line) on SIGTERM and exit.
    import signal

    live_children = [cpu_child]  # the TPU child joins per attempt

    def _on_term(signum, frame):  # noqa: ARG001
        line = next((l for l in cpu_child.lines if _is_json(l)), None)
        if line is not None:
            obj = json.loads(line)
            obj["error"] = (
                "killed before budget elapsed (driver timeout); banked CPU "
                "fallback: " + "; ".join(errors)[-400:]
            )
            print(json.dumps(obj), flush=True)
        else:
            print(
                json.dumps(
                    {
                        "metric": fail_metric,
                        "value": 0.0,
                        "unit": "unavailable",
                        "vs_baseline": 0.0,
                        "error": "killed before any measurement finished: "
                        + "; ".join(errors)[-400:],
                    }
                ),
                flush=True,
            )
        # reap the JAX subprocesses: an orphaned TPU child would hold the
        # device for up to its full measurement window after we exit
        for c in live_children:
            try:
                c.kill()
            except Exception:  # noqa: BLE001 — exiting anyway
                pass
        os._exit(0)

    def _disarm() -> None:
        # exactly ONE JSON line: a SIGTERM landing after the final print
        # must not add a second, contradictory line
        signal.signal(signal.SIGTERM, signal.SIG_DFL)

    signal.signal(signal.SIGTERM, _on_term)

    tpu_line = None
    micro_banked = False
    probe_idx = 0
    while time.monotonic() < deadline - 30:
        probe_s = PROBE_SCHEDULE_S[min(probe_idx, len(PROBE_SCHEDULE_S) - 1)]
        probe_idx += 1
        probe_s = min(probe_s, max(deadline - time.monotonic() - 10, 15))
        child = _Child(
            cpu=False,
            mesh_spec=mesh_spec,
            # once a micro artifact is banked this run, later attempts go
            # straight to the full bench — no duplicate BENCH_TPU.md rows,
            # no ~30 s of a possibly-short window re-measuring it.  Learn
            # mode has its own single program; no micro phase.
            # BENCH_SKIP_MICRO: the dedup is process-local, so payload
            # steps AFTER the banking bench-fast step set it to spend
            # their whole post-ack window on their own measurement.
            fast=(
                None if learn or os.environ.get("BENCH_SKIP_MICRO")
                else ("only" if fast_only else (None if micro_banked else "first"))
            ),
            learn=learn,
            mode=mode,
        )
        live_children.append(child)
        backend_line = child.wait_for(lambda l: l.startswith("backend:"), probe_s)
        if backend_line is None:
            child.kill()
            if child.proc.returncode not in (None, -9):
                errors.append(f"probe rc={child.proc.returncode}: {child.error_tail()}")
            else:
                errors.append(f"probe timeout after {probe_s:.0f}s")
            time.sleep(min(10, max(0, deadline - time.monotonic())))
            continue
        backend = backend_line.split(":", 1)[1].strip()
        if backend not in ("tpu", "gpu"):
            # default backend IS cpu — no accelerator behind the tunnel;
            # the dedicated pinned-CPU child is the authoritative number
            child.kill()
            break
        measure_s = min(MEASURE_TIMEOUT_S, max(deadline - time.monotonic(), 60))
        json_line = child.wait_for(_is_json, measure_s)
        if json_line is not None and _is_micro(json_line):
            # bank the micro artifact THE MOMENT it lands — a tunnel drop
            # during the full bench no longer loses the whole window
            _log_tpu_success(json_line)
            micro_banked = True
            if fast_only:
                tpu_line = json_line
                child.kill()
                break
            micro = json_line
            # recompute against the deadline: reusing the pre-micro
            # measure_s would let the wait overrun BUDGET_S by a full
            # MEASURE_TIMEOUT_S
            measure_s = min(
                MEASURE_TIMEOUT_S, max(deadline - time.monotonic(), 60)
            )
            json_line = child.wait_for(
                lambda l: _is_json(l) and l != micro, measure_s
            )
        if json_line is not None:
            tpu_line = json_line
            child.kill()
            break
        child.kill()
        errors.append(
            f"{backend} measurement failed/hung after backend ack "
            f"(limit {measure_s:.0f}s): {child.error_tail()}"
        )

    if tpu_line is not None:
        cpu_child.kill()
        if not _is_micro(tpu_line):  # micro lines were banked on arrival
            _log_tpu_success(tpu_line)
        _disarm()
        print(tpu_line)
        return

    # Give-up path: surface the banked CPU number, annotated.  The probe
    # loop runs the budget down to ~0, so always grant the CPU child real
    # grace beyond the deadline — a number slightly past budget beats a
    # 0.0 line on time (the child usually finished long ago and this
    # returns instantly from the buffered line).
    cpu_wait = max(deadline - time.monotonic(), 0) + 240
    line = cpu_child.wait_for(_is_json, min(cpu_wait, CPU_ATTEMPT_TIMEOUT_S))
    if line is not None:
        obj = json.loads(line)
        if errors:
            obj["error"] = "tpu backend failed, CPU fallback: " + "; ".join(errors)[-600:]
        _disarm()
        print(json.dumps(obj))
        cpu_child.kill()
        return
    cpu_child.kill()
    errors.append(f"cpu fallback: no result ({cpu_child.error_tail()})")
    _disarm()
    print(
        json.dumps(
            {
                "metric": fail_metric,
                "value": 0.0,
                "unit": "unavailable",
                "vs_baseline": 0.0,
                "error": "; ".join(errors)[-800:],
            }
        )
    )


def _argv_mesh() -> str | None:
    argv = sys.argv[1:]
    if "--mesh" in argv:
        i = argv.index("--mesh")
        if i + 1 >= len(argv):
            raise SystemExit("--mesh requires a spec argument, e.g. --mesh dp=8")
        return argv[i + 1]
    return None


if __name__ == "__main__":
    if "--profile-dir" in sys.argv[1:]:
        # ride through the environment so the measurement CHILD (a separate
        # process) sees it; RLArguments.profile_dir covers trainer runs
        _i = sys.argv.index("--profile-dir")
        if _i + 1 >= len(sys.argv):
            raise SystemExit("--profile-dir requires a directory argument")
        os.environ["BENCH_PROFILE_DIR"] = sys.argv[_i + 1]
    if "--probe" in sys.argv[1:]:  # kept for manual tunnel checks
        import jax

        print("backend:", jax.default_backend(), flush=True)
    elif "--run" in sys.argv[1:]:
        if "--cpu" in sys.argv[1:]:
            import jax

            jax.config.update("jax_platforms", "cpu")
        fast_mode = None
        if "--fast-mode" in sys.argv[1:]:
            fast_mode = sys.argv[sys.argv.index("--fast-mode") + 1]
        bench_mode = None
        if "--bench-mode" in sys.argv[1:]:
            bench_mode = sys.argv[sys.argv.index("--bench-mode") + 1]
        try:
            if "--learn-run" in sys.argv[1:]:
                _run_learn_measurement()
            else:
                _run_measurement(_argv_mesh(), fast=fast_mode, mode=bench_mode)
        except Exception:  # noqa: BLE001 — parent needs the traceback on stderr
            import traceback

            traceback.print_exc()
            sys.exit(1)
    else:
        if "--learn" in sys.argv[1:] and _argv_mesh() is not None:
            raise SystemExit(
                "--learn --mesh is not supported: the learn bench measures "
                "one device (run bench.py --mesh for the multi-chip shape)"
            )
        _mode = None
        if "--mode" in sys.argv[1:]:
            _mi = sys.argv.index("--mode")
            if _mi + 1 >= len(sys.argv):
                raise SystemExit("--mode requires an argument (anakin | sharded)")
            _mode = sys.argv[_mi + 1]
            if _mode not in (
                "anakin", "sharded", "serving", "traffic", "genrl", "disagg"
            ):
                raise SystemExit(
                    f"unknown --mode {_mode!r}; supported: anakin, sharded, "
                    "serving, traffic, genrl, disagg"
                )
            if _mode == "genrl" and "--continuous" in sys.argv[1:]:
                # --mode genrl --continuous: the continuous-batching decode
                # variant (its own like-for-like history under mode
                # "genrl-continuous", same headline metric)
                _mode = "genrl-continuous"
        try:
            main(
                _argv_mesh(),
                fast_only="--fast" in sys.argv[1:],
                learn="--learn" in sys.argv[1:],
                mode=_mode,
            )
        except Exception as e:  # noqa: BLE001 — must always print one JSON line
            print(
                json.dumps(
                    {
                        "metric": (
                            "impala_learn_step_frames_per_sec"
                            if "--learn" in sys.argv[1:]
                            else "sharded_train_step_frames_per_sec"
                            if _mode == "sharded"
                            else "serving_requests_per_sec"
                            if _mode == "serving"
                            else "traffic_goodput_rps"
                            if _mode == "traffic"
                            else "genrl_decode_tokens_per_sec_per_chip"
                            if _mode in ("genrl", "genrl-continuous")
                            else "disagg_sequences_per_sec"
                            if _mode == "disagg"
                            else "impala_atari_env_frames_per_sec_aggregate"
                            if _argv_mesh() is not None
                            else "impala_atari_env_frames_per_sec_per_chip"
                        ),
                        "value": 0.0,
                        "unit": "unavailable",
                        "vs_baseline": 0.0,
                        "error": f"orchestrator: {type(e).__name__}: {e}"[:800],
                    }
                )
            )
