"""Headline benchmark: IMPALA Atari-shaped env-frames/sec on one chip.

Runs the flagship path — the fully-fused on-device actor-learner loop
(``scalerl_tpu/runtime/device_loop.py``: env step + AtariNet forward +
action sample + V-trace learner update, all one XLA program) — on the
synthetic Atari-shaped pixel env at real frame shapes ``[84, 84, 4]``.

Baseline: the driver target (BASELINE.json north star) of >=100k
env-frames/sec aggregate on a v5e-16, i.e. 6,250 frames/sec/chip;
``vs_baseline`` is measured frames/sec/chip over that number.

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

import jax

BASELINE_FPS_PER_CHIP = 100_000 / 16  # v5e-16 north star, per chip


def main() -> None:
    import jax.numpy as jnp

    from scalerl_tpu.agents.impala import ImpalaAgent, make_impala_learn_fn
    from scalerl_tpu.config import ImpalaArguments
    from scalerl_tpu.envs.jax_envs.base import JaxVecEnv
    from scalerl_tpu.envs.jax_envs.synthetic import SyntheticPixelEnv
    from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

    platform = jax.default_backend()
    # batch/unroll sized for one chip (swept: B=512/iters=5 beats B=128/10
    # by ~21% — bigger batches keep the MXU busy between infeed boundaries);
    # CPU fallback shrinks to stay quick
    on_accel = platform in ("tpu", "gpu")
    B = 512 if on_accel else 16
    T = 20
    iters_per_call = 5 if on_accel else 2

    args = ImpalaArguments(
        use_lstm=False,
        hidden_size=512,
        rollout_length=T,
        batch_size=B,
        max_timesteps=0,
    )
    env = SyntheticPixelEnv()
    venv = JaxVecEnv(env, num_envs=B)
    agent = ImpalaAgent(args, obs_shape=env.observation_shape, num_actions=env.num_actions)
    learn = make_impala_learn_fn(agent.model, agent.optimizer, args)
    loop = DeviceActorLearnerLoop(
        model=agent.model,
        venv=venv,
        learn_fn=learn,
        unroll_length=T,
        iters_per_call=iters_per_call,
    )

    key = jax.random.PRNGKey(0)
    carry = loop.init_carry(key)
    state = agent.state
    frames_per_call = T * B * iters_per_call

    # warmup: compile + one full call.  Synchronize by *fetching a scalar*:
    # under the axon tunnel block_until_ready can return before the program
    # finishes, but a host transfer of an output cannot.
    state, carry, m = loop._train_many(state, carry, jax.random.PRNGKey(1))
    float(m["total_loss"])

    target_s = 20.0 if on_accel else 8.0
    frames = 0
    t0 = time.perf_counter()
    i = 0
    while True:
        key, sub = jax.random.split(key)
        state, carry, metrics = loop._train_many(state, carry, sub)
        i += 1
        frames += frames_per_call
        float(metrics["total_loss"])
        if time.perf_counter() - t0 >= target_s and i >= 3:
            break
    elapsed = time.perf_counter() - t0

    fps = frames / elapsed
    print(
        json.dumps(
            {
                "metric": "impala_atari_env_frames_per_sec_per_chip",
                "value": round(fps, 1),
                "unit": f"frames/sec/chip ({platform})",
                "vs_baseline": round(fps / BASELINE_FPS_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
