"""DQN agent: jitted double-DQN learner + device eps-greedy actor.

Parity target: ``DQNAgent`` (``scalerl/algorithms/dqn/dqn_agent.py:19-233``):
double-DQN targets, soft/hard target updates, linear eps decay, optional
PER importance weights, checkpoint save/load.  TPU-shaped design:

- All state (online params, target params, optimizer state, step counter)
  lives in one ``DQNTrainState`` pytree; ``learn`` is a pure jitted function
  with donated state, so the update runs in-place in HBM.
- The reference's ``accelerator.prepare``/``backward`` DDP machinery
  (``dqn_agent.py:194-198,173-174``) is replaced by constructing the train
  step under ``jax.jit``; ``DQNAgent.enable_mesh`` pjit-s the same learn
  core over a device mesh with the batch axis sharded (see
  ``scalerl_tpu.parallel``) — gradients then all-reduce over ICI, the DDP
  capability as one method call.
- Target-net updates are pure pytree ops inside the step (no host sync).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from scalerl_tpu.agents.base import BaseAgent
from scalerl_tpu.config import DQNArguments
from scalerl_tpu.models.mlp import C51QNet, QNet
from scalerl_tpu.ops.losses import (
    c51_loss,
    categorical_projection,
    categorical_q_values,
    double_dqn_targets,
    dqn_loss,
    make_support,
)
from scalerl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint
from scalerl_tpu.utils.schedulers import LinearDecayScheduler
from scalerl_tpu.utils.tree import soft_target_update


@struct.dataclass
class DQNTrainState:
    params: Any
    target_params: Any
    opt_state: Any
    step: jnp.ndarray  # int32


def _make_learn_core(
    optimizer: optax.GradientTransformation,
    gamma: float,
    n_step: int,
    use_soft_update: bool,
    soft_update_tau: float,
    target_update_frequency: int,
    make_loss_fn,
):
    """Shared (state, batch) -> (state, metrics, per_sample) update plumbing.

    ``make_loss_fn(state, obs, next_obs, actions, rewards, discounts,
    weights)`` returns the variant's ``loss_fn(params) -> (loss,
    (per_sample, q))`` — scalar-Q TD loss or C51 cross-entropy; everything
    else (batch unpack, n-step discounts, grad/optimizer step, soft/hard
    target update, metrics) is identical between the variants and lives here
    once.
    """

    def learn(state: DQNTrainState, batch: Mapping[str, jnp.ndarray]):
        obs = batch["obs"]
        next_obs = batch["next_obs"]
        actions = batch["action"].astype(jnp.int32)
        rewards = batch["reward"].astype(jnp.float32)
        dones = batch["done"].astype(jnp.float32)
        weights = batch.get("weights")
        # n-step samples discount by gamma^k with the realized window length
        n_steps = batch.get("n_steps")
        if n_steps is None:
            discounts = (1.0 - dones) * (gamma**n_step)
        else:
            discounts = (1.0 - dones) * (gamma ** n_steps.astype(jnp.float32))

        loss_fn = make_loss_fn(
            state, obs, next_obs, actions, rewards, discounts, weights
        )
        (loss, (per_sample, q)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)

        step = state.step + 1
        if use_soft_update:
            target_params = soft_target_update(
                params, state.target_params, soft_update_tau
            )
        else:
            do_update = (step % target_update_frequency) == 0
            target_params = jax.tree_util.tree_map(
                lambda o, t: jnp.where(do_update, o, t), params, state.target_params
            )

        new_state = DQNTrainState(
            params=params,
            target_params=target_params,
            opt_state=opt_state,
            step=step,
        )
        metrics = {
            "loss": loss,
            "td_error_mean": jnp.mean(per_sample),
            "q_mean": jnp.mean(q),
        }
        return new_state, metrics, per_sample

    return learn


def make_dqn_learn_fn(
    network: QNet,
    optimizer: optax.GradientTransformation,
    gamma: float,
    n_step: int,
    double_dqn: bool,
    use_soft_update: bool,
    soft_update_tau: float,
    target_update_frequency: int,
):
    """Build the pure (state, batch) -> (state, metrics) update function."""

    def make_loss_fn(state, obs, next_obs, actions, rewards, discounts, weights):
        q_next_online = network.apply(state.params, next_obs)
        q_next_target = network.apply(state.target_params, next_obs)
        targets = double_dqn_targets(
            q_next_online, q_next_target, rewards, discounts, double_dqn=double_dqn
        )

        def loss_fn(params):
            q = network.apply(params, obs)
            loss, td_abs = dqn_loss(q, actions, targets, weights=weights)
            return loss, (td_abs, q)

        return loss_fn

    return _make_learn_core(
        optimizer,
        gamma,
        n_step,
        use_soft_update,
        soft_update_tau,
        target_update_frequency,
        make_loss_fn,
    )


def make_c51_learn_fn(
    network: C51QNet,
    optimizer: optax.GradientTransformation,
    support: jnp.ndarray,
    gamma: float,
    n_step: int,
    double_dqn: bool,
    use_soft_update: bool,
    soft_update_tau: float,
    target_update_frequency: int,
):
    """Categorical (C51) variant of :func:`make_dqn_learn_fn`.

    Same train-state plumbing (``_make_learn_core``); the TD target becomes
    the projected Bellman distribution (``ops/losses.categorical_projection``)
    and the loss the cross-entropy to it.  Per-sample CE doubles as the PER
    priority signal.
    """

    def make_loss_fn(state, obs, next_obs, actions, rewards, discounts, weights):
        logits_next_t = network.apply(state.target_params, next_obs)  # [B,A,N]
        if double_dqn:
            logits_next_o = network.apply(state.params, next_obs)
            next_q = categorical_q_values(logits_next_o, support)
        else:
            next_q = categorical_q_values(logits_next_t, support)
        next_actions = jnp.argmax(next_q, axis=-1)  # [B]
        next_probs = jax.nn.softmax(
            jnp.take_along_axis(
                logits_next_t, next_actions[:, None, None], axis=1
            )[:, 0],
            axis=-1,
        )  # [B, N]
        target_probs = categorical_projection(next_probs, rewards, discounts, support)

        def loss_fn(params):
            logits = network.apply(params, obs)
            loss, ce = c51_loss(logits, actions, target_probs, weights=weights)
            return loss, (ce, categorical_q_values(logits, support))

        return loss_fn

    return _make_learn_core(
        optimizer,
        gamma,
        n_step,
        use_soft_update,
        soft_update_tau,
        target_update_frequency,
        make_loss_fn,
    )


def make_dqn_priority_fn(network: QNet, gamma: float, double_dqn: bool):
    """Build the pure |TD-error| function actors use to compute initial
    Ape-X priorities for their own transitions (``apex/worker.py:59-79``).

    Shapes: obs/next_obs [B, ...], action/reward/done/n_steps [B].
    """

    def priority(params, target_params, obs, action, reward, next_obs, done, n_steps):
        discounts = (1.0 - done.astype(jnp.float32)) * (
            gamma ** n_steps.astype(jnp.float32)
        )
        q_next_online = network.apply(params, next_obs)
        q_next_target = network.apply(target_params, next_obs)
        targets = double_dqn_targets(
            q_next_online, q_next_target, reward, discounts, double_dqn=double_dqn
        )
        q = network.apply(params, obs)
        q_sa = jnp.take_along_axis(q, action.astype(jnp.int32)[:, None], axis=-1)[:, 0]
        return jnp.abs(q_sa - targets)

    return priority


class DQNAgent(BaseAgent):
    def __init__(
        self,
        args: DQNArguments,
        obs_shape: Tuple[int, ...],
        action_dim: int,
        key: Optional[jax.Array] = None,
        donate_state: bool = True,
    ) -> None:
        # donate_state=False is required when actor threads read
        # ``state.params`` concurrently with ``learn`` (Ape-X): donation
        # invalidates the old param buffers mid-read.
        self.args = args
        self.action_dim = action_dim
        self.obs_shape = tuple(obs_shape)
        key = key if key is not None else jax.random.PRNGKey(args.seed)
        self._key = key

        self.categorical = bool(getattr(args, "categorical_dqn", False))
        self.support = (
            make_support(args.v_min, args.v_max, args.num_atoms)
            if self.categorical
            else None
        )
        if self.categorical:
            self.network = C51QNet(
                action_dim=action_dim,
                num_atoms=args.num_atoms,
                hidden_sizes=args.hidden_sizes,
                dueling=args.dueling_dqn,
                noisy=args.noisy_dqn,
                noisy_std=args.noisy_std,
            )
        else:
            self.network = QNet(
                action_dim=action_dim,
                hidden_sizes=args.hidden_sizes,
                dueling=args.dueling_dqn,
                noisy=args.noisy_dqn,
                noisy_std=args.noisy_std,
            )
        dummy = jnp.zeros((1,) + self.obs_shape, jnp.float32)
        params = self.network.init(key, dummy)

        tx = [optax.clip_by_global_norm(args.max_grad_norm)] if args.max_grad_norm else []
        if args.lr_scheduler == "linear":
            lr = optax.linear_schedule(
                args.learning_rate,
                args.min_learning_rate,
                int(args.max_timesteps // max(args.train_frequency, 1)),
            )
        else:
            lr = args.learning_rate
        tx.append(optax.adam(lr))
        self.optimizer = optax.chain(*tx)

        self.state = DQNTrainState(
            params=params,
            target_params=jax.tree_util.tree_map(jnp.copy, params),
            opt_state=self.optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )

        self.eps_scheduler = LinearDecayScheduler(
            args.eps_greedy_start,
            args.eps_greedy_end,
            int(args.max_timesteps * args.exploration_fraction),
        )
        self.eps = args.eps_greedy_start

        if self.categorical:
            learn_fn = make_c51_learn_fn(
                self.network,
                self.optimizer,
                support=self.support,
                gamma=args.gamma,
                n_step=args.n_steps,
                double_dqn=args.double_dqn,
                use_soft_update=args.use_soft_update,
                soft_update_tau=args.soft_update_tau,
                target_update_frequency=args.target_update_frequency,
            )
        else:
            learn_fn = make_dqn_learn_fn(
                self.network,
                self.optimizer,
                gamma=args.gamma,
                n_step=args.n_steps,
                double_dqn=args.double_dqn,
                use_soft_update=args.use_soft_update,
                soft_update_tau=args.soft_update_tau,
                target_update_frequency=args.target_update_frequency,
            )
        from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

        # all-finite guard: a non-finite update (poisoned batch, exploding
        # grads) is skipped and counted instead of silently corrupting the
        # params; wrapping BEFORE _learn_raw covers the mesh re-wrap too
        learn_fn = maybe_guard_nonfinite(learn_fn, args)
        self._learn_raw = learn_fn  # un-jitted, for enable_mesh re-wrap
        self._donate_state = donate_state
        self._shard_batch = None
        self._learn_mesh = None
        self.mesh = None
        self._learn = jax.jit(
            learn_fn, donate_argnums=(0,) if donate_state else ()
        )

        def q_of(params, obs):
            out = self.network.apply(params, obs)
            if self.categorical:
                return categorical_q_values(out, self.support)
            return out

        def act(params, obs, eps, key):
            greedy = jnp.argmax(q_of(params, obs), axis=-1)
            k1, k2 = jax.random.split(key)
            random_actions = jax.random.randint(k1, greedy.shape, 0, action_dim)
            explore = jax.random.uniform(k2, greedy.shape) < eps
            return jnp.where(explore, random_actions, greedy)

        self._act = jax.jit(act)
        self._predict = jax.jit(
            lambda params, obs: jnp.argmax(q_of(params, obs), axis=-1)
        )

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def get_action(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        obs = jnp.asarray(obs, jnp.float32)
        squeeze = obs.ndim == len(self.obs_shape)
        if squeeze:
            obs = obs[None]
        actions = self._act(self.state.params, obs, self.eps, self._next_key())
        out = np.asarray(actions)
        return out[0] if squeeze else out

    def predict(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        obs = jnp.asarray(obs, jnp.float32)
        squeeze = obs.ndim == len(self.obs_shape)
        if squeeze:
            obs = obs[None]
        actions = self._predict(self.state.params, obs)
        out = np.asarray(actions)
        return out[0] if squeeze else out

    def update_exploration(self, num_env_steps: int = 1) -> float:
        self.eps = self.eps_scheduler.step(num_env_steps)
        return self.eps

    def enable_mesh(self, mesh_or_spec) -> None:
        """Data-parallel learn over a mesh — the reference's one *working*
        distributed path (Accelerate/DDP DQN, ``dqn_agent.py:194-198`` +
        ``accelerate_config.yaml``), as a pjit: the batch dim shards over
        ``dp×fsdp``, big params over ``fsdp/tp`` where divisible, GSPMD
        all-reduces gradients over ICI, and the per-sample |TD| vector
        comes back replicated for PER priority feedback.  Call once before
        training; numerically identical to the single-device update at the
        same global batch (asserted by test)."""
        from scalerl_tpu.parallel import enable_offpolicy_mesh

        enable_offpolicy_mesh(self, mesh_or_spec, donate_state=self._donate_state)

    def learn(self, batch: Mapping[str, Any]) -> Dict[str, float]:
        if self._learn_mesh is not None:
            sharded = self._shard_batch(dict(batch))
            self.state, (metrics, td_abs) = self._learn_mesh(self.state, sharded)
        else:
            self.state, metrics, td_abs = self._learn(self.state, dict(batch))
        from scalerl_tpu.runtime.dispatch import get_metrics

        out = get_metrics(metrics)  # ONE batched device->host transfer
        out["td_abs"] = td_abs  # device array, for PER priority feedback
        out["eps"] = self.eps
        return out

    def get_weights(self):
        return self.state.params

    def set_weights(self, weights) -> None:
        self.state = self.state.replace(params=weights)

    def save_checkpoint(self, path: str) -> str:
        return save_checkpoint(path, self.state)

    def load_checkpoint(self, path: str) -> None:
        self.state = load_checkpoint(path, self.state)
