"""PPO: clipped-surrogate policy optimization on the on-policy runtime.

Beyond-parity algorithm family: the reference implements A3C/DQN/Ape-X/
IMPALA and cites DD-PPO in its architecture bibliography (``README.md:
21-53``) without shipping an implementation.  This module completes the
on-policy runtime (``trainer/on_policy.py`` — the same rollout collection
A3C uses; the trajectory's ``logits`` rows double as the behavior policy)
with the PPO update:

- GAE advantages and value targets are computed ONCE per rollout chunk from
  the pre-update policy, then ``ppo_epochs`` passes of ``num_minibatches``
  clipped-surrogate steps run as a single ``lax.scan`` — one XLA program
  per chunk, no per-minibatch host dispatch.
- Minibatches split over env *lanes* (full ``[T+1]`` sequences), never over
  time, so recurrent policies replay each lane from its stored entering
  LSTM state exactly as collected (recurrent-PPO-safe shuffling).
- The lane shuffle is deterministic from ``state.step`` (``fold_in``), so
  the learn fn stays a pure ``(state, traj) -> (state, metrics)`` function
  — resumable, jittable, and mesh-shardable unchanged.

DD-PPO on TPU = ``agent.enable_mesh("dp=N")``: the pjit'd learner runs the
whole epochs x minibatch schedule data-parallel with gradient all-reduce
per minibatch step — decentralized-distributed PPO (Wijmans et al. 2020)
without a parameter server, numerically identical to the single-device
update at the same global batch (the shuffle permutes the *global* lane
axis).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from scalerl_tpu.agents.a3c import build_model as build_policy_value_model
from scalerl_tpu.agents.a3c import make_a3c_optimizer
from scalerl_tpu.agents.policy_value import PolicyValueAgent, frames_counter
from scalerl_tpu.config import PPOArguments
from scalerl_tpu.data.trajectory import Trajectory
from scalerl_tpu.ops.losses import clipped_surrogate_loss, entropy_loss
from scalerl_tpu.ops.returns import gae_advantages
from scalerl_tpu.ops.vtrace import action_log_probs


@struct.dataclass
class PPOTrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray
    env_frames: jnp.ndarray


def ppo_loss(
    params,
    model,
    mb: Dict[str, Any],
    clip_range: float,
    clip_range_vf: float,
    value_loss_coef: float,
    entropy_coef: float,
    normalize_advantage: bool,
    loss_reduction: str = "sum",
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Clipped surrogate + (optionally clipped) value loss + entropy bonus
    over one lane minibatch (full sequences, ``[T+1, b]`` rows).

    ``mb`` carries the trajectory rows plus the chunk-level precomputations:
    ``advantages`` / ``value_targets`` (GAE under the pre-update policy),
    ``behavior_logp`` (collection-time), and ``old_values`` (for the
    PPO2-style value clip).  Sum convention over [T, b] for the losses,
    ``mean_*`` for diagnostics — the metric-name contract of
    ``agents/impala.py``.

    NOTE on learning rates: the default sum convention means the gradient
    scale grows with ``rollout_length`` x lanes-per-minibatch, unlike SB3/
    baselines PPO which averages over the minibatch.  Published PPO
    learning rates (e.g. 3e-4) do not transfer directly under "sum" —
    pass ``loss_reduction="mean"`` (divides every term by the [T, b]
    element count, making gradients batch-shape invariant and published
    lrs usable as-is), or retune per batch shape (see PPOArguments).
    """
    out, _ = model.apply(
        params, mb["obs"], mb["action"], mb["reward"], mb["done"], mb["core_state"]
    )
    logits = out.policy_logits[:-1]  # [T, b, A]
    values_new = out.baseline[:-1]  # [T, b]
    actions_taken = mb["action"][1:]

    adv = mb["advantages"]
    if normalize_advantage:
        adv = (adv - jnp.mean(adv)) / (jnp.std(adv) + 1e-8)

    new_logp = action_log_probs(logits, actions_taken)
    pg, aux = clipped_surrogate_loss(new_logp, mb["behavior_logp"], adv, clip_range)

    vs = jax.lax.stop_gradient(mb["value_targets"])
    if clip_range_vf > 0.0:
        # PPO2 value clip: bound the value update around the pre-update
        # prediction, pessimistically taking the worse of the two errors
        v_old = jax.lax.stop_gradient(mb["old_values"])
        v_clipped = v_old + jnp.clip(values_new - v_old, -clip_range_vf, clip_range_vf)
        vl = 0.5 * jnp.sum(
            jnp.maximum(
                jnp.square(values_new - vs), jnp.square(v_clipped - vs)
            )
        )
    else:
        vl = 0.5 * jnp.sum(jnp.square(values_new - vs))
    vl = value_loss_coef * vl
    ent = entropy_coef * entropy_loss(logits)

    if loss_reduction == "mean":
        scale = 1.0 / (values_new.shape[0] * values_new.shape[1])  # [T, b] count
        pg, vl, ent = pg * scale, vl * scale, ent * scale

    total = pg + vl + ent
    metrics = {
        "total_loss": total,
        "pg_loss": pg,
        "value_loss": vl,
        "entropy_loss": ent,
        "mean_value": jnp.mean(values_new),
        "mean_advantage": jnp.mean(mb["advantages"]),
        **aux,
    }
    return total, metrics


def make_ppo_learn_fn(
    model, optimizer: optax.GradientTransformation, args: PPOArguments
) -> Callable:
    """Build the pure (state, traj) -> (state, metrics) PPO update.

    One call consumes one ``[T+1, B]`` on-policy chunk and runs the full
    ``ppo_epochs x num_minibatches`` schedule as a ``lax.scan`` over lane
    slabs.  Logged loss metrics are the mean over the scanned minibatch
    updates (each itself sum-convention over its [T, B/M] elements).
    """

    def learn(state: PPOTrainState, traj: Trajectory):
        T1, B = traj.reward.shape
        T = T1 - 1
        M = args.num_minibatches
        if B % M != 0:
            # validate() checks args.num_workers, but the runtime batch comes
            # from the env fleet and can disagree — fail here with a clear
            # message instead of a cryptic trace-time reshape error
            raise ValueError(
                f"trajectory batch ({B} env lanes) must divide by "
                f"num_minibatches ({M})"
            )
        mb_lanes = B // M

        # ---- chunk-level precomputation under the pre-update policy ----
        out, _ = model.apply(
            state.params, traj.obs, traj.action, traj.reward, traj.done,
            traj.core_state,
        )
        values = jax.lax.stop_gradient(out.baseline)  # [T+1, B]
        rewards = traj.reward[1:]
        discounts = args.gamma * (1.0 - traj.done[1:].astype(jnp.float32))
        advantages, value_targets = gae_advantages(
            rewards, discounts, values[:-1], values[-1], lambda_=args.gae_lambda
        )
        advantages = jax.lax.stop_gradient(advantages)
        behavior_logp = action_log_probs(traj.logits[:-1], traj.action[1:])

        # ---- deterministic lane shuffle per epoch (pure fn of step) ----
        key = jax.random.fold_in(jax.random.PRNGKey(args.seed), state.step)
        perms = jax.vmap(lambda k: jax.random.permutation(k, B))(
            jax.random.split(key, args.ppo_epochs)
        )  # [E, B]
        lane_slabs = perms.reshape(args.ppo_epochs * M, mb_lanes)

        def take_lanes(x, lanes, axis):
            return jnp.take(x, lanes, axis=axis)

        def mb_step(carry, lanes):
            params, opt_state = carry
            mb = {
                "obs": take_lanes(traj.obs, lanes, 1),
                "action": take_lanes(traj.action, lanes, 1),
                "reward": take_lanes(traj.reward, lanes, 1),
                "done": take_lanes(traj.done, lanes, 1),
                "core_state": jax.tree_util.tree_map(
                    lambda x: take_lanes(x, lanes, 0), traj.core_state
                ),
                "advantages": take_lanes(advantages, lanes, 1),
                "value_targets": take_lanes(value_targets, lanes, 1),
                "behavior_logp": take_lanes(behavior_logp, lanes, 1),
                "old_values": take_lanes(values[:-1], lanes, 1),
            }
            (_, metrics), grads = jax.value_and_grad(ppo_loss, has_aux=True)(
                params,
                model,
                mb,
                clip_range=args.clip_range,
                clip_range_vf=args.clip_range_vf,
                value_loss_coef=args.value_loss_coef,
                entropy_coef=args.entropy_coef,
                normalize_advantage=args.normalize_advantage,
                loss_reduction=args.loss_reduction,
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["grad_norm"] = optax.global_norm(grads)
            return (params, opt_state), metrics

        (params, opt_state), scanned = jax.lax.scan(
            mb_step, (state.params, state.opt_state), lane_slabs
        )
        metrics = {k: jnp.mean(v) for k, v in scanned.items()}
        new_state = PPOTrainState(
            params=params,
            opt_state=opt_state,
            step=state.step + 1,
            env_frames=state.env_frames + T * B,
        )
        return new_state, metrics

    from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

    # all-finite guard: skip (and count) non-finite updates — see impala.py
    return maybe_guard_nonfinite(learn, args)


def make_ppo_optimizer(args: PPOArguments) -> optax.GradientTransformation:
    """Adam + global-norm clip (the standard PPO recipe; clip 0.5) — the
    same shared recipe as A3C, reused so the two on-policy agents cannot
    silently diverge."""
    return make_a3c_optimizer(args)


class PPOAgent(PolicyValueAgent):
    """Host-facing PPO agent: jitted act + fused epochs/minibatch learn.

    Drops into ``trainer/on_policy.py`` unchanged (same act/learn surface
    as A3C); the model zoo is shared with A3C (``agents/a3c.py``
    ``build_model``: MLP for flat obs, conv[+LSTM] AtariNet for pixels).
    """

    def make_learn_fn(self):
        """Learn fn from *this agent's* model/optimizer/args — callers (the
        fused-loop experiments/tests) must not re-derive hyperparameters
        from a possibly-different args object (parity with
        ``ImpalaAgent.make_learn_fn``)."""
        return make_ppo_learn_fn(self.model, self.optimizer, self.args)

    def __init__(
        self,
        args: PPOArguments,
        obs_shape: Tuple[int, ...],
        num_actions: int,
        obs_dtype=jnp.float32,
        key: Optional[jax.Array] = None,
    ) -> None:
        args.validate()
        self.args = args
        model = build_policy_value_model(args, obs_shape, num_actions)
        optimizer = make_ppo_optimizer(args)
        self._setup(
            model=model,
            optimizer=optimizer,
            make_state=lambda params, opt_state: PPOTrainState(
                params=params,
                opt_state=opt_state,
                step=jnp.zeros((), jnp.int32),
                env_frames=frames_counter(),
            ),
            learn_fn=make_ppo_learn_fn(model, optimizer, args),
            obs_shape=obs_shape,
            num_actions=num_actions,
            obs_dtype=obs_dtype,
            seed=args.seed,
            key=key,
        )
