from scalerl_tpu.agents.base import BaseAgent  # noqa: F401
from scalerl_tpu.agents.dqn import DQNAgent, DQNTrainState  # noqa: F401
