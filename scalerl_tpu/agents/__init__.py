from scalerl_tpu.agents.base import BaseAgent  # noqa: F401
from scalerl_tpu.agents.dqn import DQNAgent, DQNTrainState  # noqa: F401
from scalerl_tpu.agents.a3c import A3CAgent, A3CTrainState  # noqa: F401
from scalerl_tpu.agents.impala import ImpalaAgent, ImpalaTrainState  # noqa: F401
from scalerl_tpu.agents.ppo import PPOAgent, PPOTrainState  # noqa: F401
from scalerl_tpu.agents.r2d2 import R2D2Agent, R2D2TrainState  # noqa: F401
from scalerl_tpu.agents.sac import SACAgent, SACTrainState  # noqa: F401
from scalerl_tpu.agents.td3 import TD3Agent, TD3TrainState  # noqa: F401
from scalerl_tpu.agents.token_ppo import (  # noqa: F401
    TokenPPOAgent,
    TokenPPOTrainState,
)
