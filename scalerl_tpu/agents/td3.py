"""TD3: twin-delayed DDPG for continuous control (beyond-parity).

Companion to ``agents/sac.py`` on the same off-policy pipeline: a
deterministic tanh actor with exploration noise, clipped double-Q
critics, TARGET POLICY SMOOTHING (clipped Gaussian noise on the target
action — the trick that distinguishes TD3 from DDPG), and DELAYED actor
+ target updates every ``policy_delay`` critic steps.  The whole update
is one jitted pure function; the delay is a ``lax.cond``-free masked
update (selective where over the actor/target trees), so the program
stays a single static graph.

Reference context: like SAC, this makes the reference's declared-but-
unused continuous MLP heads (``network.py:27-67``) load-bearing.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from scalerl_tpu.agents.base import BaseAgent
from scalerl_tpu.config import TD3Arguments
from scalerl_tpu.models.mlp import DeterministicActor, TwinQNet
from scalerl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


@struct.dataclass
class TD3TrainState:
    actor_params: Any
    target_actor_params: Any
    critic_params: Any
    target_critic_params: Any
    actor_opt: Any
    critic_opt: Any
    step: jnp.ndarray


def make_td3_learn_fn(actor, critic, actor_tx, critic_tx, args: TD3Arguments,
                      action_scale, action_bias):
    low = action_bias - action_scale
    high = action_bias + action_scale

    def learn(state: TD3TrainState, batch: Mapping[str, jnp.ndarray]):
        # pure fn of (state, batch): target-smoothing noise folds out of the
        # step counter (the PPO fold_in pattern) — resumable and mesh-
        # shardable with no key plumbed through the batch
        key = jax.random.fold_in(jax.random.PRNGKey(args.seed + 0x7D3), state.step)
        obs = batch["obs"]
        next_obs = batch["next_obs"]
        action = batch["action"]
        reward = batch["reward"]
        done = batch["done"].astype(jnp.float32)
        weights = batch.get("weights", jnp.ones_like(reward))
        n_steps = batch.get("n_steps")
        if n_steps is None:
            discount = (1.0 - done) * (args.gamma**args.n_steps)
        else:
            discount = (1.0 - done) * (args.gamma ** n_steps.astype(jnp.float32))

        # -- target policy smoothing: clipped noise on the TARGET action
        next_a = actor.apply(state.target_actor_params, next_obs)
        next_a = next_a * action_scale + action_bias
        noise = jnp.clip(
            args.target_noise_std
            * action_scale
            * jax.random.normal(key, next_a.shape),
            -args.target_noise_clip * action_scale,
            args.target_noise_clip * action_scale,
        )
        next_a = jnp.clip(next_a + noise, low, high)
        tq1, tq2 = critic.apply(state.target_critic_params, next_obs, next_a)
        target = jax.lax.stop_gradient(
            reward + discount * jnp.minimum(tq1, tq2)
        )

        def critic_loss_fn(cp):
            q1, q2 = critic.apply(cp, obs, action)
            l = jnp.mean(
                weights * (jnp.square(q1 - target) + jnp.square(q2 - target))
            )
            return 0.5 * l, jnp.abs(q1 - target)

        (c_loss, td_abs), c_grads = jax.value_and_grad(
            critic_loss_fn, has_aux=True
        )(state.critic_params)
        c_updates, critic_opt = critic_tx.update(
            c_grads, state.critic_opt, state.critic_params
        )
        critic_params = optax.apply_updates(state.critic_params, c_updates)

        # -- delayed actor + target updates: compute always (static graph),
        # apply only every policy_delay steps via a scalar mask
        def actor_loss_fn(ap):
            a = actor.apply(ap, obs) * action_scale + action_bias
            q1, _ = critic.apply(critic_params, obs, a)
            return -jnp.mean(q1)

        a_loss, a_grads = jax.value_and_grad(actor_loss_fn)(state.actor_params)
        a_updates, actor_opt_new = actor_tx.update(
            a_grads, state.actor_opt, state.actor_params
        )
        actor_params_new = optax.apply_updates(state.actor_params, a_updates)

        step = state.step + 1
        apply_actor = step % args.policy_delay == 0  # bool scalar

        def select(new, old):
            # dtype-preserving (optimizer state carries integer counters —
            # an arithmetic lerp would silently float-ify them)
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(apply_actor, n, o), new, old
            )

        actor_params = select(actor_params_new, state.actor_params)
        actor_opt = select(actor_opt_new, state.actor_opt)
        tau = args.soft_update_tau * apply_actor.astype(jnp.float32)

        def polyak(t, o):
            return jax.tree_util.tree_map(
                lambda tv, ov: (1.0 - tau) * tv + tau * ov, t, o
            )

        target_actor_params = polyak(state.target_actor_params, actor_params)
        target_critic_params = polyak(state.target_critic_params, critic_params)

        new_state = TD3TrainState(
            actor_params=actor_params,
            target_actor_params=target_actor_params,
            critic_params=critic_params,
            target_critic_params=target_critic_params,
            actor_opt=actor_opt,
            critic_opt=critic_opt,
            step=step,
        )
        metrics = {
            "loss": c_loss,
            "critic_loss": c_loss,
            "actor_loss": a_loss,
            "mean_q_target": jnp.mean(target),
        }
        return new_state, metrics, td_abs

    from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

    # all-finite guard: skip (and count) non-finite updates — see impala.py
    return maybe_guard_nonfinite(learn, args)


class TD3Agent(BaseAgent):
    def __init__(
        self,
        args: TD3Arguments,
        obs_shape: Tuple[int, ...],
        action_low,
        action_high,
        key: Optional[jax.Array] = None,
    ) -> None:
        args.validate()
        self.args = args
        self.obs_shape = tuple(obs_shape)
        low = np.asarray(action_low, np.float32)
        high = np.asarray(action_high, np.float32)
        if low.ndim != 1:
            raise ValueError(
                f"TD3Agent expects a 1-D Box action space; got bounds of "
                f"shape {low.shape}"
            )
        self.action_dim = int(low.shape[0])
        self.action_scale = jnp.asarray((high - low) / 2.0)
        self.action_bias = jnp.asarray((high + low) / 2.0)
        self._low = jnp.asarray(low)
        self._high = jnp.asarray(high)
        self.actor = DeterministicActor(
            action_dim=self.action_dim, hidden_sizes=args.hidden_sizes
        )
        self.critic = TwinQNet(hidden_sizes=args.hidden_sizes)
        actor_tx = optax.adam(args.actor_learning_rate)
        critic_tx = optax.adam(args.learning_rate)

        key = key if key is not None else jax.random.PRNGKey(args.seed)
        k_a, k_c, self._key = jax.random.split(key, 3)
        dummy_obs = jnp.zeros((1,) + self.obs_shape, jnp.float32)
        dummy_act = jnp.zeros((1, self.action_dim), jnp.float32)
        actor_params = self.actor.init(k_a, dummy_obs)
        critic_params = self.critic.init(k_c, dummy_obs, dummy_act)
        self.state = TD3TrainState(
            actor_params=actor_params,
            target_actor_params=jax.tree_util.tree_map(jnp.copy, actor_params),
            critic_params=critic_params,
            target_critic_params=jax.tree_util.tree_map(jnp.copy, critic_params),
            actor_opt=actor_tx.init(actor_params),
            critic_opt=critic_tx.init(critic_params),
            step=jnp.zeros((), jnp.int32),
        )
        self._learn_raw = make_td3_learn_fn(
            self.actor, self.critic, actor_tx, critic_tx, args,
            self.action_scale, self.action_bias,
        )
        self._learn = jax.jit(self._learn_raw)
        self._act = jax.jit(self._act_impl)
        self.mesh = None
        self._learn_mesh = None
        self._shard_batch = None

    def _act_impl(self, actor_params, obs, noise_std, key):
        a = self.actor.apply(actor_params, obs)
        a = a * self.action_scale + self.action_bias
        noise = noise_std * self.action_scale * jax.random.normal(key, a.shape)
        return jnp.clip(a + noise, self._low, self._high)

    def get_action(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        self._key, sub = jax.random.split(self._key)
        return np.asarray(
            self._act(self.state.actor_params, obs, self.args.explore_noise_std, sub)
        )

    def predict(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        return np.asarray(
            self._act(
                self.state.actor_params, obs, 0.0, jax.random.PRNGKey(0)
            )
        )

    def enable_mesh(self, mesh_or_spec) -> None:
        """Data-parallel TD3 over a mesh — same contract as
        ``SACAgent.enable_mesh`` (batch over ``dp×fsdp``, params over
        ``fsdp/tp`` where divisible, gradient psum by GSPMD, replicated
        |TD| for PER).  Numerically identical to the single-device update
        at the same global batch (asserted by test)."""
        from scalerl_tpu.parallel import enable_offpolicy_mesh

        enable_offpolicy_mesh(self, mesh_or_spec)

    def learn(self, batch: Mapping[str, Any]) -> Dict[str, Any]:
        if self._learn_mesh is not None:
            sharded = self._shard_batch(dict(batch))
            self.state, (metrics, td_abs) = self._learn_mesh(self.state, sharded)
        else:
            self.state, metrics, td_abs = self._learn(self.state, dict(batch))
        from scalerl_tpu.runtime.dispatch import get_metrics

        out: Dict[str, Any] = get_metrics(metrics)  # one batched transfer
        out["td_abs"] = td_abs
        return out

    def get_weights(self):
        return self.state.actor_params

    def set_weights(self, weights) -> None:
        self.state = self.state.replace(actor_params=weights)

    def save_checkpoint(self, path: str) -> str:
        return save_checkpoint(path, self.state)

    def load_checkpoint(self, path: str) -> None:
        self.state = load_checkpoint(path, self.state)
