"""R2D2: recurrent replay distributed DQN (Kapturowski et al. 2019).

Beyond-parity algorithm family: the reference's DQN lineage is feed-
forward only (``scalerl/algorithms/dqn``, ``scalerl/algorithms/apex``) and
its README cites the Ape-X line without a recurrent member.  R2D2 = Ape-X
plus: sequence replay with the actor's stored LSTM state, burn-in to
de-stale that state before the gradient window, n-step double-Q targets
under the invertible value rescaling ``h``, and per-sequence priorities
``eta * max|td| + (1 - eta) * mean|td|``.

TPU shape: the whole learn step — burn-in unrolls, train unrolls of both
online and target nets, n-step target assembly, IS-weighted loss, new
priorities — is ONE jitted pure function over ``[B, T+1]`` sequence
batches (time axes static, ``nn.scan`` LSTM), so XLA fuses it the same
way it does the IMPALA learner.  Actors ride the host actor plane's
``[T+1, B]`` slot machinery unchanged (``fill_rollout_slot`` stores
entering core states already).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from scalerl_tpu.agents.base import BaseAgent, RecurrentEvalState
from scalerl_tpu.config import R2D2Arguments
from scalerl_tpu.models.recurrent_q import RecurrentQNet


def value_rescale(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    """h(x) = sign(x)(sqrt(|x|+1) - 1) + eps*x  (R2D2 eq. from Pohlen et
    al. 2018): compresses large returns so one fixed lr handles Atari-scale
    reward magnitudes without clipping away magnitude information."""
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def value_rescale_inv(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    """Closed-form inverse of :func:`value_rescale`."""
    return jnp.sign(x) * (
        jnp.square(
            (jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps)) - 1.0)
            / (2.0 * eps)
        )
        - 1.0
    )


@struct.dataclass
class R2D2TrainState:
    params: Any
    target_params: Any
    opt_state: Any
    step: jnp.ndarray


def build_model(args: R2D2Arguments, num_actions: int) -> RecurrentQNet:
    return RecurrentQNet(
        num_actions=num_actions,
        use_lstm=args.use_lstm,
        hidden_size=args.hidden_size,
        lstm_layers=args.lstm_layers,
        dueling=args.dueling_dqn,
    )


def n_step_double_q_targets(
    q_online: jnp.ndarray,  # [Tt, B, A] over train rows (post burn-in)
    q_target: jnp.ndarray,  # [Tt, B, A]
    action: jnp.ndarray,  # [T1, B] trajectory rows (model-input convention)
    reward: jnp.ndarray,  # [T1, B]
    done: jnp.ndarray,  # [T1, B] bool
    burn_in: int,
    n_steps: int,
    gamma: float,
    rescale_eps: float,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(td_errors [M, B], qa [M, B]) for the M = T1 - burn_in - n valid rows.

    Row convention (``data/trajectory.py``): ``action[t]``/``reward[t]``
    are the action leading TO ``obs[t]`` / the reward received with it, so
    the transition at row g pairs ``Q(s_g, action[g+1])`` with rewards
    ``g+1..g+n`` and a bootstrap at row ``g+n``.
    """
    T1 = action.shape[0]
    b = burn_in
    M = T1 - b - n_steps
    # Q(s_g, a_g-taken) over rows g in [b, b+M)
    a_taken = action[b + 1 : b + 1 + M]  # [M, B]
    qa = jnp.take_along_axis(q_online[:M], a_taken[..., None], axis=-1)[..., 0]

    rewards = reward[1:]  # index g-1 holds r_{g} arrival reward for row g
    disc = 1.0 - done[1:].astype(jnp.float32)
    ret = jnp.zeros_like(qa)
    live = jnp.ones_like(qa)
    for k in range(n_steps):
        ret = ret + (gamma**k) * live * rewards[b + k : b + k + M]
        live = live * disc[b + k : b + k + M]

    # double-Q at the bootstrap row g + n (local index g + n - b)
    q_boot_online = q_online[n_steps : n_steps + M]  # [M, B, A]
    q_boot_target = q_target[n_steps : n_steps + M]
    a_star = jnp.argmax(q_boot_online, axis=-1)
    boot = jnp.take_along_axis(q_boot_target, a_star[..., None], axis=-1)[..., 0]

    target = value_rescale(
        ret
        + (gamma**n_steps)
        * live
        * value_rescale_inv(boot, rescale_eps),
        rescale_eps,
    )
    td = qa - jax.lax.stop_gradient(target)
    return td, qa


def make_r2d2_learn_fn(
    model: RecurrentQNet, optimizer, args: R2D2Arguments,
    grad_axis: Optional[str] = None,
):
    """Pure (state, fields[B,T1,...], core, is_weights) ->
    (state, metrics, new_priorities).

    ``grad_axis``: when the step runs INSIDE ``shard_map`` with the sequence
    batch sharded over a mesh axis (the fused multi-device R2D2 loop,
    ``trainer/r2d2_device.py``), gradients ``psum`` over that axis before
    the optimizer update — same contract as ``make_impala_learn_fn``:
    sum-convention losses psum, ``mean_*`` metrics pmean, so dp=N at global
    batch B matches a single device at batch B.  ``new_priorities`` stay
    LOCAL (each shard scatters into its own replay block).
    """
    b = args.burn_in

    def unroll(params, obs, action, reward, done, core):
        out, core = model.apply(params, obs, action, reward, done, core)
        return out.q_values, core

    def loss_fn(params, target_params, fields, core, weights):
        # [B, T1, ...] -> time-major [T1, B, ...]
        obs = jnp.moveaxis(fields["obs"], 0, 1)
        action = jnp.moveaxis(fields["action"], 0, 1)
        reward = jnp.moveaxis(fields["reward"], 0, 1)
        done = jnp.moveaxis(fields["done"], 0, 1)

        if b > 0:
            # burn-in: advance both cores over the stale prefix, no grads
            _, warm_core = unroll(
                params, obs[:b], action[:b], reward[:b], done[:b], core
            )
            warm_core = jax.lax.stop_gradient(warm_core)
            _, warm_core_t = unroll(
                target_params, obs[:b], action[:b], reward[:b], done[:b], core
            )
        else:
            warm_core = warm_core_t = core
        q_online, _ = unroll(
            params, obs[b:], action[b:], reward[b:], done[b:], warm_core
        )
        q_target, _ = unroll(
            target_params, obs[b:], action[b:], reward[b:], done[b:], warm_core_t
        )
        q_target = jax.lax.stop_gradient(q_target)

        td, qa = n_step_double_q_targets(
            q_online, q_target, action, reward, done,
            burn_in=b, n_steps=args.n_steps, gamma=args.gamma,
            rescale_eps=args.value_rescale_eps,
        )
        # mean over the sequence's TD rows, IS-weighted sum over the batch
        # (repo loss convention: sums over batch — lr tuning is batch-size
        # dependent, see agents/ppo.py note)
        per_seq = jnp.mean(jnp.square(td), axis=0)  # [B]
        loss = 0.5 * jnp.sum(weights * per_seq)

        abs_td = jnp.abs(td)
        new_prio = args.priority_eta * jnp.max(abs_td, axis=0) + (
            1.0 - args.priority_eta
        ) * jnp.mean(abs_td, axis=0)
        metrics = {
            "total_loss": loss,
            "mean_q": jnp.mean(qa),
            "mean_abs_td": jnp.mean(abs_td),
        }
        return loss, (metrics, new_prio)

    def learn(state: R2D2TrainState, fields, core, weights):
        (loss, (metrics, new_prio)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.target_params, fields, core, weights)
        if grad_axis is not None:
            grads = jax.lax.psum(grads, grad_axis)
            metrics = {
                k: jax.lax.pmean(v, grad_axis)
                if k.startswith("mean_")
                else jax.lax.psum(v, grad_axis)
                for k, v in metrics.items()
            }
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        step = state.step + 1
        target_params = optax.periodic_update(
            params, state.target_params, step, args.target_update_frequency
        )
        return (
            R2D2TrainState(
                params=params,
                target_params=target_params,
                opt_state=opt_state,
                step=step,
            ),
            metrics,
            new_prio,
        )

    from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

    # all-finite guard: skip (and count) non-finite updates — see impala.py
    return maybe_guard_nonfinite(learn, args)


class _EpsGreedyActorView:
    """Per-actor policy facade for ``fill_rollout_slot``: eps-greedy over
    the agent's LIVE params (central inference), Ape-X eps ladder."""

    def __init__(self, agent: "R2D2Agent", eps: float, seed: int) -> None:
        self._agent = agent
        self._eps = eps
        self._key = jax.random.PRNGKey(seed)

    def initial_state(self, batch_size: int):
        return self._agent.model.initial_state(batch_size)

    def act(self, obs, last_action, reward, done, core_state):
        self._key, sub = jax.random.split(self._key)
        action, q, core = self._agent._act(
            self._agent.state.params, obs, last_action, reward, done,
            core_state, self._eps, sub,
        )
        # q rides the slot's logits field: not consumed by the learner,
        # but keeps the slot layout identical to the IMPALA planes
        return action, q, core

    def close(self) -> None:
        pass


class R2D2Agent(BaseAgent):
    def __init__(
        self,
        args: R2D2Arguments,
        obs_shape: Tuple[int, ...],
        num_actions: int,
        obs_dtype=np.float32,
        key: Optional[jax.Array] = None,
    ) -> None:
        args.validate()
        self.args = args
        self.obs_shape = tuple(obs_shape)
        self.num_actions = num_actions
        self.obs_dtype = obs_dtype
        self.model = build_model(args, num_actions)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(args.max_grad_norm)
            if getattr(args, "max_grad_norm", 0) and args.max_grad_norm > 0
            else optax.identity(),
            optax.adam(args.learning_rate),
        )
        key = key if key is not None else jax.random.PRNGKey(args.seed)
        dummy_obs = jnp.zeros((1, 1) + self.obs_shape, obs_dtype)
        params = self.model.init(
            key,
            dummy_obs,
            jnp.zeros((1, 1), jnp.int32),
            jnp.zeros((1, 1), jnp.float32),
            jnp.zeros((1, 1), bool),
            self.model.initial_state(1),
        )
        self.state = R2D2TrainState(
            params=params,
            # a COPY, not an alias: the mesh learn step donates the state,
            # and XLA refuses to donate the same buffer twice
            target_params=jax.tree_util.tree_map(jnp.copy, params),
            opt_state=self.optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )
        self._learn_raw = make_r2d2_learn_fn(self.model, self.optimizer, args)
        self._learn = jax.jit(self._learn_raw)
        self._act = jax.jit(self._act_impl)
        self._eval_state = RecurrentEvalState(self.model.initial_state)
        self.mesh = None
        self._learn_mesh = None

    # -- acting --------------------------------------------------------
    def _act_impl(self, params, obs, last_action, reward, done, core, eps, key):
        out, new_core = self.model.apply(
            params,
            jnp.asarray(obs)[None],
            jnp.asarray(last_action)[None],
            jnp.asarray(reward)[None],
            jnp.asarray(done)[None],
            core,
        )
        q = out.q_values[0]  # [B, A]
        greedy = jnp.argmax(q, axis=-1).astype(jnp.int32)
        B = greedy.shape[0]
        k_eps, k_rand = jax.random.split(key)
        explore = jax.random.uniform(k_eps, (B,)) < eps
        random_a = jax.random.randint(k_rand, (B,), 0, self.num_actions)
        return jnp.where(explore, random_a, greedy), q, new_core

    def actor_view(self, actor_id: int) -> _EpsGreedyActorView:
        """Ape-X eps ladder: eps_i = eps_base ** (1 + i/(N-1) * alpha)."""
        n = max(self.args.num_actors, 1)
        frac = actor_id / max(n - 1, 1)
        eps = self.args.eps_base ** (1.0 + frac * self.args.eps_alpha)
        return _EpsGreedyActorView(self, eps, self.args.seed + 101 * actor_id)

    def initial_state(self, batch_size: int):
        return self.model.initial_state(batch_size)

    def get_action(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        """Eps-greedy actions with a persistent LSTM carry: the core
        survives across calls, rows resetting where ``done`` (the previous
        step's ``term | trunc``) is True."""
        B = obs.shape[0]
        core, prev_a, prev_r, done_in = self._eval_state.step_inputs("explore", B, done)
        a, _q, new_core = self._default_view().act(obs, prev_a, prev_r, done_in, core)
        self._eval_state.update("explore", a, new_core)
        return np.asarray(a)

    def _default_view(self) -> _EpsGreedyActorView:
        if not hasattr(self, "_dview"):
            self._dview = self.actor_view(0)
        return self._dview

    def predict(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        """Greedy actions, same persistent-core contract as get_action."""
        B = obs.shape[0]
        core, prev_a, prev_r, done_in = self._eval_state.step_inputs("greedy", B, done)
        a, _q, new_core = self._act(
            self.state.params, obs, prev_a, prev_r, done_in,
            core, 0.0, jax.random.PRNGKey(0),
        )
        self._eval_state.update("greedy", a, new_core)
        return np.asarray(a)

    # -- learning ------------------------------------------------------
    def enable_mesh(self, mesh_or_spec) -> None:
        """Data-parallel R2D2 learner over a mesh (the DDP story every
        other family has): the SEQUENCE batch dim shards over ``dp×fsdp``,
        big params over ``fsdp/tp`` where divisible, GSPMD all-reduces
        gradients over ICI, and the per-sequence priorities come back
        replicated for the PER write-back.  Call once before training;
        numerically identical to the single-device update at the same
        global batch (asserted by test)."""
        from scalerl_tpu.parallel import make_parallel_learn_fn, resolve_mesh

        mesh = resolve_mesh(mesh_or_spec)
        n_shards = mesh.shape["dp"] * mesh.shape["fsdp"]
        if self.args.batch_size % n_shards != 0:
            raise ValueError(
                f"batch_size ({self.args.batch_size}) must divide by the "
                f"mesh's dp*fsdp extent ({n_shards}) to shard the sequence "
                "batch"
            )
        raw = self._learn_raw  # the un-jitted fn kept from __init__

        def two_out(state, batch):
            state, metrics, prio = raw(
                state, batch["fields"], batch["core"], batch["weights"]
            )
            return state, (metrics, prio)

        plearn = make_parallel_learn_fn(
            two_out, mesh, self.state,
            batch_time_major=False,  # sequence batches are [B, T1, ...]
            # NO donation: R2D2's actor threads read agent.state.params
            # concurrently for central inference — a donating learn step
            # would delete the buffers mid-read ("Array has been deleted")
            donate_state=False,
        )
        self.mesh = mesh
        self.state = plearn.shard_state(self.state)
        self._learn_mesh = plearn

    def learn_sequences(self, fields, core, weights):
        """One update on a sampled sequence batch; returns (metrics,
        new_priorities) with the state updated in place."""
        if self._learn_mesh is not None:
            batch = self._learn_mesh.shard_batch(
                {"fields": dict(fields), "core": core, "weights": weights}
            )
            self.state, (metrics, prio) = self._learn_mesh(self.state, batch)
        else:
            self.state, metrics, prio = self._learn(
                self.state, fields, core, weights
            )
        return metrics, prio

    def learn(self, batch) -> Dict[str, float]:
        from scalerl_tpu.runtime.dispatch import get_metrics

        metrics, _ = self.learn_sequences(
            batch["fields"], batch["core"], batch["weights"]
        )
        return get_metrics(metrics)  # one batched device->host transfer

    def get_weights(self):
        return self.state.params

    def set_weights(self, weights) -> None:
        self.state = self.state.replace(params=weights)
        # a carried eval core was produced by the OLD weights; drop it
        self._eval_state.reset()
