"""Token-level PPO learner for the sequence-RL plane.

The learning half of the MindSpeed-RL-shaped dataflow (``genrl/``): a
PPO-clip update over *generated token sequences* where every response
token is one action —

- **per-token importance ratios** against the STORED behavior logprobs
  (the sampling distribution the generation engine actually drew from),
  so replayed / stale sequences are corrected exactly like IMPALA corrects
  actor lag;
- **KL-to-reference penalty**: a frozen reference copy of the initial
  params rides the train state, and ``kl_cost > 0`` adds the forward KL
  from the current policy to it per token (the RLHF anchor keeping the
  policy from collapsing onto the reward);
- **length-masked losses over padded buckets**: sequences live in static
  (prompt bucket + response bucket) shapes; every loss/metric term is
  masked by the real-token mask and normalized by real token count, so
  bucket padding is numerically invisible;
- **pad-free packed rows** (ISSUE 15): with ``learner_packing`` the batch
  instead carries ``genrl/rollout.py``'s bin-packed ``[rows, S]`` layout
  (``segment_ids`` present) and :func:`token_ppo_packed_loss` runs
  segment-blocked causal attention — same loss and gradients to 1e-5,
  none of the pad FLOPs; the learn fn dispatches on the batch layout at
  trace time, so the padded path stays the packed path's parity twin;
- the whole update is ONE pure jitted ``(state, batch) -> (state,
  metrics)`` function riding the existing machinery: the nonfinite guard
  (``maybe_guard_nonfinite``), the dp×mp sharded learn step
  (``enable_mesh`` -> ``make_parallel_learn_fn`` with the logical mp rule
  table), and the one-batched-transfer metric discipline
  (``learn_device`` + ``get_metrics``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from scalerl_tpu.models.transformer import (
    TransformerPolicy,
    sequence_attention_mask,
    sequence_positions,
)
from scalerl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


@struct.dataclass
class TokenPPOTrainState:
    params: Any
    ref_params: Any  # frozen KL anchor (identity through every update)
    opt_state: Any
    step: jnp.ndarray  # learner updates
    tokens_seen: jnp.ndarray  # real (unmasked) response tokens consumed


def masked_mean(x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Mean of ``x`` over positions where ``mask`` is 1 (safe on empty)."""
    return jnp.sum(x * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def token_ppo_loss(
    params,
    ref_params,
    model: TransformerPolicy,
    batch: Dict[str, jnp.ndarray],
    clip_range: float,
    value_cost: float,
    entropy_cost: float,
    kl_cost: float,
    adv_norm: bool,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """PPO-clip over one ``[B, S]`` packed-sequence batch.

    ``batch`` carries the ``genrl/rollout.py`` fields: ``tokens [B, S]``
    (left-padded prompt + response), ``behavior_logp``/``value``/``mask``
    ``[B, R]``, ``reward``/``prompt_len``/``generation`` ``[B]``, plus an
    optional ``is_weight [B]`` (PER importance weights).  The prompt pad
    ``P = S - R`` is static by shape, so one compile covers every batch at
    the same bucket pair.
    """
    tokens = batch["tokens"]
    behavior_logp = batch["behavior_logp"]
    behavior_value = batch["value"]
    mask = batch["mask"]
    reward = batch["reward"]
    prompt_len = batch["prompt_len"]
    B, S = tokens.shape
    R = behavior_logp.shape[1]
    P = S - R
    seq_w = batch.get("is_weight")
    w_mask = mask if seq_w is None else mask * seq_w[:, None]

    positions = sequence_positions(prompt_len, P, S)
    attn_mask = sequence_attention_mask(prompt_len, P, S)
    out = model.apply(
        params, tokens, positions=positions, attn_mask=attn_mask
    )
    # token at absolute position p is predicted by the output at p-1:
    # response tokens occupy [P, S) -> predicting slice [P-1, S-1)
    pred_logits = out.policy_logits[:, P - 1:S - 1]  # [B, R, V]
    values = out.baseline[:, P - 1:S - 1]  # [B, R]
    resp_tokens = tokens[:, P:S]
    logp_all = jax.nn.log_softmax(pred_logits, axis=-1)
    new_logp = jnp.take_along_axis(
        logp_all, resp_tokens[..., None], axis=-1
    )[..., 0]

    # terminal sequence-level reward, undiscounted credit to every real
    # token; baseline = the sampling-time value estimate
    adv = reward[:, None] - behavior_value
    if adv_norm:
        mu = masked_mean(adv, mask)
        var = masked_mean(jnp.square(adv - mu), mask)
        adv = (adv - mu) * jax.lax.rsqrt(var + 1e-8)
    adv = jax.lax.stop_gradient(adv * mask)

    log_ratio = new_logp - jax.lax.stop_gradient(behavior_logp)
    ratio = jnp.exp(log_ratio)
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1.0 - clip_range, 1.0 + clip_range) * adv
    pg_loss = -masked_mean(jnp.minimum(unclipped, clipped), w_mask)

    value_loss = value_cost * 0.5 * masked_mean(
        jnp.square(values - reward[:, None]), w_mask
    )
    # entropy bonus (negative entropy minimised, the ops/losses convention)
    ent = jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
    entropy_term = entropy_cost * masked_mean(ent, w_mask)

    total = pg_loss + value_loss + entropy_term
    metrics = {
        "pg_loss": pg_loss,
        "value_loss": value_loss,
        "entropy": -masked_mean(ent, mask),
        "mean_ratio": masked_mean(ratio, mask),
        "mean_approx_kl": masked_mean((ratio - 1.0) - log_ratio, mask),
        "mean_clip_frac": masked_mean(
            (jnp.abs(ratio - 1.0) > clip_range).astype(jnp.float32), mask
        ),
        "mean_reward": jnp.mean(reward),
        "mean_value": masked_mean(values, mask),
        "mean_generation": jnp.mean(batch["generation"].astype(jnp.float32)),
        "mean_response_len": jnp.mean(jnp.sum(mask, axis=1)),
    }
    if kl_cost > 0.0:
        ref_out = model.apply(
            ref_params, tokens, positions=positions, attn_mask=attn_mask
        )
        ref_logp = jax.lax.stop_gradient(
            jax.nn.log_softmax(ref_out.policy_logits[:, P - 1:S - 1], axis=-1)
        )
        # forward KL(pi || pi_ref), per token, over the full vocab
        kl = jnp.sum(jnp.exp(logp_all) * (logp_all - ref_logp), axis=-1)
        kl_term = kl_cost * masked_mean(kl, w_mask)
        total = total + kl_term
        metrics["kl_ref"] = masked_mean(kl, mask)
    metrics["total_loss"] = total
    metrics = {
        k: v if k == "total_loss" else jax.lax.stop_gradient(v)
        for k, v in metrics.items()
    }
    return total, metrics


def token_ppo_packed_loss(
    params,
    ref_params,
    model: TransformerPolicy,
    batch: Dict[str, jnp.ndarray],
    clip_range: float,
    value_cost: float,
    entropy_cost: float,
    kl_cost: float,
    adv_norm: bool,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """PPO-clip over PACKED learner rows — the pad-free twin of
    :func:`token_ppo_loss`.

    ``batch`` carries the ``genrl/rollout.py`` packed-row fields, all
    ``[N, S]`` per-token: ``tokens`` (compact prompt+response segments),
    ``segment_ids`` (0 = pad), ``positions`` (reset per segment),
    ``behavior_logp``/``value``/``reward``/``generation`` aligned at each
    response token's own offset, and ``mask`` = the loss mask (1 exactly
    on response tokens).  Token ``t`` is predicted by the model output at
    ``t - 1`` — always in-segment, because every segment starts with at
    least one prompt token — so all per-token terms shift by one and the
    math is the padded loss over the identical token multiset: the two
    paths agree to float tolerance on loss AND gradients (the parity
    contract the tests pin at 1e-5).  An optional ``is_weight [N]`` (PER
    weights, per ROW — the replay unit) scales the loss mask exactly like
    the padded path's per-sequence weight.
    """
    tokens = batch["tokens"]
    seg = batch["segment_ids"]
    positions = batch["positions"]
    seq_w = batch.get("is_weight")
    w_full = (
        batch["mask"] if seq_w is None else batch["mask"] * seq_w[:, None]
    )

    out = model.apply(
        params, tokens, positions=positions, segment_ids=seg
    )
    # output at row offset t-1 predicts the token at offset t
    pred_logits = out.policy_logits[:, :-1]  # [N, S-1, V]
    values = out.baseline[:, :-1]
    tgt = tokens[:, 1:]
    mask = batch["mask"][:, 1:]
    w_mask = w_full[:, 1:]
    behavior_logp = batch["behavior_logp"][:, 1:]
    behavior_value = batch["value"][:, 1:]
    reward = batch["reward"][:, 1:]
    logp_all = jax.nn.log_softmax(pred_logits, axis=-1)
    new_logp = jnp.take_along_axis(logp_all, tgt[..., None], axis=-1)[
        ..., 0
    ]

    adv = reward - behavior_value
    if adv_norm:
        mu = masked_mean(adv, mask)
        var = masked_mean(jnp.square(adv - mu), mask)
        adv = (adv - mu) * jax.lax.rsqrt(var + 1e-8)
    adv = jax.lax.stop_gradient(adv * mask)

    log_ratio = new_logp - jax.lax.stop_gradient(behavior_logp)
    ratio = jnp.exp(log_ratio)
    unclipped = ratio * adv
    clipped = jnp.clip(ratio, 1.0 - clip_range, 1.0 + clip_range) * adv
    pg_loss = -masked_mean(jnp.minimum(unclipped, clipped), w_mask)

    value_loss = value_cost * 0.5 * masked_mean(
        jnp.square(values - reward), w_mask
    )
    ent = jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
    entropy_term = entropy_cost * masked_mean(ent, w_mask)

    total = pg_loss + value_loss + entropy_term
    # rows hold several sequences: sequence counts come from the max
    # segment id per row, reward/generation means are token-weighted
    # (the padded metrics are sequence-weighted — loss terms, not these
    # diagnostics, carry the parity contract)
    num_seqs = jnp.sum(jnp.max(seg, axis=1).astype(jnp.float32))
    metrics = {
        "pg_loss": pg_loss,
        "value_loss": value_loss,
        "entropy": -masked_mean(ent, mask),
        "mean_ratio": masked_mean(ratio, mask),
        "mean_approx_kl": masked_mean((ratio - 1.0) - log_ratio, mask),
        "mean_clip_frac": masked_mean(
            (jnp.abs(ratio - 1.0) > clip_range).astype(jnp.float32), mask
        ),
        "mean_reward": masked_mean(reward, mask),
        "mean_value": masked_mean(values, mask),
        "mean_generation": masked_mean(
            batch["generation"][:, 1:].astype(jnp.float32), mask
        ),
        "mean_response_len": jnp.sum(batch["mask"])
        / jnp.maximum(num_seqs, 1.0),
        "real_token_frac": jnp.mean((seg > 0).astype(jnp.float32)),
    }
    if kl_cost > 0.0:
        ref_out = model.apply(
            ref_params, tokens, positions=positions, segment_ids=seg
        )
        ref_logp = jax.lax.stop_gradient(
            jax.nn.log_softmax(ref_out.policy_logits[:, :-1], axis=-1)
        )
        kl = jnp.sum(jnp.exp(logp_all) * (logp_all - ref_logp), axis=-1)
        kl_term = kl_cost * masked_mean(kl, w_mask)
        total = total + kl_term
        metrics["kl_ref"] = masked_mean(kl, mask)
    metrics["total_loss"] = total
    metrics = {
        k: v if k == "total_loss" else jax.lax.stop_gradient(v)
        for k, v in metrics.items()
    }
    return total, metrics


def make_token_ppo_learn_fn(
    model: TransformerPolicy, optimizer: optax.GradientTransformation, args
) -> Callable:
    """Build the pure ``(state, batch) -> (state, metrics)`` update,
    wrapped in the all-finite guard like every other learn-fn factory.

    Dispatches per batch LAYOUT at trace time: a batch carrying
    ``segment_ids`` takes the packed-row loss, anything else the padded
    bucket-pair loss — dict structure is static under jit, so one learn
    fn serves both paths (the padded path stays the packed path's parity
    twin) and each layout compiles exactly once.
    """

    def learn(state: TokenPPOTrainState, batch: Dict[str, jnp.ndarray]):
        loss_fn = (
            token_ppo_packed_loss
            if "segment_ids" in batch
            else token_ppo_loss
        )
        (loss, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(
            state.params,
            state.ref_params,
            model,
            batch,
            clip_range=args.clip_range,
            value_cost=args.value_cost,
            entropy_cost=args.entropy_cost,
            kl_cost=args.kl_cost,
            adv_norm=args.adv_norm,
        )
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        new_state = TokenPPOTrainState(
            params=params,
            ref_params=state.ref_params,
            opt_state=opt_state,
            step=state.step + 1,
            tokens_seen=state.tokens_seen
            + jnp.sum(batch["mask"]).astype(state.tokens_seen.dtype),
        )
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

    return maybe_guard_nonfinite(learn, args)


class TokenPPOAgent:
    """Host-facing token-PPO agent: jitted learn + weight pub + mesh hookup.

    Not a :class:`PolicyValueAgent` — the acting path is the generation
    engine, not the recurrent per-step signature — but it speaks the same
    learner dialect: ``learn_device`` leaves metrics on device,
    ``learn`` reads them back with ONE batched transfer, ``enable_mesh``
    re-jits through ``make_parallel_learn_fn`` with the logical mp layout
    (heads/mlp/vocab over ``mp``) when the mesh has model parallelism.
    """

    def __init__(
        self,
        args,
        model: TransformerPolicy,
        key: Optional[jax.Array] = None,
    ) -> None:
        if model.vocab_size is None:
            raise ValueError(
                "TokenPPOAgent needs a token-mode TransformerPolicy "
                "(vocab_size set)"
            )
        self.args = args
        self.model = model
        key = key if key is not None else jax.random.PRNGKey(args.seed)
        dummy = jnp.zeros((1, min(2, model.max_len)), jnp.int32)
        params = model.init(key, dummy)
        self.optimizer = self._make_optimizer(args)
        from scalerl_tpu.runtime.param_server import _tree_map, jnp_copy

        self.state = TokenPPOTrainState(
            params=params,
            ref_params=_tree_map(jnp_copy, params),
            opt_state=self.optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
            tokens_seen=jnp.zeros((), jnp.int32),
        )
        self._learn_fn = make_token_ppo_learn_fn(model, self.optimizer, args)
        self._learn = jax.jit(self._learn_fn)
        self._shard_batch = None
        self.mesh = None

    @staticmethod
    def _make_optimizer(args) -> optax.GradientTransformation:
        tx = optax.chain(
            optax.clip_by_global_norm(args.max_grad_norm),
            optax.adam(args.learning_rate),
        )
        if getattr(args, "bf16_params", False):
            from scalerl_tpu.parallel.train_step import fp32_optimizer_state

            tx = fp32_optimizer_state(tx)
        return tx

    def make_learn_fn(self) -> Callable:
        """Learn fn from this agent's model/optimizer/args (the
        ``enable_mesh`` rebuild contract, ``agents/impala.py``)."""
        return make_token_ppo_learn_fn(self.model, self.optimizer, self.args)

    def enable_mesh(self, mesh_or_spec, batch_example=None) -> None:
        """Shard the learn step over a device mesh; with ``mp > 1`` the
        transformer's heads/mlp/vocab dims lay out per the logical rule
        table and inter-layer activations pin batch-over-dp."""
        from scalerl_tpu.parallel import (
            activation_constraint,
            has_mp_params,
            make_parallel_learn_fn,
            mp_param_sharding,
            resolve_mesh,
        )

        mesh = resolve_mesh(mesh_or_spec)
        param_specs = None
        if mesh.shape.get("mp", 1) > 1:
            if not has_mp_params(self.state.params):
                raise ValueError(
                    "mesh has mp > 1 but the model carries no "
                    "model-parallel shardable params"
                )
            if self.model.constrain is None:
                self.model = self.model.clone(
                    constrain=activation_constraint(mesh)
                )
                self._learn_fn = self.make_learn_fn()
            param_specs = mp_param_sharding(self.state, mesh)
        plearn = make_parallel_learn_fn(
            self._learn_fn, mesh, self.state,
            batch_example=batch_example,
            batch_time_major=False,  # packed batches are [B, ...]
            param_specs=param_specs,
        )
        self.mesh = mesh
        self.state = plearn.shard_state(self.state)
        self._learn = plearn
        self._shard_batch = plearn.shard_batch

    def learn_device(self, batch) -> Dict[str, Any]:
        """One train step, metrics left as device arrays (the hot-loop
        half of the one-batched-transfer discipline)."""
        if self._shard_batch is not None:
            batch = self._shard_batch(batch)
        self.state, metrics = self._learn(self.state, batch)  # graftlint: disable=JG002 (single-threaded learner loop; genrl has no actor threads)
        return metrics

    def learn(self, batch) -> Dict[str, float]:
        from scalerl_tpu.runtime.dispatch import get_metrics

        return get_metrics(self.learn_device(batch))  # one batched transfer

    def get_weights(self):
        return self.state.params

    def set_weights(self, weights) -> None:
        self.state = self.state.replace(params=weights)

    def save_checkpoint(self, path: str) -> str:
        return save_checkpoint(path, self.state)

    def load_checkpoint(self, path: str) -> None:
        restored = load_checkpoint(path, self.state)
        if self._shard_batch is not None and hasattr(self._learn, "shard_state"):
            restored = self._learn.shard_state(restored)
        self.state = restored
