"""Agent interface.

Parity target: ``BaseAgent`` (``scalerl/algorithms/base.py:7-124``):
``get_action`` (exploration) / ``predict`` (greedy) / ``learn`` /
``get_weights`` / ``set_weights`` / ``save_checkpoint`` / ``load_checkpoint``.
TPU-shaped differences: weights are parameter pytrees (not state dicts), and
``learn`` consumes a device-resident batch dict and returns a metrics dict.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, Mapping

import numpy as np


class BaseAgent(ABC):
    """Algorithm-agnostic agent API consumed by the trainers."""

    @abstractmethod
    def get_action(self, obs: np.ndarray) -> np.ndarray:
        """Sample actions with exploration (host entry point for actors)."""

    @abstractmethod
    def predict(self, obs: np.ndarray) -> np.ndarray:
        """Greedy/argmax actions (evaluation)."""

    @abstractmethod
    def learn(self, batch: Mapping[str, Any]) -> Dict[str, float]:
        """One gradient step on a batch; returns scalar metrics."""

    def get_weights(self) -> Any:
        """Return the current parameter pytree (for parameter servers)."""
        raise NotImplementedError

    def set_weights(self, weights: Any) -> None:
        raise NotImplementedError

    def save_checkpoint(self, path: str) -> str:
        raise NotImplementedError

    def load_checkpoint(self, path: str) -> None:
        raise NotImplementedError
