"""Agent interface.

Parity target: ``BaseAgent`` (``scalerl/algorithms/base.py:7-124``):
``get_action`` (exploration) / ``predict`` (greedy) / ``learn`` /
``get_weights`` / ``set_weights`` / ``save_checkpoint`` / ``load_checkpoint``.
TPU-shaped differences: weights are parameter pytrees (not state dicts), and
``learn`` consumes a device-resident batch dict and returns a metrics dict.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, Mapping

import numpy as np


class RecurrentEvalState:
    """Persistent recurrent carry behind the host ``get_action``/``predict``
    API (one slot per mode, so exploration and greedy eval don't clobber
    each other's memory).

    The signatures are per-call, but a recurrent agent needs its core to
    survive across calls: rows reset where the caller's ``done`` flag is
    True, everything rebuilds on a batch-size change, and with ``done=None``
    on a fresh slot the whole batch resets (the post-env-reset case).
    Rewards are not part of this host API, so the reward input is zero —
    exact recurrent rollouts go through ``actor_view``/``act`` with a
    caller-held core.
    """

    def __init__(self, initial_state_fn) -> None:
        self._initial_state_fn = initial_state_fn
        self._modes: Dict[str, Dict[str, Any]] = {}

    def step_inputs(self, mode: str, batch_size: int, done):
        st = self._modes.get(mode)
        if st is None or st["batch"] != batch_size:
            st = {
                "batch": batch_size,
                "core": self._initial_state_fn(batch_size),
                "prev_action": np.zeros(batch_size, np.int32),
            }
            self._modes[mode] = st
            done_in = np.ones(batch_size, bool)
        elif done is None:
            done_in = np.zeros(batch_size, bool)
        else:
            done_in = np.asarray(done, bool)
        # fresh episodes start with a zero last-action input (matching the
        # core reset the model applies on done rows)
        prev_action = np.where(done_in, 0, st["prev_action"]).astype(np.int32)
        reward = np.zeros(batch_size, np.float32)
        return st["core"], prev_action, reward, done_in

    def update(self, mode: str, action, core) -> None:
        st = self._modes[mode]
        st["prev_action"] = np.asarray(action, np.int32)
        st["core"] = core

    def reset(self) -> None:
        """Drop all carried cores (e.g. after loading new weights)."""
        self._modes.clear()


class BaseAgent(ABC):
    """Algorithm-agnostic agent API consumed by the trainers."""

    @abstractmethod
    def get_action(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        """Sample actions with exploration (host entry point for actors).

        ``done`` is the previous step's episode-boundary flag
        (``term | trunc``) per env lane. Recurrent agents use it to reset
        rows of their persistent core; stateless agents ignore it. Pass
        all-ones on the first step after an env reset.
        """

    @abstractmethod
    def predict(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        """Greedy/argmax actions (evaluation). ``done`` as in get_action."""

    @abstractmethod
    def learn(self, batch: Mapping[str, Any]) -> Dict[str, float]:
        """One gradient step on a batch; returns scalar metrics."""

    def get_weights(self) -> Any:
        """Return the current parameter pytree (for parameter servers)."""
        raise NotImplementedError

    def set_weights(self, weights: Any) -> None:
        raise NotImplementedError

    def save_checkpoint(self, path: str) -> str:
        raise NotImplementedError

    def load_checkpoint(self, path: str) -> None:
        raise NotImplementedError
