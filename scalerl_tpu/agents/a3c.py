"""A3C family: synchronous batched advantage actor-critic on TPU.

Parity target: ``ParallelA3C`` (``scalerl/algorithms/a3c/parallel_a3c.py:
71-507``) and its variants (``parallel_ac.py``, ``ray_a3c.py``).  The
reference's Hogwild design — per-worker CPU models pushing gradients into a
shared-memory model under ``SharedAdam`` (``parallel_a3c.py:221-233``,
``share_optim.py:9-122``) — is intentionally *not* reproduced: lock-free
racing parameter writes have no XLA equivalent and waste the MXU.  Instead
the same actor fleet feeds one synchronous batched update (documented
divergence, SURVEY.md §7 step 8):

- N actors (vector-env lanes) advance ``rollout_length`` steps using central
  batched inference — one jitted forward over the whole ``[B]`` slab instead
  of B per-process CPU forwards (``parallel_a3c.py:296-310``).
- The learner computes GAE advantages (``gae_lambda=1.0`` reduces to the
  reference's discounted-return advantage, ``parallel_a3c.py:251-262``),
  policy-gradient + value + entropy losses (``compute_loss``,
  ``parallel_a3c.py:235-288``), and takes ONE Adam step for the whole fleet
  — the role ``SharedAdam`` played, without the races.

The update consumes the universal ``Trajectory`` chunk, so the same pjit
data-parallel wrapper used by IMPALA (``scalerl_tpu.parallel``) shards A3C
across chips unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from scalerl_tpu.agents.policy_value import PolicyValueAgent, frames_counter
from scalerl_tpu.config import A3CArguments
from scalerl_tpu.data.trajectory import Trajectory
from scalerl_tpu.models.atari import AtariNet
from scalerl_tpu.models.policy import MLPPolicyNet
from scalerl_tpu.ops.losses import (
    baseline_loss,
    entropy_loss,
    policy_gradient_loss,
)
from scalerl_tpu.ops.returns import gae_advantages


@struct.dataclass
class A3CTrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray
    env_frames: jnp.ndarray


def a3c_loss(
    params,
    model,
    traj: Trajectory,
    gamma: float,
    gae_lambda: float,
    value_loss_coef: float,
    entropy_coef: float,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """The A2C objective over one on-policy [T+1, B] trajectory chunk.

    Matches ``ParallelA3C.compute_loss`` (``parallel_a3c.py:235-288``):
    GAE advantages (value targets stop-gradiented, as the reference detaches
    the return), NLL x advantage policy loss, 0.5 * sum(R - V)^2 value loss,
    entropy bonus.
    """
    out, _ = model.apply(
        params, traj.obs, traj.action, traj.reward, traj.done, traj.core_state
    )
    logits = out.policy_logits  # [T+1, B, A]
    values = out.baseline  # [T+1, B]

    actions_taken = traj.action[1:]
    rewards = traj.reward[1:]
    discounts = gamma * (1.0 - traj.done[1:].astype(jnp.float32))
    advantages, vs = gae_advantages(
        rewards, discounts, values[:-1], values[-1], lambda_=gae_lambda
    )

    pg = policy_gradient_loss(logits[:-1], actions_taken, advantages)
    vl = value_loss_coef * baseline_loss(jax.lax.stop_gradient(vs) - values[:-1])
    ent = entropy_coef * entropy_loss(logits[:-1])
    total = pg + vl + ent
    metrics = {
        "total_loss": total,
        "pg_loss": pg,
        "value_loss": vl,
        "entropy_loss": ent,
        "mean_value": jnp.mean(values),
        "mean_reward": jnp.mean(rewards),
        "mean_advantage": jnp.mean(advantages),
    }
    return total, metrics


def make_a3c_learn_fn(
    model, optimizer: optax.GradientTransformation, args: A3CArguments
) -> Callable:
    """Build the pure (state, traj) -> (state, metrics) A2C update."""

    def learn(state: A3CTrainState, traj: Trajectory):
        (loss, metrics), grads = jax.value_and_grad(a3c_loss, has_aux=True)(
            state.params,
            model,
            traj,
            gamma=args.gamma,
            gae_lambda=args.gae_lambda,
            value_loss_coef=args.value_loss_coef,
            entropy_coef=args.entropy_coef,
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        T, B = traj.reward.shape[0] - 1, traj.reward.shape[1]
        new_state = A3CTrainState(
            params=params,
            opt_state=opt_state,
            step=state.step + 1,
            env_frames=state.env_frames + T * B,
        )
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

    # all-finite guard: skip (and count) non-finite updates — see impala.py
    return maybe_guard_nonfinite(learn, args)


def make_a3c_optimizer(args: A3CArguments) -> optax.GradientTransformation:
    """Adam + global-norm clip: the one optimizer the fleet shares (the
    ``SharedAdam`` capability, ``share_optim.py:9-122``, without the
    shared-memory races; grad clip parity ``parallel_a3c.py:368``)."""
    return optax.chain(
        optax.clip_by_global_norm(args.max_grad_norm),
        optax.adam(args.learning_rate),
    )


def build_model(args: A3CArguments, obs_shape: Tuple[int, ...], num_actions: int):
    """Pixel obs -> conv+LSTM AtariNet (the reference's A3C Atari model,
    ``a3c/utils/atari_model.py:57-144``: convs + LSTMCell(256));
    flat obs -> MLPPolicyNet (``parallel_a3c.py:27-68``).
    ``args.policy_arch`` overrides with the mp-shardable big-model families
    (transformer/MoE adapters — the DD-PPO-on-a-big-policy story)."""
    from scalerl_tpu.models.transformer_policy import build_mp_policy

    mp_model = build_mp_policy(args, obs_shape, num_actions)
    if mp_model is not None:
        return mp_model
    norm_init = bool(getattr(args, "normalized_init", False))
    if len(obs_shape) == 3:
        return AtariNet(
            num_actions=num_actions,
            use_lstm=args.use_lstm,
            hidden_size=args.hidden_size,
            normalized_init=norm_init,
        )
    hidden = tuple(int(h) for h in str(args.hidden_sizes).split(",") if h)
    return MLPPolicyNet(
        num_actions=num_actions, hidden_sizes=hidden, normalized_init=norm_init
    )


class A3CAgent(PolicyValueAgent):
    """Host-facing A3C agent: jitted act + batched-sync learn."""

    def __init__(
        self,
        args: A3CArguments,
        obs_shape: Tuple[int, ...],
        num_actions: int,
        obs_dtype=jnp.float32,
        key: Optional[jax.Array] = None,
    ) -> None:
        self.args = args
        model = build_model(args, obs_shape, num_actions)
        optimizer = make_a3c_optimizer(args)
        self._setup(
            model=model,
            optimizer=optimizer,
            make_state=lambda params, opt_state: A3CTrainState(
                params=params,
                opt_state=opt_state,
                step=jnp.zeros((), jnp.int32),
                env_frames=frames_counter(),
            ),
            learn_fn=make_a3c_learn_fn(model, optimizer, args),
            obs_shape=obs_shape,
            num_actions=num_actions,
            obs_dtype=obs_dtype,
            seed=args.seed,
            key=key,
        )
