"""SAC: soft actor-critic for continuous control (beyond-parity).

The reference's network zoo declares continuous-capable actor/critic MLPs
(``scalerl/algorithms/utils/network.py:27-67``) but no algorithm ever
uses them — its DQN/A3C/Ape-X/IMPALA families are all discrete.  SAC
(Haarnoja et al. 2018) completes the story TPU-style: the entire update
— squashed-Gaussian reparameterized actor, clipped double-Q critic
targets with the entropy bonus, automatic temperature tuning toward
``-action_dim``, and the polyak target update — is ONE jitted pure
function over device-replay batches, riding the same ``OffPolicyTrainer``
/ ``Sampler`` pipeline as DQN (including PER via the |TD| feedback).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from scalerl_tpu.agents.base import BaseAgent
from scalerl_tpu.config import SACArguments
from scalerl_tpu.models.mlp import TanhGaussianActor, TwinQNet
from scalerl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def squash_log_prob(u: jnp.ndarray, log_std: jnp.ndarray, mean: jnp.ndarray,
                    action_scale: jnp.ndarray) -> jnp.ndarray:
    """log pi(a|s) for a = tanh(u) * scale, u ~ N(mean, std).

    Uses the numerically stable tanh-correction
    ``log(1 - tanh(u)^2) = 2*(log 2 - u - softplus(-2u))`` and the affine
    |det| term ``-sum(log scale)``.
    """
    std = jnp.exp(log_std)
    normal_logp = jnp.sum(
        -0.5 * jnp.square((u - mean) / std) - log_std - 0.5 * jnp.log(2.0 * jnp.pi),
        axis=-1,
    )
    tanh_corr = jnp.sum(
        2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1
    )
    scale_corr = jnp.sum(jnp.log(action_scale))
    return normal_logp - tanh_corr - scale_corr


def squash(u: jnp.ndarray, action_scale, action_bias) -> jnp.ndarray:
    """a = tanh(u) * scale + bias — THE squash transform; every sampler
    (learn-side and act-side) must route through this one helper so the
    bounds convention cannot diverge between them."""
    return jnp.tanh(u) * action_scale + action_bias


@struct.dataclass
class SACTrainState:
    actor_params: Any
    critic_params: Any
    target_critic_params: Any
    log_alpha: jnp.ndarray
    actor_opt: Any
    critic_opt: Any
    alpha_opt: Any
    step: jnp.ndarray


def make_sac_learn_fn(actor, critic, actor_tx, critic_tx, alpha_tx,
                      args: SACArguments, action_scale, action_bias,
                      target_entropy: float):
    def sample_action(actor_params, obs, key):
        mean, log_std = actor.apply(actor_params, obs)
        u = mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)
        a = squash(u, action_scale, action_bias)
        logp = squash_log_prob(u, log_std, mean, action_scale)
        return a, logp

    def learn(state: SACTrainState, batch: Mapping[str, jnp.ndarray]):
        # pure fn of (state, batch): the per-step RNG folds out of the step
        # counter (the PPO fold_in pattern), so the update is resumable and
        # mesh-shardable with no key plumbed through the batch
        key = jax.random.fold_in(jax.random.PRNGKey(args.seed + 0x5AC), state.step)
        obs = batch["obs"]
        next_obs = batch["next_obs"]
        action = batch["action"]
        reward = batch["reward"]
        done = batch["done"].astype(jnp.float32)
        weights = batch.get("weights", jnp.ones_like(reward))
        k_next, k_pi = jax.random.split(key)
        alpha = jnp.exp(state.log_alpha)

        # -- critics: clipped double-Q target with the entropy bonus.
        # n-step samples discount by gamma^k with the REALIZED window length
        # (the sampler folds rewards and bootstraps n steps ahead — same
        # contract as agents/dqn.py)
        n_steps = batch.get("n_steps")
        if n_steps is None:
            discount = (1.0 - done) * (args.gamma**args.n_steps)
        else:
            discount = (1.0 - done) * (args.gamma ** n_steps.astype(jnp.float32))
        next_a, next_logp = sample_action(state.actor_params, next_obs, k_next)
        tq1, tq2 = critic.apply(state.target_critic_params, next_obs, next_a)
        target = reward + discount * (jnp.minimum(tq1, tq2) - alpha * next_logp)
        target = jax.lax.stop_gradient(target)

        def critic_loss_fn(cp):
            q1, q2 = critic.apply(cp, obs, action)
            l = jnp.mean(weights * (jnp.square(q1 - target) + jnp.square(q2 - target)))
            return 0.5 * l, jnp.abs(q1 - target)

        (c_loss, td_abs), c_grads = jax.value_and_grad(
            critic_loss_fn, has_aux=True
        )(state.critic_params)
        c_updates, critic_opt = critic_tx.update(
            c_grads, state.critic_opt, state.critic_params
        )
        critic_params = optax.apply_updates(state.critic_params, c_updates)

        # -- actor: maximize E[min Q - alpha * logp] (reparameterized)
        def actor_loss_fn(ap):
            a, logp = sample_action(ap, obs, k_pi)
            q1, q2 = critic.apply(critic_params, obs, a)
            return jnp.mean(alpha * logp - jnp.minimum(q1, q2)), logp

        (a_loss, logp), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(state.actor_params)
        a_updates, actor_opt = actor_tx.update(
            a_grads, state.actor_opt, state.actor_params
        )
        actor_params = optax.apply_updates(state.actor_params, a_updates)

        # -- temperature: drive E[logp] toward -target_entropy
        if args.auto_alpha:
            def alpha_loss_fn(log_alpha):
                return -jnp.mean(
                    jnp.exp(log_alpha)
                    * jax.lax.stop_gradient(logp + target_entropy)
                )

            al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(state.log_alpha)
            al_updates, alpha_opt = alpha_tx.update(
                al_grad, state.alpha_opt, state.log_alpha
            )
            log_alpha = optax.apply_updates(state.log_alpha, al_updates)
        else:
            al_loss = jnp.zeros(())
            alpha_opt = state.alpha_opt
            log_alpha = state.log_alpha

        # -- polyak target update
        tau = args.soft_update_tau
        target_critic_params = jax.tree_util.tree_map(
            lambda t, o: (1.0 - tau) * t + tau * o,
            state.target_critic_params,
            critic_params,
        )

        new_state = SACTrainState(
            actor_params=actor_params,
            critic_params=critic_params,
            target_critic_params=target_critic_params,
            log_alpha=log_alpha,
            actor_opt=actor_opt,
            critic_opt=critic_opt,
            alpha_opt=alpha_opt,
            step=state.step + 1,
        )
        metrics = {
            "loss": c_loss,  # "loss" key: OffPolicyTrainer's log line reads it
            "critic_loss": c_loss,
            "actor_loss": a_loss,
            "alpha_loss": al_loss,
            "alpha": jnp.exp(log_alpha),
            "entropy": -jnp.mean(logp),
            "mean_q_target": jnp.mean(target),
        }
        return new_state, metrics, td_abs

    from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

    # all-finite guard: skip (and count) non-finite updates — see impala.py
    return maybe_guard_nonfinite(learn, args)


class SACAgent(BaseAgent):
    def __init__(
        self,
        args: SACArguments,
        obs_shape: Tuple[int, ...],
        action_low,
        action_high,
        key: Optional[jax.Array] = None,
    ) -> None:
        args.validate()
        self.args = args
        self.obs_shape = tuple(obs_shape)
        low = np.asarray(action_low, np.float32)
        high = np.asarray(action_high, np.float32)
        if low.ndim != 1:
            raise ValueError(
                f"SACAgent expects a 1-D Box action space; got bounds of "
                f"shape {low.shape} — flatten the env's action space (or "
                "wrap it) before constructing the agent"
            )
        self.action_dim = int(low.shape[0])
        self.action_scale = jnp.asarray((high - low) / 2.0)
        self.action_bias = jnp.asarray((high + low) / 2.0)
        self.actor = TanhGaussianActor(
            action_dim=self.action_dim, hidden_sizes=args.hidden_sizes
        )
        self.critic = TwinQNet(hidden_sizes=args.hidden_sizes)
        actor_tx = optax.adam(args.actor_learning_rate)
        critic_tx = optax.adam(args.learning_rate)
        alpha_tx = optax.adam(args.alpha_learning_rate)

        key = key if key is not None else jax.random.PRNGKey(args.seed)
        k_a, k_c, self._key = jax.random.split(key, 3)
        dummy_obs = jnp.zeros((1,) + self.obs_shape, jnp.float32)
        dummy_act = jnp.zeros((1, self.action_dim), jnp.float32)
        actor_params = self.actor.init(k_a, dummy_obs)
        critic_params = self.critic.init(k_c, dummy_obs, dummy_act)
        log_alpha = jnp.asarray(np.log(args.init_alpha), jnp.float32)
        self.state = SACTrainState(
            actor_params=actor_params,
            critic_params=critic_params,
            target_critic_params=jax.tree_util.tree_map(jnp.copy, critic_params),
            log_alpha=log_alpha,
            actor_opt=actor_tx.init(actor_params),
            critic_opt=critic_tx.init(critic_params),
            alpha_opt=alpha_tx.init(log_alpha),
            step=jnp.zeros((), jnp.int32),
        )
        target_entropy = -self.action_dim * args.target_entropy_scale
        self._learn_raw = make_sac_learn_fn(
            self.actor, self.critic, actor_tx, critic_tx, alpha_tx,
            args, self.action_scale, self.action_bias, target_entropy,
        )
        self._learn = jax.jit(self._learn_raw)
        self._sample = jax.jit(self._sample_impl)
        self._mean_act = jax.jit(self._mean_act_impl)
        self.mesh = None
        self._learn_mesh = None
        self._shard_batch = None

    # -- acting --------------------------------------------------------
    def _sample_impl(self, actor_params, obs, key):
        mean, log_std = self.actor.apply(actor_params, obs)
        u = mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)
        return squash(u, self.action_scale, self.action_bias)

    def _mean_act_impl(self, actor_params, obs):
        mean, _ = self.actor.apply(actor_params, obs)
        return squash(mean, self.action_scale, self.action_bias)

    def get_action(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        self._key, sub = jax.random.split(self._key)
        return np.asarray(self._sample(self.state.actor_params, obs, sub))

    def predict(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        return np.asarray(self._mean_act(self.state.actor_params, obs))

    # -- learning ------------------------------------------------------
    def enable_mesh(self, mesh_or_spec) -> None:
        """Data-parallel SAC over a mesh (the DDP story every other agent
        family has, ``docs/MIGRATION.md`` DQN row): the replay batch dim
        shards over ``dp×fsdp``, big params over ``fsdp/tp`` where
        divisible, GSPMD all-reduces gradients over ICI, and the
        per-sample |TD| vector comes back replicated for PER feedback.
        Call once before training; numerically identical to the
        single-device update at the same global batch (asserted by
        test)."""
        from scalerl_tpu.parallel import enable_offpolicy_mesh

        enable_offpolicy_mesh(self, mesh_or_spec)

    def learn(self, batch: Mapping[str, Any]) -> Dict[str, Any]:
        if self._learn_mesh is not None:
            sharded = self._shard_batch(dict(batch))
            self.state, (metrics, td_abs) = self._learn_mesh(self.state, sharded)
        else:
            self.state, metrics, td_abs = self._learn(self.state, dict(batch))
        from scalerl_tpu.runtime.dispatch import get_metrics

        out: Dict[str, Any] = get_metrics(metrics)  # one batched transfer
        out["td_abs"] = td_abs  # device array, PER priority feedback
        return out

    def get_weights(self):
        return self.state.actor_params

    def set_weights(self, weights) -> None:
        self.state = self.state.replace(actor_params=weights)

    def save_checkpoint(self, path: str) -> str:
        return save_checkpoint(path, self.state)

    def load_checkpoint(self, path: str) -> None:
        self.state = load_checkpoint(path, self.state)
