"""IMPACT: importance-weighted actor-learner with clipped target networks
(Luo et al. 2020, arxiv 1912.00167).

The sample-efficiency counterweight to the sharded big-model learner: when
the learn step gets heavier (an mp-sharded transformer/MoE policy), the
async actor plane can no longer feed it fresh chunks fast enough.  IMPACT
keeps the learner busy by replaying each chunk ``replay_times`` times out
of a circular surrogate buffer (``data/circular.py``) and makes that safe
with a *clipped target-network* surrogate:

- a slow-moving target network ``pi_target`` (refreshed from the learner
  every ``target_update_frequency`` updates) anchors the objective, so the
  K replays of a chunk all optimize against the same reference policy
  instead of chasing their own tail;
- V-trace corrections are computed target-vs-behavior (``rho =
  pi_target / mu``), decoupling off-policy correction from the fast-moving
  learner weights;
- the policy loss is the PPO-style clipped surrogate on the
  learner-vs-target ratio ``r = pi / pi_target``:
  ``-sum(min(r * adv, clip(r, 1-eps, 1+eps) * adv))``.

Drops into every IMPALA host/trainer surface unchanged: same uniform model
signature, same ``learn(traj)`` contract (one incoming chunk -> K sharded
updates -> ONE batched metric read), same ``enable_mesh`` path —
``ImpactArguments(mp_size=2, policy_arch="transformer")`` runs the full
dp×mp story.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from scalerl_tpu.agents.impala import build_model, make_impala_optimizer
from scalerl_tpu.agents.policy_value import PolicyValueAgent, frames_counter
from scalerl_tpu.config import ImpactArguments
from scalerl_tpu.data.circular import CircularTrajectoryBuffer
from scalerl_tpu.data.trajectory import Trajectory
from scalerl_tpu.ops.losses import baseline_loss, entropy_loss
from scalerl_tpu.ops.vtrace import vtrace_from_logits


@struct.dataclass
class ImpactTrainState:
    params: Any
    target_params: Any
    opt_state: Any
    step: jnp.ndarray
    env_frames: jnp.ndarray


def _action_logp(logits: jnp.ndarray, actions: jnp.ndarray) -> jnp.ndarray:
    """log pi(a_t | s_t) over [T, B] from [T, B, A] logits."""
    logp = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(logp, actions[..., None], axis=-1)[..., 0]


def impact_loss(
    params,
    target_params,
    model,
    traj: Trajectory,
    discounting: float,
    baseline_cost: float,
    entropy_cost: float,
    clip_eps: float,
    reward_clipping: str = "abs_one",
    rho_clip: float = 1.0,
    c_clip: float = 1.0,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """The IMPACT objective over one [T+1, B] chunk.

    Metric-name contract matches ``impala_loss``: ``mean_``-prefixed keys
    are true means (pmean under a dp axis), the rest sum over the batch.
    """
    out, _ = model.apply(
        params, traj.obs, traj.action, traj.reward, traj.done, traj.core_state
    )
    tout, _ = model.apply(
        jax.lax.stop_gradient(target_params),
        traj.obs, traj.action, traj.reward, traj.done, traj.core_state,
    )
    logits = out.policy_logits  # [T+1, B, A], learner policy
    target_logits = jax.lax.stop_gradient(tout.policy_logits)
    values = out.baseline  # [T+1, B], learner critic

    actions_taken = traj.action[1:]
    behavior_logits = traj.logits[:-1]
    rewards = traj.reward[1:]
    if reward_clipping == "abs_one":
        rewards = jnp.clip(rewards, -1.0, 1.0)
    discounts = discounting * (1.0 - traj.done[1:].astype(jnp.float32))

    # V-trace corrections computed TARGET-vs-behavior: the slow-moving
    # anchor absorbs the off-policyness, so K replays of this chunk see
    # stable advantages
    vt = vtrace_from_logits(
        behavior_logits=behavior_logits,
        target_logits=target_logits[:-1],
        actions=actions_taken,
        discounts=discounts,
        rewards=rewards,
        values=values[:-1],
        bootstrap_value=values[-1],
        clip_rho_threshold=rho_clip,
        clip_pg_rho_threshold=rho_clip,
        clip_c_threshold=c_clip,
    )

    # clipped surrogate on the learner-vs-target ratio (IMPACT eq. 1)
    logp_cur = _action_logp(logits[:-1], actions_taken)
    logp_tgt = _action_logp(target_logits[:-1], actions_taken)
    ratio = jnp.exp(logp_cur - logp_tgt)
    adv = jax.lax.stop_gradient(vt.pg_advantages)
    clipped = jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps)
    pg = -jnp.sum(jnp.minimum(ratio * adv, clipped * adv))
    bl = baseline_cost * baseline_loss(vt.vs - values[:-1])
    ent = entropy_cost * entropy_loss(logits[:-1])
    total = pg + bl + ent
    metrics = {
        "total_loss": total,
        "pg_loss": pg,
        "baseline_loss": bl,
        "entropy_loss": ent,
        "mean_value": jnp.mean(values),
        "mean_reward": jnp.mean(rewards),
        "mean_ratio": jnp.mean(ratio),
        "mean_clip_frac": jnp.mean(
            (jnp.abs(ratio - 1.0) > clip_eps).astype(jnp.float32)
        ),
    }
    return total, metrics


def make_impact_learn_fn(
    model,
    optimizer: optax.GradientTransformation,
    args: ImpactArguments,
    grad_axis: Optional[str] = None,
) -> Callable:
    """Pure ``(state, traj) -> (state, metrics)`` IMPACT update.

    The target network refreshes *inside* the jitted step — every
    ``target_update_frequency`` updates a ``jnp.where`` select copies the
    fresh params over the target leaves (no host round-trip, donation
    keeps both copies in the same buffers across steps).
    """

    def learn(state: ImpactTrainState, traj: Trajectory):
        (loss, metrics), grads = jax.value_and_grad(impact_loss, has_aux=True)(
            state.params,
            state.target_params,
            model,
            traj,
            discounting=args.discounting,
            baseline_cost=args.baseline_cost,
            entropy_cost=args.entropy_cost,
            clip_eps=args.impact_clip,
            reward_clipping=args.reward_clipping,
            rho_clip=args.vtrace_rho_clip,
            c_clip=args.vtrace_c_clip,
        )
        n_shards = 1
        if grad_axis is not None:
            grads = jax.lax.psum(grads, grad_axis)
            metrics = {
                k: jax.lax.pmean(v, grad_axis)
                if k.startswith("mean_")
                else jax.lax.psum(v, grad_axis)
                for k, v in metrics.items()
            }
            n_shards = jax.lax.psum(1, grad_axis)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_step = state.step + 1
        refresh = (new_step % args.target_update_frequency) == 0
        target_params = jax.tree_util.tree_map(
            lambda p, t: jnp.where(refresh, p, t), params, state.target_params
        )
        del n_shards  # frames are counted at insertion, not per update
        new_state = ImpactTrainState(
            params=params,
            target_params=target_params,
            opt_state=opt_state,
            step=new_step,
            # replayed chunks don't consume new env frames: the agent
            # counts frames once per inserted chunk (learn_device), so K
            # replays don't inflate the frame axis of every curve
            env_frames=state.env_frames,
        )
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

    return maybe_guard_nonfinite(learn, args)


class ImpactAgent(PolicyValueAgent):
    """Host-facing IMPACT agent: IMPALA's act surface + the clipped-target
    replayed learner.  ``learn``/``learn_device`` insert the incoming chunk
    into the circular surrogate buffer and run ``replay_times`` updates per
    insertion — K dispatches, still ONE batched metric read per call."""

    def __init__(
        self,
        args: ImpactArguments,
        obs_shape: Tuple[int, ...],
        num_actions: int,
        obs_dtype=jnp.uint8,
        key: Optional[jax.Array] = None,
    ) -> None:
        args.validate()
        self.args = args
        model = build_model(args, obs_shape, num_actions)
        optimizer = make_impala_optimizer(args)
        self._setup(
            model=model,
            optimizer=optimizer,
            make_state=lambda params, opt_state: ImpactTrainState(
                params=params,
                # an independent copy: the donated learn step must never
                # alias the same buffer into two argument slots
                target_params=jax.tree_util.tree_map(jnp.copy, params),
                opt_state=opt_state,
                step=jnp.zeros((), jnp.int32),
                env_frames=frames_counter(),
            ),
            learn_fn=make_impact_learn_fn(model, optimizer, args),
            obs_shape=obs_shape,
            num_actions=num_actions,
            obs_dtype=obs_dtype,
            seed=args.seed,
            key=key,
        )
        self.surrogate = CircularTrajectoryBuffer(
            capacity=args.surrogate_capacity, replay_times=args.replay_times
        )

    def make_learn_fn(self, grad_axis: Optional[str] = None):
        """Learn fn from this agent's model/optimizer/args (the mesh
        re-wrap contract shared with ``ImpalaAgent.make_learn_fn``)."""
        return make_impact_learn_fn(
            self.model, self.optimizer, self.args, grad_axis=grad_axis
        )

    def learn_device(self, traj) -> Dict[str, Any]:
        """Insert ``traj`` and run ``replay_times`` surrogate updates.

        Metrics of the LAST update are returned as device arrays — the
        caller (or ``learn``) materializes them in one batched transfer,
        so K replays still cost one host sync.
        """
        self.surrogate.add(traj)
        metrics: Dict[str, Any] = {}
        for _ in range(self.args.replay_times):
            batch = self.surrogate.sample()
            if self._shard_batch is not None:
                batch = self._shard_batch(batch)
            # callers own the mesh dispatch guard (HostPlaneMixin), same
            # contract as PolicyValueAgent.learn_device
            self.state, metrics = self._learn(self.state, batch)  # graftlint: disable=JG002 (guarded at call site)
        # frame accounting at insertion: one chunk of fresh env frames per
        # learn() call regardless of K (replays reuse frames, that's the
        # point) — keep the counter on the host-visible state
        T, B = traj.reward.shape[0] - 1, traj.reward.shape[1]
        self.state = self.state.replace(
            env_frames=self.state.env_frames + T * B
        )
        return metrics
