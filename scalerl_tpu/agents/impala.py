"""IMPALA: V-trace actor-learner agent (the flagship algorithm).

Parity target: ``ImpalaTrainer.learn`` (``scalerl/algorithms/impala/
impala_atari.py:270-349``): learner forward over ``[T+1, B]`` trajectories,
V-trace targets, pg/baseline/entropy losses (``loss_fn.py:5-23``), RMSProp
with grad clipping, and weight publication back to actors.

TPU-shaped design: the entire update — forward, V-trace (reverse scan),
losses, backward, RMSProp, grad clip — is ONE jitted pure function over an
``ImpalaTrainState``, with the trajectory batch donated.  Data-parallelism
is the same function pjit'd over a mesh with the batch axis sharded
(``scalerl_tpu.parallel``); XLA inserts the gradient ``psum`` over ICI where
the reference ran NCCL all-reduce.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from scalerl_tpu.agents.policy_value import PolicyValueAgent, frames_counter
from scalerl_tpu.config import ImpalaArguments
from scalerl_tpu.data.trajectory import Trajectory
from scalerl_tpu.models.atari import AtariNet
from scalerl_tpu.models.policy import MLPPolicyNet
from scalerl_tpu.ops.losses import (
    baseline_loss,
    entropy_loss,
    policy_gradient_loss,
)
from scalerl_tpu.ops.vtrace import vtrace_from_logits


@struct.dataclass
class ImpalaTrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray  # learner updates
    env_frames: jnp.ndarray  # env frames consumed


def impala_loss(
    params,
    model,
    traj: Trajectory,
    discounting: float,
    baseline_cost: float,
    entropy_cost: float,
    reward_clipping: str = "abs_one",
    rho_clip: float = 1.0,
    c_clip: float = 1.0,
    vtrace_impl: str = "scan",
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """The IMPALA objective over one [T+1, B] trajectory chunk.

    Metric-name contract: keys prefixed ``mean_`` are true means over the
    chunk; every other key sums over the batch (the reference's loss
    convention).  ``make_impala_learn_fn`` relies on the prefix to pick the
    cross-shard collective (pmean vs psum) under a dp mesh — name new
    metrics accordingly.
    """
    out, _ = model.apply(
        params, traj.obs, traj.action, traj.reward, traj.done, traj.core_state
    )
    target_logits = out.policy_logits  # [T+1, B, A]
    values = out.baseline  # [T+1, B]

    actions_taken = traj.action[1:]  # action taken at obs[t] is action[t+1]
    behavior_logits = traj.logits[:-1]
    rewards = traj.reward[1:]
    if reward_clipping == "abs_one":
        rewards = jnp.clip(rewards, -1.0, 1.0)
    discounts = discounting * (1.0 - traj.done[1:].astype(jnp.float32))

    vt = vtrace_from_logits(
        behavior_logits=behavior_logits,
        target_logits=target_logits[:-1],
        actions=actions_taken,
        discounts=discounts,
        rewards=rewards,
        values=values[:-1],
        bootstrap_value=values[-1],
        clip_rho_threshold=rho_clip,
        clip_pg_rho_threshold=rho_clip,
        clip_c_threshold=c_clip,
        impl=vtrace_impl,
    )

    pg = policy_gradient_loss(target_logits[:-1], actions_taken, vt.pg_advantages)
    bl = baseline_cost * baseline_loss(vt.vs - values[:-1])
    ent = entropy_cost * entropy_loss(target_logits[:-1])
    total = pg + bl + ent
    metrics = {
        "total_loss": total,
        "pg_loss": pg,
        "baseline_loss": bl,
        "entropy_loss": ent,
        "mean_value": jnp.mean(values),
        "mean_reward": jnp.mean(rewards),
    }
    return total, metrics


def make_impala_learn_fn(
    model,
    optimizer: optax.GradientTransformation,
    args: ImpalaArguments,
    grad_axis: Optional[str] = None,
) -> Callable:
    """Build the pure (state, traj) -> (state, metrics) learner update.

    ``grad_axis``: when the learn step runs *inside* ``shard_map`` with the
    batch sharded over a mesh axis (the fused multi-device loop,
    ``runtime/device_loop.py``), gradients are ``psum``-ed over that axis
    before the optimizer update — the data-parallel all-reduce the
    reference delegated to NCCL (``dqn_agent.py:173-174`` capability).
    ``psum``, not ``pmean``: the loss sums over the batch (reference
    convention), so summing shard gradients makes dp=N at global batch B
    numerically identical to a single device at batch B.  Metrics follow
    their own conventions: sum-over-batch losses are ``psum``-ed (so logged
    curves match the single-device value at the same global batch), true
    means are ``pmean``-ed.
    """

    # optional linear entropy anneal (config: entropy_cost_end /
    # entropy_anneal_frames), evaluated at the learner step inside the
    # jitted update — same pattern as the LR schedule in
    # make_impala_optimizer, so the annealed cost is traced, not baked
    ent_schedule = None
    end_cost = getattr(args, "entropy_cost_end", None)
    anneal_frames = getattr(args, "entropy_anneal_frames", 0)
    if end_cost is not None and anneal_frames > 0:
        n_updates = max(
            anneal_frames // (args.rollout_length * args.batch_size), 1
        )
        ent_schedule = optax.linear_schedule(
            args.entropy_cost, end_cost, n_updates
        )

    # RLArguments.use_pallas routes the V-trace targets through the fused
    # Pallas kernel (ops/pallas_vtrace.py; interpreter mode off-TPU) —
    # gradient-safe because V-trace outputs are stop_gradient-ed constants
    vtrace_impl = "pallas" if getattr(args, "use_pallas", False) else "scan"

    def learn(state: ImpalaTrainState, traj: Trajectory):
        ent_cost = (
            ent_schedule(state.step) if ent_schedule is not None
            else args.entropy_cost
        )
        (loss, metrics), grads = jax.value_and_grad(impala_loss, has_aux=True)(
            state.params,
            model,
            traj,
            discounting=args.discounting,
            baseline_cost=args.baseline_cost,
            entropy_cost=ent_cost,
            reward_clipping=args.reward_clipping,
            rho_clip=args.vtrace_rho_clip,
            c_clip=args.vtrace_c_clip,
            vtrace_impl=vtrace_impl,
        )
        n_shards = 1
        if grad_axis is not None:
            grads = jax.lax.psum(grads, grad_axis)
            # the metric NAME encodes its collective (impala_loss contract):
            # "mean_*" are true means -> pmean; everything else sums over the
            # batch -> psum, so each shard's sum over B/n lanes aggregates to
            # the same value a single device reports at the global batch
            metrics = {
                k: jax.lax.pmean(v, grad_axis)
                if k.startswith("mean_")
                else jax.lax.psum(v, grad_axis)
                for k, v in metrics.items()
            }
            n_shards = jax.lax.psum(1, grad_axis)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        T, B = traj.reward.shape[0] - 1, traj.reward.shape[1]
        new_state = ImpalaTrainState(
            params=params,
            opt_state=opt_state,
            step=state.step + 1,
            env_frames=state.env_frames + T * B * n_shards,
        )
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

    # all-finite guard (lax.cond-gated): a non-finite update is skipped and
    # counted (skipped_steps/nonfinite_grads ride the batched metrics) —
    # applies identically to the host plane and the fused/sharded drivers,
    # which all build their learn step through this factory
    return maybe_guard_nonfinite(learn, args)


def make_impala_optimizer(args: ImpalaArguments) -> optax.GradientTransformation:
    """RMSProp + global-norm clip, matching ``impala_atari.py:313-320``.

    ``args.bf16_params``: the chain is wrapped in ``fp32_optimizer_state``
    — grads/params upcast to f32 around the update, moments kept f32,
    updates downcast to the (bf16) param dtype — the sharded learner's
    mixed-precision layout."""
    lr: Any = args.learning_rate
    if args.total_steps > 0:
        # linear decay to 0 over total env frames, as the reference schedules
        lr = optax.linear_schedule(
            args.learning_rate, 0.0, max(args.total_steps // (args.rollout_length * args.batch_size), 1)
        )
    tx = optax.chain(
        optax.clip_by_global_norm(args.max_grad_norm),
        optax.rmsprop(
            lr,
            decay=args.rmsprop_alpha,
            eps=args.rmsprop_eps,
            momentum=args.rmsprop_momentum,
        ),
    )
    if getattr(args, "bf16_params", False):
        from scalerl_tpu.parallel.train_step import fp32_optimizer_state

        tx = fp32_optimizer_state(tx)
    return tx


def build_model(args: ImpalaArguments, obs_shape: Tuple[int, ...], num_actions: int):
    """Pixel obs -> AtariNet; flat obs -> MLPPolicyNet (same signature).
    ``args.policy_arch`` overrides with the mp-shardable big-model families
    (transformer/MoE adapters, ``models/transformer_policy.py``)."""
    from scalerl_tpu.models.transformer_policy import build_mp_policy

    mp_model = build_mp_policy(args, obs_shape, num_actions)
    if mp_model is not None:
        return mp_model
    if len(obs_shape) == 3:
        return AtariNet(
            num_actions=num_actions,
            use_lstm=args.use_lstm,
            hidden_size=args.hidden_size,
            dtype=jnp.dtype(getattr(args, "compute_dtype", "float32")),
        )
    return MLPPolicyNet(num_actions=num_actions, hidden_sizes=(args.hidden_size, args.hidden_size))


class ImpalaAgent(PolicyValueAgent):
    """Host-facing IMPALA agent: jitted act + learn + weight pub/sub."""

    def __init__(
        self,
        args: ImpalaArguments,
        obs_shape: Tuple[int, ...],
        num_actions: int,
        obs_dtype=jnp.uint8,
        key: Optional[jax.Array] = None,
    ) -> None:
        self.args = args
        model = build_model(args, obs_shape, num_actions)
        optimizer = make_impala_optimizer(args)
        self._setup(
            model=model,
            optimizer=optimizer,
            make_state=lambda params, opt_state: ImpalaTrainState(
                params=params,
                opt_state=opt_state,
                step=jnp.zeros((), jnp.int32),
                env_frames=frames_counter(),
            ),
            learn_fn=make_impala_learn_fn(model, optimizer, args),
            obs_shape=obs_shape,
            num_actions=num_actions,
            obs_dtype=obs_dtype,
            seed=args.seed,
            key=key,
        )

    def make_learn_fn(self, grad_axis: Optional[str] = None):
        """Learn fn from *this agent's* model/optimizer/args — callers (the
        mesh trainers) must not re-derive loss hyperparameters from a
        possibly-different args object."""
        return make_impala_learn_fn(
            self.model, self.optimizer, self.args, grad_axis=grad_axis
        )
