"""Shared scaffolding for policy-value agents (IMPALA, A3C, ...).

Every actor-learner agent drives the uniform recurrent-policy signature
(``models/policy.py``) and needs the same host plumbing: dummy-shape param
init, a jitted sampling/greedy act pair, a thread-safe RNG stream (multiple
actor threads call ``act`` concurrently), train-state stepping, and weight
pub / checkpoint methods.  Subclasses supply the model, the optimizer, and
the pure learn function; everything else lives here once.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scalerl_tpu.agents.base import BaseAgent, RecurrentEvalState
from scalerl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


class PolicyValueAgent(BaseAgent):
    """Host-facing agent over a recurrent policy-value model.

    Subclass contract: call ``_setup(...)`` from ``__init__`` with the built
    model, optimizer, train-state constructor, and learn fn.
    """

    def _setup(
        self,
        model,
        optimizer,
        make_state: Callable[[Any, Any], Any],  # (params, opt_state) -> TrainState
        learn_fn: Callable,
        obs_shape: Tuple[int, ...],
        num_actions: int,
        obs_dtype,
        seed: int,
        key: Optional[jax.Array] = None,
    ) -> None:
        self.obs_shape = tuple(obs_shape)
        self.num_actions = num_actions
        key = key if key is not None else jax.random.PRNGKey(seed)
        self._key = key
        self._key_lock = threading.Lock()

        self.model = model
        T1, B = 2, 1
        dummy_obs = jnp.zeros((T1, B) + self.obs_shape, obs_dtype)
        dummy_a = jnp.zeros((T1, B), jnp.int32)
        dummy_r = jnp.zeros((T1, B), jnp.float32)
        dummy_d = jnp.zeros((T1, B), jnp.bool_)
        core = model.initial_state(B)
        params = model.init(key, dummy_obs, dummy_a, dummy_r, dummy_d, core)

        self.optimizer = optimizer
        self.state = make_state(params, optimizer.init(params))
        self._learn_fn = learn_fn  # raw (un-jitted) for enable_mesh re-wrap
        self._learn = jax.jit(learn_fn)
        self._shard_batch = None
        self.mesh = None

        def act(params, obs, last_action, reward, done, core_state, key):
            """One acting step: obs [B, ...] -> sampled actions, logits, state."""
            out, new_core = model.apply(
                params, obs[None], last_action[None], reward[None], done[None], core_state
            )
            logits = out.policy_logits[0]
            action = jax.random.categorical(key, logits, axis=-1)
            return action, logits, new_core

        self._act = jax.jit(act)

        def act_greedy(params, obs, last_action, reward, done, core_state):
            out, new_core = model.apply(
                params, obs[None], last_action[None], reward[None], done[None], core_state
            )
            return out.policy_logits[0].argmax(-1), new_core

        self._act_greedy = jax.jit(act_greedy)
        self._eval_state = RecurrentEvalState(self.initial_state)

    # ------------------------------------------------------------------
    def initial_state(self, batch_size: int):
        return self.model.initial_state(batch_size)

    def _next_key(self) -> jax.Array:
        # multiple actor threads call act() concurrently (actor_learner.py);
        # an unsynchronized read-split-write would hand two actors the same key
        with self._key_lock:
            self._key, sub = jax.random.split(self._key)
        return sub

    def act(self, obs, last_action, reward, done, core_state):
        """Central batched inference for a [B, ...] slab of actor lanes.

        Thread-safety note: under ``enable_mesh`` this is a multi-device
        dispatch — the trainers enter their mesh dispatch guard around the
        call site (``fill_rollout_slot(dispatch_guard=...)``), which is why
        the dispatches below carry graftlint JG002 suppressions: the lock
        is owned one level up, shared with the learner's dispatch sites.
        """
        return self._act(  # graftlint: disable=JG002 (guarded at call site)
            self.state.params,
            jnp.asarray(obs),
            jnp.asarray(last_action, jnp.int32),
            jnp.asarray(reward, jnp.float32),
            jnp.asarray(done, jnp.bool_),
            core_state,
            self._next_key(),
        )

    def get_action(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        """Sampled actions with a persistent recurrent core (rows reset
        where the previous step's ``done`` flag is True)."""
        B = np.asarray(obs).shape[0]
        core, prev_a, prev_r, done_in = self._eval_state.step_inputs("explore", B, done)
        a, _, new_core = self.act(obs, prev_a, prev_r, done_in, core)
        self._eval_state.update("explore", a, new_core)
        return np.asarray(a)

    def predict(self, obs: np.ndarray, *, done: np.ndarray | None = None) -> np.ndarray:
        """Greedy actions, same persistent-core contract as get_action."""
        B = np.asarray(obs).shape[0]
        core, prev_a, prev_r, done_in = self._eval_state.step_inputs("greedy", B, done)
        a, new_core = self._act_greedy(  # graftlint: disable=JG002 (eval path; guarded by callers that run actor threads)
            self.state.params,
            jnp.asarray(obs),
            jnp.asarray(prev_a, jnp.int32),
            jnp.asarray(prev_r, jnp.float32),
            jnp.asarray(done_in, jnp.bool_),
            core,
        )
        self._eval_state.update("greedy", a, new_core)
        return np.asarray(a)

    def enable_mesh(self, mesh_or_spec, batch_example=None) -> None:
        """Shard the learn step over a device mesh (the ``--mesh-shape`` /
        ``dp_size``×``mp_size`` path).  Call once, before training;
        subsequent ``learn()`` calls shard incoming batches.

        Pure-dp (and fsdp/tp) meshes keep the heuristic layout: batch over
        dp×fsdp, params over fsdp/tp where divisible, gradient psum
        inserted by GSPMD.  A mesh with ``mp > 1`` switches to the sharded
        big-model learner plane: params/opt state laid out by the logical
        rule table (heads/mlp/vocab/experts over ``mp``,
        ``parallel/logical.py``), inter-layer activations pinned
        batch-over-dp via ``with_sharding_constraint`` (the learn fn is
        rebuilt against a constraint-carrying model clone), and the train
        state donated so the sharded buffers are reused in place.
        """
        from scalerl_tpu.parallel import (
            activation_constraint,
            has_mp_params,
            make_parallel_learn_fn,
            mp_param_sharding,
            resolve_mesh,
        )

        mesh = resolve_mesh(mesh_or_spec)
        param_specs = None
        if mesh.shape.get("mp", 1) > 1:
            if not has_mp_params(self.state.params):
                raise ValueError(
                    "mesh has mp > 1 but this agent's model has no "
                    "model-parallel sharding rules — use a transformer/MoE "
                    "policy (policy_arch='transformer'|'moe') or a pure-dp "
                    "mesh"
                )
            if getattr(self.model, "constrain", "absent") is None and hasattr(
                self, "make_learn_fn"
            ):
                # the constraint needs the mesh, which didn't exist at
                # construction: clone the model with the seam filled and
                # re-derive the pure learn fn from the clone
                self.model = self.model.clone(
                    constrain=activation_constraint(mesh)
                )
                self._learn_fn = self.make_learn_fn()
            param_specs = mp_param_sharding(self.state, mesh)
        plearn = make_parallel_learn_fn(
            self._learn_fn, mesh, self.state,
            batch_example=batch_example, param_specs=param_specs,
        )
        self.mesh = mesh
        self.state = plearn.shard_state(self.state)
        self._learn = plearn
        self._shard_batch = plearn.shard_batch

    def learn_device(self, traj) -> Dict[str, Any]:
        """One train step, metrics left as device arrays.

        ``float()``-ing a metric blocks until the step finishes on device;
        hot learner loops (``trainer/actor_learner.py``) call this and
        materialize metrics only at logging intervals, so consecutive learn
        dispatches queue up without a host sync in between.
        """
        if self._shard_batch is not None:
            traj = self._shard_batch(traj)
        # the hot learner loops enter the trainer's mesh dispatch guard
        # around this call (HostPlaneMixin._dispatch_guard)
        self.state, metrics = self._learn(self.state, traj)  # graftlint: disable=JG002 (guarded at call site)
        return metrics

    def learn(self, traj) -> Dict[str, float]:
        from scalerl_tpu.runtime.dispatch import get_metrics

        return get_metrics(self.learn_device(traj))  # one batched transfer

    def get_weights(self):
        return self.state.params

    def set_weights(self, weights) -> None:
        self.state = self.state.replace(params=weights)
        # a carried eval core was produced by the OLD weights; drop it
        self._eval_state.reset()

    def save_checkpoint(self, path: str) -> str:
        return save_checkpoint(path, self.state)

    def load_checkpoint(self, path: str) -> None:
        restored = load_checkpoint(path, self.state)
        if self._shard_batch is not None and hasattr(self._learn, "shard_state"):
            # meshed agent: re-place the restored leaves into the learn
            # step's sharded layout (a no-op for leaves orbax already
            # restored with their saved shardings; host arrays from an
            # unsharded or differently-meshed checkpoint get re-sharded)
            restored = self._learn.shard_state(restored)
        self.state = restored
        self._eval_state.reset()


def frames_counter() -> jnp.ndarray:
    """A zero env-frames counter in the widest enabled int dtype."""
    return (
        jnp.zeros((), jnp.int64) if jax.config.jax_enable_x64 else jnp.zeros((), jnp.int32)
    )
