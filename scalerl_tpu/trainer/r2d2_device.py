"""Device-native R2D2: on-device collection feeding on-device replay.

The TPU-fast R2D2 topology, mirroring what ``runtime/device_loop.py``
does for IMPALA: env stepping, recurrent-Q inference, and eps-greedy
action selection run as ONE jitted collector over a ``JaxVecEnv``
(``lax.scan`` over the unroll), the produced ``[B, T+1]`` sequences are
inserted into the device-resident prioritized sequence replay with a
batched dynamic-slice write, and the R2D2 learn step (burn-in + n-step
double-Q + priority write-back) is the same single jitted program the
host plane uses.  The host's whole duty per iteration is a handful of
dispatches — no trajectory ever visits host memory.

Off-policyness note: unlike the fused IMPALA loop (structurally
on-policy), this loop is genuinely off-policy — replayed sequences were
collected under OLD params and OLD (higher) epsilons, which is exactly
the regime the stored-state + burn-in machinery exists for.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp

from scalerl_tpu.agents.r2d2 import R2D2Agent
from scalerl_tpu.config import R2D2Arguments
from scalerl_tpu.runtime import dispatch, telemetry
from scalerl_tpu.runtime.dispatch import get_metrics
from scalerl_tpu.data.sequence_replay import (
    seq_add,
    seq_init,
    seq_sample,
    seq_update_priorities,
    seq_update_priorities_keep_empty,
)
from scalerl_tpu.trainer.base import BaseTrainer


class _CollectCarry(NamedTuple):
    env_state: object
    obs: jnp.ndarray  # [B, ...]
    last_action: jnp.ndarray  # [B]
    reward: jnp.ndarray  # [B]
    done: jnp.ndarray  # [B]
    core: tuple  # model recurrent state
    return_sum: jnp.ndarray  # [B] completed-episode return accumulator
    episode_return: jnp.ndarray  # [B] running
    episode_count: jnp.ndarray  # [B]


class DeviceR2D2Trainer(BaseTrainer):
    """R2D2 over a device-native env (``envs/jax_envs``)."""

    def __init__(
        self,
        args: R2D2Arguments,
        agent: R2D2Agent,
        venv,
        run_name: Optional[str] = None,
        fused: bool = True,
        mesh=None,
        axis_name: str = "dp",
    ) -> None:
        """``fused``: run each iteration (collect + insert + all learn
        steps + priority write-back) as ONE jitted dispatch — the TPU-fast
        default.  ``False`` keeps the piecewise path (one dispatch per
        stage), useful for debugging stage boundaries.

        ``mesh``: run the FUSED iteration data-parallel over a device mesh
        (the Anakin treatment ``runtime/device_loop.py`` gives IMPALA): env
        lanes, collector carry, and the sequence-replay ring all shard over
        ``axis_name`` — each shard keeps an independent local ring fed by
        its own lanes (zero insert comms) — while the learn step psums
        gradients so params stay replicated.  Sampling draws
        ``batch_size/S`` per shard with globally-normalized IS weights
        (``data/sharded_replay.seq_sample_sharded_local``).  Requires
        ``fused=True`` and a plain (non-``enable_mesh``) agent: the mesh
        treatment here subsumes the agent-side DDP form.
        """
        super().__init__(args, run_name=run_name)
        if getattr(agent, "_learn_mesh", None) is not None:
            if mesh is not None:
                raise ValueError(
                    "pass EITHER DeviceR2D2Trainer(mesh=...) (fused sharded "
                    "loop, replay included) OR agent.enable_mesh (DDP learn "
                    "step only, piecewise loop) — not both"
                )
            if fused:
                raise ValueError(
                    "fused=True runs the raw single-device learn fn and would "
                    "silently bypass agent.enable_mesh's sharded learner; use "
                    "DeviceR2D2Trainer(mesh=...) for the fused sharded loop, "
                    "or fused=False for the piecewise DDP combination"
                )
        if mesh is not None and not fused:
            raise ValueError("mesh= requires fused=True (the sharded fused loop)")
        self.fused = fused
        self.mesh = mesh
        self.axis_name = axis_name
        self.agent = agent
        self.venv = venv
        B = venv.num_envs
        T1 = args.rollout_length + 1
        obs_shape = venv.env.observation_shape
        obs_dtype = venv.env.observation_dtype
        field_shapes = {
            "obs": ((T1,) + tuple(obs_shape), obs_dtype),
            "action": ((T1,), jnp.int32),
            "reward": ((T1,), jnp.float32),
            "done": ((T1,), bool),
        }
        core = agent.initial_state(1)
        core_shapes = tuple(tuple(c.shape[1:]) for c, _ in core)
        self.replay = seq_init(field_shapes, core_shapes, args.replay_capacity)
        self._collect = jax.jit(self._collect_impl, donate_argnums=(1,))
        if mesh is None:
            # fused iteration: collect + insert + train_intensity x
            # (sample + learn + priority write-back) as ONE program — one
            # host dispatch per iteration instead of ~3 + train_intensity
            # (each dispatch costs ~50-100 ms under the axon tunnel)
            self._fused_iter = jax.jit(
                self._fused_iter_impl, donate_argnums=(0, 1, 2)
            )
            self._collect_insert = jax.jit(
                self._collect_insert_impl, donate_argnums=(1, 2)
            )
        else:
            n = mesh.shape[axis_name]
            for what, val in (
                ("venv.num_envs", B),
                ("replay_capacity", args.replay_capacity),
                ("batch_size", args.batch_size),
            ):
                if val % n != 0:
                    raise ValueError(
                        f"{what} ({val}) must divide by mesh axis "
                        f"{axis_name!r} size ({n}) for the fused sharded loop"
                    )
            from scalerl_tpu.agents.r2d2 import make_r2d2_learn_fn

            self._learn_shard = make_r2d2_learn_fn(
                agent.model, agent.optimizer, args, grad_axis=axis_name
            )
            self._fused_iter = None  # built lazily (needs pytree structure)
            self._collect_insert = None
        self._max_priority = 1.0
        self.env_frames = 0
        # observed skipped-update events (guarded learn; sampled at metric
        # boundaries, so this undercounts dense bursts — a diagnostic, not
        # an exact tally)
        self.nonfinite_events = 0
        # PER search method pinned at construction (not at first trace of
        # the fused program), so SCALERL_PER_METHOD / backend changes
        # can't be silently ignored
        from scalerl_tpu.ops.pallas_per import resolve_sample_method

        self._seq_method = resolve_sample_method("auto")

    # ------------------------------------------------------------------
    def init_carry(self, key: jax.Array) -> _CollectCarry:
        B = self.venv.num_envs
        env_state, obs = self.venv.reset(key)
        return _CollectCarry(
            env_state=env_state,
            obs=obs,
            last_action=jnp.zeros(B, jnp.int32),
            reward=jnp.zeros(B, jnp.float32),
            done=jnp.ones(B, jnp.bool_),
            core=self.agent.initial_state(B),
            return_sum=jnp.zeros(B, jnp.float32),
            episode_return=jnp.zeros(B, jnp.float32),
            episode_count=jnp.zeros(B, jnp.float32),
        )

    def _collect_impl(self, params, carry: _CollectCarry, eps, key):
        """One [T+1, B] chunk under eps-greedy; returns the sequence batch
        in replay layout ([B, T1, ...]) plus the ENTERING core state."""
        model = self.agent.model
        T = self.args.rollout_length
        entry_core = carry.core

        def step(c: _CollectCarry, k):
            out, new_core = model.apply(
                params, c.obs[None], c.last_action[None], c.reward[None],
                c.done[None], c.core,
            )
            q = out.q_values[0]  # [B, A]
            greedy = jnp.argmax(q, axis=-1).astype(jnp.int32)
            k_eps, k_rand, k_env = jax.random.split(k, 3)
            B = greedy.shape[0]
            explore = jax.random.uniform(k_eps, (B,)) < eps
            rand_a = jax.random.randint(k_rand, (B,), 0, q.shape[-1])
            action = jnp.where(explore, rand_a, greedy)
            env_state, next_obs, rew, done = self.venv.step(
                c.env_state, action, k_env
            )
            row = (c.obs, c.last_action, c.reward, c.done)
            ep_ret = c.episode_return + rew
            new_c = _CollectCarry(
                env_state=env_state,
                obs=next_obs,
                last_action=action,
                reward=rew,
                done=done,
                core=new_core,
                return_sum=c.return_sum + jnp.where(done, ep_ret, 0.0),
                episode_return=jnp.where(done, 0.0, ep_ret),
                episode_count=c.episode_count + done.astype(jnp.float32),
            )
            return new_c, row

        keys = jax.random.split(key, T)
        carry, rows = jax.lax.scan(step, carry, keys)
        obs_r, act_r, rew_r, done_r = rows
        # rows + the boundary row, then sequence-major for the replay
        fields = {
            "obs": jnp.moveaxis(
                jnp.concatenate([obs_r, carry.obs[None]], axis=0), 0, 1
            ),
            "action": jnp.moveaxis(
                jnp.concatenate([act_r, carry.last_action[None]], axis=0), 0, 1
            ),
            "reward": jnp.moveaxis(
                jnp.concatenate([rew_r, carry.reward[None]], axis=0), 0, 1
            ),
            "done": jnp.moveaxis(
                jnp.concatenate([done_r, carry.done[None]], axis=0), 0, 1
            ),
        }
        return carry, fields, entry_core

    # ------------------------------------------------------------------
    def _collect_insert_impl(self, params, replay, carry, max_prio, eps, key):
        """Warmup phase fused step: collect one chunk and insert it."""
        B = self.venv.num_envs
        carry, fields, entry_core = self._collect_impl(params, carry, eps, key)
        replay = seq_add(
            replay, fields, entry_core, jnp.full((B,), max_prio, jnp.float32)
        )
        return replay, carry

    def _fused_iter_impl(self, agent_state, replay, carry, max_prio, eps, key):
        """One full R2D2 iteration as one XLA program.

        ``max_prio`` rides the program as a traced scalar (the host keeps
        no priority state), so consecutive fused calls chain without any
        host-side reduction between them.
        """
        args = self.args
        B = self.venv.num_envs
        k_c, key = jax.random.split(key)
        carry, fields, entry_core = self._collect_impl(
            agent_state.params, carry, eps, k_c
        )
        replay = seq_add(
            replay, fields, entry_core, jnp.full((B,), max_prio, jnp.float32)
        )
        metrics = {}
        learn_raw = self.agent._learn_raw
        for _ in range(args.train_intensity):  # static, small
            key, k_s = jax.random.split(key)
            f, c, idx, w = seq_sample(
                replay, k_s, args.batch_size,
                alpha=args.per_alpha, beta=args.per_beta,
                method=self._seq_method,
            )
            agent_state, metrics, new_prio = learn_raw(agent_state, f, c, w)
            replay = seq_update_priorities(replay, idx, new_prio)
            max_prio = jnp.maximum(max_prio, jnp.max(new_prio))
        return agent_state, replay, carry, max_prio, metrics

    # ------------------------------------------------------------------
    # mesh-fused path: per-shard bodies + lazy shard_map builder

    def _fused_iter_local(self, agent_state, replay, carry, max_prio, eps, key):
        """Per-shard body of the mesh-fused iteration (inside shard_map).

        ``replay`` is this shard's INDEPENDENT local ring (capacity/S
        slots) fed by its own env lanes — inserts need no communication;
        the learn step psums gradients over ``axis_name`` so the replicated
        ``agent_state`` stays bit-identical across shards."""
        from scalerl_tpu.data.sharded_replay import seq_sample_sharded_local

        args = self.args
        axis = self.axis_name
        n = self.mesh.shape[axis]
        shard = jax.lax.axis_index(axis)
        key = jax.random.fold_in(key, shard)
        k_c, key = jax.random.split(key)
        carry, fields, entry_core = self._collect_impl(
            agent_state.params, carry, eps, k_c
        )
        B_l = fields["action"].shape[0]
        replay = seq_add(
            replay, fields, entry_core, jnp.full((B_l,), max_prio, jnp.float32)
        )
        local_cap = replay.priorities.shape[0]
        gsize = jax.lax.psum(replay.size, axis)
        metrics = {}
        for _ in range(args.train_intensity):  # static, small
            key, k_s = jax.random.split(key)
            f, c, idx, w = seq_sample_sharded_local(
                replay, k_s, args.batch_size // n,
                axes=(axis,), n_shards=n, local_capacity=local_cap,
                alpha=args.per_alpha, beta=args.per_beta, global_size=gsize,
                method=self._seq_method,
            )
            agent_state, metrics, new_prio = self._learn_shard(
                agent_state, f, c, w
            )
            # keep-empty form: a zero-weighted draw from a not-yet-filled
            # slot must not enter the distribution via its |TD| write-back
            replay = seq_update_priorities_keep_empty(
                replay, idx - shard * local_cap, new_prio
            )
            max_prio = jnp.maximum(
                max_prio, jax.lax.pmax(jnp.max(new_prio), axis)
            )
        return agent_state, replay, carry, max_prio, metrics

    def _collect_insert_local(self, params, replay, carry, max_prio, eps, key):
        """Per-shard warmup body: collect a chunk, insert into the local ring."""
        key = jax.random.fold_in(key, jax.lax.axis_index(self.axis_name))
        carry, fields, entry_core = self._collect_impl(params, carry, eps, key)
        B_l = fields["action"].shape[0]
        replay = seq_add(
            replay, fields, entry_core, jnp.full((B_l,), max_prio, jnp.float32)
        )
        return replay, carry

    def _build_sharded_fns(self, carry) -> None:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        axis = self.axis_name

        def leaf_spec(x):
            if getattr(x, "ndim", 0) >= 1:
                return P(axis, *([None] * (x.ndim - 1)))
            return P()  # replay cursors (pos/size) replicate

        replay_spec = jax.tree_util.tree_map(leaf_spec, self.replay)
        carry_spec = jax.tree_util.tree_map(leaf_spec, carry)
        # agent state / params / scalars / metrics: replicated (P() prefix)
        self._fused_iter = jax.jit(
            shard_map(
                self._fused_iter_local,
                mesh=self.mesh,
                in_specs=(P(), replay_spec, carry_spec, P(), P(), P()),
                out_specs=(P(), replay_spec, carry_spec, P(), P()),
                check_rep=False,
            ),
            donate_argnums=(0, 1, 2),
        )
        self._collect_insert = jax.jit(
            shard_map(
                self._collect_insert_local,
                mesh=self.mesh,
                in_specs=(P(), replay_spec, carry_spec, P(), P(), P()),
                out_specs=(replay_spec, carry_spec),
                check_rep=False,
            ),
            donate_argnums=(1, 2),
        )

    # ------------------------------------------------------------------
    def _eps(self, frames: int) -> float:
        """Linear decay 1.0 -> eps_base over the first 4x``warmup_sequences``
        INSERTED sequences, then constant eps_base (single-stream schedule;
        the actor-ladder eps_alpha applies to the host plane's many
        actors, not this one synchronized batch).

        Expressed in the same unit ``frames`` accrues in: each chunk adds
        ``rollout_length * num_envs`` frames and ``num_envs`` sequences, so
        one inserted sequence == ``rollout_length`` accrued frames and the
        horizon is exact for any ``num_envs`` (advisor r3).
        """
        horizon = max(self.args.warmup_sequences * 4 * self.args.rollout_length, 1)
        frac = min(frames / horizon, 1.0)
        return 1.0 + (self.args.eps_base - 1.0) * frac

    def train(self, total_frames: Optional[int] = None) -> Dict[str, float]:
        args = self.args
        total_frames = total_frames or args.max_timesteps
        B = self.venv.num_envs
        frames_per_chunk = args.rollout_length * B
        key = jax.random.PRNGKey(args.seed)
        key, k_init = jax.random.split(key)
        carry = self.init_carry(k_init)
        if self.mesh is not None and self._fused_iter is None:
            self._build_sharded_fns(carry)
        inserted = 0
        metrics: Dict = {}
        start = time.time()
        last_log = 0
        prev_sum = prev_cnt = 0.0
        windowed = float("nan")
        # final-window mark, independent of logger_frequency: the summary's
        # return_windowed covers the LAST quarter of training, never the
        # lifetime mean (which drags the eps=1 random warmup along)
        final_mark = None
        # the running max priority lives ON DEVICE for BOTH paths: it chains
        # through consecutive iterations without any host reduction — a
        # per-step float(jnp.max(...)) read would block the host on every
        # learn step (graftlint JG001); one explicit device_get at the end
        # of train() persists it back to the host mirror
        max_prio = jnp.asarray(self._max_priority, jnp.float32)
        # per-branch first-call flags: compilation may place host constants
        # on device, so only steady-state calls run under the transfer guard
        steady = {"warm": False, "cold": False}
        while self.env_frames < total_frames:
            key, k_c, k_s = jax.random.split(key, 3)
            # eps rides as a device scalar: uploading it here (outside the
            # guard) keeps the guarded fused dispatch free of implicit
            # host->device traffic
            eps = self._eps(self.env_frames)
            eps_dev = jnp.asarray(eps, jnp.float32)
            # count THIS iteration's insert: learning must start on the
            # iteration that reaches warmup (the pre-fusion semantics)
            warm = inserted + B >= args.warmup_sequences
            if self.fused:
                branch = "warm" if warm else "cold"
                guard = (
                    dispatch.steady_state_guard()
                    if steady[branch]
                    else nullcontext()
                )
                with guard:
                    if warm:
                        (
                            self.agent.state, self.replay, carry, max_prio, metrics
                        ) = self._fused_iter(
                            self.agent.state, self.replay, carry, max_prio,
                            eps_dev, k_c,
                        )
                    else:
                        self.replay, carry = self._collect_insert(
                            self.agent.state.params, self.replay, carry,
                            max_prio, eps_dev, k_c,
                        )
                steady[branch] = True
                self.env_frames += frames_per_chunk
                inserted += B
            else:
                carry, fields, entry_core = self._collect(
                    self.agent.state.params, carry, eps_dev, k_c
                )
                prio = jnp.full((B,), max_prio, jnp.float32)
                self.replay = seq_add(self.replay, fields, entry_core, prio)
                self.env_frames += frames_per_chunk
                inserted += B
                if warm:
                    for _ in range(args.train_intensity):
                        key, k_l = jax.random.split(key)
                        f, c, idx, w = seq_sample(
                            self.replay, k_l, args.batch_size,
                            alpha=args.per_alpha, beta=args.per_beta,
                            method=self._seq_method,
                        )
                        metrics, new_prio = self.agent.learn_sequences(f, c, w)
                        self.replay = seq_update_priorities(
                            self.replay, idx, new_prio
                        )
                        # async device-side reduction — no per-step host sync
                        max_prio = jnp.maximum(max_prio, jnp.max(new_prio))
            if final_mark is None and self.env_frames >= 0.75 * total_frames:
                # one batched transfer for the pair (not two blocking reads)
                mark = get_metrics(
                    {"s": jnp.sum(carry.return_sum),
                     "c": jnp.sum(carry.episode_count)}
                )
                final_mark = (mark["s"], mark["c"])
            if self.env_frames - last_log >= args.logger_frequency:
                last_log = self.env_frames
                # episode sums ride the same batched transfer as the learn
                # metrics: ONE device->host round trip per log boundary
                host = get_metrics(
                    {**metrics, "_ret_sum": jnp.sum(carry.return_sum),
                     "_ep_cnt": jnp.sum(carry.episode_count)}
                )
                s = host.pop("_ret_sum")
                c = host.pop("_ep_cnt")
                if host.get("skipped_steps", 0.0) > 0.0:
                    # the guarded learn skipped a non-finite update in the
                    # last fused iteration (flag rides the SAME batched
                    # transfer — no extra host sync to count it)
                    self.nonfinite_events += 1
                if c > prev_cnt:
                    # windowed: episodes completed since the previous log —
                    # the learning signal (the cumulative mean drags the
                    # random-policy prefix along forever)
                    windowed = (s - prev_sum) / (c - prev_cnt)
                    prev_sum, prev_cnt = s, c
                # registry-backed write path off the same host dict (the
                # guard counters fold into train.skipped_steps etc.);
                # per-chunk cadence, compiled out when telemetry is off
                if self._instrument:
                    telemetry.observe_train_metrics(host)
                    reg = telemetry.get_registry()
                    reg.set_gauges(
                        {**host, "return_windowed": windowed, "eps": eps},
                        prefix="train.",
                    )
                    self.logger.log_registry(
                        self.env_frames, step_type="train", include_prefixes=("train.",)
                    )
                if self.is_main_process:
                    self.text_logger.info(
                        f"frames {self.env_frames} | eps {eps:.2f} | "
                        f"return {windowed:.2f}"
                    )
        # persist the device-side running max across train() calls — ONE
        # explicit end-of-run transfer (both paths now keep it on device)
        self._max_priority = float(jax.device_get(max_prio))
        final = get_metrics(
            {**metrics, "_ret_sum": jnp.sum(carry.return_sum),
             "_ep_cnt": jnp.sum(carry.episode_count)}
        )
        s = final.pop("_ret_sum")
        c = final.pop("_ep_cnt")
        mark_s, mark_c = final_mark if final_mark is not None else (0.0, 0.0)
        if c > mark_c:
            windowed = (s - mark_s) / (c - mark_c)
        if final.get("skipped_steps", 0.0) > 0.0:
            self.nonfinite_events += 1
        sps = self.env_frames / max(time.time() - start, 1e-8)
        return {
            **final,
            "env_frames": float(self.env_frames),
            "sps": float(sps),
            "learn_steps": int(self.agent.state.step),
            "return_mean": s / max(c, 1.0),
            "return_windowed": windowed,
            "episodes": c,
            "nonfinite_events": float(self.nonfinite_events),
        }
