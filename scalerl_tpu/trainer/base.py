"""Trainer base: run-dir layout, logger wiring, main-process gating.

Parity target: ``BaseTrainer`` (``scalerl/trainer/base.py:26-179``): log-dir
layout ``work_dir/project/env/algo/{tb_log,text_log,model_dir}``, main-process
gating (JAX process index replaces ``accelerator.is_main_process``), and
TensorBoard-vs-W&B logger selection.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from scalerl_tpu.config import RLArguments
from scalerl_tpu.utils.loggers import BaseLogger, make_logger
from scalerl_tpu.utils.logging import get_logger, process_index


class BaseTrainer:
    def __init__(self, args: RLArguments, run_name: Optional[str] = None) -> None:
        self.args = args
        self.is_main_process = process_index() == 0
        stamp = time.strftime("%Y%m%d_%H%M%S")
        run_name = run_name or f"{args.algo_name}_{args.seed}_{stamp}"
        root = os.path.join(args.work_dir, args.project, args.env_id, args.algo_name, run_name)
        self.work_dir = root
        self.tb_log_dir = os.path.join(root, "tb_log")
        self.text_log_dir = os.path.join(root, "text_log")
        self.model_save_dir = os.path.join(root, "model_dir")
        self.video_dir = os.path.join(root, "video_dir")
        if self.is_main_process:
            for d in (self.tb_log_dir, self.text_log_dir, self.model_save_dir):
                os.makedirs(d, exist_ok=True)

        self.text_logger = get_logger(
            "scalerl_tpu",
            log_file=os.path.join(self.text_log_dir, f"{run_name}.log")
            if self.is_main_process
            else None,
        )
        if self.is_main_process and args.logger_backend != "none":
            self.logger: BaseLogger = make_logger(
                args.logger_backend,
                self.tb_log_dir,
                project=args.project,
                name=run_name,
                config=vars(args),
                train_interval=args.logger_frequency,
                update_interval=args.logger_frequency,
            )
        else:
            self.logger = make_logger("none", self.tb_log_dir)

    def close(self) -> None:
        self.logger.close()
