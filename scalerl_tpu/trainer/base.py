"""Trainer base: run-dir layout, logger wiring, main-process gating.

Parity target: ``BaseTrainer`` (``scalerl/trainer/base.py:26-179``): log-dir
layout ``work_dir/project/env/algo/{tb_log,text_log,model_dir}``, main-process
gating (JAX process index replaces ``accelerator.is_main_process``), and
TensorBoard-vs-W&B logger selection.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from scalerl_tpu.config import RLArguments
from scalerl_tpu.utils.loggers import BaseLogger, make_logger
from scalerl_tpu.utils.logging import get_logger, process_index


class BaseTrainer:
    def __init__(self, args: RLArguments, run_name: Optional[str] = None) -> None:
        self.args = args
        self.is_main_process = process_index() == 0
        self.resuming = bool(getattr(args, "resume", ""))
        if self.resuming:
            # resume into the old run dir so tb events append and the resume
            # checkpoint under model_dir is found
            root = args.resume.rstrip("/")
            run_name = os.path.basename(root)
        else:
            stamp = time.strftime("%Y%m%d_%H%M%S")
            run_name = run_name or f"{args.algo_name}_{args.seed}_{stamp}"
            root = os.path.join(
                args.work_dir, args.project, args.env_id, args.algo_name, run_name
            )
        self.work_dir = root
        self.tb_log_dir = os.path.join(root, "tb_log")
        self.text_log_dir = os.path.join(root, "text_log")
        self.model_save_dir = os.path.join(root, "model_dir")
        self.video_dir = os.path.join(root, "video_dir")
        if self.is_main_process:
            for d in (self.tb_log_dir, self.text_log_dir, self.model_save_dir):
                os.makedirs(d, exist_ok=True)

        self.text_logger = get_logger(
            "scalerl_tpu",
            log_file=os.path.join(self.text_log_dir, f"{run_name}.log")
            if self.is_main_process
            else None,
        )
        if self.is_main_process and args.logger_backend != "none":
            self.logger: BaseLogger = make_logger(
                args.logger_backend,
                self.tb_log_dir,
                project=args.project,
                name=run_name,
                config=vars(args),
                train_interval=args.logger_frequency,
                update_interval=args.logger_frequency,
            )
        else:
            self.logger = make_logger("none", self.tb_log_dir)

        # telemetry plane: periodic JSONL + Prometheus exposition off the
        # process registry (runtime/telemetry.py); the same registry the
        # interval-gated logger backends read via log_registry.
        # telemetry_interval_s <= 0 is the FAST-OFF toggle: trainers gate
        # every registry write on self._instrument, so the instrument path
        # is compiled out of the hot loops, not skipped at runtime
        # (docs/PERFORMANCE.md "Guard & telemetry amortization").
        self.telemetry_export = None
        interval_s = float(getattr(args, "telemetry_interval_s", 0.0) or 0.0)
        self._instrument = interval_s > 0
        if self.is_main_process and interval_s > 0:
            from scalerl_tpu.runtime.telemetry import (
                TelemetryExportLoop,
                get_registry,
            )

            out_dir = getattr(args, "telemetry_dir", "") or os.path.join(
                root, "telemetry"
            )
            self.telemetry_export = TelemetryExportLoop(
                out_dir, interval_s=interval_s
            ).start()
            get_registry().set_gauges(
                {"seed": float(args.seed)}, prefix="run."
            )

    # -- resume checkpointing ------------------------------------------
    @property
    def resume_ckpt_path(self) -> str:
        return os.path.join(self.model_save_dir, "resume")

    def save_resume_checkpoint(self, state: dict, env_step: int, grad_step: int) -> None:
        """Write the full-trainer resume state + logger save markers.

        ``state``: pytree of everything needed to continue (train state,
        replay state, counters).  Logger markers mirror the reference's
        ``save_data`` (``tensorboard.py:41-63``) so ``restore_data`` can
        recover the interval-gating counters from the event files alone.
        """
        if not self.is_main_process:
            return
        from scalerl_tpu.utils.checkpoint import save_checkpoint

        # keep-last-N retention: the displaced checkpoint survives as
        # resume.prev (…prevN) and load falls back to it when the latest is
        # corrupt — a preemption mid-save can never cost the run
        save_checkpoint(
            self.resume_ckpt_path,
            state,
            keep_last=getattr(self.args, "checkpoint_keep_last", 1),
        )
        self.logger.save_data(0, env_step, grad_step)

    def load_resume_checkpoint(self, target: dict) -> Optional[dict]:
        """Restore the resume pytree + logger counters.

        When the user explicitly asked for ``--resume`` but no checkpoint
        exists at the target, raise instead of returning None — silently
        retraining from step 0 into the old run dir would corrupt the tb
        event stream the user believes is a continuation.
        """
        if not os.path.exists(self.resume_ckpt_path):
            if self.resuming:
                raise FileNotFoundError(
                    f"--resume={self.args.resume}: no resume checkpoint at "
                    f"{self.resume_ckpt_path} (pass the run directory that "
                    "holds model_dir/resume, written at save_frequency)"
                )
            return None
        from scalerl_tpu.utils.checkpoint import load_checkpoint

        state = load_checkpoint(self.resume_ckpt_path, target)
        self.logger.restore_data()
        return state

    def close(self) -> None:
        if self.telemetry_export is not None:
            self.telemetry_export.stop()  # final flush: files hold end state
            self.telemetry_export = None
        self.logger.close()
