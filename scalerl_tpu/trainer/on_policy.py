"""On-policy trainer: the A3C/A2C runtime over a vector-env actor fleet.

Parity target: ``ParallelA3C.run`` (``scalerl/algorithms/a3c/parallel_a3c.py:
468-507``) — N rollout workers plus one evaluator — re-architected for TPU:
the N worker processes' env lanes become one vector env; per-worker CPU
forwards become one central jitted batched inference; the Hogwild gradient
hand-off becomes one synchronous batched update (see ``agents/a3c.py``).

The rollout loop maintains the universal ``[T+1, B]`` trajectory layout
(row t holds obs[t] plus the last-action/reward/done *leading into* it), so
recurrent policies carry their LSTM state across chunk boundaries exactly
like the IMPALA path.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import numpy as np

from scalerl_tpu.agents.a3c import A3CAgent
from scalerl_tpu.config import A3CArguments
from scalerl_tpu.data.trajectory import Trajectory
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.runtime.dispatch import get_metrics
from scalerl_tpu.trainer.base import BaseTrainer
from scalerl_tpu.utils.metrics import EpisodeMetrics


class OnPolicyTrainer(BaseTrainer):
    def __init__(
        self,
        args: A3CArguments,
        agent: A3CAgent,
        train_envs,
        eval_envs=None,
        run_name: Optional[str] = None,
    ) -> None:
        super().__init__(args, run_name=run_name)
        self.agent = agent
        # dp×mp sharded learner hookup: RLArguments.{mesh_shape,dp_size,
        # mp_size} resolve to agent.enable_mesh here (idempotent — entry
        # scripts that already enabled a mesh are left alone)
        from scalerl_tpu.parallel.train_step import maybe_enable_mesh_from_args

        maybe_enable_mesh_from_args(agent, args)
        self.train_envs = train_envs
        self.eval_envs = eval_envs
        self.num_envs = getattr(train_envs, "num_envs", 1)
        self.global_step = 0
        self.learn_steps = 0
        self.metrics = EpisodeMetrics(self.num_envs)

    # ------------------------------------------------------------------
    def collect_rollout(self, obs, last_action, last_reward, last_done, core_state):
        """Advance the fleet ``rollout_length`` steps; returns the trajectory
        chunk plus the carried state for the next chunk."""
        T = self.args.rollout_length
        B = self.num_envs
        obs_buf = np.zeros((T + 1, B) + obs.shape[1:], dtype=np.asarray(obs).dtype)
        act_buf = np.zeros((T + 1, B), np.int32)
        rew_buf = np.zeros((T + 1, B), np.float32)
        done_buf = np.zeros((T + 1, B), bool)
        logits_buf = np.zeros((T + 1, B, self.agent.num_actions), np.float32)

        obs_buf[0] = obs
        act_buf[0] = last_action
        rew_buf[0] = last_reward
        done_buf[0] = last_done
        entering_core = core_state

        for t in range(T):
            action, logits, core_state = self.agent.act(
                obs, act_buf[t], rew_buf[t], done_buf[t], core_state
            )
            action = np.asarray(action)
            logits_buf[t] = np.asarray(logits)
            next_obs, reward, term, trunc, _ = self.train_envs.step(action)
            done = np.logical_or(term, trunc)
            obs_buf[t + 1] = next_obs
            act_buf[t + 1] = action
            rew_buf[t + 1] = reward
            done_buf[t + 1] = done
            self.metrics.step(reward, done)
            obs = next_obs
            self.global_step += B

        # row T logits stay zero: no consumer reads them (the A2C loss
        # recomputes logits from params and reads behavior rows [:-1] only)
        traj = Trajectory(
            obs=jax.numpy.asarray(obs_buf),
            action=jax.numpy.asarray(act_buf),
            reward=jax.numpy.asarray(rew_buf),
            done=jax.numpy.asarray(done_buf),
            logits=jax.numpy.asarray(logits_buf),
            core_state=entering_core,
        )
        carry = (obs, act_buf[T], rew_buf[T], done_buf[T], core_state)
        return traj, carry

    def run_evaluate_episodes(self, n_episodes: Optional[int] = None) -> Dict[str, float]:
        """Greedy evaluation (the reference's dedicated eval process,
        ``parallel_a3c.py:391-447``, inlined between updates)."""
        envs = self.eval_envs or self.train_envs
        n_episodes = n_episodes or self.args.eval_episodes
        num_envs = getattr(envs, "num_envs", 1)
        obs, _ = envs.reset(seed=self.args.seed + 100)
        returns: list = []
        ep_ret = np.zeros(num_envs)
        ep_len = np.zeros(num_envs, int)
        prev_done = np.ones(num_envs, bool)
        while len(returns) < n_episodes:
            actions = self.agent.predict(obs, done=prev_done)
            obs, reward, term, trunc, _ = envs.step(np.asarray(actions))
            ep_ret += reward
            ep_len += 1
            done = np.logical_or(term, trunc)
            prev_done = done
            for i in np.nonzero(done)[0]:
                returns.append((ep_ret[i], ep_len[i]))
                ep_ret[i] = 0.0
                ep_len[i] = 0
        rets = np.array([r for r, _ in returns[:n_episodes]])
        lens = np.array([l for _, l in returns[:n_episodes]])
        return {
            "reward_mean": float(rets.mean()),
            "reward_std": float(rets.std()),
            "length_mean": float(lens.mean()),
        }

    # ------------------------------------------------------------------
    def _resume_pytree(self) -> Dict:
        return {
            "agent": self.agent.state,
            "global_step": np.asarray(self.global_step, np.int64),
            "learn_steps": np.asarray(self.learn_steps, np.int64),
        }

    def save_resume(self) -> None:
        self.save_resume_checkpoint(
            self._resume_pytree(), self.global_step, self.learn_steps
        )

    def try_resume(self) -> bool:
        """Restore train state + counters; on-policy has no replay to carry
        (the next rollout chunk is regenerated from the restored policy)."""
        state = self.load_resume_checkpoint(self._resume_pytree())
        if state is None:
            return False
        self.agent.state = state["agent"]
        self.global_step = int(state["global_step"])
        self.learn_steps = int(state["learn_steps"])
        if self.is_main_process:
            self.text_logger.info(
                f"resumed from {self.resume_ckpt_path}: step {self.global_step}"
            )
        return True

    def run(self) -> Dict[str, float]:
        args = self.args
        if self.resuming:
            self.try_resume()
        B = self.num_envs
        obs, _ = self.train_envs.reset(seed=args.seed)
        carry = (
            obs,
            np.zeros(B, np.int32),
            np.zeros(B, np.float32),
            np.zeros(B, bool),
            self.agent.initial_state(B),
        )
        start = time.time()
        start_step = self.global_step
        last_log = self.global_step
        last_eval = self.global_step
        last_save = self.global_step
        train_info: Dict[str, float] = {}

        while self.global_step < args.max_timesteps:
            traj, carry = self.collect_rollout(*carry)
            train_info = self.agent.learn(traj)
            self.learn_steps += 1

            if self.global_step - last_log >= args.logger_frequency:
                last_log = self.global_step
                fps = int(
                    (self.global_step - start_step) / max(time.time() - start, 1e-8)
                )
                summary = self.metrics.summary()
                # one batched transfer, then the registry-backed write path
                # (per log interval — chunk cadence; self._instrument is the
                # telemetry_interval_s<=0 fast-off)
                train_info = get_metrics(train_info)
                if self._instrument:
                    telemetry.observe_train_metrics(train_info)
                    reg = telemetry.get_registry()
                    reg.set_gauges(train_info, prefix="train.")
                    reg.set_gauges(summary, prefix="train.")
                    reg.set_gauges(
                        {"fps": float(fps), "learn_steps": float(self.learn_steps)},
                        prefix="train.",
                    )
                    self.logger.log_registry(
                        self.global_step, step_type="train", include_prefixes=("train.",)
                    )
                if self.is_main_process:
                    ret = summary.get("return_mean", float("nan"))
                    self.text_logger.info(
                        f"step {self.global_step} | fps {fps} | return {ret:.1f} "
                        f"| loss {train_info.get('total_loss', float('nan')):.4f}"
                    )

            if self.eval_envs is not None and self.global_step - last_eval >= args.eval_frequency:
                last_eval = self.global_step
                eval_info = self.run_evaluate_episodes()
                self.logger.log_test_data(eval_info, self.global_step)
                if self.is_main_process:
                    self.text_logger.info(
                        f"eval @ {self.global_step}: return "
                        f"{eval_info['reward_mean']:.1f} +- {eval_info['reward_std']:.1f}"
                    )

            if (
                args.save_model
                and not args.disable_checkpoint
                and self.global_step - last_save >= args.save_frequency
            ):
                last_save = self.global_step
                if self.is_main_process:
                    self.agent.save_checkpoint(f"{self.model_save_dir}/ckpt_{self.global_step}")
                    self.save_resume()

        if args.save_model and not args.disable_checkpoint and self.is_main_process:
            self.agent.save_checkpoint(f"{self.model_save_dir}/ckpt_final")
            self.save_resume()
        return self.metrics.summary()
