"""Process-actor IMPALA: monobeast-topology actors over the C++ shm ring.

The reference's IMPALA runs each actor as a *process* with its own CPU model
copy (``scalerl/algorithms/impala/impala_atari.py:153-220,420-434``) — the
torchbeast/monobeast topology, where V-trace exists precisely to correct the
actor-side policy lag.  The thread-based ``HostActorLearnerTrainer``
(SEED-style central inference) covers the other topology; this trainer covers
the reference's, with two upgrades the reference lacked:

- rollout hand-off is the lock-free C++ shared-memory slot ring
  (``runtime/shm_ring.py`` / ``csrc/shm_ring.cpp``), not pickled
  ``SimpleQueue`` tensors — actors write trajectory slots through zero-copy
  numpy views;
- actors are **spawned**, not forked (fork-after-JAX deadlocks in XLA's
  thread pools), and each pins its own single-process CPU JAX backend for
  local inference, so actors scale GIL-free across host cores while the
  learner keeps the accelerator.

Weight sync mirrors the reference's ``actor_model.load_state_dict`` pub
(``impala_atari.py:348``) as a versioned pull over a pipe: actors request
``{"kind": "params", "have": v}`` between chunks and the learner's weight
service replies with the newest numpy pytree (or ``None`` if current).
Failure handling: actor exceptions funnel back as ``{"kind": "error"}``
messages and re-raise in the learner; teardown closes the ring (the shared
stop flag), then joins with timeouts (``impala_atari.py:473-494`` ladder).
"""

from __future__ import annotations

import multiprocessing as mp
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from scalerl_tpu.config import ImpalaArguments
from scalerl_tpu.fleet.transport import PipeConnection, send_recv, wait_readable
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.runtime.param_server import ParameterServer
from scalerl_tpu.runtime.shm_ring import ShmRolloutRing, SlotSpec
from scalerl_tpu.runtime.supervisor import (
    CheckpointCadence,
    PreemptionGuard,
    StallWatchdog,
)
from scalerl_tpu.trainer.base import BaseTrainer
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class _ProcActorConfig:
    actor_id: int
    args: ImpalaArguments
    obs_shape: Tuple[int, ...]
    num_actions: int
    obs_dtype_name: str
    envs_per_actor: int
    seed: int
    atari: bool = False


def _proc_actor_main(conn: PipeConnection, cfg: _ProcActorConfig, ring: ShmRolloutRing) -> None:
    """Actor process: vector env + local CPU policy + shm slot writes."""
    import os
    import sys

    failed = False

    # Pin a single-device CPU backend before any JAX device use: this is a
    # fresh spawned interpreter, but under the axon tunnel JAX_PLATFORMS is
    # ignored, so the config knob is the reliable pin (tests/conftest.py).
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized (embedded test caller): keep it

    from scalerl_tpu.agents.impala import ImpalaAgent
    from scalerl_tpu.envs import make_vect_envs
    from scalerl_tpu.trainer.actor_learner import fill_rollout_slot

    try:
        obs_dtype = np.dtype(cfg.obs_dtype_name)
        agent = ImpalaAgent(
            cfg.args,
            obs_shape=cfg.obs_shape,
            num_actions=cfg.num_actions,
            obs_dtype=obs_dtype,
            key=jax.random.PRNGKey(cfg.seed),
        )
        # the project factory, not raw gym.make: same DeepMind Atari wrapper
        # stack and SAME_STEP autoreset semantics as the thread actor plane —
        # the learner must see identical trajectory boundary conventions
        # whichever --actor-mode produced the slots
        envs = make_vect_envs(
            cfg.args.env_id,
            num_envs=cfg.envs_per_actor,
            seed=cfg.seed,
            async_envs=False,  # one env pool per actor process already
            atari=cfg.atari,
        )
        B = cfg.envs_per_actor
        T = cfg.args.rollout_length
        obs, _ = envs.reset(seed=cfg.seed)
        last_action = np.zeros(B, np.int32)
        reward = np.zeros(B, np.float32)
        done = np.ones(B, bool)
        core_state = agent.initial_state(B)
        version = -1
        ep_ret = np.zeros(B, np.float64)
        returns: List[float] = []

        def on_step(rew: np.ndarray, dn: np.ndarray) -> None:
            nonlocal ep_ret
            ep_ret += rew
            for b in np.nonzero(dn)[0]:
                returns.append(float(ep_ret[b]))
                ep_ret[b] = 0.0

        while not ring.closed:
            # pull newest weights (None reply = already current)
            try:
                reply = send_recv(conn, {"kind": "params", "have": version})
            except (EOFError, OSError, ConnectionError):
                break
            if reply is not None:
                version = int(reply["version"])
                agent.set_weights(reply["weights"])
            idx = ring.acquire(timeout=1.0)
            if idx is None:
                continue
            try:
                slot = ring.slot(idx)
                returns.clear()
                obs, last_action, reward, done, core_state = fill_rollout_slot(
                    slot, agent, envs, obs, last_action, reward, done,
                    core_state, T, on_step=on_step,
                )
                slot["meta"][0] = cfg.actor_id
                slot["meta"][1] = version
            except BaseException:
                # funneled failure mid-fill: hand the slot back before the
                # error propagates, or each elastic restart strands one of
                # num_buffers slots until the ring starves (mirror of the
                # thread plane's q.recycle on crash)
                slot = None  # drop views first so detach() can close later
                ring.release(idx)
                raise
            ring.commit(idx)
            slot = None  # release shm views now: a live view at loop exit
            # keeps the mapping exported and detach() cannot close it
            if returns:
                try:
                    conn.send({"kind": "stats", "actor_id": cfg.actor_id,
                               "returns": list(returns)})
                except (BrokenPipeError, OSError):
                    break
        envs.close()
    except KeyboardInterrupt:
        pass
    except (EOFError, OSError, ConnectionError):
        # benign ONLY at shutdown (the learner closed the ring/pipe under
        # us).  Outside shutdown this is a real failure — e.g. an env
        # backend raising OSError — and exiting 0 silently here would give
        # the elastic learner neither an error message nor a nonzero exit
        # to react to (it treats exit 0 as a clean departure)
        if not ring.closed:
            import traceback

            failed = True
            try:
                conn.send({"kind": "error", "actor_id": cfg.actor_id,
                           "traceback": traceback.format_exc()})
            except Exception:  # noqa: BLE001 — pipe may be the casualty
                pass
    except Exception:  # noqa: BLE001 - funneled to the learner
        import traceback

        failed = True
        try:
            conn.send({"kind": "error", "actor_id": cfg.actor_id,
                       "traceback": traceback.format_exc()})
        except Exception:
            pass
    finally:
        ring.detach()
        try:
            conn.close()
        except Exception:
            pass
    if failed:
        sys.exit(1)  # nonzero: never classified as a clean departure


class ProcessActorLearnerTrainer(BaseTrainer):
    """IMPALA with GIL-free actor processes (reference topology, shm ring)."""

    def __init__(
        self,
        args: ImpalaArguments,
        agent,
        envs_per_actor: Optional[int] = None,
        run_name: Optional[str] = None,
        max_actor_restarts: int = 0,
    ) -> None:
        """``max_actor_restarts``: elastic actors — an actor that fails is
        respawned (same actor id/seed/config, fresh pipe) up to this many
        times across the run instead of failing the learner.

        Contract: recovery is guaranteed only for *funneled* failures (the
        actor caught its exception and sent ``{"kind": "error"}`` — env
        crashes, OOM in the actor's Python, etc.); the actor releases its
        acquired-but-uncommitted ring slot before the error propagates, so
        the ring stays whole.  A hard-killed actor (SIGKILL mid-ring-push)
        is respawned best-effort, but a producer that died between
        claiming and publishing a ring cell wedges the lock-free ring for
        every later consumer at that position — no user-space recovery
        exists for that, by the nature of lock-free shared memory.  0
        (default) keeps fail-fast."""
        super().__init__(args, run_name=run_name)
        self.agent = agent
        # args.num_envs is the TOTAL env-lane count (CLI semantics shared
        # with the thread backend); each actor process drives its share
        self.envs_per_actor = envs_per_actor or max(
            args.num_envs // args.num_actors, 1
        )
        from scalerl_tpu.trainer.actor_learner import check_queue_depth

        # slot-aware ring floor (the learner pops batch_size/envs_per_actor
        # full slots per step; a shallower ring starves it forever)
        check_queue_depth(args, self.envs_per_actor)
        self.param_server = ParameterServer()
        self.returns: List[float] = []
        self.env_frames = 0
        self._stop = threading.Event()
        self._actor_error: List[str] = []
        self.max_actor_restarts = max_actor_restarts
        self.actor_restarts = 0
        self.procs: List[mp.process.BaseProcess] = []
        self.conns: List[PipeConnection] = []
        self._actor_of: Dict[PipeConnection, int] = {}
        self._cfgs: List[_ProcActorConfig] = []
        self._dying: Dict[int, float] = {}  # actor_id -> recheck deadline

        T1 = args.rollout_length + 1
        B = self.envs_per_actor
        core = agent.initial_state(B)
        fields: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {
            "obs": ((T1, B) + tuple(agent.obs_shape), np.dtype(self._obs_dtype_name())),
            "action": ((T1, B), np.dtype(np.int32)),
            "reward": ((T1, B), np.dtype(np.float32)),
            "done": ((T1, B), np.dtype(bool)),
            "logits": ((T1, B, agent.num_actions), np.dtype(np.float32)),
            "meta": ((2,), np.dtype(np.float64)),
        }
        for i, (c, h) in enumerate(core):
            fields[f"core_{i}_c"] = (tuple(c.shape), np.dtype(np.float32))
            fields[f"core_{i}_h"] = (tuple(h.shape), np.dtype(np.float32))
        self._core_leaves = len(core)
        self.ring = ShmRolloutRing(SlotSpec(fields), num_slots=args.num_buffers)
        self._weight_thread = threading.Thread(
            target=self._weight_service, daemon=True
        )

    def _obs_dtype_name(self) -> str:
        return "uint8" if len(self.agent.obs_shape) == 3 else "float32"

    # -- weight / stats / error service --------------------------------
    def _grant_restart(self) -> bool:
        if self.actor_restarts >= self.max_actor_restarts:
            return False
        self.actor_restarts += 1
        return True

    def _drop_conn(self, conn: PipeConnection, reason: str) -> None:
        """A connection died: respawn its actor (elastic) or record the
        failure (fail-fast).  Clean shutdown drops silently."""
        if conn in self.conns:
            self.conns.remove(conn)
        actor_id = self._actor_of.pop(conn, None)
        if actor_id is None or self._stop.is_set():
            return
        proc = self.procs[actor_id]
        if proc.is_alive():
            # pipe EOF'd while the process is still tearing down (the
            # actor closes its conn in `finally` before interpreter exit):
            # PARK it for the service loop to recheck — forgetting it here
            # would yield neither restart nor error, and the learner would
            # starve waiting on a producer that no longer exists
            self._dying[actor_id] = time.monotonic() + 30.0
            return
        self._handle_actor_death(actor_id, reason, proc.exitcode)

    def _handle_actor_death(self, actor_id: int, reason: str, exitcode) -> None:
        if exitcode == 0:
            # clean exit outside shutdown: the actor decided it was done
            # (ring closed under it); nothing to recover, nothing to raise
            return
        if self._grant_restart():
            logger.warning(
                "actor process %d died (%s, exit %s); respawning "
                "(restart %d/%d)",
                actor_id, reason, exitcode,
                self.actor_restarts, self.max_actor_restarts,
            )
            self._spawn_actor(actor_id)
        else:
            self._actor_error.append(
                f"actor {actor_id} died ({reason}, exit {exitcode})"
            )

    def _check_dying(self) -> None:
        """Recheck parked actors (pipe gone, process was still alive)."""
        for actor_id, deadline in list(self._dying.items()):
            proc = self.procs[actor_id]
            if not proc.is_alive():
                del self._dying[actor_id]
                self._handle_actor_death(actor_id, "pipe dead", proc.exitcode)
            elif time.monotonic() > deadline:
                del self._dying[actor_id]
                self._actor_error.append(
                    f"actor {actor_id}: pipe closed but process still "
                    "alive after 30s (hung teardown)"
                )

    def _weight_service(self) -> None:
        while not self._stop.is_set():
            self._check_dying()
            if not self.conns:
                self._stop.wait(0.05)
                continue
            ready, dead = wait_readable(self.conns, timeout=0.1)
            for conn in dead:
                self._drop_conn(conn, "pipe dead")
            for conn in ready:
                try:
                    msg = conn.recv()
                except (EOFError, OSError, ConnectionError, ValueError):
                    self._drop_conn(conn, "recv failed")
                    continue
                if msg is None:
                    continue
                if msg["kind"] == "params":
                    weights, version = self.param_server.pull(int(msg["have"]))
                    try:
                        conn.send(
                            None
                            if weights is None
                            else {"version": version, "weights": weights}
                        )
                    except (BrokenPipeError, OSError):
                        continue
                elif msg["kind"] == "stats":
                    self.returns.extend(float(r) for r in msg["returns"])
                elif msg["kind"] == "error":
                    actor_id = int(msg["actor_id"])
                    if self._grant_restart():
                        logger.warning(
                            "actor %d failed; respawning (restart %d/%d):\n%s",
                            actor_id, self.actor_restarts,
                            self.max_actor_restarts, msg["traceback"],
                        )
                        # no blocking join here: it would stall weight/stats
                        # service for every OTHER actor while the errored
                        # process tears down; _spawn_actor retires the old
                        # pipe, and mp reaps the finished child on the next
                        # Process creation
                        self._spawn_actor(actor_id)
                    else:
                        self._actor_error.append(
                            f"actor {actor_id}:\n{msg['traceback']}"
                        )

    def _spawn_actor(self, i: int) -> None:
        # retire any previous pipe registered for this actor slot
        for c, a in list(self._actor_of.items()):
            if a == i:
                self._actor_of.pop(c, None)
                if c in self.conns:
                    self.conns.remove(c)
        parent, child = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(
            target=_proc_actor_main,
            args=(PipeConnection(child), self._cfgs[i], self.ring),
            daemon=True,
        )
        proc.start()
        child.close()
        if i < len(self.procs):
            self.procs[i] = proc
        else:
            self.procs.append(proc)
        conn = PipeConnection(parent)
        self.conns.append(conn)
        self._actor_of[conn] = i

    def start_actors(self) -> None:
        # spawn, not fork: the learner has JAX initialized (ADVICE r1 /
        # envs/vector/async_vec.py hazard note)
        self._ctx = mp.get_context("spawn")
        env_id = self.args.env_id
        atari = env_id.startswith("ALE/") or "NoFrameskip" in env_id
        for i in range(self.args.num_actors):
            self._cfgs.append(
                _ProcActorConfig(
                    actor_id=i,
                    args=self.args,
                    obs_shape=tuple(self.agent.obs_shape),
                    num_actions=self.agent.num_actions,
                    obs_dtype_name=self._obs_dtype_name(),
                    envs_per_actor=self.envs_per_actor,
                    seed=self.args.seed + 7919 * i,
                    atari=atari,
                )
            )
            self._spawn_actor(i)
        self._weight_thread.start()

    # -- resume (parity with HostActorLearnerTrainer) ------------------
    def _resume_pytree(self) -> Dict:
        return {
            "agent": self.agent.state,
            "env_frames": np.asarray(self.env_frames, np.int64),
        }

    def save_resume(self) -> None:
        self.save_resume_checkpoint(
            self._resume_pytree(), self.env_frames, int(self.agent.state.step)
        )

    def try_resume(self) -> bool:
        state = self.load_resume_checkpoint(self._resume_pytree())
        if state is None:
            return False
        self.agent.state = state["agent"]
        self.env_frames = int(state["env_frames"])
        if self.is_main_process:
            self.text_logger.info(
                f"resumed from {self.resume_ckpt_path}: frames {self.env_frames}"
            )
        return True

    # -- learner -------------------------------------------------------
    def _pop_batch(self, n_slots: int) -> Optional[List[int]]:
        idxs: List[int] = []
        while len(idxs) < n_slots:
            if self._actor_error:
                for i in idxs:
                    self.ring.release(i)
                raise RuntimeError(
                    "actor process failed:\n" + "\n".join(self._actor_error)
                )
            # verified pop: a torn/corrupt slot (producer killed mid-write)
            # is detected by its checksum, released, and skipped
            idx = self.ring.pop_full_verified(timeout=1.0)
            if idx is None:
                if self.ring.closed or self._stop.is_set():
                    for i in idxs:
                        self.ring.release(i)
                    return None
                continue
            idxs.append(idx)
        return idxs

    def _batch_to_host(self, idxs: List[int]) -> Dict[str, np.ndarray]:
        views = [self.ring.slot(i) for i in idxs]
        batch: Dict[str, np.ndarray] = {}
        for name in views[0]:
            if name == "meta":
                continue
            axis = 0 if name.startswith("core_") else 1
            batch[name] = np.concatenate([v[name] for v in views], axis=axis)
        self._lag = float(
            np.mean([self.param_server.version - v["meta"][1] for v in views])
        )
        return batch

    def train(self, total_frames: Optional[int] = None) -> Dict[str, float]:
        from scalerl_tpu.data.trajectory import batch_to_trajectory

        args = self.args
        total_frames = total_frames or args.total_steps
        frames_per_slot = args.rollout_length * self.envs_per_actor
        n_slots = max(args.batch_size // self.envs_per_actor, 1)
        if self.resuming:
            self.try_resume()
        self.param_server.push(self.agent.get_weights())
        if not self.procs:
            self.start_actors()
        # supervision: preemption saves at the next slot boundary; watchdog
        # dumps stacks + ring occupancy when frames stop advancing (a wedged
        # actor fleet or a dead weight service both freeze this counter)
        guard = PreemptionGuard().install() if args.handle_preemption else None
        watchdog: Optional[StallWatchdog] = None
        if args.watchdog_timeout_s > 0:
            watchdog = StallWatchdog(
                args.watchdog_timeout_s, name="process-actor-learner"
            )
            watchdog.watch("env_frames", lambda: self.env_frames)
            watchdog.add_probe("shm_ring", self.ring.stats)
            watchdog.add_probe("actor_restarts", lambda: self.actor_restarts)
            watchdog.add_probe(
                "actors_alive",
                lambda: sum(1 for p in self.procs if p.is_alive()),
            )
            watchdog.start()
        start = time.time()
        start_frames = self.env_frames  # nonzero after resume
        last_log = start_frames
        cadence = CheckpointCadence(
            args.save_frequency, args.checkpoint_interval_s, start_frames
        )
        metrics: Dict[str, float] = {}
        self._lag = float("nan")
        try:
            while self.env_frames < total_frames:
                if watchdog is not None:
                    watchdog.check()
                if guard is not None and guard.triggered:
                    if args.save_model and not args.disable_checkpoint:
                        self.save_resume()
                    break
                idxs = self._pop_batch(n_slots)
                if idxs is None:
                    break
                batch = self._batch_to_host(idxs)  # copies out of the slots
                for i in idxs:
                    self.ring.release(i)
                traj = batch_to_trajectory(batch)
                metrics = self.agent.learn(traj)
                self.param_server.push(self.agent.get_weights())
                self.env_frames += n_slots * frames_per_slot

                if (
                    args.save_model
                    and not args.disable_checkpoint
                    and cadence.due(self.env_frames)
                ):
                    cadence.mark_saved(self.env_frames)
                    self.save_resume()

                if self.env_frames - last_log >= args.logger_frequency:
                    last_log = self.env_frames
                    sps = (self.env_frames - start_frames) / max(
                        time.time() - start, 1e-8
                    )
                    ret = (
                        float(np.mean(self.returns[-50:]))
                        if self.returns
                        else float("nan")
                    )
                    # registry-backed write: ring + guard counters ride
                    # along.  Lazy import: actor children must pin their
                    # platform BEFORE anything imports jax (dispatch does)
                    from scalerl_tpu.runtime.dispatch import get_metrics

                    host_info = get_metrics(metrics)
                    if self._instrument:
                        telemetry.observe_train_metrics(host_info)
                        reg = telemetry.get_registry()
                        reg.set_gauges(
                            {**host_info, "sps": sps, "return_mean": ret,
                             "weights_lag": self._lag},
                            prefix="train.",
                        )
                        self.logger.log_registry(
                            self.env_frames,
                            step_type="train",
                            include_prefixes=("train.", "ring."),
                        )
                    if self.is_main_process:
                        self.text_logger.info(
                            f"frames {self.env_frames} | sps {sps:.0f} | "
                            f"return {ret:.1f} | lag {self._lag:.1f}"
                        )
        finally:
            if watchdog is not None:
                watchdog.stop()
            if guard is not None:
                guard.restore()
            self.stop()
        if args.save_model and not args.disable_checkpoint:
            self.save_resume()
        sps = (self.env_frames - start_frames) / max(time.time() - start, 1e-8)
        return {
            **metrics,
            "env_frames": float(self.env_frames),
            "sps": float(sps),
            "return_mean": float(np.mean(self.returns[-100:]))
            if self.returns
            else float("nan"),
            "episodes": float(len(self.returns)),
        }

    def stop(self) -> None:
        self.ring.close()
        self._stop.set()
        if self._weight_thread.is_alive():
            self._weight_thread.join(timeout=2.0)
        # close parent pipe ends BEFORE joining: an actor that entered
        # send_recv just as the weight service exited is blocked in recv();
        # EOF unblocks it, otherwise every such actor burns the join timeout
        # and gets terminate()d mid-teardown
        for c in self.conns:
            try:
                c.close()
            except Exception:
                pass
        self.conns.clear()
        for p in self.procs:
            p.join(timeout=5.0)
        for p in self.procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=2.0)
        self.ring.unlink()
