"""Sequence-RL trainer: the generate -> score -> learn round loop.

The orchestration glue of the ``genrl/`` plane (MindSpeed RL's dataflow at
single-host scale, Podracer's fused-program discipline inside each stage):

1. **generate** — the KV-cached engine runs one jitted round (prefill +
   whole decode loop) and returns host numpy with ONE batched read, under
   the steady-state transfer guard once the bucket pair is warm;
2. **score** — the task's rule-based reward runs on host numpy (the
   verifier stays off-device by design);
3. **pack + replay** — sequences become prioritized sequence-replay
   chunks (``genrl/rollout.py`` -> ``data/sequence_replay.py``), inserted
   and sampled on device with the ``seq_*`` jitted entry points;
4. **learn** — one token-PPO step (``agents/token_ppo.py``), metrics read
   back with ONE batched transfer; the learner then publishes a fresh
   generation to the engine (device-side copy, no host sync) and reports
   generation staleness off the metrics that already crossed the host
   boundary — no extra transfers anywhere in the round.

dp×mp sharding rides ``maybe_enable_mesh_from_args`` exactly like the
other trainer families.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import nullcontext
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from scalerl_tpu.agents.token_ppo import TokenPPOAgent
from scalerl_tpu.config import GenRLArguments
from scalerl_tpu.data.sequence_replay import (
    seq_add,
    seq_export,
    seq_import,
    seq_init,
    seq_sample,
)
from scalerl_tpu.genrl.continuous import ContinuousConfig, ContinuousEngine
from scalerl_tpu.genrl.engine import GenerationConfig, GenerationEngine
from scalerl_tpu.genrl.rollout import (
    pack_completions,
    pack_sequences,
    packed_field_shapes,
    packed_rows_from_completions,
    packed_rows_from_result,
    sequence_field_shapes,
)
from scalerl_tpu.genrl.task import TokenRecallTask
from scalerl_tpu.models.transformer import TransformerPolicy
from scalerl_tpu.ops.pallas_per import resolve_sample_method
from scalerl_tpu.parallel.train_step import maybe_enable_mesh_from_args
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.utils.buckets import bucket_for, default_buckets
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def build_genrl_model(args: GenRLArguments) -> TransformerPolicy:
    """Token-mode transformer sized off the shared policy fields, with
    ``max_len`` covering the largest (prompt, response) bucket pair (and
    the packed row length when the pad-free learner is on)."""
    max_p = bucket_for(args.prompt_len, default_buckets(args.prompt_len))
    max_r = bucket_for(
        args.max_new_tokens, default_buckets(args.max_new_tokens)
    )
    max_len = max_p + max_r
    seg_fn = None
    if getattr(args, "learner_packing", False):
        from scalerl_tpu.ops.pallas_attention import make_segment_attn_fn

        seg_fn = make_segment_attn_fn(args.learner_packed_attn)
        max_len = max(max_len, args.learner_pack_len or 0)
    bf16 = bool(getattr(args, "bf16_params", False))
    import jax.numpy as jnp

    return TransformerPolicy(
        num_actions=args.vocab_size,
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        num_heads=args.n_heads,
        num_layers=args.n_layers,
        max_len=max_len,
        dtype=jnp.bfloat16 if bf16 else jnp.float32,
        param_dtype=jnp.bfloat16 if bf16 else jnp.float32,
        segment_attn_fn=seg_fn,
    )


def _bucketed_rows(pk, row_buckets, pad_gauge):
    """Bucket a :class:`PackedLearnerBatch`'s row count up the pow2
    ladder (shape-stable ``seq_add``), publish the batch pad ratio, and
    return ``(fields, priorities, decode_tokens)`` — the insert triple
    both trainers feed the replay."""
    pk = pk.bucketed(bucket_for(max(pk.rows, 1), row_buckets))
    pad_gauge.set(pk.pad_ratio)
    fields, priorities = pk.fields()
    return fields, priorities, pk.decode_tokens


class SequenceRLTrainer:
    """Single-learner sequence-RL loop over a synthetic (or injected) task.

    ``task``: anything with ``sample_prompts(batch, rng) -> (prompts,
    lengths)`` and ``score(prompts, lengths, response, response_len) ->
    rewards`` — defaults to the hermetic :class:`TokenRecallTask`.
    """

    def __init__(
        self,
        args: GenRLArguments,
        task: Optional[Any] = None,
        agent: Optional[TokenPPOAgent] = None,
    ) -> None:
        args.validate()
        self.args = args
        self.task = task or TokenRecallTask(
            vocab_size=args.vocab_size,
            prompt_len=args.prompt_len,
            response_len=args.max_new_tokens,
        )
        self.agent = agent or TokenPPOAgent(args, build_genrl_model(args))
        maybe_enable_mesh_from_args(self.agent, args)
        self._mesh_lock = threading.Lock()
        base_cfg = dict(
            vocab_size=args.vocab_size,
            max_prompt_len=max(
                getattr(self.task, "max_prompt_len", args.prompt_len),
                args.prompt_len,
            ),
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            eos_token=args.eos_token,
            seed=args.seed,
        )
        self.continuous = args.genrl_engine == "continuous"
        if self.continuous:
            self.engine = ContinuousEngine(
                self.agent.model,
                self.agent.get_weights(),
                ContinuousConfig(
                    lanes=args.genrl_lanes or args.genrl_batch,
                    page_size=args.genrl_page_size,
                    num_pages=args.genrl_num_pages,
                    steps_per_macro=args.genrl_macro_steps,
                    admit_max_wait_s=args.genrl_admit_wait_ms / 1e3,
                    max_pending=args.genrl_max_pending,
                    paged_attn=args.genrl_paged_attn,
                    steps_in_flight=args.genrl_steps_in_flight,
                    prefix_cache=args.genrl_prefix_cache,
                    spec_k=args.spec_k if args.spec_enable else 0,
                    spec_ngram=args.spec_ngram,
                    **base_cfg,
                ),
                iter_mode=args.genrl_iter_mode,
            )
            # a macro-step can finish more lanes than one learn batch
            # consumes; extras carry into the next round so insert batches
            # stay shape-stable (seq_add compiles once per batch size)
            self._completion_backlog = []
        else:
            self.engine = GenerationEngine(
                self.agent.model,
                self.agent.get_weights(),
                GenerationConfig(**base_cfg),
                iter_mode=args.genrl_iter_mode,
            )
        # replay geometry is pinned to the engine's LARGEST bucket pair so
        # one buffer covers every round (smaller rounds still land in the
        # max buckets: generate() buckets by the batch's true max length,
        # and the fixed task geometry keeps that constant per run)
        self._prompt_pad = bucket_for(
            self.engine.config.max_prompt_len,
            self.engine.config.resolved_prompt_buckets(),
        )
        self._response_pad = bucket_for(
            args.max_new_tokens,
            self.engine.config.resolved_response_buckets(),
        )
        # pad-free packed learner (ISSUE 15): the replay unit becomes a
        # packed ROW of several compact sequences; insert row counts pad
        # up a pow2 ladder so seq_add compiles once per bucket
        self.packing = bool(args.learner_packing)
        self._pack_len = args.learner_pack_len or (
            self._prompt_pad + self._response_pad
        )
        self._row_buckets = default_buckets(args.genrl_batch)
        self.replay = seq_init(
            packed_field_shapes(self._pack_len)
            if self.packing
            else sequence_field_shapes(
                self._prompt_pad, self._response_pad
            ),
            (),  # no recurrent core: attention over the cache is the memory
            args.genrl_buffer_sequences,
        )
        self._seq_method = resolve_sample_method("auto")
        self._rng = np.random.default_rng(args.seed)
        self._sample_key = jax.random.PRNGKey(args.seed + 1)
        self.learn_steps = 0
        reg = telemetry.get_registry()
        self._learn_meter = reg.meter("genrl.learn_steps_per_s")
        self._reward_gauge = reg.gauge("genrl.mean_reward")
        self._stale_gauge = reg.gauge("genrl.staleness")
        self._kl_gauge = reg.gauge("genrl.kl_ref")
        self._pad_gauge = reg.gauge("genrl.pad_ratio")
        self.reward_history: List[float] = []

    def _dispatch_guard(self):
        """Serialize multi-device dispatch when the agent is meshed (the
        HostPlaneMixin idiom, graftlint JG002): single-device runs keep
        the lock-free fast path."""
        if (
            getattr(self.agent, "mesh", None) is not None
            or getattr(self.agent, "_learn_mesh", None) is not None
        ):
            return self._mesh_lock
        return nullcontext()

    def _generate_round(self):
        B = self.args.genrl_batch
        spp = self.args.samples_per_prompt
        if spp > 1:
            # group sampling on the cohort engine: tile each distinct
            # prompt spp times — the GRPO data layout (groups contiguous);
            # the cohort path pays full prefill per lane, the prefix-CoW
            # savings live on the continuous engine
            prompts, lengths = self.task.sample_prompts(B // spp, self._rng)
            prompts = np.repeat(prompts, spp, axis=0)
            lengths = np.repeat(lengths, spp, axis=0)
        else:
            prompts, lengths = self.task.sample_prompts(B, self._rng)
        result = self.engine.generate(prompts, lengths)
        rewards = self.task.score(
            prompts, lengths, result.response_tokens, result.response_len
        )
        return result, rewards

    def _round_cohort(self):
        result, rewards = self._generate_round()
        if result.prompt_pad != self._prompt_pad or (
            result.response_pad != self._response_pad
        ):
            raise ValueError(
                "generation round landed outside the replay bucket pair "
                f"({result.prompt_pad}x{result.response_pad} vs "
                f"{self._prompt_pad}x{self._response_pad})"
            )
        if self.packing:
            pk = packed_rows_from_result(result, rewards, self._pack_len)
            fields, priorities, decode = _bucketed_rows(
                pk, self._row_buckets, self._pad_gauge
            )
            return fields, priorities, rewards, decode
        self._pad_gauge.set(
            1.0
            - (result.prompt_tokens + result.decode_tokens)
            / max(result.sequences.size, 1)
        )
        fields, priorities = pack_sequences(result, rewards)
        return fields, priorities, rewards, result.decode_tokens

    def _round_continuous(self):
        """One continuous round: keep the lane pool fed, then pack exactly
        ``genrl_batch`` finished sequences (macro-steps that overshoot bank
        their extras in the backlog — insert batches stay shape-stable)."""
        B = self.args.genrl_batch
        spp = self.args.samples_per_prompt
        while len(self._completion_backlog) < B:
            deficit = (
                B
                - len(self._completion_backlog)
                - self.engine.live_lanes
                - self.engine.pending
            )
            if deficit > 0:
                # group sampling: one submit_group per distinct prompt
                # fans out into spp lanes sharing the prompt KV
                # copy-on-write (overshoot banks in the backlog)
                n_groups = -(-deficit // spp)
                prompts, lengths = self.task.sample_prompts(
                    n_groups, self._rng
                )
                for i in range(n_groups):
                    self.engine.submit_group(prompts[i], spp, lengths[i])
            self._completion_backlog.extend(self.engine.step())
        batch = self._completion_backlog[:B]
        self._completion_backlog = self._completion_backlog[B:]
        packed = pack_completions(
            batch, self._prompt_pad, self._response_pad
        )
        rewards = self.task.score(
            packed.prompts,
            packed.prompt_len,
            packed.response_tokens,
            packed.response_len,
        )
        if self.packing:
            pk = packed_rows_from_completions(
                packed, rewards, self._pack_len
            )
            fields, priorities, decode = _bucketed_rows(
                pk, self._row_buckets, self._pad_gauge
            )
            return fields, priorities, rewards, decode
        self._pad_gauge.set(
            1.0
            - (packed.prompt_len.sum() + packed.mask.sum())
            / max(packed.sequences.size, 1)
        )
        fields, priorities = packed.fields(rewards)
        return fields, priorities, rewards, packed.decode_tokens

    def train_round(self) -> Dict[str, float]:
        """One generate -> score -> insert -> sample -> learn round."""
        # head-sampled per-round trace (SCALERL_TRACE_SAMPLE): monotonic
        # stamps around work the round already does — tracing off is a
        # handful of no-op calls, never a transfer (JG001 twin)
        root = tracing.start_span("genrl.round", kind="genrl")
        t_gen0 = time.monotonic()
        fields, priorities, rewards, decode_tokens = (
            self._round_continuous()
            if self.continuous
            else self._round_cohort()
        )
        t_add0 = time.monotonic()
        with self._dispatch_guard():
            self.replay = seq_add(self.replay, fields, (), priorities)
            self._sample_key, sub = jax.random.split(self._sample_key)
            batch, _core, _idx, weights = seq_sample(
                self.replay,
                sub,
                self.args.genrl_sample_batch,
                method=self._seq_method,
            )
            batch = dict(batch)
            batch["is_weight"] = weights
            t_learn0 = time.monotonic()
            metrics = self.agent.learn(batch)  # ONE batched transfer
        if root.sampled:
            t_learn1 = time.monotonic()
            tracing.record_span(
                "round.generate", parent=root, t_start=t_gen0, t_end=t_add0,
                kind="genrl", decode_tokens=float(decode_tokens),
            )
            tracing.record_span(
                "round.seq_add", parent=root, t_start=t_add0,
                t_end=t_learn0, kind="genrl",
            )
            tracing.record_span(
                "round.learn", parent=root, t_start=t_learn0,
                t_end=t_learn1, kind="genrl",
            )
            root.end(step=self.learn_steps + 1)
        self.learn_steps += 1
        self._learn_meter.mark()
        if self.learn_steps % self.args.genrl_push_every == 0:
            # learner_step feeds the plane's gen -> step map, so staleness
            # below reports the UNIFIED definition (learner steps behind
            # the newest generation, docs/OBSERVABILITY.md)
            self.engine.push_params(
                self.agent.get_weights(), learner_step=self.learn_steps
            )
        # staleness off the metric that already crossed the host boundary
        # inside the batched read — no extra transfer
        staleness = self.engine.staleness_steps(
            int(round(metrics["mean_generation"]))
        )
        self._stale_gauge.set(staleness)
        telemetry.observe_staleness(staleness, plane="genrl")
        mean_reward = float(np.mean(rewards))
        self._reward_gauge.set(mean_reward)
        if "kl_ref" in metrics:
            self._kl_gauge.set(metrics["kl_ref"])
        metrics["round_reward"] = mean_reward
        metrics["staleness"] = staleness
        metrics["decode_tokens"] = float(decode_tokens)
        self.reward_history.append(mean_reward)
        return metrics

    def train(self, rounds: Optional[int] = None) -> Dict[str, float]:
        rounds = rounds if rounds is not None else self.args.genrl_rounds
        metrics: Dict[str, float] = {}
        log_every = max(getattr(self.args, "logger_frequency", 50) or 50, 1)
        for i in range(rounds):
            metrics = self.train_round()
            if (i + 1) % log_every == 0 or i + 1 == rounds:
                logger.info(
                    "genrl round %d/%d reward=%.3f loss=%.4f staleness=%.1f",
                    i + 1,
                    rounds,
                    metrics.get("round_reward", 0.0),
                    metrics.get("total_loss", 0.0),
                    metrics.get("staleness", 0.0),
                )
        summary = dict(metrics)
        tail = self.reward_history[-10:]
        summary["final_reward_mean"] = float(np.mean(tail)) if tail else 0.0
        summary["rounds"] = float(len(self.reward_history))
        return summary


# ---------------------------------------------------------------------------
# the disaggregated topology (ISSUE 12): generation fleet -> this learner


class _WireCompletion:
    """Adapter: one wire sequence payload viewed through the
    ``CompletedSequence`` attribute surface ``pack_completions`` reads."""

    __slots__ = (
        "prompt", "prompt_len", "response_tokens", "behavior_logp",
        "values", "generation",
    )

    def __init__(self, payload: Dict[str, Any]) -> None:
        self.prompt = np.asarray(payload["prompt"], np.int32)
        self.prompt_len = int(payload["prompt_len"])
        self.response_tokens = np.asarray(
            payload["response_tokens"], np.int32
        )
        self.behavior_logp = np.asarray(payload["behavior_logp"], np.float32)
        self.values = np.asarray(payload["values"], np.float32)
        self.generation = int(payload["generation"])


class _CohortShellFactory:
    """Picklable engine factory for the generation hosts: builds the
    token-mode model + fixed-cohort engine from the run args INSIDE the
    host process — the only seam of the disagg shell that touches jax."""

    def __init__(self, args: GenRLArguments, round_batch: int) -> None:
        self.args = args
        self.round_batch = round_batch

    def __call__(self, params: Any, generation: int):
        from scalerl_tpu.genrl.disagg import CohortEngineShell, _device_ready

        args = self.args
        engine = GenerationEngine(
            build_genrl_model(args),
            _device_ready(params),
            GenerationConfig(
                vocab_size=args.vocab_size,
                max_prompt_len=args.prompt_len,
                max_new_tokens=args.max_new_tokens,
                temperature=args.temperature,
                top_k=args.top_k,
                eos_token=args.eos_token,
                seed=args.seed,
            ),
            iter_mode=args.genrl_iter_mode,
        )
        return CohortEngineShell(
            engine, self.round_batch, initial_generation=generation
        )


class DisaggSequenceRLTrainer:
    """Sequence RL over the disaggregated dataflow (``genrl/disagg.py``):
    ``disagg_hosts`` generation hosts behind jax-free shells stream
    completed, generation-tagged sequences over the codec-v2 fleet wire
    into this learner's sequence replay; quantized param snapshots flow
    back every ``genrl_push_every`` learn steps.  The learn half (replay,
    token-PPO step, dp×mp mesh) is identical to
    :class:`SequenceRLTrainer` — disaggregation changes WHERE sequences
    are born, not how they are learned from.

    ``use_threads=True`` (default) runs the hosts as in-process threads —
    the wire, lease/ack/dedup, and snapshot protocol all still flow, with
    no per-host jax process spin-up; ``False`` spawns real host processes
    (the chaos/soak shape).

    Preemption tolerance (docs/DISTRIBUTED.md "Preemption & elastic
    membership"): with ``ledger_dir`` set, the trainer rides the durable
    learner ledger — a :class:`~scalerl_tpu.runtime.supervisor.
    PreemptionGuard` safe-point between rounds turns SIGTERM into
    :meth:`save_resume` (full learner accounting plane + replay contents +
    agent weights + lease cursor/RNG in ONE crash-safe frame), and the
    next construction against the same ``ledger_dir`` resumes at the same
    learn step under a bumped learner epoch.
    """

    def __init__(
        self,
        args: GenRLArguments,
        task: Optional[Any] = None,
        agent: Optional[TokenPPOAgent] = None,
        engine_factory: Optional[Any] = None,
        use_threads: bool = True,
        ledger_dir: Optional[str] = None,
        guard: Optional[Any] = None,
    ) -> None:
        from scalerl_tpu.genrl.disagg import (
            DisaggConfig,
            LocalGenerationFleet,
            SequenceLearner,
            record_consumption_trace,
        )
        from scalerl_tpu.runtime.param_server import _to_host

        self._record_consumption_trace = record_consumption_trace

        args.validate()
        self.args = args
        self._to_host = _to_host
        self.task = task or TokenRecallTask(
            vocab_size=args.vocab_size,
            prompt_len=args.prompt_len,
            response_len=args.max_new_tokens,
        )
        self.agent = agent or TokenPPOAgent(args, build_genrl_model(args))
        maybe_enable_mesh_from_args(self.agent, args)
        self._mesh_lock = threading.Lock()
        self._prompt_pad = bucket_for(
            args.prompt_len, default_buckets(args.prompt_len)
        )
        self._response_pad = bucket_for(
            args.max_new_tokens, default_buckets(args.max_new_tokens)
        )
        # disaggregation changes WHERE sequences are born, not how they
        # are learned from: the packed learner rides identically here
        self.packing = bool(args.learner_packing)
        self._pack_len = args.learner_pack_len or (
            self._prompt_pad + self._response_pad
        )
        self._row_buckets = default_buckets(args.genrl_batch)
        self.replay = seq_init(
            packed_field_shapes(self._pack_len)
            if self.packing
            else sequence_field_shapes(
                self._prompt_pad, self._response_pad
            ),
            (),
            args.genrl_buffer_sequences,
        )
        self._seq_method = resolve_sample_method("auto")
        self._sample_key = jax.random.PRNGKey(args.seed + 1)
        lanes = args.disagg_lanes_per_host or max(
            1, args.genrl_batch // args.disagg_hosts
        )
        self.config = DisaggConfig(
            num_hosts=args.disagg_hosts,
            lanes_per_host=lanes,
            upload_batch=args.disagg_upload_batch,
            snapshot_quantize=args.disagg_quantize,
            # a shallow accepted-sequence queue + stale-eviction keeps the
            # consumed data fresh: queue depth IS worst-case staleness
            seq_maxsize=max(4 * args.genrl_batch, 2 * lanes * args.disagg_hosts),
        )
        # the learner owns the prompts: leases carry the task-sampled
        # tokens so generation hosts stay task-agnostic decode capacity
        self._lease_rng = np.random.default_rng(args.seed + 2)
        self._lease_lock = threading.Lock()
        self._lease_seq = 0
        self.guard = guard
        ledger_dir = ledger_dir or getattr(args, "disagg_ledger_dir", "")
        self.ledger_path = (
            os.path.join(ledger_dir, "learner_ledger") if ledger_dir else None
        )
        self.learner = SequenceLearner(
            self.config, self._next_lease, ledger_path=self.ledger_path
        )
        self.learn_steps = 0
        self.reward_history: List[float] = []
        if self.learner.restored_extra is not None:
            self._adopt_restored(self.learner.restored_extra)
        self.learner.start()
        if self.learner.generation == 0:
            # fresh start only: a restored learner already holds the wire
            # snapshot (and generation counter) its hosts must adopt
            self.learner.publish(
                self._to_host(self.agent.get_weights()), learner_step=0
            )
        self.fleet = LocalGenerationFleet(
            self.learner,
            self.config,
            engine_factory or _CohortShellFactory(args, lanes),
            use_threads=use_threads,
        )
        self.fleet.start()
        reg = telemetry.get_registry()
        self._learn_meter = reg.meter("genrl.learn_steps_per_s")
        self._reward_gauge = reg.gauge("genrl.mean_reward")
        self._pad_gauge = reg.gauge("genrl.pad_ratio")

    def _adopt_restored(self, extra: Dict[str, Any]) -> None:
        """Rebuild the trainer half of a preempted run from the ledger's
        ``extra`` tree: learn step, replay contents, agent weights, the
        lease cursor + RNG (so resumed prompt leases continue the exact
        pre-restart sequence), and the reward history."""
        self.learn_steps = int(extra.get("learn_steps", 0))
        self._lease_seq = int(extra.get("lease_seq", 0))
        rng_state = extra.get("lease_rng")
        if rng_state:
            # PCG64 state words are 128-bit — they ride the ledger as a
            # JSON string, not codec ints
            self._lease_rng.bit_generator.state = json.loads(rng_state)
        if "replay" in extra:
            self.replay = seq_import(extra["replay"])
        if "agent" in extra:
            self.agent.set_weights(jax.device_put(extra["agent"]))
        self.reward_history = [
            float(r) for r in extra.get("reward_history", [])
        ]
        logger.info(
            "disagg trainer resumed at learn step %d (epoch %d, "
            "%d leases reissued)",
            self.learn_steps, self.learner.learner_epoch,
            self.learner.resumed_sequences_reissued,
        )

    def save_resume(self) -> Optional[str]:
        """The PreemptionGuard safe-point action: stop the plane and
        persist learner ledger + trainer state as one crash-safe frame
        (write-new-then-rotate + sha256 manifest).  Returns the ledger
        path, or None when no ``ledger_dir`` is configured."""
        self.learner.stop()
        if self.ledger_path is None:
            return None
        extra = {
            "learn_steps": self.learn_steps,
            "lease_seq": self._lease_seq,
            "lease_rng": json.dumps(self._lease_rng.bit_generator.state),
            "reward_history": [float(r) for r in self.reward_history],
            "replay": seq_export(self.replay),
            "agent": self._to_host(self.agent.get_weights()),
        }
        return self.learner.save_ledger(self.ledger_path, extra=extra)

    def _dispatch_guard(self):
        """Serialize multi-device dispatch when the agent is meshed (the
        HostPlaneMixin idiom, graftlint JG002).  Meshed runs should pair
        this with PROCESS hosts (``use_threads=False``) so generation
        dispatch lives in its own jax runtime entirely."""
        if (
            getattr(self.agent, "mesh", None) is not None
            or getattr(self.agent, "_learn_mesh", None) is not None
        ):
            return self._mesh_lock
        return nullcontext()

    def _next_lease(self) -> Dict[str, Any]:
        with self._lease_lock:
            self._lease_seq += 1
            seq = self._lease_seq
            prompts, lengths = self.task.sample_prompts(1, self._lease_rng)
        n = int(lengths[0])
        lease = {
            "seed": seq,
            "prompt": prompts[0, :n].astype(np.int32),
            "length": n,
        }
        spp = self.args.samples_per_prompt
        if spp > 1:
            # group sampling: this lease fans out into spp completions on
            # the generation host (submit_group on the continuous engine,
            # tiled lanes on the cohort engine) — the learner counts the
            # lease complete when all spp samples arrived
            lease["samples"] = spp
        return lease

    def train_round(self) -> Dict[str, float]:
        """One disaggregated round: drain ``genrl_batch`` wire sequences
        from the fleet -> pack -> score -> insert -> sample -> learn ->
        publish a quantized snapshot."""
        B = self.args.genrl_batch
        batch: List[_WireCompletion] = []
        raw: List[Dict[str, Any]] = []  # keeps the trace/_t_q wire keys
        deadline = time.monotonic() + self.args.disagg_round_timeout_s
        while len(batch) < B:
            payload = self.learner.get_sequence(timeout=0.2)
            if payload is not None:
                raw.append(payload)
                batch.append(_WireCompletion(payload))
            elif time.monotonic() > deadline:
                raise RuntimeError(
                    f"disagg round starved: {len(batch)}/{B} sequences "
                    f"after {self.args.disagg_round_timeout_s:.0f}s "
                    f"(live hosts: {self.learner.live_host_count()})"
                )
        t_drain = time.monotonic()
        packed = pack_completions(
            batch, self._prompt_pad, self._response_pad
        )
        rewards = self.task.score(
            packed.prompts,
            packed.prompt_len,
            packed.response_tokens,
            packed.response_len,
        )
        if self.packing:
            pk = packed_rows_from_completions(
                packed, rewards, self._pack_len
            )
            fields, priorities, _decode = _bucketed_rows(
                pk, self._row_buckets, self._pad_gauge
            )
        else:
            self._pad_gauge.set(
                1.0
                - (packed.prompt_len.sum() + packed.mask.sum())
                / max(packed.sequences.size, 1)
            )
            fields, priorities = packed.fields(rewards)
        t_add0 = time.monotonic()
        with self._dispatch_guard():
            self.replay = seq_add(self.replay, fields, (), priorities)
            self._sample_key, sub = jax.random.split(self._sample_key)
            learn_batch, _core, _idx, weights = seq_sample(
                self.replay,
                sub,
                self.args.genrl_sample_batch,
                method=self._seq_method,
            )
            learn_batch = dict(learn_batch)
            learn_batch["is_weight"] = weights
            t_learn0 = time.monotonic()
            metrics = self.agent.learn(learn_batch)  # ONE batched transfer
        self.learn_steps += 1
        # extend each consumed sequence's trace with the learner-side edges
        # (replay wait -> seq_add -> the learn step that consumed it) — the
        # monotonic stamps above were taken around work the round already
        # does, so tracing off costs nothing
        self._record_consumption_trace(
            raw, t_drain, t_add0, t_learn0, t_learn0, time.monotonic(),
            self.learn_steps,
        )
        self._learn_meter.mark()
        if self.learn_steps % self.args.genrl_push_every == 0:
            self.learner.publish(
                self._to_host(self.agent.get_weights()),
                learner_step=self.learn_steps,
            )
        staleness = self.learner.observe_consumed(
            int(round(metrics["mean_generation"]))
        )
        mean_reward = float(np.mean(rewards))
        self._reward_gauge.set(mean_reward)
        metrics["round_reward"] = mean_reward
        metrics["staleness"] = staleness
        metrics["decode_tokens"] = float(packed.decode_tokens)
        self.reward_history.append(mean_reward)
        return metrics

    def train(self, rounds: Optional[int] = None) -> Dict[str, float]:
        rounds = rounds if rounds is not None else self.args.genrl_rounds
        metrics: Dict[str, float] = {}
        try:
            for _ in range(rounds):
                if self.guard is not None and self.guard.poll_chaos(
                    "learner"
                ):
                    # the safe-point: SIGTERM (real, or the chaos plan's
                    # seeded preempt draw) landed — save the full plane
                    # between rounds and exit; the next construction
                    # against the same ledger_dir resumes this step
                    telemetry.record_event(
                        "preemption_exit",
                        plane="disagg",
                        step=self.learn_steps,
                    )
                    self.save_resume()
                    break
                metrics = self.train_round()
        finally:
            self.close()
        summary = dict(metrics)
        tail = self.reward_history[-10:]
        summary["final_reward_mean"] = float(np.mean(tail)) if tail else 0.0
        summary["rounds"] = float(len(self.reward_history))
        summary["wire_sequences"] = float(self.learner.total_sequences)
        summary["learn_steps"] = float(self.learn_steps)
        return summary

    def close(self) -> None:
        self.learner.stop()
        self.fleet.join(timeout=5.0)
