"""Sequence-RL trainer: the generate -> score -> learn round loop.

The orchestration glue of the ``genrl/`` plane (MindSpeed RL's dataflow at
single-host scale, Podracer's fused-program discipline inside each stage):

1. **generate** — the KV-cached engine runs one jitted round (prefill +
   whole decode loop) and returns host numpy with ONE batched read, under
   the steady-state transfer guard once the bucket pair is warm;
2. **score** — the task's rule-based reward runs on host numpy (the
   verifier stays off-device by design);
3. **pack + replay** — sequences become prioritized sequence-replay
   chunks (``genrl/rollout.py`` -> ``data/sequence_replay.py``), inserted
   and sampled on device with the ``seq_*`` jitted entry points;
4. **learn** — one token-PPO step (``agents/token_ppo.py``), metrics read
   back with ONE batched transfer; the learner then publishes a fresh
   generation to the engine (device-side copy, no host sync) and reports
   generation staleness off the metrics that already crossed the host
   boundary — no extra transfers anywhere in the round.

dp×mp sharding rides ``maybe_enable_mesh_from_args`` exactly like the
other trainer families.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from scalerl_tpu.agents.token_ppo import TokenPPOAgent
from scalerl_tpu.config import GenRLArguments
from scalerl_tpu.data.sequence_replay import seq_add, seq_init, seq_sample
from scalerl_tpu.genrl.continuous import ContinuousConfig, ContinuousEngine
from scalerl_tpu.genrl.engine import GenerationConfig, GenerationEngine
from scalerl_tpu.genrl.rollout import (
    pack_completions,
    pack_sequences,
    sequence_field_shapes,
)
from scalerl_tpu.genrl.task import TokenRecallTask
from scalerl_tpu.models.transformer import TransformerPolicy
from scalerl_tpu.ops.pallas_per import resolve_sample_method
from scalerl_tpu.parallel.train_step import maybe_enable_mesh_from_args
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.utils.buckets import bucket_for, default_buckets
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def build_genrl_model(args: GenRLArguments) -> TransformerPolicy:
    """Token-mode transformer sized off the shared policy fields, with
    ``max_len`` covering the largest (prompt, response) bucket pair."""
    max_p = bucket_for(args.prompt_len, default_buckets(args.prompt_len))
    max_r = bucket_for(
        args.max_new_tokens, default_buckets(args.max_new_tokens)
    )
    bf16 = bool(getattr(args, "bf16_params", False))
    import jax.numpy as jnp

    return TransformerPolicy(
        num_actions=args.vocab_size,
        vocab_size=args.vocab_size,
        d_model=args.d_model,
        num_heads=args.n_heads,
        num_layers=args.n_layers,
        max_len=max_p + max_r,
        dtype=jnp.bfloat16 if bf16 else jnp.float32,
        param_dtype=jnp.bfloat16 if bf16 else jnp.float32,
    )


class SequenceRLTrainer:
    """Single-learner sequence-RL loop over a synthetic (or injected) task.

    ``task``: anything with ``sample_prompts(batch, rng) -> (prompts,
    lengths)`` and ``score(prompts, lengths, response, response_len) ->
    rewards`` — defaults to the hermetic :class:`TokenRecallTask`.
    """

    def __init__(
        self,
        args: GenRLArguments,
        task: Optional[Any] = None,
        agent: Optional[TokenPPOAgent] = None,
    ) -> None:
        args.validate()
        self.args = args
        self.task = task or TokenRecallTask(
            vocab_size=args.vocab_size,
            prompt_len=args.prompt_len,
            response_len=args.max_new_tokens,
        )
        self.agent = agent or TokenPPOAgent(args, build_genrl_model(args))
        maybe_enable_mesh_from_args(self.agent, args)
        base_cfg = dict(
            vocab_size=args.vocab_size,
            max_prompt_len=max(
                getattr(self.task, "max_prompt_len", args.prompt_len),
                args.prompt_len,
            ),
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            eos_token=args.eos_token,
            seed=args.seed,
        )
        self.continuous = args.genrl_engine == "continuous"
        if self.continuous:
            self.engine = ContinuousEngine(
                self.agent.model,
                self.agent.get_weights(),
                ContinuousConfig(
                    lanes=args.genrl_lanes or args.genrl_batch,
                    page_size=args.genrl_page_size,
                    num_pages=args.genrl_num_pages,
                    steps_per_macro=args.genrl_macro_steps,
                    admit_max_wait_s=args.genrl_admit_wait_ms / 1e3,
                    max_pending=args.genrl_max_pending,
                    paged_attn=args.genrl_paged_attn,
                    **base_cfg,
                ),
                iter_mode=args.genrl_iter_mode,
            )
            # a macro-step can finish more lanes than one learn batch
            # consumes; extras carry into the next round so insert batches
            # stay shape-stable (seq_add compiles once per batch size)
            self._completion_backlog = []
        else:
            self.engine = GenerationEngine(
                self.agent.model,
                self.agent.get_weights(),
                GenerationConfig(**base_cfg),
                iter_mode=args.genrl_iter_mode,
            )
        # replay geometry is pinned to the engine's LARGEST bucket pair so
        # one buffer covers every round (smaller rounds still land in the
        # max buckets: generate() buckets by the batch's true max length,
        # and the fixed task geometry keeps that constant per run)
        self._prompt_pad = bucket_for(
            self.engine.config.max_prompt_len,
            self.engine.config.resolved_prompt_buckets(),
        )
        self._response_pad = bucket_for(
            args.max_new_tokens,
            self.engine.config.resolved_response_buckets(),
        )
        self.replay = seq_init(
            sequence_field_shapes(self._prompt_pad, self._response_pad),
            (),  # no recurrent core: attention over the cache is the memory
            args.genrl_buffer_sequences,
        )
        self._seq_method = resolve_sample_method("auto")
        self._rng = np.random.default_rng(args.seed)
        self._sample_key = jax.random.PRNGKey(args.seed + 1)
        self.learn_steps = 0
        reg = telemetry.get_registry()
        self._learn_meter = reg.meter("genrl.learn_steps_per_s")
        self._reward_gauge = reg.gauge("genrl.mean_reward")
        self._stale_gauge = reg.gauge("genrl.staleness")
        self._kl_gauge = reg.gauge("genrl.kl_ref")
        self.reward_history: List[float] = []

    def _generate_round(self):
        prompts, lengths = self.task.sample_prompts(
            self.args.genrl_batch, self._rng
        )
        result = self.engine.generate(prompts, lengths)
        rewards = self.task.score(
            prompts, lengths, result.response_tokens, result.response_len
        )
        return result, rewards

    def _round_cohort(self):
        result, rewards = self._generate_round()
        if result.prompt_pad != self._prompt_pad or (
            result.response_pad != self._response_pad
        ):
            raise ValueError(
                "generation round landed outside the replay bucket pair "
                f"({result.prompt_pad}x{result.response_pad} vs "
                f"{self._prompt_pad}x{self._response_pad})"
            )
        fields, priorities = pack_sequences(result, rewards)
        return fields, priorities, rewards, result.decode_tokens

    def _round_continuous(self):
        """One continuous round: keep the lane pool fed, then pack exactly
        ``genrl_batch`` finished sequences (macro-steps that overshoot bank
        their extras in the backlog — insert batches stay shape-stable)."""
        B = self.args.genrl_batch
        while len(self._completion_backlog) < B:
            deficit = (
                B
                - len(self._completion_backlog)
                - self.engine.live_lanes
                - self.engine.pending
            )
            if deficit > 0:
                prompts, lengths = self.task.sample_prompts(
                    deficit, self._rng
                )
                for i in range(deficit):
                    self.engine.submit(prompts[i], lengths[i])
            self._completion_backlog.extend(self.engine.step())
        batch = self._completion_backlog[:B]
        self._completion_backlog = self._completion_backlog[B:]
        packed = pack_completions(
            batch, self._prompt_pad, self._response_pad
        )
        rewards = self.task.score(
            packed.prompts,
            packed.prompt_len,
            packed.response_tokens,
            packed.response_len,
        )
        fields, priorities = packed.fields(rewards)
        return fields, priorities, rewards, packed.decode_tokens

    def train_round(self) -> Dict[str, float]:
        """One generate -> score -> insert -> sample -> learn round."""
        fields, priorities, rewards, decode_tokens = (
            self._round_continuous()
            if self.continuous
            else self._round_cohort()
        )
        self.replay = seq_add(self.replay, fields, (), priorities)
        self._sample_key, sub = jax.random.split(self._sample_key)
        batch, _core, _idx, weights = seq_sample(
            self.replay,
            sub,
            self.args.genrl_sample_batch,
            method=self._seq_method,
        )
        batch = dict(batch)
        batch["is_weight"] = weights
        metrics = self.agent.learn(batch)  # ONE batched transfer
        self.learn_steps += 1
        self._learn_meter.mark()
        if self.learn_steps % self.args.genrl_push_every == 0:
            self.engine.push_params(self.agent.get_weights())
        # staleness in generations, off the metric that already crossed
        # the host boundary inside the batched read — no extra transfer
        staleness = max(
            float(self.engine.generation) - metrics["mean_generation"], 0.0
        )
        self._stale_gauge.set(staleness)
        mean_reward = float(np.mean(rewards))
        self._reward_gauge.set(mean_reward)
        if "kl_ref" in metrics:
            self._kl_gauge.set(metrics["kl_ref"])
        metrics["round_reward"] = mean_reward
        metrics["staleness"] = staleness
        metrics["decode_tokens"] = float(decode_tokens)
        self.reward_history.append(mean_reward)
        return metrics

    def train(self, rounds: Optional[int] = None) -> Dict[str, float]:
        rounds = rounds if rounds is not None else self.args.genrl_rounds
        metrics: Dict[str, float] = {}
        log_every = max(getattr(self.args, "logger_frequency", 50) or 50, 1)
        for i in range(rounds):
            metrics = self.train_round()
            if (i + 1) % log_every == 0 or i + 1 == rounds:
                logger.info(
                    "genrl round %d/%d reward=%.3f loss=%.4f staleness=%.1f",
                    i + 1,
                    rounds,
                    metrics.get("round_reward", 0.0),
                    metrics.get("total_loss", 0.0),
                    metrics.get("staleness", 0.0),
                )
        summary = dict(metrics)
        tail = self.reward_history[-10:]
        summary["final_reward_mean"] = float(np.mean(tail)) if tail else 0.0
        summary["rounds"] = float(len(self.reward_history))
        return summary
