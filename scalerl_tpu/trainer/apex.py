"""Ape-X: distributed prioritized experience replay (Horgan et al. 2018).

Parity target: the reference's Ape-X skeleton (``scalerl/algorithms/apex/
apex_train.py:11-93``, ``worker.py``, ``memory.py``) — N actor processes
writing TD-error-prioritized transitions into a shared PER, one learner
sampling with importance weights and feeding updated priorities back — which
is import-broken as shipped (SURVEY.md §2.4).  This is the working,
TPU-shaped version:

- **Actors** are threads each driving their own vector-env slab with
  per-actor epsilon ``eps_i = base^(1 + i/(N-1) * alpha)`` (the Ape-X
  exploration ladder; ``ApexArguments``).  Action selection is central
  batched inference on the device — not per-process CPU nets.
- Actors fold their rollout chunks into **n-step transitions locally**
  (the reference accumulates per-env deques in each actor,
  ``replay_buffer.py:230-273``) and compute **initial priorities** with a
  jitted |TD| function, then enqueue the slab.
- The **learner** thread is the single owner of the device PER state
  (one writer, no locks on HBM): it drains slabs into the prioritized
  buffer (``per_add_with_priorities``), samples with IS weights, runs the
  jitted double-DQN update, and scatters fresh priorities back — all
  device-side, no segment trees (SURVEY.md §7).
- Weights: in-process actors read the learner's latest params directly
  (zero-copy); a versioned ``ParameterServer`` snapshot is exported every
  ``actor_update_frequency`` learn steps for off-host actor fleets.
"""

from __future__ import annotations

import queue
import threading
import time
from contextlib import nullcontext
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from scalerl_tpu.agents.dqn import DQNAgent, make_dqn_learn_fn, make_dqn_priority_fn
from scalerl_tpu.config import ApexArguments
from scalerl_tpu.data.prioritized import PrioritizedReplayBuffer
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.runtime.dispatch import get_metrics
from scalerl_tpu.runtime.param_server import ParameterServer
from scalerl_tpu.runtime.supervisor import (
    CheckpointCadence,
    PreemptionGuard,
    StallWatchdog,
)
from scalerl_tpu.trainer.base import BaseTrainer
from scalerl_tpu.utils.metrics import EpisodeMetrics
from scalerl_tpu.utils.schedulers import LinearDecayScheduler
from scalerl_tpu.utils.timers import Timings


def fold_n_step(
    obs: np.ndarray,  # [T, W, ...]
    action: np.ndarray,  # [T, W]
    reward: np.ndarray,  # [T, W]
    next_obs: np.ndarray,  # [T, W, ...]
    term: np.ndarray,  # [T, W] bool: episode terminated (no bootstrap)
    trunc: np.ndarray,  # [T, W] bool: episode truncated (bootstrap, no reward leak)
    gamma: float,
    n: int,
) -> Dict[str, np.ndarray]:
    """Fold a rollout chunk into [(T-n+1)*W] n-step transitions (host side).

    Window semantics match ``data.replay.n_step_fold`` extended with
    truncation: rewards accumulate up to and including the first episode
    boundary (termination OR truncation — never across an autoreset into
    the next episode); ``next_obs`` bootstraps from that boundary step
    (for truncation this is the stashed final observation); ``done`` is
    True only for *termination* (a truncated window still bootstraps);
    ``n_steps`` is the realized window length for the ``gamma**n`` discount.
    """
    T, W = reward.shape[:2]
    m = T - n + 1
    if m <= 0:
        raise ValueError(f"rollout of {T} steps cannot fold n_step={n} windows")
    stop = term | trunc  # any episode boundary cuts the window
    stopf = stop.astype(np.float32)
    out_r = np.zeros((m, W), np.float32)
    alive = np.ones((m, W), np.float32)
    last = np.full((m, W), n - 1, np.int64)
    stop_found = np.zeros((m, W), bool)
    for k in range(n):
        out_r += (gamma**k) * alive * reward[k : k + m]
        s_k = stop[k : k + m]
        newly = s_k & ~stop_found
        last[newly] = k
        stop_found |= s_k
        alive *= 1.0 - stopf[k : k + m]
    rows = np.arange(m)[:, None] + last  # [m, W] absolute step index
    cols = np.broadcast_to(np.arange(W), (m, W))
    done = term[rows, cols]  # terminated at the window end (no bootstrap)
    return {
        "obs": obs[:m].reshape((m * W,) + obs.shape[2:]),
        "action": action[:m].reshape(m * W),
        "reward": out_r.reshape(m * W),
        "next_obs": next_obs[rows, cols].reshape((m * W,) + next_obs.shape[2:]),
        "done": done.reshape(m * W),
        "n_steps": (last + 1).astype(np.int32).reshape(m * W),
    }


class _ApexActorThread(threading.Thread):
    """One actor: own env slab, own eps, own RNG; enqueues prioritized slabs."""

    def __init__(self, actor_id: int, trainer: "ApexTrainer", envs) -> None:
        super().__init__(name=f"apex-actor-{actor_id}", daemon=True)
        self.actor_id = actor_id
        self.trainer = trainer
        self.envs = envs
        args = trainer.args
        n_actors = max(args.num_actors, 1)
        frac = actor_id / max(n_actors - 1, 1)
        self.eps = float(args.eps_greedy_base ** (1 + frac * args.eps_greedy_alpha))
        self.key = jax.random.PRNGKey(args.seed * 1000 + actor_id)
        self.timings = Timings()
        self.error: Optional[BaseException] = None

    def run(self) -> None:
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 - funneled to the learner
            self.error = e
            self.trainer._actor_error(self.actor_id, e)

    def _next_key(self) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        return sub

    def _run(self) -> None:
        tr = self.trainer
        args = tr.args
        agent = tr.agent
        T = args.rollout_length
        W = getattr(self.envs, "num_envs", 1)
        obs, _ = self.envs.reset(seed=args.seed + 7919 * self.actor_id)
        obs_dtype = np.asarray(obs).dtype

        while not tr._stop.is_set():
            obs_buf = np.zeros((T, W) + obs.shape[1:], obs_dtype)
            act_buf = np.zeros((T, W), np.int32)
            rew_buf = np.zeros((T, W), np.float32)
            next_buf = np.zeros((T, W) + obs.shape[1:], obs_dtype)
            term_buf = np.zeros((T, W), bool)
            trunc_buf = np.zeros((T, W), bool)
            self.timings.reset()
            for t in range(T):
                with tr._dispatch_guard():
                    actions = np.asarray(
                        agent._act(
                            agent.state.params,
                            jnp.asarray(obs, jnp.float32),
                            self.eps,
                            self._next_key(),
                        )
                    )
                next_obs, reward, term, trunc, infos = self.envs.step(actions)
                real_next = np.asarray(next_obs).copy()
                final_obs = infos.get("final_obs") if isinstance(infos, dict) else None
                if final_obs is not None:
                    for i in np.nonzero(infos.get("_final_obs"))[0]:
                        real_next[i] = final_obs[i]
                obs_buf[t] = obs
                act_buf[t] = actions
                rew_buf[t] = reward
                next_buf[t] = real_next
                term_buf[t] = term
                trunc_buf[t] = trunc
                tr.metrics.step(reward, np.logical_or(term, trunc), lane0=self.actor_id * W)
                obs = next_obs
            self.timings.time("rollout")
            slab = fold_n_step(
                obs_buf, act_buf, rew_buf, next_buf, term_buf, trunc_buf,
                args.gamma, args.n_steps,
            )
            self.timings.time("fold")
            # one H2D upload: the device slab feeds both the priority
            # computation and (via the queue) the learner's PER insert
            dev_slab = {
                "obs": jnp.asarray(slab["obs"], jnp.float32),
                "next_obs": jnp.asarray(slab["next_obs"], jnp.float32),
                "action": jnp.asarray(slab["action"]),
                "reward": jnp.asarray(slab["reward"]),
                "done": jnp.asarray(slab["done"]),
                "n_steps": jnp.asarray(slab["n_steps"]),
            }
            with tr._dispatch_guard():
                st = agent.state  # one snapshot: params/target_params stay paired
                prio = tr._priority(
                    st.params,
                    st.target_params,
                    dev_slab["obs"],
                    dev_slab["action"],
                    dev_slab["reward"],
                    dev_slab["next_obs"],
                    dev_slab["done"],
                    dev_slab["n_steps"],
                )
                if tr._mesh_lock is not None:
                    # drain before releasing the lock: a meshed priority
                    # program still in flight while the learner enqueues its
                    # own multi-device program re-opens the ordering hazard
                    prio.block_until_ready()
            self.timings.time("priority")
            # stop-aware put: if the learner exits while the queue is full,
            # a bare put() would deadlock this thread past teardown
            while not tr._stop.is_set():
                try:
                    tr._slab_queue.put((dev_slab, prio), timeout=1.0)
                    break
                except queue.Full:
                    continue
            self.timings.time("enqueue")
            with tr._step_lock:
                tr.global_step += T * W


class ApexTrainer(BaseTrainer):
    """N prioritized actors + one PER learner (``apex_train.py:64-93``)."""

    def __init__(
        self,
        args: ApexArguments,
        agent: DQNAgent,
        make_envs,  # callable (actor_id) -> vector env for that actor
        eval_envs=None,
        run_name: Optional[str] = None,
    ) -> None:
        super().__init__(args, run_name=run_name)
        args.validate()
        if getattr(args, "categorical_dqn", False):
            raise ValueError(
                "categorical_dqn (C51) is not supported by ApexTrainer: its "
                "priority/learn paths are scalar-Q "
                "(make_dqn_priority_fn/make_dqn_learn_fn); use DQNAgent with "
                "OffPolicyTrainer for C51"
            )
        self.agent = agent
        self.eval_envs = eval_envs
        self._actor_envs = [make_envs(i) for i in range(args.num_actors)]
        env0 = self._actor_envs[0]
        self.envs_per_actor = getattr(env0, "num_envs", 1)
        obs_space = env0.single_observation_space

        # folded slabs arrive with their realized window length stored; the
        # buffer row width is one slab, so capacity (in transitions) converts
        # to rows.  n_step=1: windows never span interleaved actor slabs.
        slab_width = (args.rollout_length - args.n_steps + 1) * self.envs_per_actor
        buffer_kw = dict(
            obs_shape=obs_space.shape,
            capacity=max(args.buffer_size // slab_width, 2),
            num_envs=slab_width,
            alpha=args.per_alpha,
            n_step=1,  # transitions are pre-folded by the actors
            gamma=args.gamma,
            extra_fields={"n_steps": ((), jnp.int32)},
        )
        mesh = getattr(agent, "mesh", None)
        if mesh is not None:
            # pod-scale Ape-X (the BASELINE "replay sharded across TPU HBM"
            # row): the PER planes shard over the learner's dp/fsdp axes and
            # the per-shard stratified sample lands already laid out for the
            # mesh learn step — agent._shard_batch's device_put is a no-op
            from scalerl_tpu.data.sharded_replay import ShardedPrioritizedReplay

            if getattr(agent, "_donate_state", False):
                # the mesh learn step donates the train state by default,
                # but actor threads read state.params concurrently (the
                # same hazard the no-donation re-jit of agent._learn below
                # guards) — rebuild the pjit'd learner without donation
                from scalerl_tpu.parallel import enable_offpolicy_mesh

                agent._donate_state = False
                enable_offpolicy_mesh(agent, mesh, donate_state=False)

            self.buffer = ShardedPrioritizedReplay(mesh=mesh, **buffer_kw)
        else:
            self.buffer = PrioritizedReplayBuffer(**buffer_kw)
        # Meshed state makes EVERY jitted call here (actor _act, priority,
        # learn, PER insert/sample) a multi-device program.  XLA runs each
        # device's queue in enqueue order, so two threads dispatching
        # multi-device programs concurrently can enqueue them in different
        # orders on different devices and deadlock the whole client — the
        # exact wedge the seed suite hit in
        # test_apex_sharded_replay_mesh_e2e (actors inside _act, learner
        # inside the pjit'd add_with_priorities, forever).  One lock around
        # every dispatch site serializes enqueue ordering; single-device
        # runs keep the lock-free fast path.
        self._mesh_lock: Optional[threading.Lock] = (
            threading.Lock() if mesh is not None else None
        )
        self._priority = jax.jit(
            make_dqn_priority_fn(agent.network, args.gamma, args.double_dqn)
        )
        # re-jit the agent's learn WITHOUT state donation: actor threads read
        # state.params concurrently, and donation would free those buffers
        # mid-read (DQNAgent defaults to donating for the single-threaded
        # off-policy trainer)
        from scalerl_tpu.parallel.train_step import maybe_guard_nonfinite

        agent._learn = jax.jit(
            # re-apply the all-finite guard: this re-jit replaces the
            # agent's (already guarded) learn, and Ape-X must keep the same
            # skip-non-finite-updates contract
            maybe_guard_nonfinite(
                make_dqn_learn_fn(
                    agent.network,
                    agent.optimizer,
                    gamma=args.gamma,
                    n_step=args.n_steps,
                    double_dqn=args.double_dqn,
                    use_soft_update=args.use_soft_update,
                    soft_update_tau=args.soft_update_tau,
                    target_update_frequency=args.target_update_frequency,
                ),
                args,
            )
        )
        self.per_beta = LinearDecayScheduler(
            args.per_beta, args.per_beta_final, args.max_timesteps
        )
        self.param_server = ParameterServer()
        self.param_server.push(agent.get_weights())

        self._slab_queue: "queue.Queue" = queue.Queue(maxsize=4 * args.num_actors)
        self._stop = threading.Event()
        self._step_lock = threading.Lock()
        self._errors: "queue.Queue" = queue.Queue()
        self.global_step = 0
        self.learn_steps = 0
        self.metrics = EpisodeMetrics(args.num_actors * self.envs_per_actor)
        self.timings = Timings()

    # ------------------------------------------------------------------
    def _dispatch_guard(self):
        """Serialize multi-device dispatch under a mesh (see __init__)."""
        return self._mesh_lock if self._mesh_lock is not None else nullcontext()

    def _actor_error(self, actor_id: int, err: BaseException) -> None:
        self._errors.put((actor_id, err))

    def _drain_slabs(self, block: bool) -> int:
        """Move pending actor slabs into the device PER (single writer)."""
        drained = 0
        while True:
            try:
                slab, prio = self._slab_queue.get(block=block and drained == 0, timeout=1.0)
            except queue.Empty:
                break
            with self._dispatch_guard():
                self.buffer.add_with_priorities(slab, prio)
            self.timings.time("insert")
            drained += 1
            block = False
        return drained

    def train_step(self) -> Dict[str, float]:
        beta = self.per_beta.value(self.global_step)
        self.timings.reset()
        with self._dispatch_guard():
            batch = self.buffer.sample(self.args.batch_size, beta=beta)
            self.timings.time("sample")
            info = self.agent.learn(batch)
            self.timings.time("learn")
            self.buffer.update_priorities(batch["indices"], info["td_abs"] + 1e-6)
            self.timings.time("update_prio")
        info.pop("td_abs", None)
        self.learn_steps += 1
        if self.learn_steps % self.args.actor_update_frequency == 0:
            self.param_server.push(self.agent.get_weights())
        return info

    # -- resume --------------------------------------------------------
    def _resume_pytree(self) -> Dict:
        return {
            "agent": self.agent.state,
            "replay": self.buffer.state,
            "global_step": np.asarray(self.global_step, np.int64),
            "learn_steps": np.asarray(self.learn_steps, np.int64),
        }

    def save_resume(self) -> None:
        self.save_resume_checkpoint(
            self._resume_pytree(), self.global_step, self.learn_steps
        )

    def try_resume(self) -> bool:
        """Restore learner state, the FULL prioritized replay (sharded or
        not — losing it would cost warmup plus every learned priority),
        and counters; re-lays arrays out on the mesh when one is active."""
        state = self.load_resume_checkpoint(self._resume_pytree())
        if state is None:
            return False
        agent_state = state["agent"]
        replay_state = state["replay"]
        mesh_learn = getattr(self.agent, "_learn_mesh", None)
        if mesh_learn is not None:
            agent_state = jax.device_put(agent_state, mesh_learn.state_sharding)
        if hasattr(self.buffer, "_state_sh"):
            replay_state = jax.device_put(replay_state, self.buffer._state_sh)
        self.agent.state = agent_state
        self.buffer.state = replay_state
        self.global_step = int(state["global_step"])
        self.learn_steps = int(state["learn_steps"])
        self.param_server.push(self.agent.get_weights())
        if self.is_main_process:
            self.text_logger.info(
                f"resumed from {self.resume_ckpt_path}: step {self.global_step}"
            )
        return True

    def run_evaluate_episodes(self, n_episodes: Optional[int] = None) -> Dict[str, float]:
        envs = self.eval_envs
        if envs is None:
            return {}
        n_episodes = n_episodes or self.args.eval_episodes
        num_envs = getattr(envs, "num_envs", 1)
        obs, _ = envs.reset(seed=self.args.seed + 100)
        returns: list = []
        ep_ret = np.zeros(num_envs)
        prev_done = np.ones(num_envs, bool)
        while len(returns) < n_episodes:
            with self._dispatch_guard():  # actors dispatch concurrently
                actions = self.agent.predict(obs, done=prev_done)
            obs, reward, term, trunc, _ = envs.step(np.asarray(actions))
            ep_ret += reward
            done = np.logical_or(term, trunc)
            prev_done = done
            for i in np.nonzero(done)[0]:
                returns.append(ep_ret[i])
                ep_ret[i] = 0.0
        rets = np.array(returns[:n_episodes])
        return {"reward_mean": float(rets.mean()), "reward_std": float(rets.std())}

    # ------------------------------------------------------------------
    def run(self) -> Dict[str, float]:
        args = self.args
        if self.resuming:
            self.try_resume()
        # preemption (SIGTERM/SIGINT) -> save_resume at the next loop
        # boundary; stall watchdog dumps all-thread stacks + queue depths
        # when neither env steps nor learn steps advance for the deadline
        guard = PreemptionGuard().install() if args.handle_preemption else None
        watchdog: Optional[StallWatchdog] = None
        if args.watchdog_timeout_s > 0:
            watchdog = StallWatchdog(args.watchdog_timeout_s, name="apex")
            watchdog.watch("global_step", lambda: self.global_step)
            watchdog.watch("learn_steps", lambda: self.learn_steps)
            watchdog.add_probe("slab_queue_depth", self._slab_queue.qsize)
            watchdog.add_probe("replay_size", lambda: len(self.buffer))
            watchdog.add_probe(
                "actor_errors_pending", lambda: self._errors.qsize()
            )
            watchdog.start()
        actors = [
            _ApexActorThread(i, self, env) for i, env in enumerate(self._actor_envs)
        ]
        for a in actors:
            a.start()

        start = time.time()
        # seed the interval gates from the (possibly resumed) step, or the
        # first iteration immediately fires a log line and a full blocking
        # eval sweep at the restored step
        last_log = self.global_step
        last_eval = self.global_step
        cadence = CheckpointCadence(
            args.save_frequency, args.checkpoint_interval_s, self.global_step
        )
        train_info: Dict[str, float] = {}
        try:
            while self.global_step < args.max_timesteps:
                if watchdog is not None:
                    watchdog.check()
                if guard is not None and guard.triggered:
                    if args.save_model and not args.disable_checkpoint:
                        self.save_resume()
                    break
                if not self._errors.empty():
                    actor_id, err = self._errors.get()
                    raise RuntimeError(f"apex actor {actor_id} crashed") from err
                self._drain_slabs(block=True)
                if len(self.buffer) >= args.warmup_learn_steps:
                    train_info = self.train_step()

                if self.global_step - last_log >= args.logger_frequency:
                    last_log = self.global_step
                    fps = int(self.global_step / max(time.time() - start, 1e-8))
                    summary = self.metrics.summary()
                    # registry-backed write: one batched transfer for any
                    # device scalars, then instruments are the source
                    train_info = get_metrics(train_info)
                    if self._instrument:
                        telemetry.observe_train_metrics(train_info)
                        reg = telemetry.get_registry()
                        reg.set_gauges(train_info, prefix="train.")
                        reg.set_gauges(summary, prefix="train.")
                        reg.set_gauges(
                            {
                                "rpm_size": float(len(self.buffer)),
                                "fps": float(fps),
                                "learn_steps": float(self.learn_steps),
                                "weight_version": float(self.param_server.version),
                            },
                            prefix="train.",
                        )
                        self.logger.log_registry(
                            self.global_step,
                            step_type="train",
                            include_prefixes=("train.",),
                        )
                    if self.is_main_process:
                        ret = summary.get("return_mean", float("nan"))
                        self.text_logger.info(
                            f"step {self.global_step} | fps {fps} | return {ret:.1f} "
                            f"| loss {train_info.get('loss', float('nan')):.4f} "
                            f"| learn {self.learn_steps}"
                        )

                if self.eval_envs is not None and self.global_step - last_eval >= args.eval_frequency:
                    last_eval = self.global_step
                    eval_info = self.run_evaluate_episodes()
                    self.logger.log_test_data(eval_info, self.global_step)

                if (
                    args.save_model
                    and not args.disable_checkpoint
                    and cadence.due(self.global_step)
                ):
                    cadence.mark_saved(self.global_step)
                    self.save_resume()
        finally:
            self._stop.set()
            if watchdog is not None:
                watchdog.stop()
            if guard is not None:
                guard.restore()
            for a in actors:
                a.join(timeout=10.0)
            if args.save_model and not args.disable_checkpoint and self.is_main_process:
                self.agent.save_checkpoint(f"{self.model_save_dir}/ckpt_final")
        return self.metrics.summary()

    def close(self) -> None:
        self._stop.set()
        for envs in self._actor_envs:
            try:
                envs.close()
            except Exception:
                pass
        super().close()
