"""R2D2 trainer: host actor plane -> sequence replay -> recurrent learner.

Topology (beyond-parity; completes the Ape-X lineage recurrently):

- actor THREADS drive vector envs and fill ``[T+1, B]`` trajectory slots
  through the exact machinery the IMPALA host plane uses
  (``fill_rollout_slot`` already stores each chunk's entering LSTM state)
  — each actor acts through its own eps-greedy view on the agent's live
  params (central inference, Ape-X eps ladder);
- the learner drains slots, inserts every env lane as one SEQUENCE into
  the device-resident prioritized sequence replay
  (``data/sequence_replay.py``) at the running max priority, then runs
  ``train_intensity`` jitted R2D2 updates per drained batch: sample,
  burn-in + n-step double-Q under value rescaling, priority write-back.

Failure handling, resume, and metrics mirror ``HostActorLearnerTrainer``
(same queue error funnel, same Orbax resume pytree shape).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from scalerl_tpu.agents.r2d2 import R2D2Agent
from scalerl_tpu.config import R2D2Arguments
from scalerl_tpu.data.sequence_replay import (
    seq_add,
    seq_init,
    seq_sample,
    seq_update_priorities,
)
from scalerl_tpu.data.trajectory import TrajectorySpec
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.runtime.dispatch import get_metrics
from scalerl_tpu.runtime.param_server import ParameterServer
from scalerl_tpu.runtime.rollout_queue import RolloutQueue
from scalerl_tpu.trainer.actor_learner import (
    HostPlaneMixin,
    _ActorThread,
    check_queue_depth,
)
from scalerl_tpu.trainer.base import BaseTrainer
from scalerl_tpu.utils.metrics import EpisodeMetrics


class R2D2Trainer(HostPlaneMixin, BaseTrainer):
    def __init__(
        self,
        args: R2D2Arguments,
        agent: R2D2Agent,
        env_fns,  # list of callables, one vector env per actor
        run_name: Optional[str] = None,
        max_actor_restarts: int = 0,
    ) -> None:
        super().__init__(args, run_name=run_name)
        self.agent = agent
        self.env_fns = env_fns
        self.stop_event = threading.Event()
        self.frame_lock = threading.Lock()
        self.env_frames = 0
        self.max_actor_restarts = max_actor_restarts
        self.actor_restarts = 0
        self._restart_lock = threading.Lock()
        self.param_server = ParameterServer()

        probe_env = env_fns[0]()
        self.envs_per_actor = probe_env.num_envs
        obs_shape = probe_env.single_observation_space.shape
        num_actions = probe_env.single_action_space.n
        self._probe_env = probe_env

        core = agent.initial_state(self.envs_per_actor)
        self.spec = TrajectorySpec(
            unroll_length=args.rollout_length,
            batch_size=self.envs_per_actor,
            obs_shape=obs_shape,
            num_actions=num_actions,
            obs_dtype=jnp.uint8 if len(obs_shape) == 3 else jnp.float32,
            core_state_shapes=tuple(tuple(c.shape) for c, _ in core),
        )
        check_queue_depth(args, self.envs_per_actor)
        self.queue = RolloutQueue(self.spec, num_slots=args.num_buffers)
        self.episode_metrics = [
            EpisodeMetrics(self.envs_per_actor) for _ in range(len(env_fns))
        ]

        T1 = args.rollout_length + 1
        np_obs_dtype = np.uint8 if len(obs_shape) == 3 else np.float32
        field_shapes = {
            "obs": ((T1,) + tuple(obs_shape), np_obs_dtype),
            "action": ((T1,), np.int32),
            "reward": ((T1,), np.float32),
            "done": ((T1,), bool),
        }
        core_shapes = tuple(tuple(c.shape[1:]) for c, _ in core)
        if getattr(agent, "mesh", None) is not None:
            # pod-scale sequence memory (BASELINE "replay sharded across TPU
            # HBM"): the ring's capacity axis shards over the DDP agent's
            # mesh, per-shard stratified sampling lands already laid out for
            # the sharded learn step
            from scalerl_tpu.data.sharded_replay import ShardedSequenceReplay

            self._sharded_replay = ShardedSequenceReplay(
                field_shapes, core_shapes, args.replay_capacity, agent.mesh,
                alpha=args.per_alpha, beta=args.per_beta,
            )
            self.replay = None
        else:
            self._sharded_replay = None
            self.replay = seq_init(field_shapes, core_shapes, args.replay_capacity)
        # running max priority lives ON DEVICE: a host-side
        # float(jnp.max(...)) mirror would block the learner on every learn
        # step (graftlint JG001); it is materialized with one explicit
        # device_get only at checkpoint time
        self._max_prio_dev = jnp.asarray(1.0, jnp.float32)
        self._rng = jax.random.PRNGKey(args.seed + 13)
        # serializes multi-device dispatch when the agent is meshed — see
        # HostPlaneMixin._dispatch_guard (the apex mesh e2e deadlock class)
        self._mesh_lock = threading.Lock()
        # PER search method pinned at construction (not at first trace),
        # so SCALERL_PER_METHOD / backend changes can't be silently ignored
        from scalerl_tpu.ops.pallas_per import resolve_sample_method

        self._seq_method = resolve_sample_method("auto")

    @property
    def _max_priority(self) -> float:
        """Host view of the device-resident running max priority — ONE
        explicit transfer; diagnostic/checkpoint accessor, never the hot
        path (the learn loop reduces on device via ``_max_prio_dev``)."""
        return float(jax.device_get(self._max_prio_dev))

    # grant_actor_restart comes from HostPlaneMixin (shared with the IMPALA
    # thread plane); resume extends the mixin's (agent, env_frames) pytree
    # with the REPLAY state — losing a pod-scale sequence memory on restart
    # costs warmup_sequences of fresh collection plus every learned
    # priority, so the buffer (sharded or not: both are pytrees Orbax
    # handles, sharded arrays included) rides the same async checkpoint.

    def _resume_pytree(self) -> Dict:
        tree = super()._resume_pytree()
        tree["replay"] = (
            self._sharded_replay.state
            if self._sharded_replay is not None
            else self.replay
        )
        # one explicit transfer at checkpoint time (cold path)
        tree["max_priority"] = np.asarray(
            jax.device_get(self._max_prio_dev), np.float64
        )
        return tree

    def try_resume(self) -> bool:
        state = self.load_resume_checkpoint(self._resume_pytree())
        if state is None:
            return False
        self.agent.state = state["agent"]
        self.env_frames = int(state["env_frames"])
        if self._sharded_replay is not None:
            # restore into the mesh layout the buffer was constructed with
            self._sharded_replay.state = jax.device_put(
                state["replay"], self._sharded_replay._state_sh
            )
        else:
            self.replay = state["replay"]
        self._max_prio_dev = jnp.asarray(
            float(state["max_priority"]), jnp.float32
        )
        self.param_server.push(self.agent.get_weights())
        if self.is_main_process:
            self.text_logger.info(
                f"resumed from {self.resume_ckpt_path}: frames {self.env_frames}"
            )
        return True

    # ------------------------------------------------------------------
    def _insert_slots(self, n_slots: int) -> None:
        """Drain slots and insert each env lane as one sequence."""
        batch, idxs = self.queue.get_batch(n_slots)
        # time-major [T1, B*] host arrays -> sequence-major [B*, T1, ...]
        fields = {
            "obs": np.moveaxis(batch["obs"], 0, 1),
            "action": np.moveaxis(batch["action"], 0, 1),
            "reward": np.moveaxis(batch["reward"], 0, 1),
            "done": np.moveaxis(batch["done"], 0, 1),
        }
        core = tuple(
            (batch[f"core_{i}_c"], batch[f"core_{i}_h"])
            for i in range(len(self.spec.core_state_shapes))
        )
        self.queue.recycle(idxs)
        B = fields["action"].shape[0]
        # broadcast of the device-side running max: no host read here
        prio = jnp.full((B,), self._max_prio_dev, jnp.float32)
        with self._dispatch_guard():  # actors dispatch _act concurrently
            if self._sharded_replay is not None:
                self._sharded_replay.add(fields, core, prio)
            else:
                self.replay = seq_add(self.replay, fields, core, prio)

    def _learn_once(self) -> Dict[str, jnp.ndarray]:
        self._rng, sub = jax.random.split(self._rng)
        with self._dispatch_guard():  # actors dispatch _act concurrently
            if self._sharded_replay is not None:
                fields, core, idx, weights = self._sharded_replay.sample(
                    self.args.batch_size, key=sub
                )
                metrics, prio = self.agent.learn_sequences(fields, core, weights)
                self._sharded_replay.update_priorities(idx, prio)
            else:
                fields, core, idx, weights = seq_sample(
                    self.replay, sub, self.args.batch_size,
                    alpha=self.args.per_alpha, beta=self.args.per_beta,
                    method=self._seq_method,
                )
                metrics, prio = self.agent.learn_sequences(fields, core, weights)
                self.replay = seq_update_priorities(self.replay, idx, prio)
            # async device-side reduction — no per-learn-step host sync
            self._max_prio_dev = jnp.maximum(self._max_prio_dev, jnp.max(prio))
        return metrics

    # ------------------------------------------------------------------
    def train(self, total_frames: Optional[int] = None) -> Dict[str, float]:
        args = self.args
        total_frames = total_frames or args.max_timesteps
        if self.resuming:
            self.try_resume()
        actors = []
        for i, fn in enumerate(self.env_fns):
            envs = self._probe_env if i == 0 else fn()
            actors.append(
                _ActorThread(i, self, envs, policy=self.agent.actor_view(i))
            )
        self.actors = actors
        for a in actors:
            a.start()

        start = time.time()
        start_frames = self.env_frames
        last_log_frames = start_frames
        last_save_frames = start_frames
        n_slots = max(args.batch_size // self.envs_per_actor, 1)
        seqs_per_drain = n_slots * self.envs_per_actor
        metrics: Dict = {}
        inserted = 0
        try:
            while self.env_frames < total_frames and not self.stop_event.is_set():
                self._insert_slots(n_slots)
                inserted += seqs_per_drain
                if inserted >= args.warmup_sequences:
                    for _ in range(args.train_intensity):
                        metrics = self._learn_once()
                    # version bump for off-host pullers; thread actors read
                    # the live params directly (central inference).  The
                    # device-side snapshot copy is itself a (multi-device
                    # when meshed) program — keep it behind the guard too
                    with self._dispatch_guard():
                        self.param_server.push(
                            self.agent.get_weights(), to_host=False
                        )
                if (
                    args.save_model
                    and not args.disable_checkpoint
                    and self.env_frames - last_save_frames >= args.save_frequency
                ):
                    # periodic, not just exit-time: a crash-restart must find
                    # a fresh replay+learner snapshot (the durability claim)
                    last_save_frames = self.env_frames
                    self.save_resume()
                if self.env_frames - last_log_frames >= args.logger_frequency:
                    last_log_frames = self.env_frames
                    sps = (self.env_frames - start_frames) / max(
                        time.time() - start, 1e-8
                    )
                    rets = [
                        r
                        for m in self.episode_metrics
                        for r in m.episode_returns[-20:]
                    ]
                    ret_mean = float(np.mean(rets)) if rets else float("nan")
                    # one batched device->host transfer for the whole dict
                    host_metrics = get_metrics(metrics)
                    if self._instrument:
                        telemetry.observe_train_metrics(host_metrics)
                        reg = telemetry.get_registry()
                        reg.set_gauges(
                            {**host_metrics, "sps": sps, "return_mean": ret_mean},
                            prefix="train.",
                        )
                        self.logger.log_registry(
                            self.env_frames,
                            step_type="train",
                            include_prefixes=("train.", "queue."),
                        )
                    if self.is_main_process:
                        self.text_logger.info(
                            f"frames {self.env_frames} | sps {sps:.0f} | "
                            f"return {ret_mean:.1f} | "
                            f"loss {host_metrics.get('total_loss', float('nan')):.3f}"
                        )
        finally:
            self.stop_event.set()
            self.queue.close()
            for a in actors:
                a.join(timeout=5.0)
            for a in actors:
                try:
                    a.envs.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        if args.save_model and not args.disable_checkpoint:
            self.save_resume()
        sps = (self.env_frames - start_frames) / max(time.time() - start, 1e-8)
        rets = [r for m in self.episode_metrics for r in m.episode_returns]
        return {
            **get_metrics(metrics),
            "env_frames": float(self.env_frames),
            "sps": float(sps),
            "learn_steps": int(self.agent.state.step),
            "return_mean": float(np.mean(rets[-100:])) if rets else float("nan"),
            "episodes": float(len(rets)),
        }
