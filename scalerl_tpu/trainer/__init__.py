from scalerl_tpu.trainer.base import BaseTrainer  # noqa: F401
from scalerl_tpu.trainer.off_policy import OffPolicyTrainer  # noqa: F401
from scalerl_tpu.trainer.on_policy import OnPolicyTrainer  # noqa: F401
from scalerl_tpu.trainer.apex import ApexTrainer  # noqa: F401
from scalerl_tpu.trainer.parallel_dqn import ParallelDQNTrainer  # noqa: F401
from scalerl_tpu.trainer.process_actor_learner import (  # noqa: F401
    ProcessActorLearnerTrainer,
)
from scalerl_tpu.trainer.r2d2 import R2D2Trainer  # noqa: F401
from scalerl_tpu.trainer.r2d2_device import DeviceR2D2Trainer  # noqa: F401
from scalerl_tpu.trainer.sequence_rl import SequenceRLTrainer  # noqa: F401
