"""Parallel DQN: actor *processes* + central TPU learner over the shm ring.

Parity target: ``ParallelDQNv2`` (``scalerl/algorithms/dqn/parallel_dqn.py:
106-443``) — N actor processes running eps-greedy episodes and pushing
transitions through an ``mp.Queue(maxsize=500)`` to a learner process that
drains into replay and trains.  TPU-shaped differences:

- Transport is the lock-free C++ shared-memory slot ring
  (``runtime/shm_ring.py``; Python-queue fallback) instead of a pickling
  ``mp.Queue``: actors write fixed ``[T, ...]`` rollout slabs into shared
  memory via zero-copy numpy views; the learner drains with one native
  memcpy gather per batch and one device transfer per slab.
- Actors do CPU inference with *numpy* forwards on versioned weight
  snapshots (``models/np_forward.py``) — no JAX runtime in the children —
  pulled over a pipe weight service (the ``ParameterServer`` capability,
  per-actor eps from the Ape-X exploration ladder).
- The learner owns device replay (uniform or PER) and the jitted
  double-DQN update; weight publication is versioned so idle actors skip
  no-op pulls.

Episode stats ride the weight-service pipes (tiny), never the data ring.
"""

from __future__ import annotations

import multiprocessing as mp
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from scalerl_tpu.config import DQNArguments
from scalerl_tpu.fleet.transport import (
    PipeConnection,
    send_recv,
    wait_readable,
)
from scalerl_tpu.models.np_forward import mlp_qnet_forward
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.runtime.param_server import ParameterServer
from scalerl_tpu.runtime.shm_ring import ShmRolloutRing, SlotSpec
from scalerl_tpu.trainer.base import BaseTrainer
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class _ActorConfig:
    actor_id: int
    env_id: str
    obs_shape: tuple
    rollout_length: int
    eps: float
    seed: int
    dueling: bool
    max_episode_steps: int = 500


def _actor_main(conn: PipeConnection, cfg: _ActorConfig, ring: ShmRolloutRing) -> None:
    """Actor process: env + numpy inference + slab writes.

    Pipe protocol: {"kind": "params", "have": v} -> {"version", "weights"}
    or None; {"kind": "stats", ...} fire-and-forget; recv None = stop.
    """
    import gymnasium as gym

    try:
        env = gym.make(cfg.env_id)
        rng = np.random.default_rng(cfg.seed)
        obs, _ = env.reset(seed=cfg.seed)
        weights: Any = None
        version = -1
        T = cfg.rollout_length
        ep_ret, ep_len = 0.0, 0
        while not ring.closed:
            try:
                reply = send_recv(conn, {"kind": "params", "have": version})
            except (EOFError, OSError, ConnectionError):
                break
            if reply is not None:
                version = int(reply["version"])
                weights = reply["weights"]
            idx = ring.acquire(timeout=1.0)
            if idx is None:
                continue
            slot = ring.slot(idx)
            returns: List[float] = []
            for t in range(T):
                if weights is None or rng.random() < cfg.eps:
                    a = int(rng.integers(env.action_space.n))
                else:
                    q = mlp_qnet_forward(weights, obs[None], cfg.dueling)
                    a = int(np.argmax(q[0]))
                nxt, r, term, trunc, _ = env.step(a)
                ep_ret += float(r)
                ep_len += 1
                ep_end = bool(term or trunc or ep_len >= cfg.max_episode_steps)
                slot["obs"][t] = obs
                slot["action"][t] = a
                slot["reward"][t] = r
                slot["next_obs"][t] = nxt
                slot["done"][t] = term
                # episode boundary incl. truncation/step-cap: bounds the
                # n-step fold so windows never cross this actor's resets
                slot["boundary"][t] = ep_end
                if ep_end:
                    returns.append(ep_ret)
                    ep_ret, ep_len = 0.0, 0
                    obs, _ = env.reset()
                else:
                    obs = nxt
            slot["meta"][0] = cfg.actor_id
            slot["meta"][1] = version
            ring.commit(idx)
            if returns:
                conn.send({"kind": "stats", "actor_id": cfg.actor_id,
                           "returns": returns})
        env.close()
    except (KeyboardInterrupt, EOFError, OSError, ConnectionError):
        pass
    finally:
        ring.detach()
        try:
            conn.close()
        except Exception:
            pass


class ParallelDQNTrainer(BaseTrainer):
    """N actor processes -> shm ring -> device replay + jitted learner."""

    def __init__(
        self,
        args: DQNArguments,
        agent,  # DQNAgent
        env_id: str,
        obs_shape: tuple,
        num_actors: int = 4,
        num_slots: int = 16,
        eps_base: float = 0.4,
        eps_alpha: float = 7.0,
        use_per: Optional[bool] = None,
        run_name: Optional[str] = None,
    ) -> None:
        super().__init__(args, run_name=run_name)
        if getattr(args, "categorical_dqn", False):
            raise ValueError(
                "categorical_dqn (C51) is not supported by ParallelDQNTrainer: "
                "actor processes run scalar-Q numpy inference "
                "(models/np_forward.py); use DQNAgent with OffPolicyTrainer"
            )
        self.agent = agent
        self.num_actors = num_actors
        self.env_id = env_id
        T = args.rollout_length
        spec = SlotSpec({
            "obs": ((T,) + tuple(obs_shape), np.float32),
            "action": ((T,), np.int32),
            "reward": ((T,), np.float32),
            "next_obs": ((T,) + tuple(obs_shape), np.float32),
            "done": ((T,), np.bool_),
            "boundary": ((T,), np.bool_),  # term | trunc | step-cap
            "meta": ((2,), np.int64),  # actor_id, weight version
        })
        self.ring = ShmRolloutRing(spec, num_slots=num_slots)
        self.param_server = ParameterServer()
        self.param_server.push(agent.get_weights())

        use_per = args.use_per if use_per is None else use_per
        if use_per:
            from scalerl_tpu.data.prioritized import PrioritizedReplayBuffer

            self.replay: Any = PrioritizedReplayBuffer(
                obs_shape=obs_shape,
                capacity=args.buffer_size,
                num_envs=1,
                alpha=args.per_alpha,
                n_step=args.n_steps,
                gamma=args.gamma,
            )
        else:
            from scalerl_tpu.data.replay import ReplayBuffer

            self.replay = ReplayBuffer(
                obs_shape=obs_shape,
                capacity=args.buffer_size,
                num_envs=1,
                n_step=args.n_steps,
                gamma=args.gamma,
            )
        self.use_per = use_per
        self._stop = threading.Event()
        self.returns: List[float] = []
        self.env_steps = 0
        self.learn_steps = 0
        self.procs: List[mp.Process] = []
        self.conns: List[PipeConnection] = []
        self._eps = [
            float(eps_base ** (1 + (i / max(num_actors - 1, 1)) * eps_alpha))
            for i in range(num_actors)
        ]
        self._weight_thread = threading.Thread(
            target=self._weight_service, daemon=True
        )

    # -- weight + stats service over pipes -----------------------------
    def _weight_service(self) -> None:
        while not self._stop.is_set():
            if not self.conns:
                self._stop.wait(0.05)
                continue
            ready, dead = wait_readable(self.conns, timeout=0.1)
            for conn in dead:
                self.conns.remove(conn)
            for conn in ready:
                try:
                    msg = conn.recv()
                except (EOFError, OSError, ConnectionError, ValueError):
                    if conn in self.conns:
                        self.conns.remove(conn)
                    continue
                if msg is None:
                    continue
                if msg["kind"] == "params":
                    weights, version = self.param_server.pull(int(msg["have"]))
                    try:
                        if weights is None:
                            conn.send(None)
                        else:
                            conn.send(
                                {"version": version, "weights": weights}
                            )
                    except (BrokenPipeError, OSError):
                        continue
                elif msg["kind"] == "stats":
                    self.returns.extend(float(r) for r in msg["returns"])

    def start_actors(self) -> None:
        # spawn, not fork: the learner process has JAX initialized, and
        # forking after that can deadlock in XLA's thread pools (the same
        # hazard envs/vector/async_vec.py documents).  Everything crossing
        # the boundary (_ActorConfig, PipeConnection, ShmRolloutRing) is
        # picklable by design.
        ctx = mp.get_context("spawn")
        for i in range(self.num_actors):
            parent, child = ctx.Pipe(duplex=True)
            cfg = _ActorConfig(
                actor_id=i,
                env_id=self.env_id,
                obs_shape=tuple(self.agent.obs_shape),
                rollout_length=self.args.rollout_length,
                eps=self._eps[i],
                seed=self.args.seed + 7919 * i,
                dueling=self.args.dueling_dqn,
            )
            proc = ctx.Process(
                target=_actor_main,
                args=(PipeConnection(child), cfg, self.ring),
                daemon=True,
            )
            proc.start()
            child.close()
            self.procs.append(proc)
            self.conns.append(PipeConnection(parent))
        self._weight_thread.start()

    # -- learner -------------------------------------------------------
    def _drain(self, max_slabs: int = 8) -> int:
        drained = 0
        while drained < max_slabs:
            # verified pop: torn slots are detected/released, never trained on
            idx = self.ring.pop_full_verified(timeout=0.05 if drained else 0.5)
            if idx is None:
                break
            slab = self.ring.gather_batch([idx])
            self.ring.release(idx)
            if self.use_per:
                self._per_insert(slab)
            else:
                self.replay.save_chunk(
                    obs=slab["obs"][0, :, None],
                    action=slab["action"][0, :, None],
                    reward=slab["reward"][0, :, None],
                    next_obs=slab["next_obs"][0, :, None],
                    done=slab["done"][0, :, None],
                    boundary=slab["boundary"][0, :, None],
                )
            self.env_steps += self.args.rollout_length
            drained += 1
        return drained

    def _per_insert(self, slab: Dict[str, np.ndarray]) -> None:
        T = self.args.rollout_length
        for t in range(T):  # PER insert assigns max-priority rows
            self.replay.save_to_memory(
                obs=slab["obs"][0, t][None],
                next_obs=slab["next_obs"][0, t][None],
                action=slab["action"][0, t][None],
                reward=slab["reward"][0, t][None],
                done=slab["done"][0, t][None],
                boundary=slab["boundary"][0, t][None],
            )

    def train(self, total_steps: Optional[int] = None) -> Dict[str, float]:
        args = self.args
        total_steps = total_steps or args.max_timesteps
        self.start_actors()
        info: Dict[str, float] = {}
        start = time.time()
        last_log = 0
        try:
            while self.env_steps < total_steps:
                self._drain()
                if len(self.replay) >= args.warmup_learn_steps:
                    if self.use_per:
                        batch = self.replay.sample(args.batch_size, beta=args.per_beta)
                        info = self.agent.learn(batch)
                        self.replay.update_priorities(
                            batch["indices"], info.pop("td_abs", 1.0) + 1e-6
                        )
                    else:
                        info = self.agent.learn(self.replay.sample(args.batch_size))
                        info.pop("td_abs", None)
                    self.learn_steps += 1
                    if self.learn_steps % 10 == 0:
                        self.param_server.push(self.agent.get_weights())
                if self.env_steps - last_log >= args.logger_frequency:
                    last_log = self.env_steps
                    sps = self.env_steps / max(time.time() - start, 1e-8)
                    ret = float(np.mean(self.returns[-20:])) if self.returns else float("nan")
                    # registry-backed write: ring occupancy and torn_reads
                    # (bound by ShmRolloutRing) ride alongside.  get_metrics
                    # imports lazily: this module must stay jax-free for the
                    # spawned np_forward actor children
                    from scalerl_tpu.runtime.dispatch import get_metrics

                    host_info = get_metrics(info)
                    if self._instrument:
                        telemetry.observe_train_metrics(host_info)
                        reg = telemetry.get_registry()
                        reg.set_gauges(
                            {**host_info, "sps": sps, "return_mean": ret},
                            prefix="train.",
                        )
                        self.logger.log_registry(
                            self.env_steps,
                            step_type="train",
                            include_prefixes=("train.", "ring."),
                        )
                    if self.is_main_process:
                        self.text_logger.info(
                            f"steps {self.env_steps} | sps {sps:.0f} | "
                            f"return {ret:.1f} | learn {self.learn_steps} | "
                            f"weights v{self.param_server.version}"
                        )
        finally:
            self.stop()
        ret = float(np.mean(self.returns[-20:])) if self.returns else float("nan")
        return {
            **info,
            "env_steps": float(self.env_steps),
            "learn_steps": float(self.learn_steps),
            "episodes": float(len(self.returns)),
            "return_mean": ret,
        }

    def stop(self) -> None:
        self._stop.set()
        self.ring.close()
        for p in self.procs:
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
        for c in self.conns:
            try:
                c.close()
            except Exception:
                pass
        self.ring.unlink()
