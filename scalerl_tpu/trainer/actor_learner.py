"""Actor-learner trainers: host actor plane (SEED-style) and fused device loop.

Parity target: ``ImpalaTrainer`` (``scalerl/algorithms/impala/impala_atari.py:
40-521``), re-architected per SURVEY.md §7:

- **HostActorLearnerTrainer** — CPU actors run *envs only*; every neural-net
  forward (acting inference) is a central jitted batched call on the device
  (SEED-RL topology), unlike the reference where each actor process runs its
  own CPU model copy (``impala_atari.py:196-198``).  Actor threads each
  drive a vector-env slab, fill pinned trajectory slots from a free/full
  ``RolloutQueue``, and the learner thread drains, ships, and updates.
  Weight "publication" is implicit: central inference always reads the
  learner's latest params (behavior lag <= one chunk), and a
  ``ParameterServer`` snapshot is exported for off-host actors.
- **DeviceActorLearnerTrainer** — the fully-fused path for device-native
  envs (``runtime/device_loop.py``); orders of magnitude faster when env
  dynamics compile.

Failure handling parity (SURVEY.md §5): actor exceptions funnel through
``RolloutQueue.report_error`` and re-raise in the learner; teardown joins
with timeouts (reference ladders: ``impala_atari.py:473-494``).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from contextlib import nullcontext
from typing import Dict, Optional

import jax
import numpy as np

from scalerl_tpu.agents.impala import ImpalaAgent
from scalerl_tpu.config import ImpalaArguments
from scalerl_tpu.data.trajectory import TrajectorySpec, batch_to_trajectory
from scalerl_tpu.runtime import telemetry
from scalerl_tpu.runtime.dispatch import get_metrics
from scalerl_tpu.runtime.param_server import ParameterServer
from scalerl_tpu.runtime.rollout_queue import RolloutQueue
from scalerl_tpu.runtime.supervisor import (
    CheckpointCadence,
    PreemptionGuard,
    StallWatchdog,
)
from scalerl_tpu.trainer.base import BaseTrainer
from scalerl_tpu.utils.metrics import EpisodeMetrics
from scalerl_tpu.utils.profiling import maybe_trace
from scalerl_tpu.utils.timers import Timings


def fill_rollout_slot(
    slot,
    agent,
    envs,
    obs,
    last_action,
    reward,
    done,
    core_state,
    unroll_length: int,
    on_step=None,
    timings: Optional[Timings] = None,
    dispatch_guard=None,
):
    """Write one ``[T+1, B]`` trajectory slot — the protocol shared by the
    thread (SEED) and process (monobeast) actor planes.

    Row convention matches ``data/trajectory.py``: each row holds the model
    *inputs* at that step; row T is model-input-only — the learner reads
    ``logits[:-1]`` and the boundary obs is consumed by the next chunk's
    row 0, so running inference there would advance the LSTM core over
    ``obs_T`` twice (slots are recycled, so its stale logits row is cleared).

    Returns the carried ``(obs, last_action, reward, done, core_state)``.
    ``on_step(reward, done)`` fires after every env step (episode metrics);
    ``timings`` (optional) records the ``model``/``step`` phase split.
    ``dispatch_guard`` (optional, a context-manager factory): entered around
    each central-inference call — the thread planes pass the trainer's mesh
    dispatch guard so actor-thread dispatch cannot interleave multi-device
    program enqueues with the learner's (graftlint JG002).
    """
    _dispatch_guard = dispatch_guard if dispatch_guard is not None else nullcontext
    for i, (c, h) in enumerate(core_state):
        slot[f"core_{i}_c"][:] = np.asarray(c)
        slot[f"core_{i}_h"][:] = np.asarray(h)
    for t in range(unroll_length + 1):
        slot["obs"][t] = obs
        slot["action"][t] = last_action
        slot["reward"][t] = reward
        slot["done"][t] = done
        if timings is not None:
            # separate mark: the obs row memcpy is the dominant write cost
            # at pixel shapes and must not be attributed to "model"
            timings.time("write_row")
        if t == unroll_length:
            slot["logits"][t] = 0.0
            break
        with _dispatch_guard():
            action, logits, core_state = agent.act(
                obs, last_action, reward, done, core_state
            )
        slot["logits"][t] = np.asarray(logits)
        if timings is not None:
            timings.time("model")
        obs, reward, term, trunc, _ = envs.step(np.asarray(action))
        done = np.logical_or(term, trunc)
        reward = np.asarray(reward, np.float32)
        last_action = np.asarray(action, np.int32)
        if on_step is not None:
            on_step(reward, done)
        if timings is not None:
            timings.time("step")
    return obs, last_action, reward, done, core_state


class _ActorThread(threading.Thread):
    """One actor: owns a vector-env slab, fills trajectory slots."""

    def __init__(
        self,
        actor_id: int,
        trainer,
        envs,
        policy=None,
    ) -> None:
        """``policy``: the acting facade (``act`` + ``initial_state``);
        defaults to ``trainer.agent`` (IMPALA central inference).  R2D2
        passes per-actor eps-greedy views so each actor gets its own rung
        of the Ape-X exploration ladder."""
        super().__init__(name=f"actor-{actor_id}", daemon=True)
        self.actor_id = actor_id
        self.trainer = trainer
        self.envs = envs
        self.policy = policy if policy is not None else trainer.agent
        self.timings = Timings()

    def run(self) -> None:
        tr = self.trainer
        q = tr.queue
        while True:
            try:
                self._act_loop()
                return
            except Exception as e:  # noqa: BLE001 - restart or funnel
                if not tr.grant_actor_restart(self.actor_id, e):
                    q.report_error(e)
                    return
                # the env stack is suspect after a crash (a dead subprocess
                # env can't step again): rebuild it from the factory
                try:
                    self.envs.close()
                except Exception:  # noqa: BLE001 - already broken
                    pass
                try:
                    self.envs = tr.env_fns[self.actor_id]()
                except Exception as rebuild_err:  # noqa: BLE001
                    q.report_error(rebuild_err)
                    return

    def _act_loop(self) -> None:
        tr = self.trainer
        agent = self.policy
        # remote policies (serving plane) are host IO: entering the mesh
        # dispatch guard around them would serialize the learner against
        # network latency for no safety gain (the InferenceServer holds
        # the guard around its own device dispatch)
        dispatch_guard = (
            None
            if getattr(agent, "_remote_policy", False)
            else getattr(tr, "_dispatch_guard", None)
        )
        q = tr.queue
        T = tr.args.rollout_length
        B = self.envs.num_envs
        obs, _ = self.envs.reset(seed=tr.args.seed + 1000 * self.actor_id)
        last_action = np.zeros(B, np.int32)
        reward = np.zeros(B, np.float32)
        done = np.ones(B, bool)
        core_state = agent.initial_state(B)
        metrics = tr.episode_metrics[self.actor_id]
        while not tr.stop_event.is_set():
            idx = q.acquire(timeout=1.0)
            if idx is None:
                continue
            self.timings.reset()
            committed = False
            try:
                obs, last_action, reward, done, core_state = fill_rollout_slot(
                    q.slots[idx],
                    agent,  # central batched inference on device
                    self.envs,
                    obs,
                    last_action,
                    reward,
                    done,
                    core_state,
                    T,
                    on_step=metrics.step,
                    timings=self.timings,
                    dispatch_guard=dispatch_guard,
                )
                q.commit(idx)
                committed = True
            except BaseException:
                # crash mid-fill: the acquired slot was never committed —
                # hand it back or the pool shrinks one slot per restart
                # until acquire() starves
                if not committed:
                    q.recycle([idx])
                raise
            self.timings.time("write")
            with tr.frame_lock:
                tr.env_frames += T * B


class HostPlaneMixin:
    """Shared scaffolding for host actor-plane trainers (IMPALA threads,
    R2D2): the elastic-actor restart budget and the agent-state resume
    trio.  ONE implementation — a fix to restart accounting or checkpoint
    shape must not have to be mirrored between planes.

    Expects the trainer to define: ``agent`` / ``env_frames`` /
    ``param_server`` / ``max_actor_restarts`` / ``actor_restarts`` /
    ``_restart_lock`` / ``_mesh_lock`` plus BaseTrainer's resume plumbing.
    """

    def _dispatch_guard(self):
        """Serialize multi-device dispatch when the agent is meshed.

        Same hazard ApexTrainer locks against (the PR 2
        ``test_apex_sharded_replay_mesh_e2e`` deadlock): with
        ``agent.enable_mesh`` active, actor threads' central inference and
        the learner's update are all multi-device programs; two threads
        enqueueing them concurrently can order the per-device queues
        differently and wedge the whole XLA client.  One lock around every
        dispatch site serializes enqueue order; single-device runs keep the
        lock-free fast path (the mesh check is a cheap attribute read).
        """
        if (
            getattr(self.agent, "mesh", None) is not None
            or getattr(self.agent, "_learn_mesh", None) is not None
        ):
            return self._mesh_lock
        return nullcontext()

    def grant_actor_restart(self, actor_id: int, exc: BaseException) -> bool:
        """Consume one unit of the elastic-actor budget; False = fail fast."""
        with self._restart_lock:
            if self.actor_restarts >= self.max_actor_restarts:
                return False
            self.actor_restarts += 1
            used = self.actor_restarts
        if self.is_main_process:
            self.text_logger.warning(
                f"actor {actor_id} crashed ({type(exc).__name__}: {exc}); "
                f"rebuilding its envs (restart {used}/{self.max_actor_restarts})"
            )
        return True

    def _resume_pytree(self) -> Dict:
        return {
            "agent": self.agent.state,
            "env_frames": np.asarray(self.env_frames, np.int64),
        }

    def save_resume(self) -> None:
        self.save_resume_checkpoint(
            self._resume_pytree(), self.env_frames, int(self.agent.state.step)
        )

    def try_resume(self) -> bool:
        """Restore learner state + frame counter (parity: the reference's
        IMPALA 10-min checkpoints, ``impala_atari.py:460-469,496-515`` —
        which it saved but never wired a restore for)."""
        state = self.load_resume_checkpoint(self._resume_pytree())
        if state is None:
            return False
        self.agent.state = state["agent"]
        self.env_frames = int(state["env_frames"])
        self.param_server.push(self.agent.get_weights())
        if self.is_main_process:
            self.text_logger.info(
                f"resumed from {self.resume_ckpt_path}: frames {self.env_frames}"
            )
        return True



def check_queue_depth(args, envs_per_actor: int) -> None:
    """Slot-aware queue floor (the check config.validate cannot do: it
    needs the env fleet shape).  ``num_buffers`` counts SLOTS of
    ``envs_per_actor`` lanes; one learn step drains
    ``batch_size / envs_per_actor`` slots, and queue depth is worst-case
    policy lag in learner steps x drained slots — deeper queues do not add
    throughput once every actor can hold a free slot, they only add
    staleness (the host-plane Breakout stall, round 4)."""
    n_slots = max(args.batch_size // envs_per_actor, 1)
    floor = max(2 * n_slots, args.num_actors)
    if args.num_buffers < floor:
        raise ValueError(
            f"num_buffers ({args.num_buffers} slots of {envs_per_actor} "
            f"lanes) must be at least max(2 * batch_size/envs_per_actor, "
            f"num_actors) = {floor} so the learner can drain a full batch "
            "while every actor holds a slot"
        )


class HostActorLearnerTrainer(HostPlaneMixin, BaseTrainer):
    def __init__(
        self,
        args: ImpalaArguments,
        agent: ImpalaAgent,
        env_fns,  # list of callables, one vector env per actor
        run_name: Optional[str] = None,
        max_actor_restarts: int = 0,
    ) -> None:
        """``max_actor_restarts``: elastic actors (beyond the reference's
        fail-fast funnels).  An actor thread that crashes — typically a
        dead env subprocess — rebuilds its env stack from ``env_fns`` and
        resumes, up to this many times across all actors; the learner sees
        a throughput dip, not a dead run.  0 keeps fail-fast (the crash
        re-raises in the learner via the rollout queue's error funnel)."""
        super().__init__(args, run_name=run_name)
        self.agent = agent
        # dp×mp sharded learner hookup: RLArguments.{mesh_shape,dp_size,
        # mp_size} resolve to agent.enable_mesh before any actor thread
        # starts (idempotent; the mesh dispatch guard below covers the
        # resulting multi-device dispatch sites)
        from scalerl_tpu.parallel.train_step import maybe_enable_mesh_from_args

        maybe_enable_mesh_from_args(agent, args)
        self.env_fns = env_fns
        self.stop_event = threading.Event()
        self.frame_lock = threading.Lock()
        self.env_frames = 0
        self.max_actor_restarts = max_actor_restarts
        self.actor_restarts = 0
        self._restart_lock = threading.Lock()
        # serializes multi-device dispatch under agent.enable_mesh — see
        # HostPlaneMixin._dispatch_guard
        self._mesh_lock = threading.Lock()
        self.param_server = ParameterServer()

        probe_env = env_fns[0]()
        self.envs_per_actor = probe_env.num_envs
        obs_shape = probe_env.single_observation_space.shape
        num_actions = probe_env.single_action_space.n
        self._probe_env = probe_env

        core = agent.initial_state(self.envs_per_actor)
        self.spec = TrajectorySpec(
            unroll_length=args.rollout_length,
            batch_size=self.envs_per_actor,
            obs_shape=obs_shape,
            num_actions=num_actions,
            obs_dtype=jax.numpy.float32 if len(obs_shape) == 1 else jax.numpy.uint8,
            core_state_shapes=tuple(tuple(c.shape) for c, _ in core),
        )
        check_queue_depth(args, self.envs_per_actor)
        self.queue = RolloutQueue(self.spec, num_slots=args.num_buffers)
        self.episode_metrics = [
            EpisodeMetrics(self.envs_per_actor) for _ in range(len(env_fns))
        ]
        self.learn_timings = Timings()

        # actor_mode="serving": the full centralized inference plane — the
        # ONE hot policy lives in an InferenceServer (dynamic batcher,
        # generation tags, SLO telemetry) and actor threads act through
        # RemotePolicyClients over in-process codec links, exactly the wire
        # shape remote env-shell hosts speak over sockets.  The agent
        # doubles as each client's local fallback, so a dead server
        # degrades the run to the thread topology instead of killing it.
        self.inference_server = None
        self._serving_clients: list = []
        if getattr(args, "actor_mode", "threads") == "serving":
            from scalerl_tpu.serving import (
                InferenceServer,
                RemotePolicyClient,
                ServingConfig,
                local_pair,
            )

            self.inference_server = InferenceServer(
                agent,
                ServingConfig.from_args(args),
                dispatch_guard=self._dispatch_guard,
            )
            self.inference_server.start()
            for _ in env_fns:
                client_end, server_end = local_pair()
                self.inference_server.add_connection(server_end)
                self._serving_clients.append(
                    RemotePolicyClient(
                        conn=client_end,
                        fallback=agent,
                        dispatch_guard=self._dispatch_guard,
                    )
                )

    # grant_actor_restart / _resume_pytree / save_resume / try_resume come
    # from HostPlaneMixin (shared with the R2D2 plane)

    def _assemble_batch(self, n_slots: int, timings: Optional[Timings] = None):
        """Drain ``n_slots`` full slots into one device trajectory — the
        single assembly path for both the inline learner loop and the
        prefetch threads."""
        batch, idxs = self.queue.get_batch(n_slots)
        if timings is not None:
            timings.time("dequeue")
        traj = batch_to_trajectory(batch)
        self.queue.recycle(idxs)
        if timings is not None:
            timings.time("device")
        return traj

    def train(self, total_frames: Optional[int] = None) -> Dict[str, float]:
        args = self.args
        total_frames = total_frames or args.total_steps
        if self.resuming:
            self.try_resume()
        actors = []
        for i, fn in enumerate(self.env_fns):
            envs = self._probe_env if i == 0 else fn()
            policy = self._serving_clients[i] if self._serving_clients else None
            actors.append(_ActorThread(i, self, envs, policy=policy))
        self.actors = actors  # exposed for phase-timing inspection (bench)
        # supervision: SIGTERM/SIGINT -> save_resume at the next learn-step
        # boundary; watchdog dumps all-thread stacks + queue occupancy when
        # neither env frames nor learn steps advance for the deadline.
        # Installed after env construction so a failing factory cannot leak
        # signal handlers (the finally below owns the teardown).
        guard = PreemptionGuard().install() if args.handle_preemption else None
        watchdog: Optional[StallWatchdog] = None
        learn_progress = None
        if args.watchdog_timeout_s > 0:
            watchdog = StallWatchdog(
                args.watchdog_timeout_s, name="host-actor-learner"
            )
            watchdog.watch("env_frames", lambda: self.env_frames)
            learn_progress = watchdog.counter("learn_steps")
            watchdog.add_probe("rollout_queue", self.queue.stats)
            watchdog.add_probe("actor_restarts", lambda: self.actor_restarts)
            watchdog.start()
        for a in actors:
            a.start()

        start = time.time()
        start_frames = self.env_frames  # nonzero after resume
        last_log_frames = start_frames
        # elasticity signals: the autoscaler's documented inputs (rates.fps
        # / rates.learn_steps_per_s, docs/OBSERVABILITY.md) are fed with
        # interval deltas at the log boundary — per-chunk cadence, and
        # telemetry-off compiles the marks out entirely
        fps_meter = learn_meter = None
        if self._instrument:
            _reg = telemetry.get_registry()
            fps_meter = _reg.meter("rates.fps")
            learn_meter = _reg.meter("rates.learn_steps_per_s")
        meter_frames = start_frames
        meter_steps = 0
        cadence = CheckpointCadence(
            args.save_frequency, args.checkpoint_interval_s, start_frames
        )
        n_slots = max(args.batch_size // self.envs_per_actor, 1)
        metrics: Dict = {}
        learn_steps_done = 0  # host-side counter (no device sync)

        # Optional assembly prefetch (wires the reference's num_learners
        # knob, ``impala_atari.py:439-456``): num_learner_threads - 1
        # assembly threads drain slots and build trajectories while the
        # device runs the previous learn step, so the TPU never waits on
        # host batch stitching (the learn step itself stays one thread —
        # it is a single jitted call and parallelizing it adds nothing)
        prefetch_q: Optional[queue_mod.Queue] = None
        assemble_threads: list = []
        if args.num_learner_threads >= 2:
            prefetch_q = queue_mod.Queue(maxsize=2)

            def _put(item) -> bool:
                # bounded put that gives up at shutdown: an unconditional
                # put() would block forever when the main loop exits with
                # the queue full, leaking the thread and a pinned batch
                while True:
                    try:
                        prefetch_q.put(item, timeout=0.5)
                        return True
                    except queue_mod.Full:
                        if self.stop_event.is_set():
                            return False

            def _assemble() -> None:
                try:
                    while not self.stop_event.is_set():
                        if not _put(self._assemble_batch(n_slots)):
                            return
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    _put(e)

            for i in range(args.num_learner_threads - 1):
                t = threading.Thread(
                    target=_assemble, name=f"learner-assemble-{i}", daemon=True
                )
                t.start()
                assemble_threads.append(t)

        def next_traj():
            if prefetch_q is None:
                self.learn_timings.reset()
                return self._assemble_batch(n_slots, timings=self.learn_timings)
            self.learn_timings.reset()
            while True:
                try:
                    item = prefetch_q.get(timeout=0.5)
                    break
                except queue_mod.Empty:
                    if self.stop_event.is_set():
                        raise RuntimeError("rollout queue closed")
            self.learn_timings.time("dequeue")
            if isinstance(item, BaseException):
                raise item
            return item

        try:
            while self.env_frames < total_frames and not self.stop_event.is_set():
                if watchdog is not None:
                    watchdog.check()
                if guard is not None and guard.triggered:
                    # preemption safe point: the previous learn step is
                    # complete, no slot is half-consumed
                    if args.save_model and not args.disable_checkpoint:
                        self.save_resume()
                    break
                traj = next_traj()
                # device metrics stay un-materialized: float() only at log
                # time, so the loop dispatches the next step without a sync.
                # Guarded: actor threads dispatch central inference
                # concurrently, and under enable_mesh both sides are
                # multi-device programs (HostPlaneMixin._dispatch_guard)
                with self._dispatch_guard():
                    metrics = self.agent.learn_device(traj)
                self.learn_timings.time("learn")
                learn_steps_done += 1
                if learn_progress is not None:
                    learn_progress.bump()
                # version bump only — actors do central inference on the
                # live device params; a to_host push would force a full
                # device->host param fetch (a sync) every learn step.  The
                # device-side snapshot copy is itself a program: guard it
                with self._dispatch_guard():
                    self.param_server.push(self.agent.get_weights(), to_host=False)
                    if self.inference_server is not None:
                        # serving plane: monotonic generation bump; every
                        # act reply from here on is tagged with the new
                        # generation (in-flight flushes keep their old tag)
                        self.inference_server.push_params(
                            self.agent.get_weights(),
                            learner_step=learn_steps_done,
                        )

                if (
                    args.save_model
                    and not args.disable_checkpoint
                    and cadence.due(self.env_frames)
                ):
                    cadence.mark_saved(self.env_frames)
                    self.save_resume()

                if self.env_frames - last_log_frames >= args.logger_frequency:
                    last_log_frames = self.env_frames
                    sps = (self.env_frames - start_frames) / max(
                        time.time() - start, 1e-8
                    )
                    rets = [
                        r
                        for m in self.episode_metrics
                        for r in m.episode_returns[-20:]
                    ]
                    ret_mean = float(np.mean(rets)) if rets else float("nan")
                    # one batched device->host transfer for the whole dict
                    # (per-key float() would pay a round trip per metric)
                    host_metrics = get_metrics(metrics)
                    if self.inference_server is not None and self._serving_clients:
                        # generation tags close the loop here: the lag
                        # between the newest push and the oldest client's
                        # last-served generation is the staleness V-trace
                        # is correcting (serving.staleness gauge)
                        self.inference_server.observe_staleness(
                            min(c.generation for c in self._serving_clients)
                        )
                    if self._instrument:
                        if fps_meter is not None:
                            fps_meter.mark(self.env_frames - meter_frames)
                            meter_frames = self.env_frames
                        if learn_meter is not None:
                            learn_meter.mark(learn_steps_done - meter_steps)
                            meter_steps = learn_steps_done
                        telemetry.observe_train_metrics(host_metrics)
                        reg = telemetry.get_registry()
                        reg.set_gauges(
                            {**host_metrics, "sps": sps, "return_mean": ret_mean},
                            prefix="train.",
                        )
                        # registry-backed write: queue occupancy and guard
                        # counters ride alongside the learner metrics
                        self.logger.log_registry(
                            self.env_frames,
                            step_type="train",
                            include_prefixes=("train.", "queue."),
                        )
                    if self.is_main_process:
                        self.text_logger.info(
                            f"frames {self.env_frames} | sps {sps:.0f} | "
                            f"return {ret_mean:.1f} | loss {host_metrics.get('total_loss', float('nan')):.3f}"
                        )
        finally:
            self.stop_event.set()
            if watchdog is not None:
                watchdog.stop()
            if guard is not None:
                guard.restore()
            self.queue.close()
            if self.inference_server is not None:
                # clients first: close() wakes blocked actors, which finish
                # their current slot on the local fallback (no degraded-mode
                # flip, no reconnect churn) and exit on stop_event
                for c in self._serving_clients:
                    c.close()
                self.inference_server.stop()
            # joins run on ONE shared wall-clock budget per group: a wedged
            # thread (env backend stuck in step) must not multiply the
            # teardown by the thread count — preemption budgets are
            # wall-clock, and daemon threads die with the process anyway.
            # After a DIAGNOSED stall the grace shrinks further: the
            # watchdog already proved the threads are wedged, so a long
            # wait buys nothing but a slower failure.
            stalled = watchdog is not None and watchdog.stalled is not None
            deadline = time.monotonic() + (0.5 if stalled else 3.0)
            for t in assemble_threads:
                t.join(timeout=max(0.05, deadline - time.monotonic()))
            if prefetch_q is not None:
                # release device-resident trajectories still queued
                while True:
                    try:
                        prefetch_q.get_nowait()
                    except queue_mod.Empty:
                        break
            deadline = time.monotonic() + (0.5 if stalled else 5.0)
            for a in actors:
                a.join(timeout=max(0.05, deadline - time.monotonic()))
            for a in actors:
                try:
                    a.envs.close()
                except Exception:
                    pass
        if args.save_model and not args.disable_checkpoint:
            self.save_resume()
        sps = (self.env_frames - start_frames) / max(time.time() - start, 1e-8)
        rets = [r for m in self.episode_metrics for r in m.episode_returns]
        return {
            **get_metrics(metrics),
            "env_frames": float(self.env_frames),
            "sps": float(sps),
            "return_mean": float(np.mean(rets[-100:])) if rets else float("nan"),
            "episodes": float(len(rets)),
        }


class DeviceActorLearnerTrainer(BaseTrainer):
    """IMPALA over device-native envs via the fused loop (flagship perf)."""

    def __init__(
        self,
        args: ImpalaArguments,
        agent: ImpalaAgent,
        venv,
        iters_per_call: int = 10,
        mesh=None,
        run_name: Optional[str] = None,
        chunks_in_flight: int = 2,
    ) -> None:
        """``mesh``: run the fused loop data-parallel (Anakin) — env lanes
        sharded over the mesh's ``dp`` axis, params replicated, gradients
        psum-ed inside the fused step.  ``chunks_in_flight``: how many
        fused chunks stay dispatched ahead of the host's (batched) metric
        reads — logging lags the device by ``chunks_in_flight - 1`` chunks
        instead of stalling it; 1 restores the synchronous driver."""
        super().__init__(args, run_name=run_name)
        from scalerl_tpu.runtime.device_loop import DeviceActorLearnerLoop

        self.agent = agent
        self.chunks_in_flight = chunks_in_flight
        # the agent owns the loss hyperparameters — never rebuild from the
        # trainer's args (which may be a different object)
        learn_fn = agent.make_learn_fn(grad_axis="dp" if mesh is not None else None)
        self.loop = DeviceActorLearnerLoop(
            model=agent.model,
            venv=venv,
            learn_fn=learn_fn,
            unroll_length=args.rollout_length,
            iters_per_call=iters_per_call,
            mesh=mesh,
        )

    def _resume_pytree(self) -> Dict:
        return {"agent": self.agent.state, "env_frames": np.asarray(0, np.int64)}

    def train(self, total_frames: Optional[int] = None) -> Dict[str, float]:
        args = self.args
        total_frames = total_frames or args.total_steps
        frames_per_call = (
            args.rollout_length * self.loop.venv.num_envs * self.loop.iters_per_call
        )
        done_frames = 0
        if self.resuming:
            prev = self.load_resume_checkpoint(self._resume_pytree())
            if prev is not None:
                self.agent.state = prev["agent"]
                done_frames = int(prev["env_frames"])
                if self.is_main_process:
                    self.text_logger.info(
                        f"resumed from {self.resume_ckpt_path}: frames {done_frames}"
                    )
        remaining = total_frames - done_frames
        if remaining <= 0:
            # resumed a finished run: nothing to do, don't over-train
            if self.is_main_process:
                self.text_logger.info(
                    f"resume frames {done_frames} >= budget {total_frames}; no-op"
                )
            return {"env_frames": float(done_frames), "sps": 0.0}
        num_calls = max(remaining // frames_per_call, 1)
        key = jax.random.PRNGKey(args.seed + done_frames % 65537)
        carry = self.loop.init_carry(key)
        start = time.time()

        def on_metrics(i: int, m: Dict[str, float]) -> None:
            # offset by done_frames so resumed runs keep logging (the logger
            # gate was restored to the old run's last step) and the tb
            # timeline continues instead of rewinding over the old events
            frames = done_frames + (i + 1) * frames_per_call
            sps = (frames - done_frames) / max(time.time() - start, 1e-8)
            # registry-backed write path: m is already host floats (the
            # driver's one batched transfer per chunk); the driver also
            # feeds train.fps/train.chunks_per_s meters.  Per-chunk cadence;
            # self._instrument compiles the writes out entirely.
            if self._instrument:
                reg = telemetry.get_registry()
                reg.set_gauges({**m, "sps": sps}, prefix="train.")
                self.logger.log_registry(
                    frames, step_type="train", include_prefixes=("train.",)
                )
            if self.is_main_process and (i % 10 == 0 or i == num_calls - 1):
                self.text_logger.info(
                    f"frames {frames} | sps {sps:.0f} | return {m.get('return_mean', float('nan')):.2f}"
                )

        # supervision: a preemption signal stops dispatch at the next chunk
        # boundary (in-flight chunks drain and count); the watchdog's
        # progress counter is bumped by the loop per dispatched chunk
        guard = PreemptionGuard().install() if args.handle_preemption else None
        watchdog: Optional[StallWatchdog] = None
        progress = None
        if args.watchdog_timeout_s > 0:
            watchdog = StallWatchdog(
                args.watchdog_timeout_s, name="device-actor-learner"
            )
            progress = watchdog.counter("fused_chunks")
            watchdog.start()
        try:
            # --profile-dir: device+host trace around the fused run; the
            # driver's per-chunk step_marker aligns chunks in the viewer
            with maybe_trace(getattr(args, "profile_dir", "") or None):
                state, carry, metrics = self.loop.run(
                    self.agent.state, carry, key, num_calls, on_metrics=on_metrics,
                    chunks_in_flight=self.chunks_in_flight,
                    progress=progress,
                    should_stop=(lambda: guard.triggered) if guard is not None else None,
                    instrument=self._instrument,
                )
        finally:
            if watchdog is not None:
                watchdog.stop()
            if guard is not None:
                guard.restore()
        self.agent.state = state
        # chunks_done < num_calls after a preemption: checkpoint the frames
        # actually trained, not the requested budget, so resume restores
        # matching counters
        chunks_done = int(metrics.pop("chunks_done", num_calls))
        frames = done_frames + chunks_done * frames_per_call
        if args.save_model and not args.disable_checkpoint:
            self.save_resume_checkpoint(
                {"agent": state, "env_frames": np.asarray(frames, np.int64)},
                frames,
                int(state.step),
            )
        metrics["env_frames"] = float(frames)
        metrics["sps"] = (frames - done_frames) / max(time.time() - start, 1e-8)
        return metrics
