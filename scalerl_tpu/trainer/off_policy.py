"""Off-policy trainer: vector-env rollouts feeding a device replay + learner.

Parity target: ``OffPolicyTrainer`` (``scalerl/trainer/off_policy.py:21-323``):
buffer/sampler wiring (uniform / PER / n-step), warmup + ``train_frequency``
gating, vector-env evaluation, fps accounting, periodic eval/log/checkpoint.
Fixes the reference's wiring bugs catalogued in SURVEY.md §2.4 (PER sampler
signature mismatch, ``next_state``/``next_obs`` field drift, PER alpha fed
from the RMSProp constant).

The rollout loop runs on the host (data-dependent episode boundaries);
acting and learning are jitted device calls through the agent.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from scalerl_tpu.agents.dqn import DQNAgent
from scalerl_tpu.config import DQNArguments
from scalerl_tpu.data.sampler import Sampler
from scalerl_tpu.runtime import chaos, telemetry
from scalerl_tpu.runtime.dispatch import get_metrics
from scalerl_tpu.runtime.supervisor import DivergenceTripwire
from scalerl_tpu.trainer.base import BaseTrainer
from scalerl_tpu.utils.metrics import EpisodeMetrics
from scalerl_tpu.utils.schedulers import LinearDecayScheduler


class OffPolicyTrainer(BaseTrainer):
    def __init__(
        self,
        args: DQNArguments,
        agent: DQNAgent,
        train_envs,
        eval_envs=None,
        run_name: Optional[str] = None,
    ) -> None:
        super().__init__(args, run_name=run_name)
        self.agent = agent
        self.train_envs = train_envs
        self.eval_envs = eval_envs
        self.num_envs = getattr(train_envs, "num_envs", 1)

        obs_space = train_envs.single_observation_space
        act_space = train_envs.single_action_space
        if hasattr(act_space, "n"):  # Discrete
            action_shape, action_dtype = (), jnp.int32
        else:  # Box (continuous control: SAC)
            action_shape, action_dtype = tuple(act_space.shape), jnp.float32
        self.sampler = Sampler(
            obs_shape=obs_space.shape,
            capacity=args.buffer_size,
            num_envs=self.num_envs,
            use_per=args.use_per,
            per_alpha=args.per_alpha,
            n_step=args.n_steps,
            gamma=args.gamma,
            use_pallas=getattr(args, "use_pallas", False),
            action_shape=action_shape,
            action_dtype=action_dtype,
        )
        self.per_beta = LinearDecayScheduler(
            args.per_beta, args.per_beta_final, args.max_timesteps
        )

        self.global_step = 0
        self.learn_steps = 0
        self.metrics = EpisodeMetrics(self.num_envs)
        # replay sampling gets its own seeded key stream: sampling without a
        # key falls back to global np.random (replay.py), which makes a run's
        # batch sequence depend on whatever np.random state previous tests /
        # callers left behind — the order-dependent flake
        # test_td3_solves_pendulum exposed (passes in-suite, fails
        # standalone).  Deriving from args.seed pins RNG isolation: the same
        # seed now samples the same batches standalone and in-suite.
        self._sample_key = jax.random.PRNGKey(args.seed + 0x53A1)
        # telemetry plane: rate meters + snapshot-time replay binding; the
        # logger's registry-backed write path reads these instead of a
        # hand-assembled metric dict.  telemetry_interval_s <= 0 compiles
        # the instrument writes out entirely (no meter objects, no marks —
        # the fast-off toggle documented in docs/PERFORMANCE.md); meters
        # are fed once per LOG INTERVAL (chunk-amortized), never per step
        # (self._instrument comes from BaseTrainer).
        self._learn_marked = 0
        if self._instrument:
            reg = telemetry.get_registry()
            self._fps_meter = reg.meter("rates.fps")
            self._learn_meter = reg.meter("rates.learn_steps_per_s")
            reg.bind("replay.size", lambda: len(self.sampler))
        # divergence tripwire: K consecutive guarded-away (non-finite) learn
        # steps restore the agent from the last good resume checkpoint
        self.tripwire = DivergenceTripwire(
            getattr(args, "divergence_rollback_steps", 0),
            self._divergence_rollback,
        )

    # ------------------------------------------------------------------
    def store_experience(
        self, obs, next_obs, action, reward, terminated, infos, truncated=None
    ) -> None:
        """Store one vector step; on done, ``next_obs`` is the true terminal
        obs from ``infos['final_obs']`` (SAME_STEP autoreset semantics).

        ``terminated`` alone is the bootstrap mask; ``terminated | truncated``
        bounds the n-step fold so windows never cross a TimeLimit reset.
        """
        real_next = np.asarray(next_obs).copy()
        final_obs = infos.get("final_obs") if isinstance(infos, dict) else None
        if final_obs is not None:
            mask = infos.get("_final_obs")
            for i in np.nonzero(mask)[0]:
                real_next[i] = final_obs[i]
        boundary = (
            np.logical_or(terminated, truncated) if truncated is not None else None
        )
        self.sampler.add(obs, real_next, action, reward, terminated, boundary=boundary)

    def train_step(self) -> Dict[str, float]:
        beta = self.per_beta.value(self.global_step)
        self._sample_key, sk = jax.random.split(self._sample_key)
        batch = self.sampler.sample(self.args.batch_size, beta=beta, key=sk)
        inj = chaos.active()
        if inj is not None:
            # seeded NaN/Inf bursts land HERE (the sampled batch, not the
            # buffer) so the guarded learn step and the tripwire below are
            # what absorbs them
            batch = dict(batch)
            inj.poison_batch(batch, site="offpolicy.batch")
        info = self.agent.learn(batch)
        if self.args.use_per:
            self.sampler.update_priorities(batch["indices"], info["td_abs"] + 1e-6)
        info.pop("td_abs", None)
        self.learn_steps += 1
        self.tripwire.observe(info)
        return info

    def _divergence_rollback(self) -> None:
        """Restore agent state from the last good resume checkpoint after K
        consecutive non-finite (skipped) learn steps.

        Cold path by definition — it runs at most once per divergence
        event — so it performs ONE explicit blocking readback of the
        restored params to assert finiteness before training resumes
        (graftlint JG001 allowlists this handler for exactly that read).
        Env progress (``global_step``) and the replay buffer are kept: the
        divergence corrupted the *params*, not the experience.
        """
        try:
            state = self.load_resume_checkpoint(self._resume_pytree())
        except FileNotFoundError:
            state = None
        if state is None:
            self.text_logger.warning(
                "divergence tripwire fired but no resume checkpoint exists; "
                "continuing with the current (guard-protected) state"
            )
            return
        self.agent.state = state["agent"]
        self.learn_steps = int(state["learn_steps"])
        leaves = jax.device_get(jax.tree_util.tree_leaves(self.agent.state))
        finite = all(
            bool(np.all(np.isfinite(leaf)))
            for leaf in leaves
            if np.issubdtype(np.asarray(leaf).dtype, np.floating)
        )
        self.text_logger.warning(
            "divergence tripwire: restored agent state from %s "
            "(learn_steps=%d, params finite=%s, rollback #%d)",
            self.resume_ckpt_path, self.learn_steps, finite, self.tripwire.trips,
        )

    def run_evaluate_episodes(self, n_episodes: Optional[int] = None) -> Dict[str, float]:
        """Greedy rollouts on the eval env pool until ``n_episodes`` finish
        (``off_policy.py:221-249`` parity)."""
        envs = self.eval_envs or self.train_envs
        n_episodes = n_episodes or self.args.eval_episodes
        num_envs = getattr(envs, "num_envs", 1)
        obs, _ = envs.reset(seed=self.args.seed + 100)
        returns: list = []
        ep_ret = np.zeros(num_envs)
        ep_len = np.zeros(num_envs, int)
        prev_done = np.ones(num_envs, bool)
        while len(returns) < n_episodes:
            actions = self.agent.predict(obs, done=prev_done)
            obs, reward, term, trunc, _ = envs.step(np.asarray(actions))
            ep_ret += reward
            ep_len += 1
            done = np.logical_or(term, trunc)
            prev_done = done
            for i in np.nonzero(done)[0]:
                returns.append((ep_ret[i], ep_len[i]))
                ep_ret[i] = 0.0
                ep_len[i] = 0
        rets = np.array([r for r, _ in returns[:n_episodes]])
        lens = np.array([l for _, l in returns[:n_episodes]])
        return {
            "reward_mean": float(rets.mean()),
            "reward_std": float(rets.std()),
            "length_mean": float(lens.mean()),
        }

    # ------------------------------------------------------------------
    def _resume_pytree(self) -> Dict:
        # counters as host numpy (int64 survives regardless of jax_enable_x64)
        return {
            "agent": self.agent.state,
            "replay": self.sampler.buffer.state,
            "global_step": np.asarray(self.global_step, np.int64),
            "learn_steps": np.asarray(self.learn_steps, np.int64),
        }

    def save_resume(self) -> None:
        self.save_resume_checkpoint(
            self._resume_pytree(), self.global_step, self.learn_steps
        )

    def try_resume(self) -> bool:
        """Restore train state, replay cursors, counters, and exploration
        schedule position from ``args.resume``; True when restored."""
        state = self.load_resume_checkpoint(self._resume_pytree())
        if state is None:
            return False
        self.agent.state = state["agent"]
        self.sampler.buffer.state = state["replay"]
        self.global_step = int(state["global_step"])
        self.learn_steps = int(state["learn_steps"])
        # fast-forward the exploration schedule to the restored step
        if hasattr(self.agent, "eps_scheduler"):  # eps-greedy agents only
            self.agent.eps_scheduler.cur_step = self.global_step
            self.agent.eps = self.agent.eps_scheduler.value(self.global_step)
        if self.is_main_process:
            self.text_logger.info(
                f"resumed from {self.resume_ckpt_path}: step {self.global_step}, "
                f"learn_steps {self.learn_steps}"
            )
        return True

    def run(self) -> Dict[str, float]:
        args = self.args
        if self.resuming:
            self.try_resume()
        if (
            self.tripwire.enabled
            and self.is_main_process
            and args.save_model
            and not args.disable_checkpoint
            and not os.path.exists(self.resume_ckpt_path)
        ):
            # rollback needs a "last good" state to return to from step 0
            self.save_resume()
        obs, _ = self.train_envs.reset(seed=args.seed)
        start = time.time()
        start_step = self.global_step
        last_log = self.global_step
        last_eval = self.global_step
        last_save = self.global_step
        train_info: Dict[str, float] = {}

        prev_done = np.ones(self.num_envs, bool)
        while self.global_step < args.max_timesteps:
            actions = self.agent.get_action(obs, done=prev_done)
            next_obs, reward, term, trunc, infos = self.train_envs.step(np.asarray(actions))
            self.store_experience(obs, next_obs, actions, reward, term, infos, trunc)
            prev_done = np.logical_or(term, trunc)
            self.metrics.step(reward, prev_done)
            obs = next_obs
            self.global_step += self.num_envs
            if hasattr(self.agent, "update_exploration"):
                self.agent.update_exploration(self.num_envs)

            if (
                len(self.sampler) >= args.warmup_learn_steps
                and self.global_step % args.train_frequency < self.num_envs
            ):
                train_info = self.train_step()

            if self.global_step - last_log >= args.logger_frequency:
                frames_delta = self.global_step - last_log
                last_log = self.global_step
                fps = int(
                    (self.global_step - start_step) / max(time.time() - start, 1e-8)
                )
                summary = self.metrics.summary()
                # one batched device->host transfer for the metric dict —
                # any device scalars still un-materialized ride together
                host_info = get_metrics(train_info)
                train_info = host_info
                if self._instrument:
                    telemetry.observe_train_metrics(host_info)
                    # registry-backed write path: instruments are the single
                    # source the logger backends read from (no hand-assembled
                    # metric dict; queue/ring/guard counters ride for free).
                    # All marks are interval-deltas — per-chunk cadence, the
                    # per-step write path no longer exists.
                    reg = telemetry.get_registry()
                    reg.set_gauges(host_info, prefix="train.")
                    reg.set_gauges(summary, prefix="train.")
                    reg.set_gauges(
                        {
                            "rpm_size": float(len(self.sampler)),
                            "fps": float(fps),
                            "learn_steps": float(self.learn_steps),
                        },
                        prefix="train.",
                    )
                    self._fps_meter.mark(frames_delta)
                    self._learn_meter.mark(
                        self.learn_steps - self._learn_marked
                    )
                    self._learn_marked = self.learn_steps
                    self.logger.log_registry(
                        self.global_step,
                        step_type="train",
                        include_prefixes=("train.",),
                    )
                if self.is_main_process:
                    ret = summary.get("return_mean", float("nan"))
                    self.text_logger.info(
                        f"step {self.global_step} | fps {fps} | return {ret:.1f} "
                        f"| eps {getattr(self.agent, 'eps', float('nan')):.3f} "
                        f"| loss {host_info.get('loss', float('nan')):.4f}"
                    )

            if self.eval_envs is not None and self.global_step - last_eval >= args.eval_frequency:
                last_eval = self.global_step
                eval_info = self.run_evaluate_episodes()
                self.logger.log_test_data(eval_info, self.global_step)
                if self.is_main_process:
                    self.text_logger.info(
                        f"eval @ {self.global_step}: return "
                        f"{eval_info['reward_mean']:.1f} +- {eval_info['reward_std']:.1f}"
                    )

            if (
                args.save_model
                and not args.disable_checkpoint
                and self.global_step - last_save >= args.save_frequency
            ):
                last_save = self.global_step
                if self.is_main_process:
                    self.agent.save_checkpoint(f"{self.model_save_dir}/ckpt_{self.global_step}")
                    self.save_resume()

        if args.save_model and not args.disable_checkpoint and self.is_main_process:
            self.agent.save_checkpoint(f"{self.model_save_dir}/ckpt_final")
            self.save_resume()
        return self.metrics.summary()
