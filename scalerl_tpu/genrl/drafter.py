"""Self-drafting for speculative decode: a jax-free n-gram prompt-lookup
table per lane (ISSUE 16).

The continuous engine's speculation loop needs k-token proposals between
macro-steps, and it needs them WITHOUT a second model — a draft model
would have to ride the :class:`~scalerl_tpu.genrl.engine
.ParamSnapshotPlane` through every ``push_params``, doubling the snapshot
wire and adding a whole second forward to the hot loop.  Instead each lane
drafts from its OWN context (prompt + tokens generated so far), the
prompt-lookup/n-gram self-drafting family: find an earlier occurrence of
the context's trailing gram — widest width first, ``n`` down to 1 — and
propose the ``k`` tokens that followed it.  The width ladder matters for
ramp-up: a lane two tokens into a repetitive continuation already drafts
off the width-1 index while the full ``n``-gram is still unseen, and a
mis-ladder draft costs nothing — the verify pass emits at least the one
bonus token either way.  On the repetitive structure RL rollouts actually
produce (recall/copy tasks, code, templated reasoning) the hit rate is
high; on incompressible text it degrades to no proposal — and the verify
pass guarantees the sampled distribution is unchanged either way, so the
drafter only ever trades FLOPs for wall-clock, never correctness.

Everything here is host-side numpy/ints on purpose: proposals happen in
the gap between the verify read and the next dispatch, so a drafter that
touched jax would serialize the host against the device (the JG001 class).
The index is incremental — O(1) per generated token, O(prompt) at
admission — because the engine calls :meth:`extend` with exactly the
tokens each verify pass emitted.

Indexing rule: when token ``t`` is appended at position ``p``, the n-gram
``ctx[p-n:p]`` (the ``n`` tokens immediately before ``t``) is recorded as
continuing at ``p`` — recorded BEFORE the append, so the context's own
trailing n-gram is never self-indexed and a proposal can never point past
the end of the context.  ALL occurrence positions are kept: a proposal
prefers the most recent occurrence that still has a full ``k``-token
continuation (recency adapts to the lane's current phrase distribution),
falling back to the earliest occurrence — the longest continuation
available — when every recent one sits too close to the context's end.
The fallback matters on exactly the sequences self-drafting is for: a
periodic continuation's latest match is always within a period of the
tail, so latest-only would truncate every draft to a fraction of ``k``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class _LaneDraft:
    """One lane's context and n-gram index."""

    __slots__ = ("tokens", "indexes", "cap", "prompt_len")

    def __init__(self, n: int, k: int) -> None:
        self.tokens: List[int] = []
        self.prompt_len = 0
        # adaptive proposal cap (AIMD via observe()): starts optimistic
        # at k; a rejection shrinks it toward the observed accept run, a
        # full accept doubles it back — so lanes whose content the table
        # predicts poorly stop paying k verified-but-rejected positions
        # per pass, which on a compute-bound substrate is the difference
        # between speculation winning and losing
        self.cap = k
        # one index per gram width 1..n: ngram -> ascending positions
        # where a continuation of it begins (propose() tries widest
        # first — the longest context match — and falls back down the
        # ladder, so a cold lane drafts off a single repeated token
        # while a warm one gets the precision of the full n-gram)
        self.indexes: List[Dict[Tuple[int, ...], List[int]]] = [
            {} for _ in range(n)
        ]


class NgramDrafter:
    """Per-lane n-gram/prompt-lookup draft tables.

    ``n``: MAXIMUM gram width matched against the context's tail; lookups
    ladder down from ``n`` to 1, widest (most reliable) match first.
    ``k``: maximum proposal length — the verify pass's token width is
    ``k + 1``, so this is a compile-shape knob, not a per-call argument.
    """

    def __init__(self, n: int = 3, k: int = 4) -> None:
        if n < 1:
            raise ValueError(f"ngram width must be >= 1, got {n}")
        if k < 1:
            raise ValueError(f"draft length must be >= 1, got {k}")
        self.n = n
        self.k = k
        self._lanes: Dict[int, _LaneDraft] = {}

    # -- lifecycle (mirrors lane occupancy) -----------------------------
    def start(self, lane_id: int, prompt: np.ndarray) -> None:
        """Begin a lane occupancy: (re)build the context from the prompt.
        O(prompt) once per admission — the per-token path is extend()."""
        lane = _LaneDraft(self.n, self.k)
        self._lanes[lane_id] = lane
        self.extend(lane_id, prompt)
        lane.prompt_len = len(lane.tokens)

    def extend(self, lane_id: int, tokens: np.ndarray) -> None:
        """Append emitted tokens, indexing each position's preceding
        n-gram before the append (the no-self-match rule)."""
        lane = self._lanes.get(lane_id)
        if lane is None:
            return
        ctx, indexes = lane.tokens, lane.indexes
        for t in tokens:
            p = len(ctx)
            for w in range(1, self.n + 1):
                if p >= w:
                    indexes[w - 1].setdefault(
                        tuple(ctx[p - w :]), []
                    ).append(p)
            ctx.append(int(t))

    def observe(self, lane_id: int, proposed: int, accepted: int) -> None:
        """Feed back one verify pass's outcome for the lane: ``proposed``
        draft tokens, ``accepted`` of them taken.  AIMD on the proposal
        cap — full acceptance doubles it (up to ``k``), a rejection
        clamps it just past the accepted run — so proposal length tracks
        how predictable the lane's content actually is."""
        lane = self._lanes.get(lane_id)
        if lane is None or proposed <= 0:
            return
        if accepted >= proposed:
            lane.cap = min(self.k, max(lane.cap, proposed) * 2)
        else:
            lane.cap = max(1, accepted + 1)

    def release(self, lane_id: int) -> None:
        """Drop a finished lane's table (the id is about to be recycled)."""
        self._lanes.pop(lane_id, None)

    # -- proposals -------------------------------------------------------
    def propose(self, lane_id: int) -> Optional[np.ndarray]:
        """Up to ``k`` proposed continuation tokens for the lane's current
        context, or ``None`` on a miss (cold lane, or no trailing gram of
        ANY width 1..n seen before — e.g. a token that never repeated)."""
        lane = self._lanes.get(lane_id)
        if lane is None or not lane.tokens:
            return None
        m, k = len(lane.tokens), min(self.k, lane.cap)
        # the narrow-width fallback exists to cover the cold-start ramp
        # (a lane two tokens into a repetition has no n-gram stats yet);
        # once the response is a full draft old the full-width index is
        # both populated and strictly more precise, and on a
        # compute-bound verify every mis-draft costs a real position —
        # so mature lanes propose full-width or not at all
        lo = self.n if m - lane.prompt_len >= self.k else 1
        for w in range(min(self.n, m), lo - 1, -1):  # widest match first
            positions = lane.indexes[w - 1].get(tuple(lane.tokens[-w:]))
            if not positions:
                continue
            start = positions[0]  # earliest = longest continuation
            for p in reversed(positions):
                if m - p >= k:  # newest with a full-k continuation
                    start = p
                    break
            draft = lane.tokens[start : start + k]
            if draft:
                return np.asarray(draft, np.int32)
        return None

    def stats(self) -> Dict[str, int]:
        return {
            "lanes": len(self._lanes),
            "indexed_ngrams": sum(
                len(ix) for l in self._lanes.values() for ix in l.indexes
            ),
        }
