"""Shared-prefix KV cache over the paged pool (jax-free, page granularity).

Sequence RL's dominant workload is *group sampling*: n completions per
prompt (the GRPO shape), where (n-1)/n of all prefill FLOPs recompute an
identical prefix — and across rounds the same prompts come back.  Because
the KV cache is block-paged, a computed prefix is reusable as a *page
chain*: a full page of prompt K/V is immutable once written (decode writes
land strictly after the prompt), so any later sequence with the same token
prefix can map the SAME physical pages into its table — sharing is purely
a page-table fact, the attention kernels never know.

:class:`PrefixCache` is the host-side index of those chains:

- **keyed by rolling hash of prompt-token blocks** — node key =
  ``crc32(block_tokens, parent_key)``, so a chain's k-th key commits to
  the whole k-page prefix; stored block bytes are compared on lookup, so
  a hash collision degrades to a miss, never to wrong tokens;
- **refcount-aware LRU eviction** — the cache holds one
  :meth:`~scalerl_tpu.genrl.paging.PageAllocator.share` ref per cached
  page; only *leaf* nodes whose page has no other holder (refcount 1 =
  cache-only, no live lane) are evictable, oldest-use first.  Eviction
  runs on demand through the allocator's reclaim hook, so cached chains
  never backpressure admission;
- **flushed on every param push** — cached K/V was computed under the
  generation that wrote it; reusing it under fresh params would break the
  temperature-0 token-identity contract, so a ``push_params`` drops the
  whole index (live lanes keep their shared pages until harvest via their
  own refs).

Telemetry: ``genrl.prefix_hits`` / ``prefix_misses`` (per lookup),
``genrl.prefix_evictions`` (nodes dropped by LRU reclaim or flush), and
``genrl.pages_shared`` (every CoW share taken on behalf of a lane) —
catalogued in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional

import numpy as np

from scalerl_tpu.genrl.paging import PageAllocator
from scalerl_tpu.runtime import telemetry

# holder label the cache registers on every page it keeps alive
CACHE_HOLDER = "prefix-cache"

_ROOT_KEY = 0x9E3779B9  # chain root sentinel (any fixed nonzero seed)


class _Node:
    """One cached full-page block: ``page`` holds the K/V of ``block``
    (page_size tokens) whose chain prefix hashes to ``parent``."""

    __slots__ = ("key", "parent", "page", "block", "children", "last_use")

    def __init__(
        self, key: int, parent: int, page: int, block: bytes, tick: int
    ) -> None:
        self.key = key
        self.parent = parent
        self.page = page
        self.block = block
        self.children = 0
        self.last_use = tick


class PrefixCache:
    """Page-granularity prompt-prefix index over a :class:`PageAllocator`.

    Single-threaded by design (driven from the continuous engine's one
    host loop).  ``lookup`` never hands out a page without the caller
    immediately taking its own ``share`` ref — the engine does both under
    one admission pass, so reclaim (which only fires inside ``alloc``)
    can never race a matched-but-unshared chain.
    """

    def __init__(self, allocator: PageAllocator, page_size: int) -> None:
        self.allocator = allocator
        self.page_size = page_size
        self._nodes: Dict[int, _Node] = {}
        self._tick = 0
        reg = telemetry.get_registry()
        self._hits = reg.counter("genrl.prefix_hits")
        self._misses = reg.counter("genrl.prefix_misses")
        self._evictions = reg.counter("genrl.prefix_evictions")

    # -- hashing -------------------------------------------------------
    @staticmethod
    def _block_key(parent: int, block: bytes) -> int:
        # rolling hash: fold the parent chain key into this block's crc so
        # equal blocks under different prefixes never collide by design
        return zlib.crc32(block, parent & 0xFFFFFFFF)

    # -- the read path -------------------------------------------------
    def lookup(self, tokens: np.ndarray, max_tokens: int) -> List[int]:
        """Longest cached chain of FULL pages covering
        ``tokens[:max_tokens]``; returns the backing page ids in chain
        order.  Callers pass ``max_tokens = prompt_len - 1`` so the
        uncached tail always has at least one token — the tail prefill is
        what produces the lane's first decode logits.
        """
        ps = self.page_size
        pages: List[int] = []
        parent = _ROOT_KEY
        n_blocks = max(min(len(tokens), max_tokens), 0) // ps
        arr = np.asarray(tokens, np.int32)
        for b in range(n_blocks):
            block = arr[b * ps : (b + 1) * ps].tobytes()
            key = self._block_key(parent, block)
            node = self._nodes.get(key)
            if node is None or node.block != block:
                break
            self._tick += 1
            node.last_use = self._tick
            pages.append(node.page)
            parent = key
        if pages:
            self._hits.inc()
        else:
            self._misses.inc()
        return pages

    # -- the write path ------------------------------------------------
    def insert(self, tokens: np.ndarray, n_tokens: int, pages: List[int]) -> int:
        """Register the chain of full-page blocks of ``tokens[:n_tokens]``
        backed by ``pages`` (the admitting lane's table prefix, in order).
        Each newly-registered page gains one cache-held ref; blocks
        already cached keep their existing backing page (the lane's
        recomputed twin stays lane-private).  Returns pages newly cached.
        """
        ps = self.page_size
        parent = _ROOT_KEY
        added = 0
        arr = np.asarray(tokens, np.int32)
        for b in range(min(n_tokens // ps, len(pages))):
            block = arr[b * ps : (b + 1) * ps].tobytes()
            key = self._block_key(parent, block)
            node = self._nodes.get(key)
            if node is not None:
                if node.block != block:
                    break  # hash collision with a live chain: stop here
                self._tick += 1
                node.last_use = self._tick
                parent = key
                continue
            self.allocator.share([pages[b]], holder=CACHE_HOLDER)
            self._tick += 1
            node = _Node(key, parent, pages[b], block, self._tick)
            self._nodes[key] = node
            pnode = self._nodes.get(parent)
            if pnode is not None:
                pnode.children += 1
            added += 1
            parent = key
        return added

    # -- eviction ------------------------------------------------------
    def _evictable(self, node: _Node) -> bool:
        # leaf-only + cache-only: an interior node keeps its children's
        # chain prefix valid, and a refcount > 1 page is mapped into a
        # live lane's table right now
        return node.children == 0 and self.allocator.refcount(node.page) == 1

    def evict(self, n_pages: int) -> int:
        """LRU-evict up to ``n_pages`` cache-only chain leaves back to the
        free list (the allocator's reclaim hook).  Chains referenced by
        live lanes are never touched."""
        freed = 0
        while freed < n_pages:
            victim: Optional[_Node] = None
            for node in self._nodes.values():
                if self._evictable(node) and (
                    victim is None or node.last_use < victim.last_use
                ):
                    victim = node
            if victim is None:
                break
            self._drop(victim)
            freed += 1
        return freed

    def _drop(self, node: _Node) -> None:
        self.allocator.free([node.page], holder=CACHE_HOLDER)
        del self._nodes[node.key]
        pnode = self._nodes.get(node.parent)
        if pnode is not None:
            pnode.children -= 1
        self._evictions.inc()

    def flush(self) -> int:
        """Invalidate the whole index (param push: cached K/V belongs to
        the old generation).  The cache's refs drop immediately; pages
        still mapped by live lanes stay alive until those lanes free."""
        dropped = len(self._nodes)
        for node in self._nodes.values():
            self.allocator.free([node.page], holder=CACHE_HOLDER)
        self._nodes.clear()
        if dropped:
            self._evictions.inc(dropped)
        return dropped

    # -- telemetry -----------------------------------------------------
    @property
    def cached_pages(self) -> int:
        return len(self._nodes)

    @property
    def hits(self) -> int:
        return int(self._hits.value)

    @property
    def misses(self) -> int:
        return int(self._misses.value)

    def stats(self) -> Dict[str, int]:
        return {
            "cached_pages": len(self._nodes),
            "hits": int(self._hits.value),
            "misses": int(self._misses.value),
            "evictions": int(self._evictions.value),
        }
