"""Token-level sequence-RL plane: generate -> score -> learn.

The scenario-diversity tier ROADMAP names after MindSpeed RL's distributed
dataflow (arxiv 2507.19017): autoregressive generation from the transformer
policy (KV-cached, bucketed static shapes, one jitted decode loop),
sequence packing into the prioritized sequence replay, and a token-level
PPO learner with per-token importance ratios against the stored behavior
logprobs.  ``genrl`` is a graftlint HOT package: the decode loop performs
exactly ONE batched host read per generation round.
"""

from scalerl_tpu.genrl.continuous import (  # noqa: F401
    CompletedSequence,
    ContinuousConfig,
    ContinuousEngine,
)
from scalerl_tpu.genrl.engine import (  # noqa: F401
    GenerationConfig,
    GenerationEngine,
    GenerationResult,
)
from scalerl_tpu.genrl.paging import PageAllocator  # noqa: F401
from scalerl_tpu.genrl.rollout import (  # noqa: F401
    pack_completions,
    pack_sequences,
    sequence_field_shapes,
)
from scalerl_tpu.genrl.task import TokenRecallTask  # noqa: F401
