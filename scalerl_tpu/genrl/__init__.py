"""Token-level sequence-RL plane: generate -> score -> learn.

The scenario-diversity tier ROADMAP names after MindSpeed RL's distributed
dataflow (arxiv 2507.19017): autoregressive generation from the transformer
policy (KV-cached, bucketed static shapes, one jitted decode loop),
sequence packing into the prioritized sequence replay, and a token-level
PPO learner with per-token importance ratios against the stored behavior
logprobs.  ``genrl`` is a graftlint HOT package: the decode loop performs
exactly ONE batched host read per generation round.

Exports resolve lazily (PEP 562): the engines pull in jax at import time,
but the disaggregated-dataflow shells (``genrl/disagg.py``) are jax-free by
design and run in fleet children that must not pay the jax import — so the
package itself stays import-light and ``scalerl_tpu.genrl.disagg`` can be
imported without touching the device stack.
"""

from typing import Any

_EXPORTS = {
    "CompletedSequence": "scalerl_tpu.genrl.continuous",
    "ContinuousConfig": "scalerl_tpu.genrl.continuous",
    "ContinuousEngine": "scalerl_tpu.genrl.continuous",
    "GenerationConfig": "scalerl_tpu.genrl.engine",
    "GenerationEngine": "scalerl_tpu.genrl.engine",
    "GenerationResult": "scalerl_tpu.genrl.engine",
    "PageAllocator": "scalerl_tpu.genrl.paging",
    "PrefixCache": "scalerl_tpu.genrl.prefix_cache",
    "pack_completions": "scalerl_tpu.genrl.rollout",
    "pack_sequences": "scalerl_tpu.genrl.rollout",
    "sequence_field_shapes": "scalerl_tpu.genrl.rollout",
    # pad-free packed learner layout (ISSUE 15)
    "PackedLearnerBatch": "scalerl_tpu.genrl.rollout",
    "greedy_pack": "scalerl_tpu.genrl.rollout",
    "pack_learner_batch": "scalerl_tpu.genrl.rollout",
    "packed_field_shapes": "scalerl_tpu.genrl.rollout",
    "packed_rows_from_completions": "scalerl_tpu.genrl.rollout",
    "packed_rows_from_result": "scalerl_tpu.genrl.rollout",
    "TokenRecallTask": "scalerl_tpu.genrl.task",
    # the disaggregated dataflow (jax-free shells)
    "CohortEngineShell": "scalerl_tpu.genrl.disagg",
    "ContinuousEngineShell": "scalerl_tpu.genrl.disagg",
    "DisaggConfig": "scalerl_tpu.genrl.disagg",
    "GenerationHost": "scalerl_tpu.genrl.disagg",
    "GenerationTierExecutor": "scalerl_tpu.genrl.disagg",
    "LocalGenerationFleet": "scalerl_tpu.genrl.disagg",
    "SequenceLearner": "scalerl_tpu.genrl.disagg",
    "disagg_signal_source": "scalerl_tpu.genrl.disagg",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str) -> Any:
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module_name), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
