"""Hermetic synthetic token tasks for the sequence-RL plane.

The token-level twin of ``envs/jax_envs/recall.py``: a reward computable
purely from (prompt, response) token arrays, so the full generate ->
score -> learn loop trains to a verifiable reward in tier-1 on CPU with no
external model, tokenizer, or dataset.

- ``recall``: the FIRST real prompt token is the cue; every response token
  should repeat it.  A memoryless/unconditional policy scores
  ``1/vocab_size`` in expectation, so crossing a high threshold requires
  the policy to attend back into the prompt — the induction behavior the
  KV-cached decode path exists to serve.
- ``copy``: response token ``t`` should equal real prompt token ``t``
  (position-wise copy; harder, needs per-position attention).

jax-free by design: prompts/scores are host numpy — the reward is the
"environment" half of the dataflow and must stay off-device (MindSpeed
RL's rule-based verifier shape), while generation/learning stay jitted.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class TokenRecallTask:
    """Cue-recall / copy reward over fixed-vocabulary token sequences.

    ``prompt_len`` may be an int (fixed) or an ``(lo, hi)`` inclusive range
    — ragged prompts exercise the engine's left-padding and bucket ladder.
    Token ids are drawn from ``[2, vocab_size)`` so 0 (pad) and 1 (a
    potential EOS) never collide with cue tokens.
    """

    def __init__(
        self,
        vocab_size: int = 16,
        prompt_len=4,
        response_len: int = 4,
        mode: str = "recall",
    ) -> None:
        if mode not in ("recall", "copy"):
            raise ValueError(f"mode must be recall | copy, got {mode!r}")
        if vocab_size < 4:
            raise ValueError(f"vocab_size must be >= 4, got {vocab_size}")
        self.vocab_size = vocab_size
        if isinstance(prompt_len, int):
            self.prompt_range = (prompt_len, prompt_len)
        else:
            self.prompt_range = (int(prompt_len[0]), int(prompt_len[1]))
        if self.prompt_range[0] < 1:
            raise ValueError("prompt_len must be >= 1")
        self.response_len = response_len
        self.mode = mode

    @property
    def max_prompt_len(self) -> int:
        return self.prompt_range[1]

    def sample_prompts(
        self, batch: int, rng: np.random.Generator
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Return ``(prompts [B, max_prompt_len] int32, lengths [B] int32)``
        — right-padded with zeros; the engine re-aligns into its buckets."""
        lo, hi = self.prompt_range
        lengths = rng.integers(lo, hi + 1, size=batch).astype(np.int32)
        prompts = rng.integers(
            2, self.vocab_size, size=(batch, hi)
        ).astype(np.int32)
        # zero out the tail beyond each lane's length (cosmetic: the engine
        # only reads the first ``lengths[b]`` tokens of lane b)
        cols = np.arange(hi)[None, :]
        prompts = np.where(cols < lengths[:, None], prompts, 0)
        return prompts, lengths

    def score(
        self,
        prompts: np.ndarray,
        prompt_lengths: np.ndarray,
        response: np.ndarray,
        response_len: np.ndarray,
    ) -> np.ndarray:
        """Per-sequence reward in ``[0, 1]``: the fraction of real response
        positions matching the target (cue token, or position-wise copy)."""
        B, R = response.shape
        cols = np.arange(R)[None, :]
        alive = cols < np.maximum(response_len[:, None], 1)
        if self.mode == "recall":
            target = np.broadcast_to(prompts[:, :1], (B, R))
        else:
            # copy: target_t = prompt token t (prompt shorter than the
            # response wraps around its real length)
            idx = cols % np.maximum(prompt_lengths[:, None], 1)
            target = np.take_along_axis(prompts, idx, axis=1)
        match = (response == target) & alive
        return (
            match.sum(axis=1) / np.maximum(alive.sum(axis=1), 1)
        ).astype(np.float32)
