"""Pack generation rounds into prioritized sequence-replay chunks.

The bridge between the generation engine (host numpy
:class:`~scalerl_tpu.genrl.engine.GenerationResult`) and
``data/sequence_replay.py``'s static-shape HBM buffer: every completed
sequence becomes one replay unit carrying everything the token-PPO learner
needs to recompute its loss off-policy —

- ``tokens`` ``[S]``: the full left-padded sequence (prompt + response),
  so the learner's forward recomputes logits over exactly the context the
  engine decoded against;
- ``behavior_logp`` / ``value`` / ``mask`` ``[R]``: the sampling-time
  logprobs (importance-ratio denominators), baselines, and real-token
  mask over the padded response bucket;
- ``reward`` / ``prompt_len`` / ``generation`` scalars: the sequence-level
  score, the left-pad offset, and the param generation that produced the
  sequence (the staleness tag the learner reports).

Priorities default to 1 (uniform proportional sampling); callers may pass
explicit per-sequence priorities (e.g. |reward - mean value|) to focus
replay on surprising sequences, the PER idea at sequence granularity.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from scalerl_tpu.genrl.engine import GenerationResult


def sequence_field_shapes(
    prompt_pad: int, response_pad: int
) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    """``seq_init`` field table for one (prompt, response) bucket pair."""
    import jax.numpy as jnp

    S = prompt_pad + response_pad
    R = response_pad
    return {
        "tokens": ((S,), jnp.int32),
        "behavior_logp": ((R,), jnp.float32),
        "value": ((R,), jnp.float32),
        "mask": ((R,), jnp.float32),
        "reward": ((), jnp.float32),
        "prompt_len": ((), jnp.int32),
        "generation": ((), jnp.int32),
    }


def pack_sequences(
    result: GenerationResult,
    rewards: np.ndarray,
    priorities: Optional[np.ndarray] = None,
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """``(fields [B, ...], priorities [B])`` ready for ``seq_add``.

    Host-side numpy only — the single host->device hop happens when
    ``seq_add``'s jit consumes the batch, alongside the learner dispatch.
    """
    B = result.sequences.shape[0]
    rewards = np.asarray(rewards, np.float32)
    if rewards.shape != (B,):
        raise ValueError(
            f"rewards must be [B={B}], got shape {rewards.shape}"
        )
    fields = {
        "tokens": result.sequences.astype(np.int32),
        "behavior_logp": result.behavior_logp.astype(np.float32),
        "value": result.values.astype(np.float32),
        "mask": result.mask.astype(np.float32),
        "reward": rewards,
        "prompt_len": result.prompt_len.astype(np.int32),
        "generation": np.full(B, result.generation, np.int32),
    }
    if priorities is None:
        priorities = np.ones(B, np.float32)
    else:
        priorities = np.maximum(
            np.asarray(priorities, np.float32), 1e-6
        )
    return fields, priorities
