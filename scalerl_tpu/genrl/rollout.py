"""Pack generation rounds into prioritized sequence-replay chunks.

The bridge between the generation engine (host numpy
:class:`~scalerl_tpu.genrl.engine.GenerationResult`) and
``data/sequence_replay.py``'s static-shape HBM buffer: every completed
sequence becomes one replay unit carrying everything the token-PPO learner
needs to recompute its loss off-policy —

- ``tokens`` ``[S]``: the full left-padded sequence (prompt + response),
  so the learner's forward recomputes logits over exactly the context the
  engine decoded against;
- ``behavior_logp`` / ``value`` / ``mask`` ``[R]``: the sampling-time
  logprobs (importance-ratio denominators), baselines, and real-token
  mask over the padded response bucket;
- ``reward`` / ``prompt_len`` / ``generation`` scalars: the sequence-level
  score, the left-pad offset, and the param generation that produced the
  sequence (the staleness tag the learner reports).

Priorities default to 1 (uniform proportional sampling); callers may pass
explicit per-sequence priorities (e.g. |reward - mean value|) to focus
replay on surprising sequences, the PER idea at sequence granularity.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from scalerl_tpu.genrl.engine import GenerationResult
from scalerl_tpu.runtime import telemetry


def sequence_field_shapes(
    prompt_pad: int, response_pad: int
) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    """``seq_init`` field table for one (prompt, response) bucket pair."""
    import jax.numpy as jnp

    S = prompt_pad + response_pad
    R = response_pad
    return {
        "tokens": ((S,), jnp.int32),
        "behavior_logp": ((R,), jnp.float32),
        "value": ((R,), jnp.float32),
        "mask": ((R,), jnp.float32),
        "reward": ((), jnp.float32),
        "prompt_len": ((), jnp.int32),
        "generation": ((), jnp.int32),
    }


def pack_sequences(
    result: GenerationResult,
    rewards: np.ndarray,
    priorities: Optional[np.ndarray] = None,
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """``(fields [B, ...], priorities [B])`` ready for ``seq_add``.

    Host-side numpy only — the single host->device hop happens when
    ``seq_add``'s jit consumes the batch, alongside the learner dispatch.
    """
    B = result.sequences.shape[0]
    rewards = np.asarray(rewards, np.float32)
    if rewards.shape != (B,):
        raise ValueError(
            f"rewards must be [B={B}], got shape {rewards.shape}"
        )
    fields = {
        "tokens": result.sequences.astype(np.int32),
        "behavior_logp": result.behavior_logp.astype(np.float32),
        "value": result.values.astype(np.float32),
        "mask": result.mask.astype(np.float32),
        "reward": rewards,
        "prompt_len": result.prompt_len.astype(np.int32),
        "generation": np.full(B, result.generation, np.int32),
    }
    if priorities is None:
        priorities = np.ones(B, np.float32)
    else:
        priorities = np.maximum(
            np.asarray(priorities, np.float32), 1e-6
        )
    return fields, priorities


class PackedCompletions(NamedTuple):
    """A variable-completion round re-batched into one bucket pair.

    The continuous engine finishes sequences one at a time (that is the
    point); the learner still wants rectangular batches.  This is the
    bridge: ``B`` completed sequences padded into the trainer's fixed
    (prompt_pad, response_pad) geometry — prompts LEFT-padded inside
    ``sequences`` (the learner-side layout every mask helper expects),
    RIGHT-padded in ``prompts`` (the task-scoring layout), responses
    zero-padded past each true length with a zeroed mask.  ``generations``
    is per-sequence: a continuous round can straddle a ``push_params``.
    """

    prompts: np.ndarray  # [B, prompt_pad] int32 right-padded (task layout)
    prompt_len: np.ndarray  # [B] int32
    sequences: np.ndarray  # [B, S] int32 left-padded prompt + response
    response_tokens: np.ndarray  # [B, response_pad] int32
    response_len: np.ndarray  # [B] int32
    behavior_logp: np.ndarray  # [B, response_pad] f32
    values: np.ndarray  # [B, response_pad] f32
    mask: np.ndarray  # [B, response_pad] f32
    generations: np.ndarray  # [B] int32 per-sequence admission generation

    @property
    def decode_tokens(self) -> int:
        return int(self.mask.sum())

    def fields(
        self, rewards: np.ndarray, priorities: Optional[np.ndarray] = None
    ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """``seq_add``-ready fields — same schema as :func:`pack_sequences`
        (one replay, either engine)."""
        B = self.sequences.shape[0]
        rewards = np.asarray(rewards, np.float32)
        if rewards.shape != (B,):
            raise ValueError(
                f"rewards must be [B={B}], got shape {rewards.shape}"
            )
        fields = {
            "tokens": self.sequences,
            "behavior_logp": self.behavior_logp,
            "value": self.values,
            "mask": self.mask,
            "reward": rewards,
            "prompt_len": self.prompt_len,
            "generation": self.generations,
        }
        if priorities is None:
            priorities = np.ones(B, np.float32)
        else:
            priorities = np.maximum(
                np.asarray(priorities, np.float32), 1e-6
            )
        return fields, priorities


def pack_completions(
    completions: List[Any],
    prompt_pad: int,
    response_pad: int,
    pad_token: int = 0,
) -> PackedCompletions:
    """Pack ``CompletedSequence``s (variable prompt/response lengths) into
    the fixed bucket-pair geometry the replay and learner compile against.

    A zero-completion round packs to an empty (``B == 0``) batch — every
    field keeps its trailing geometry, so callers can branch on ``B``
    without special-casing shapes.  A completion whose prompt or response
    exceeds the bucket pair (a foreign host shipped against a different
    ladder) is SHED — counted in ``genrl.oversize_shed`` and dropped from
    the packed batch — rather than crashing the learner's ingest loop.
    """
    fits = []
    shed = 0
    for c in completions:
        if int(c.prompt_len) > prompt_pad or (
            len(c.response_tokens) > response_pad
        ):
            shed += 1
            continue
        fits.append(c)
    if shed:
        telemetry.get_registry().counter("genrl.oversize_shed").inc(shed)
        telemetry.record_event(
            "oversize_shed",
            count=shed,
            prompt_pad=prompt_pad,
            response_pad=response_pad,
        )
    completions = fits
    B = len(completions)
    S = prompt_pad + response_pad
    prompts = np.full((B, prompt_pad), pad_token, np.int32)
    sequences = np.full((B, S), pad_token, np.int32)
    response = np.full((B, response_pad), pad_token, np.int32)
    logp = np.zeros((B, response_pad), np.float32)
    values = np.zeros((B, response_pad), np.float32)
    mask = np.zeros((B, response_pad), np.float32)
    plen = np.zeros((B,), np.int32)
    rlen = np.zeros((B,), np.int32)
    gens = np.zeros((B,), np.int32)
    for i, c in enumerate(completions):
        n = int(c.prompt_len)
        r = int(len(c.response_tokens))
        prompts[i, :n] = c.prompt[:n]
        sequences[i, prompt_pad - n : prompt_pad] = c.prompt[:n]
        sequences[i, prompt_pad : prompt_pad + r] = c.response_tokens
        response[i, :r] = c.response_tokens
        logp[i, :r] = c.behavior_logp
        values[i, :r] = c.values
        mask[i, :r] = 1.0
        plen[i] = n
        rlen[i] = r
        gens[i] = int(c.generation)
    return PackedCompletions(
        prompts=prompts,
        prompt_len=plen,
        sequences=sequences,
        response_tokens=response,
        response_len=rlen,
        behavior_logp=logp,
        values=values,
        mask=mask,
        generations=gens,
    )
