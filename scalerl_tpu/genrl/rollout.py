"""Pack generation rounds into prioritized sequence-replay chunks.

The bridge between the generation engine (host numpy
:class:`~scalerl_tpu.genrl.engine.GenerationResult`) and
``data/sequence_replay.py``'s static-shape HBM buffer: every completed
sequence becomes one replay unit carrying everything the token-PPO learner
needs to recompute its loss off-policy —

- ``tokens`` ``[S]``: the full left-padded sequence (prompt + response),
  so the learner's forward recomputes logits over exactly the context the
  engine decoded against;
- ``behavior_logp`` / ``value`` / ``mask`` ``[R]``: the sampling-time
  logprobs (importance-ratio denominators), baselines, and real-token
  mask over the padded response bucket;
- ``reward`` / ``prompt_len`` / ``generation`` scalars: the sequence-level
  score, the left-pad offset, and the param generation that produced the
  sequence (the staleness tag the learner reports).

Priorities default to 1 (uniform proportional sampling); callers may pass
explicit per-sequence priorities (e.g. |reward - mean value|) to focus
replay on surprising sequences, the PER idea at sequence granularity.

**Packed learner layout (ISSUE 15).**  The padded bucket-pair layout
above spends learner FLOPs on pad: a batch of short completions in a
large bucket attends to and backpropagates through mostly pad tokens.
:func:`greedy_pack` + :class:`PackedLearnerBatch` are the pad-free twin —
a jax-free greedy bin-packer lays several COMPACT sequences
(prompt + response, no intra-sequence pad) end to end into fixed
``[rows, pack_len]`` rows, with per-token ``segment_ids`` (1-based,
ascending, 0 = pad tail), per-segment position reset, and per-token
loss/behavior fields aligned at each token's own row offset.  The replay
unit becomes a ROW; the learner's forward runs segment-blocked causal
attention (``models/transformer.py::packed_attention_mask`` or the Pallas
segment flash kernel) so tokens never see their row-mates.  The packing
loop is host numpy by construction — lengths and tokens are already on
the host when sequences complete, and the device sees one batched
``seq_add`` upload of the assembled rows (graftlint JG001's sanctioned
shape).
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from scalerl_tpu.genrl.engine import GenerationResult
from scalerl_tpu.runtime import telemetry


def sequence_field_shapes(
    prompt_pad: int, response_pad: int
) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    """``seq_init`` field table for one (prompt, response) bucket pair."""
    import jax.numpy as jnp

    S = prompt_pad + response_pad
    R = response_pad
    return {
        "tokens": ((S,), jnp.int32),
        "behavior_logp": ((R,), jnp.float32),
        "value": ((R,), jnp.float32),
        "mask": ((R,), jnp.float32),
        "reward": ((), jnp.float32),
        "prompt_len": ((), jnp.int32),
        "generation": ((), jnp.int32),
    }


def pack_sequences(
    result: GenerationResult,
    rewards: np.ndarray,
    priorities: Optional[np.ndarray] = None,
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """``(fields [B, ...], priorities [B])`` ready for ``seq_add``.

    Host-side numpy only — the single host->device hop happens when
    ``seq_add``'s jit consumes the batch, alongside the learner dispatch.
    """
    B = result.sequences.shape[0]
    rewards = np.asarray(rewards, np.float32)
    if rewards.shape != (B,):
        raise ValueError(
            f"rewards must be [B={B}], got shape {rewards.shape}"
        )
    fields = {
        "tokens": result.sequences.astype(np.int32),
        "behavior_logp": result.behavior_logp.astype(np.float32),
        "value": result.values.astype(np.float32),
        "mask": result.mask.astype(np.float32),
        "reward": rewards,
        "prompt_len": result.prompt_len.astype(np.int32),
        "generation": np.full(B, result.generation, np.int32),
    }
    if priorities is None:
        priorities = np.ones(B, np.float32)
    else:
        priorities = np.maximum(
            np.asarray(priorities, np.float32), 1e-6
        )
    return fields, priorities


class PackedCompletions(NamedTuple):
    """A variable-completion round re-batched into one bucket pair.

    The continuous engine finishes sequences one at a time (that is the
    point); the learner still wants rectangular batches.  This is the
    bridge: ``B`` completed sequences padded into the trainer's fixed
    (prompt_pad, response_pad) geometry — prompts LEFT-padded inside
    ``sequences`` (the learner-side layout every mask helper expects),
    RIGHT-padded in ``prompts`` (the task-scoring layout), responses
    zero-padded past each true length with a zeroed mask.  ``generations``
    is per-sequence: a continuous round can straddle a ``push_params``.
    """

    prompts: np.ndarray  # [B, prompt_pad] int32 right-padded (task layout)
    prompt_len: np.ndarray  # [B] int32
    sequences: np.ndarray  # [B, S] int32 left-padded prompt + response
    response_tokens: np.ndarray  # [B, response_pad] int32
    response_len: np.ndarray  # [B] int32
    behavior_logp: np.ndarray  # [B, response_pad] f32
    values: np.ndarray  # [B, response_pad] f32
    mask: np.ndarray  # [B, response_pad] f32
    generations: np.ndarray  # [B] int32 per-sequence admission generation

    @property
    def decode_tokens(self) -> int:
        return int(self.mask.sum())

    def fields(
        self, rewards: np.ndarray, priorities: Optional[np.ndarray] = None
    ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """``seq_add``-ready fields — same schema as :func:`pack_sequences`
        (one replay, either engine)."""
        B = self.sequences.shape[0]
        rewards = np.asarray(rewards, np.float32)
        if rewards.shape != (B,):
            raise ValueError(
                f"rewards must be [B={B}], got shape {rewards.shape}"
            )
        fields = {
            "tokens": self.sequences,
            "behavior_logp": self.behavior_logp,
            "value": self.values,
            "mask": self.mask,
            "reward": rewards,
            "prompt_len": self.prompt_len,
            "generation": self.generations,
        }
        if priorities is None:
            priorities = np.ones(B, np.float32)
        else:
            priorities = np.maximum(
                np.asarray(priorities, np.float32), 1e-6
            )
        return fields, priorities


def pack_completions(
    completions: List[Any],
    prompt_pad: int,
    response_pad: int,
    pad_token: int = 0,
) -> PackedCompletions:
    """Pack ``CompletedSequence``s (variable prompt/response lengths) into
    the fixed bucket-pair geometry the replay and learner compile against.

    A zero-completion round packs to an empty (``B == 0``) batch — every
    field keeps its trailing geometry, so callers can branch on ``B``
    without special-casing shapes.  A completion whose prompt or response
    exceeds the bucket pair (a foreign host shipped against a different
    ladder) is SHED — counted in ``genrl.oversize_shed`` and dropped from
    the packed batch — rather than crashing the learner's ingest loop.
    """
    fits = []
    shed = 0
    for c in completions:
        if int(c.prompt_len) > prompt_pad or (
            len(c.response_tokens) > response_pad
        ):
            shed += 1
            continue
        fits.append(c)
    if shed:
        telemetry.get_registry().counter("genrl.oversize_shed").inc(shed)
        telemetry.record_event(
            "oversize_shed",
            count=shed,
            prompt_pad=prompt_pad,
            response_pad=response_pad,
        )
    completions = fits
    B = len(completions)
    S = prompt_pad + response_pad
    prompts = np.full((B, prompt_pad), pad_token, np.int32)
    sequences = np.full((B, S), pad_token, np.int32)
    response = np.full((B, response_pad), pad_token, np.int32)
    logp = np.zeros((B, response_pad), np.float32)
    values = np.zeros((B, response_pad), np.float32)
    mask = np.zeros((B, response_pad), np.float32)
    plen = np.zeros((B,), np.int32)
    rlen = np.zeros((B,), np.int32)
    gens = np.zeros((B,), np.int32)
    for i, c in enumerate(completions):
        n = int(c.prompt_len)
        r = int(len(c.response_tokens))
        prompts[i, :n] = c.prompt[:n]
        sequences[i, prompt_pad - n : prompt_pad] = c.prompt[:n]
        sequences[i, prompt_pad : prompt_pad + r] = c.response_tokens
        response[i, :r] = c.response_tokens
        logp[i, :r] = c.behavior_logp
        values[i, :r] = c.values
        mask[i, :r] = 1.0
        plen[i] = n
        rlen[i] = r
        gens[i] = int(c.generation)
    return PackedCompletions(
        prompts=prompts,
        prompt_len=plen,
        sequences=sequences,
        response_tokens=response,
        response_len=rlen,
        behavior_logp=logp,
        values=values,
        mask=mask,
        generations=gens,
    )


# ---------------------------------------------------------------------------
# pad-free packed learner layout (ISSUE 15)


def packed_field_shapes(
    pack_len: int,
) -> Dict[str, Tuple[Tuple[int, ...], Any]]:
    """``seq_init`` field table for packed learner ROWS.

    Every field is per-token over the row: ``segment_ids`` (0 = pad,
    1..K ascending per packed sequence), ``positions`` (reset to 0 at
    every segment start — the packed twin of ``sequence_positions``),
    ``mask`` (the LOSS mask: 1 exactly on response tokens), and
    ``behavior_logp``/``value``/``reward``/``generation`` aligned at each
    response token's own row offset (zero elsewhere).  The names shared
    with :func:`sequence_field_shapes` keep their meaning; the learner
    dispatches on the presence of ``segment_ids``.
    """
    import jax.numpy as jnp

    S = pack_len
    return {
        "tokens": ((S,), jnp.int32),
        "segment_ids": ((S,), jnp.int32),
        "positions": ((S,), jnp.int32),
        "behavior_logp": ((S,), jnp.float32),
        "value": ((S,), jnp.float32),
        "mask": ((S,), jnp.float32),
        "reward": ((S,), jnp.float32),
        "generation": ((S,), jnp.int32),
    }


def greedy_pack(
    lengths: Sequence[int], pack_len: int
) -> Tuple[List[List[int]], List[int]]:
    """First-fit-decreasing bin packing of sequence ``lengths`` into rows
    of capacity ``pack_len``.

    Returns ``(rows, shed)``: ``rows`` is a list of index lists (each
    row's members, in placement order), ``shed`` the indices whose length
    exceeds ``pack_len`` outright (counted by the caller — never an
    error).  Pure host arithmetic over python ints: deterministic for a
    given input, no device value anywhere (the JG001 fixture pair pins
    this shape).
    """
    order = sorted(
        range(len(lengths)), key=lambda i: (-int(lengths[i]), i)
    )
    rows: List[List[int]] = []
    free: List[int] = []  # remaining capacity per row
    shed: List[int] = []
    for i in order:
        n = int(lengths[i])
        if n > pack_len:
            shed.append(i)
            continue
        for r, cap in enumerate(free):
            if n <= cap:
                rows[r].append(i)
                free[r] = cap - n
                break
        else:
            rows.append([i])
            free.append(pack_len - n)
    return rows, sorted(shed)


class PackedLearnerBatch(NamedTuple):
    """``N`` packed learner rows, ``seq_add``-ready.

    ``rows == 0`` is a legitimate zero-completion outcome: every field
    keeps its trailing ``[pack_len]`` geometry so callers can branch on
    ``rows`` without special-casing shapes.
    """

    tokens: np.ndarray  # [N, S] int32 compact prompt+response segments
    segment_ids: np.ndarray  # [N, S] int32, 0 = pad tail
    positions: np.ndarray  # [N, S] int32, reset per segment
    behavior_logp: np.ndarray  # [N, S] f32 at response-token offsets
    value: np.ndarray  # [N, S] f32 at response-token offsets
    mask: np.ndarray  # [N, S] f32 loss mask (response tokens)
    reward: np.ndarray  # [N, S] f32 sequence reward at response offsets
    generation: np.ndarray  # [N, S] int32 at segment-token offsets
    priorities: np.ndarray  # [N] f32 (max over member priorities)
    sequences_packed: int  # completions that made it into rows
    sequences_shed: int  # completions longer than pack_len (dropped)

    @property
    def rows(self) -> int:
        return int(self.tokens.shape[0])

    @property
    def pack_len(self) -> int:
        return int(self.tokens.shape[1])

    @property
    def real_tokens(self) -> int:
        """Prompt + response tokens actually occupying row slots."""
        return int((self.segment_ids > 0).sum())

    @property
    def decode_tokens(self) -> int:
        return int(self.mask.sum())

    @property
    def pad_ratio(self) -> float:
        """Pad tokens / total tokens over the row batch (0.0 on empty)."""
        total = self.tokens.size
        return 1.0 - self.real_tokens / total if total else 0.0

    def fields(self) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """``(fields, priorities)`` matching :func:`packed_field_shapes`
        — same call shape as :meth:`PackedCompletions.fields`, one
        replay, either layout."""
        return {
            "tokens": self.tokens,
            "segment_ids": self.segment_ids,
            "positions": self.positions,
            "behavior_logp": self.behavior_logp,
            "value": self.value,
            "mask": self.mask,
            "reward": self.reward,
            "generation": self.generation,
        }, self.priorities

    def bucketed(self, n_rows: int) -> "PackedLearnerBatch":
        """Pad the row axis up to ``n_rows`` with all-pad rows (segment
        id 0 everywhere, priority 0 = the replay's empty-slot sentinel,
        never sampled) so ``seq_add`` compiles once per row bucket
        instead of once per arrival count."""
        n = self.rows
        if n_rows < n:
            raise ValueError(
                f"row bucket {n_rows} below packed row count {n}"
            )
        if n_rows == n:
            return self
        pad = n_rows - n

        def _pad2(a):
            return np.concatenate(
                [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0
            )

        return self._replace(
            tokens=_pad2(self.tokens),
            segment_ids=_pad2(self.segment_ids),
            positions=_pad2(self.positions),
            behavior_logp=_pad2(self.behavior_logp),
            value=_pad2(self.value),
            mask=_pad2(self.mask),
            reward=_pad2(self.reward),
            generation=_pad2(self.generation),
            priorities=_pad2(self.priorities),
        )


def pack_learner_batch(
    prompts: Sequence[np.ndarray],
    responses: Sequence[np.ndarray],
    behavior_logp: Sequence[np.ndarray],
    values: Sequence[np.ndarray],
    rewards: np.ndarray,
    generations: np.ndarray,
    pack_len: int,
    pad_token: int = 0,
    priorities: Optional[np.ndarray] = None,
) -> PackedLearnerBatch:
    """Bin-pack ``B`` completed sequences into learner rows.

    Inputs are per-sequence TRUE-length host arrays (prompt tokens,
    response tokens, and the response-aligned logp/value vectors).  The
    whole function is numpy over python loops — the packing loop never
    touches a device value; the ONE device upload is the caller's batched
    ``seq_add`` of the returned fields.  Sequences longer than
    ``pack_len`` are shed (``genrl.pack_oversize_shed`` + flight event),
    the :func:`pack_completions` convention.
    """
    B = len(prompts)
    rewards = np.asarray(rewards, np.float32)
    if rewards.shape != (B,):
        raise ValueError(f"rewards must be [B={B}], got {rewards.shape}")
    generations = np.asarray(generations, np.int32)
    if priorities is None:
        prio_in = np.ones(B, np.float32)
    else:
        prio_in = np.maximum(np.asarray(priorities, np.float32), 1e-6)
    lengths = [len(prompts[i]) + len(responses[i]) for i in range(B)]
    rows, shed = greedy_pack(lengths, pack_len)
    if shed:
        telemetry.get_registry().counter("genrl.pack_oversize_shed").inc(
            len(shed)
        )
        telemetry.record_event(
            "pack_oversize_shed", count=len(shed), pack_len=pack_len
        )
    N, S = len(rows), pack_len
    tokens = np.full((N, S), pad_token, np.int32)
    seg = np.zeros((N, S), np.int32)
    pos = np.zeros((N, S), np.int32)
    logp = np.zeros((N, S), np.float32)
    val = np.zeros((N, S), np.float32)
    mask = np.zeros((N, S), np.float32)
    rew = np.zeros((N, S), np.float32)
    gens = np.zeros((N, S), np.int32)
    prio = np.zeros((N,), np.float32)
    for r, members in enumerate(rows):
        off = 0
        for s_idx, i in enumerate(members, start=1):
            p = np.asarray(prompts[i], np.int32)
            t = np.asarray(responses[i], np.int32)
            n, m = len(p), len(t)
            L = n + m
            tokens[r, off : off + n] = p
            tokens[r, off + n : off + L] = t
            seg[r, off : off + L] = s_idx
            pos[r, off : off + L] = np.arange(L)
            gens[r, off : off + L] = int(generations[i])
            resp = slice(off + n, off + L)
            logp[r, resp] = np.asarray(behavior_logp[i], np.float32)[:m]
            val[r, resp] = np.asarray(values[i], np.float32)[:m]
            mask[r, resp] = 1.0
            rew[r, resp] = rewards[i]
            prio[r] = max(prio[r], prio_in[i])
            off += L
    return PackedLearnerBatch(
        tokens=tokens,
        segment_ids=seg,
        positions=pos,
        behavior_logp=logp,
        value=val,
        mask=mask,
        reward=rew,
        generation=gens,
        priorities=prio,
        sequences_packed=B - len(shed),
        sequences_shed=len(shed),
    )


def packed_rows_from_result(
    result: GenerationResult,
    rewards: np.ndarray,
    pack_len: int,
    pad_token: int = 0,
    priorities: Optional[np.ndarray] = None,
) -> PackedLearnerBatch:
    """Cohort-engine bridge: unpad a :class:`GenerationResult` back to
    true-length sequences and bin-pack them (the packed twin of
    :func:`pack_sequences`)."""
    B = result.sequences.shape[0]
    P = result.prompt_pad
    prompts, responses, logps, vals = [], [], [], []
    for i in range(B):
        n = int(result.prompt_len[i])
        r = int(result.response_len[i])
        prompts.append(result.sequences[i, P - n : P].astype(np.int32))
        responses.append(result.response_tokens[i, :r].astype(np.int32))
        logps.append(result.behavior_logp[i, :r])
        vals.append(result.values[i, :r])
    return pack_learner_batch(
        prompts,
        responses,
        logps,
        vals,
        rewards,
        np.full(B, result.generation, np.int32),
        pack_len,
        pad_token=pad_token,
        priorities=priorities,
    )


def packed_rows_from_completions(
    packed: PackedCompletions,
    rewards: np.ndarray,
    pack_len: int,
    pad_token: int = 0,
    priorities: Optional[np.ndarray] = None,
) -> PackedLearnerBatch:
    """Continuous/disagg bridge: re-pack a :class:`PackedCompletions`
    round (already scored against its wire/task layouts) into learner
    rows — ``pack_completions`` keeps its layouts, the LEARNER consumes
    rows."""
    B = packed.sequences.shape[0]
    prompts, responses, logps, vals = [], [], [], []
    for i in range(B):
        n = int(packed.prompt_len[i])
        r = int(packed.response_len[i])
        prompts.append(packed.prompts[i, :n].astype(np.int32))
        responses.append(packed.response_tokens[i, :r].astype(np.int32))
        logps.append(packed.behavior_logp[i, :r])
        vals.append(packed.values[i, :r])
    return pack_learner_batch(
        prompts,
        responses,
        logps,
        vals,
        rewards,
        packed.generations,
        pack_len,
        pad_token=pad_token,
        priorities=priorities,
    )
