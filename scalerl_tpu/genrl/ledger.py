"""Durable learner ledger: the jax-free twin of ``utils/checkpoint.py``.

The disaggregated :class:`~scalerl_tpu.genrl.disagg.SequenceLearner` is
jax-free by design, so it cannot ride the orbax checkpointer — but a
preempted learner must not lose its lease table, dedup keys, or accepted
sequences.  This module applies the exact PR 2 crash-safety idiom to a
single codec-v2 frame on disk:

- a save NEVER has a window with no complete ledger on disk: the new
  state lands in ``path.tmp`` first, the previous ledger is *rotated* to
  ``path.prev`` (… ``path.prevK``) before the atomic ``rename(tmp, path)``;
- a sha256 ``integrity_manifest.json`` is written INSIDE the directory
  before the rename, so a ledger is never visible without its manifest;
  restore verifies the frame bytes against it — a flipped bit or a
  truncated file is *detected*, never silently unpacked;
- a restore that finds the latest dir corrupt/partial falls back through
  the retained ``.prev`` chain instead of failing the run.

The payload is one codec-v2 frame (``fleet/framing.py``): numpy arrays,
int-keyed dicts, and nested containers round-trip bit-exact, and the
frame's own CRC gives a second, independent corruption tripwire under
the manifest's sha256.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any, Dict, List

from scalerl_tpu.fleet.framing import ProtocolError, pack_message, unpack_message
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# same manifest filename as utils/checkpoint.py: the integrity idiom is one
# idiom, whether the bytes underneath are orbax shards or a codec-v2 frame
MANIFEST_NAME = "integrity_manifest.json"
LEDGER_FILE = "ledger.bin"


class LedgerIntegrityError(RuntimeError):
    """Ledger bytes do not match the manifest digest (torn write, flipped
    bit, truncation — anything between save and restore)."""


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _prev_path(path: str, k: int) -> str:
    """k-th displaced ledger: ``path.prev``, ``path.prev2``, ..."""
    return path + (".prev" if k == 1 else f".prev{k}")


def ledger_fallbacks(path: str) -> List[str]:
    """Existing retained predecessors of ``path``, newest first."""
    out: List[str] = []
    k = 1
    while True:
        p = _prev_path(path, k)
        if not os.path.exists(p):
            break
        out.append(p)
        k += 1
    return out


def save_ledger(path: str, state: Dict[str, Any], keep_last: int = 2) -> str:
    """Write ``state`` to ``path`` (write-new-then-rotate). Returns the path.

    ``state`` is any codec-v2-encodable tree (numpy arrays, dicts with
    str/int keys, lists, scalars).  ``keep_last`` retained predecessors
    survive as ``path.prev`` … ``path.prevN`` for the fallback chain.
    """
    path = os.path.abspath(path)
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    frame = pack_message(state, compress=True)
    with open(os.path.join(tmp, LEDGER_FILE), "wb") as f:
        f.write(frame)
    manifest = {
        "format": 1,
        "leaves": [{"path": LEDGER_FILE, "sha256": _digest(frame)}],
    }
    with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=1)
    # rotate the retention chain oldest-first so each rename target is free
    if os.path.exists(path):
        oldest = _prev_path(path, max(keep_last, 1))
        if os.path.exists(oldest):
            shutil.rmtree(oldest)
        for k in range(max(keep_last, 1) - 1, 0, -1):
            src = _prev_path(path, k)
            if os.path.exists(src):
                os.rename(src, _prev_path(path, k + 1))
        os.rename(path, _prev_path(path, 1))
    os.rename(tmp, path)
    if keep_last <= 0:
        prev = _prev_path(path, 1)
        if os.path.exists(prev):
            shutil.rmtree(prev)
    inj = _chaos_active()
    if inj is not None:
        # chaos: leave the freshly-landed ledger partial (a preemption
        # mid-flush) — restores must fall back through the .prev chain
        inj.corrupt_checkpoint(path, site="ledger")
    _telemetry().record_event("ledger_save", path=path)
    _telemetry().get_registry().counter("ledger.saves").inc()
    return path


def _restore(path: str) -> Dict[str, Any]:
    fpath = os.path.join(path, LEDGER_FILE)
    with open(fpath, "rb") as f:
        frame = f.read()
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        # a save is never visible without its manifest — a missing one
        # means the rename raced a corruption; the .prev chain has truth
        raise LedgerIntegrityError(f"ledger {path} has no manifest")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        expected = {
            leaf["path"]: leaf["sha256"] for leaf in manifest["leaves"]
        }[LEDGER_FILE]
    except (ValueError, KeyError, TypeError) as e:
        raise LedgerIntegrityError(
            f"unreadable ledger manifest at {mpath}: {e}"
        ) from e
    if _digest(frame) != expected:
        raise LedgerIntegrityError(
            f"ledger {fpath} failed sha256 verification against its "
            "save-time manifest"
        )
    try:
        state = unpack_message(frame)
    except ProtocolError as e:  # CRC/structure — should be unreachable
        raise LedgerIntegrityError(f"undecodable ledger frame: {e}") from e
    if not isinstance(state, dict):
        raise LedgerIntegrityError(
            f"ledger frame decoded to {type(state).__name__}, not dict"
        )
    return state


def load_ledger(path: str, fallback: bool = True) -> Dict[str, Any]:
    """Restore the ledger at ``path``; on corruption fall back through the
    retained ``.prev`` chain (the crash-safety contract of
    :func:`save_ledger`).  The original error is chained if every
    candidate fails; ``FileNotFoundError`` if none ever existed."""
    path = os.path.abspath(path)
    candidates = [path] + (ledger_fallbacks(path) if fallback else [])
    first_err = None
    for cand in candidates:
        try:
            state = _restore(cand)
            _telemetry().record_event(
                "ledger_restore", path=cand, fallback=cand != path
            )
            _telemetry().get_registry().counter("ledger.restores").inc()
            return state
        except (OSError, LedgerIntegrityError) as e:
            if first_err is None:
                first_err = e
            if fallback and cand != candidates[-1]:
                _telemetry().record_event(
                    "ledger_fallback", path=cand, error=repr(e)
                )
                _telemetry().get_registry().counter("ledger.fallbacks").inc()
                logger.warning(
                    "ledger %s failed to restore (%r); falling back to %s",
                    cand, e, candidates[candidates.index(cand) + 1],
                )
    assert first_err is not None
    raise first_err


def ledger_exists(path: str) -> bool:
    """True when ``path`` or any retained predecessor holds a ledger."""
    path = os.path.abspath(path)
    return any(
        os.path.exists(os.path.join(p, LEDGER_FILE))
        for p in [path] + ledger_fallbacks(path)
    )


def _chaos_active():
    from scalerl_tpu.runtime import chaos

    return chaos.active()


def _telemetry():
    from scalerl_tpu.runtime import telemetry

    return telemetry
