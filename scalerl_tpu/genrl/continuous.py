"""Continuous-batching decode: a persistent lane pool over a paged KV cache.

The vLLM shape of the generation plane (ISSUE 11; MindSpeed RL argues the
generation tier is where sequence-RL throughput is won, arxiv 2507.19017):
instead of fixed cohorts where every lane waits for the slowest sequence,
:class:`ContinuousEngine` runs a FIXED number of decode lanes forever and
the host swaps *sequences* through them —

- **macro-steps** — ONE jitted program (compiled once; lane count, page
  geometry and ``steps_per_macro`` are all static) advances every lane
  ``steps_per_macro`` tokens: sample from the carried last-logits, latch
  EOS / response-budget, scatter the new K/V into pool pages, attend
  through the page table (``ops/pallas_paged_attention.py`` behind the
  ``paged_attn_fn`` seam), carry the fresh logits.  The host dispatches
  once and reads back once — PR 10's one-batched-read round discipline at
  macro-step granularity, under ``steady_state_guard()`` once warm;
- **pipelined admission/decode** (ISSUE 14) — ``steps_in_flight`` macro
  dispatches stay in flight with the host read lagging dispatch by K-1
  (the PR 1 ``MetricsPipeline`` idiom applied to the decode loop), so
  harvest, cache lookups, admission bookkeeping and prefill overlap
  device decode instead of serializing with it.  ``K=1`` is the old
  fully-synchronous semantics, parity-pinned.  A lane that latches done
  mid-flight keeps null-writing until its macro is read — exactly the
  within-macro dead-lane behavior, stretched K-1 macros;
- **continuous admission** — between macro-steps the host harvests lanes
  that finished (frees their pages immediately — KV memory tracks LIVE
  tokens), then admits queued prompts into the freed lanes through the
  serving batcher's flush-on-size-or-deadline predicate
  (:meth:`DynamicBatcher.poll_batch`) and the shared pow2 bucket ladder.
  Admission looks up the :class:`~scalerl_tpu.genrl.prefix_cache
  .PrefixCache` first: the longest cached full-page prefix is *shared*
  into the lane's table (a refcount bump, zero FLOPs) and only the
  uncached tail is prefilled — through the local-attention prefill
  program when nothing matched, or the shared-table tail-prefill program
  (gather-through-table attention) on a hit;
- **group sampling (CoW fork)** — :meth:`submit_group` admits one prompt
  into ``n`` lanes: the leader prefills (tail only, as above), the other
  ``n-1`` lanes map the SAME full prompt pages copy-on-write and only the
  last partial page is physically copied per lane by a small jitted
  page-copy program — so a GRPO-shaped round pays ~1/n of its prefill;
- **paged KV** — ``models/transformer.py``'s ``PagedKVCache`` pools plus
  the jax-free refcounting :class:`~scalerl_tpu.genrl.paging
  .PageAllocator`: admission reserves a sequence's worst-case pages
  (exhaustion backpressures, never corrupts; shared pages count against
  EVERY holder's reservation, so sharing never loosens the guarantee)
  while physical pages are drawn lazily as contexts grow.

- **speculative decoding** (ISSUE 16, ``spec_k > 0``) — the sequential-
  depth lever: each pass, every live lane proposes up to ``spec_k``
  continuation tokens from its own jax-free n-gram table
  (:class:`~scalerl_tpu.genrl.drafter.NgramDrafter` — no second model,
  nothing extra rides the snapshot plane), and ONE batched verify program
  scores all proposed tokens through the shared-table tail-prefill path:
  it samples the bonus token from the carried logits in-program, feeds
  ``[t0, d1..dk]`` at positions ``cl..cl+k``, accepts the longest draft
  prefix under the exact speculative-sampling rule (greedy match at
  temperature 0; accept-with-prob ``pi(d)`` plus a carried banned-token
  residual resample at temperature > 0 — the output distribution is
  UNCHANGED either way), and advances each lane ``1..k+1`` tokens.
  Rejected tails roll back host-side via page-cursor rewind
  (:func:`~scalerl_tpu.genrl.paging.rewind_pages` — a refcount
  decrement, never a mutation, so CoW-shared pages are untouched); the
  device needs no rollback at all because attention never reads past a
  lane's cursor and the next pass's writes overwrite the rejected slots.
  Spec mode is inherently synchronous (drafting pass ``m+1`` needs pass
  ``m``'s emitted tokens), so it runs at ``steps_in_flight = 1``
  semantics regardless of the configured depth.

Sampling math is shared with the fixed-cohort engine (``engine.py``'s
``adjust_logits``/``sample_tokens``), so at temperature 0 the two engines
are token-identical on the same params — the parity the acceptance tests
pin, with the prefix cache on or off, speculation on or off.  A sequence is tagged with the param
generation that admitted it; a ``push_params`` mid-flight rotates the
policy under lanes already decoding (inherent to continuous batching; the
token-PPO ratios absorb it exactly like actor lag) and FLUSHES the prefix
cache — cached K/V belongs to the generation that wrote it.
"""

from __future__ import annotations

import time
from collections import deque
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    List,
    NamedTuple,
    Optional,
    Tuple,
)

import jax
import jax.numpy as jnp
import numpy as np

from scalerl_tpu.genrl.engine import (
    GenerationConfig,
    ParamSnapshotPlane,
    adjust_logits,
    sample_tokens,
)
from scalerl_tpu.genrl.drafter import NgramDrafter
from scalerl_tpu.genrl.paging import PageAllocator, rewind_pages
from scalerl_tpu.genrl.prefix_cache import PrefixCache
from scalerl_tpu.models.transformer import (
    PagedKVCache,
    TransformerPolicy,
    init_paged_kv_cache,
    prompt_attention_mask,
)
from scalerl_tpu.ops.pallas_paged_attention import make_paged_attn_fn
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.runtime.device_loop import resolve_iter_mode
from scalerl_tpu.runtime.dispatch import steady_state_guard
from scalerl_tpu.serving.batcher import (
    DynamicBatcher,
    ServingConfig,
    ServingRequest,
)
from scalerl_tpu.utils.buckets import bucket_for, default_buckets

# module seams: tests monkeypatch these to count host transfers and assert
# the one-upload-one-read-per-macro-step invariant
_device_put = jax.device_put
_device_get = jax.device_get


@dataclass
class ContinuousConfig(GenerationConfig):
    """Fixed-cohort knobs plus the continuous-batching geometry.

    ``num_pages = 0`` sizes the pool for every lane's worst case (null
    page included) — no admission backpressure by default; smaller pools
    trade admission latency for KV memory and are exercised by the
    exhaustion tests.  ``admit_max_wait_s`` is the deadline half of the
    admission flush predicate (0 = admit the moment lanes are free).
    """

    lanes: int = 64
    page_size: int = 16
    num_pages: int = 0
    steps_per_macro: int = 8
    admit_max_wait_s: float = 0.0
    max_pending: int = 0  # bounded admission queue; 0 = unbounded
    paged_attn: str = "auto"  # pallas | xla | auto (backend-resolved)
    # Admission batching: hold admission until at least this many lanes are
    # free (unless the pool is fully idle), so prefill dispatches amortize
    # over bigger batches instead of firing per macro-step for a lane or
    # two.  1 = admit the moment anything frees (lowest latency); ~lanes/8
    # trades a little occupancy for much cheaper admission (the measured
    # CPU sweet spot; see docs/SEQUENCE_RL.md "Continuous batching").
    min_free_lanes: int = 1
    # Macro-step pipelining (ISSUE 14): K macro dispatches stay in flight
    # with the host read lagging by K-1, so harvest/admission/prefill
    # overlap device decode.  1 = the old read-after-every-dispatch
    # semantics (parity-pinned); 2 is the measured sweet spot — deeper
    # only lengthens harvest lag without adding overlap.
    steps_in_flight: int = 2
    # Shared-prefix KV reuse (ISSUE 14): cache full prompt pages keyed by
    # rolling block hash, share them copy-on-write into later admissions
    # of the same prefix.  Off = every admission prefills from scratch
    # (the cache-off twin the token-identity tests compare against).
    prefix_cache: bool = True
    # Speculative decoding (ISSUE 16): 0 compiles speculation out entirely
    # (the plain macro-step engine, parity-pinned); k > 0 drafts up to k
    # tokens per lane per pass from the lane's own n-gram table and
    # verifies them in ONE batched pass.  Wins when the workload's
    # acceptance rate clears ~1/(k+1); pure-noise text degrades toward
    # one token per pass (see docs/SEQUENCE_RL.md "Speculative decoding").
    spec_k: int = 0
    # n-gram width the self-drafter matches against the context tail.
    spec_ngram: int = 3

    def validate(self) -> None:
        super().validate()
        if self.lanes < 1:
            raise ValueError(f"lanes must be >= 1, got {self.lanes}")
        if self.min_free_lanes < 1 or self.min_free_lanes > self.lanes:
            raise ValueError(
                f"min_free_lanes must be in [1, lanes], got "
                f"{self.min_free_lanes}"
            )
        if self.page_size < 1:
            raise ValueError(
                f"page_size must be >= 1, got {self.page_size}"
            )
        if self.steps_per_macro < 1:
            raise ValueError(
                f"steps_per_macro must be >= 1, got {self.steps_per_macro}"
            )
        if self.num_pages < 0:
            raise ValueError(
                f"num_pages must be >= 0 (0 = auto), got {self.num_pages}"
            )
        if self.steps_in_flight < 1:
            raise ValueError(
                f"steps_in_flight must be >= 1, got {self.steps_in_flight}"
            )
        if self.spec_k < 0:
            raise ValueError(
                f"spec_k must be >= 0 (0 = speculation off), got "
                f"{self.spec_k}"
            )
        if self.spec_ngram < 1:
            raise ValueError(
                f"spec_ngram must be >= 1, got {self.spec_ngram}"
            )


class CompletedSequence(NamedTuple):
    """One finished lane occupancy, assembled host-side across the
    macro-steps it spanned."""

    prompt: np.ndarray  # [n] int32 true prompt tokens
    prompt_len: int
    response_tokens: np.ndarray  # [r] int32 real tokens only
    behavior_logp: np.ndarray  # [r] f32
    values: np.ndarray  # [r] f32
    generation: int  # param generation at admission
    submit_time: float
    admit_time: float
    finish_time: float
    # opaque caller tag carried from submit() to harvest — the disagg
    # shell routes prompt-lease ids through it so out-of-order completions
    # still close the lease that admitted them
    tag: Any = None


@dataclass
class _Lane:
    """Host-side record of one lane's current occupancy."""

    busy: bool = False
    prompt: Optional[np.ndarray] = None
    prompt_len: int = 0
    context_len: int = 0
    pages: List[int] = field(default_factory=list)
    reserved: int = 0
    tokens: List[np.ndarray] = field(default_factory=list)
    logps: List[np.ndarray] = field(default_factory=list)
    values: List[np.ndarray] = field(default_factory=list)
    generation: int = 0
    submit_time: float = 0.0
    admit_time: float = 0.0
    tag: Any = None
    # index of the first macro dispatch that includes this occupancy: a
    # pipelined read of an OLDER macro must not be applied to it (the
    # lane id may have been recycled from a finished occupancy)
    admit_macro: int = 0


class ContinuousEngine(ParamSnapshotPlane):
    """Persistent continuous-batching decode loop over a paged KV cache.

    ``model``: a token-mode :class:`TransformerPolicy` whose ``max_len``
    covers ``prompt_bucket_max + response_bucket``.  The engine compiles
    exactly ONE decode macro-step program (lane count static), one prefill
    program per (bucket, admit-bucket) pair — local-attention for cold
    prompts, shared-table for cached-prefix tails — and one page-copy fork
    program per admit bucket; the ``_decode_traces`` / ``_prefill_traces``
    / ``_fork_traces`` counters let tests pin zero retraces after warmup.
    """

    def __init__(
        self,
        model: TransformerPolicy,
        params: Any,
        config: ContinuousConfig,
        iter_mode: str = "auto",
        dispatch_guard: Optional[Callable[[], Any]] = None,
    ) -> None:
        config.validate()
        if model.vocab_size is None:
            raise ValueError(
                "ContinuousEngine needs a token-mode TransformerPolicy "
                "(vocab_size set); got a feature-embedding model"
            )
        self.config = config
        self.model = model
        self.iter_mode = resolve_iter_mode(iter_mode)
        self._dispatch_guard = dispatch_guard or nullcontext
        self._paged_attn = make_paged_attn_fn(config.paged_attn)
        if model.paged_attn_fn is None:
            # route the model's paged decode reads through the resolved impl
            # (clone shares the param structure: same names, same shapes)
            self.model = model.clone(paged_attn_fn=self._paged_attn)
        self._init_param_plane(params)
        L = config.lanes
        ps = config.page_size
        self._max_prompt_bucket = bucket_for(
            config.max_prompt_len, config.resolved_prompt_buckets()
        )
        # the response budget is the response BUCKET, mirroring the fixed
        # cohort engine (its scan runs bucket_for(max_new_tokens) steps)
        self._response_budget = bucket_for(
            config.max_new_tokens, config.resolved_response_buckets()
        )
        max_context = self._max_prompt_bucket + self._response_budget
        if model.max_len < max_context:
            raise ValueError(
                f"model.max_len ({model.max_len}) must cover prompt bucket "
                f"+ response budget ({max_context})"
            )
        self._pages_per_lane = -(-max_context // ps)  # table width (static)
        num_pages = config.num_pages or (L * self._pages_per_lane + 1)
        self.allocator = PageAllocator(num_pages, ps)
        self._worst_pages = self.allocator.pages_for_tokens(max_context)
        self._prefix_cache: Optional[PrefixCache] = None
        if config.prefix_cache:
            self._prefix_cache = PrefixCache(self.allocator, ps)
            # cached-but-unreferenced chains are reclaimed on demand, so
            # the cache occupies the pool's slack without ever
            # backpressuring admission
            self.allocator.set_reclaim_hook(self._prefix_cache.evict)
        # admission queue: the serving batcher reused verbatim — flush on
        # size (free lanes) OR deadline, bounded by max_pending with sheds
        self._batcher = DynamicBatcher(
            ServingConfig(
                max_batch=L,
                max_wait_s=config.admit_max_wait_s,
                max_pending=config.max_pending,
            )
        )
        self._admit_buckets = default_buckets(L)
        head_dim = model.d_model // model.num_heads
        # device state: pools + per-lane decode carry (donated through
        # every program; the host rebinds after each dispatch)
        self._pools = init_paged_kv_cache(
            num_pages, ps, model.num_layers, model.num_heads, head_dim
        )
        self._logits_st = jnp.zeros((L, config.vocab_size), jnp.float32)
        self._value_st = jnp.zeros((L,), jnp.float32)
        self._cl = jnp.zeros((L,), jnp.int32)
        self._done = jnp.ones((L,), jnp.bool_)  # inert until admitted
        self._resp = jnp.zeros((L,), jnp.int32)
        # host mirrors / bookkeeping
        self._lanes = [_Lane() for _ in range(L)]
        self._table = np.zeros((L, self._pages_per_lane), np.int32)
        self._key = jax.random.PRNGKey(config.seed)
        self._decode_fn = self._build_decode()
        self._prefill_fns: Dict[Tuple, Callable] = {}
        self._fork_fns: Dict[int, Callable] = {}
        # in-flight macro reads: (dispatch index, device outputs); reads
        # pop the left end once depth reaches steps_in_flight
        self._inflight: Deque[Tuple[int, Any]] = deque()
        self._decode_traces = 0
        self._prefill_traces = 0
        self._fork_traces = 0
        self._verify_traces = 0
        self._warm = False
        self.macro_steps = 0
        self.completed_total = 0
        self._occupancy_sum = 0.0
        # speculative decode (ISSUE 16): compiled out entirely at k = 0 —
        # the plain macro-step path never pays a branch, a wider program,
        # or drafter bookkeeping
        self._spec_k = config.spec_k
        self._drafter: Optional[NgramDrafter] = None
        # verify programs keyed by effective draft width: a pow2 ladder
        # over the pass's max draft length (0, 1, 2, 4, ..., k), mirroring
        # the admit path's prompt buckets.  A ramping fleet whose drafts
        # are still short verifies through a narrow program instead of
        # paying k wasted positions per lane per pass — and each bucket
        # compiles exactly once (the ladder is finite and shape-static),
        # so the retrace pin holds at <= len(buckets) forever
        self._verify_fns: Dict[int, Callable] = {}
        self._spec_buckets: Tuple[int, ...] = ()
        self._spec_warm: set = set()
        # banned-token carry for the temperature>0 residual resample: the
        # token rejected by last pass's accept-test, masked out of the
        # NEXT pass's bonus-token sampling (exact residual for a
        # point-mass drafter).  Host-side because spec mode reads every
        # pass synchronously anyway — it rides the one batched upload.
        self._banned = np.full((L,), -1, np.int32)
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_rollback_pages_total = 0
        self._spec_draft_s = 0.0
        self._spec_verify_s = 0.0
        if self._spec_k:
            self._drafter = NgramDrafter(
                n=config.spec_ngram, k=config.spec_k
            )
            ladder = [0]
            b = 1
            while b < config.spec_k:
                ladder.append(b)
                b *= 2
            ladder.append(config.spec_k)
            self._spec_buckets = tuple(ladder)
        # prefill-savings accounting (the bench's saved-ratio numerator /
        # denominator): full-page prefix tokens admitted vs those skipped
        # via cache hits and CoW group shares
        self.prefix_tokens_total = 0
        self.prefix_tokens_saved = 0
        self.prefill_tokens = 0
        reg = telemetry.get_registry()
        self._decode_meter = reg.meter("genrl.decode_tokens_per_s")
        self._prompt_meter = reg.meter("genrl.prompt_tokens_per_s")
        self._occupancy_gauge = reg.gauge("genrl.lane_occupancy")
        self._admitted_counter = reg.counter("genrl.admitted")
        self._completed_counter = reg.counter("genrl.completed")
        self._shared_counter = reg.counter("genrl.pages_shared")
        self._admit_hist = reg.histogram("genrl.admission_latency_s")
        self._spec_proposed_counter = reg.counter("genrl.spec_proposed")
        self._spec_accepted_counter = reg.counter("genrl.spec_accepted")
        self._spec_rollback_counter = reg.counter(
            "genrl.spec_rollback_pages"
        )
        self._spec_accept_gauge = reg.gauge("genrl.spec_acceptance_rate")
        reg.bind("genrl.pages", self.allocator.stats)
        if self._prefix_cache is not None:
            reg.bind("genrl.prefix", self._prefix_cache.stats)
        reg.bind(
            "genrl.continuous",
            lambda: {
                "generation": self.generation,
                "macro_steps": self.macro_steps,
                "completed": self.completed_total,
                "live_lanes": sum(l.busy for l in self._lanes),
                "pending": self._batcher.stats()["pending_lanes"],
                "in_flight": len(self._inflight),
                "shed_total": self._batcher.shed_total,
                "iter_mode": self.iter_mode,
                "spec_k": self._spec_k,
            },
        )

    # -- admission ------------------------------------------------------
    def submit(
        self,
        prompt: np.ndarray,
        prompt_length: Optional[int] = None,
        tag: Any = None,
    ) -> bool:
        """Queue one prompt for admission; False = shed (queue at
        ``max_pending``).  ``prompt``: 1-D int32 (or the right-padded
        ``[L0]`` row with an explicit true length).  ``tag`` rides the lane
        unchanged and comes back on the :class:`CompletedSequence`.
        Single prompts take the same cache-lookup admission path as
        groups (a hit still skips the cached prefix's prefill)."""
        return self.submit_group(prompt, 1, prompt_length, tag)

    def submit_group(
        self,
        prompt: np.ndarray,
        n: int,
        prompt_length: Optional[int] = None,
        tag: Any = None,
    ) -> bool:
        """Queue one prompt for ``n`` sampled completions (the GRPO group
        shape); False = shed.  The group admits atomically into ``n``
        lanes that share the prompt's KV copy-on-write: one tail prefill
        for the leader, full prompt pages shared into the other ``n-1``
        tables, and only the last partial page physically copied per
        lane.  Every member completes as its own
        :class:`CompletedSequence` carrying the same ``tag``."""
        if n < 1 or n > self.config.lanes:
            raise ValueError(
                f"group size must be in [1, lanes], got {n}"
            )
        if n * self._worst_pages > self.allocator.capacity:
            # groups admit atomically: one the pool can never cover would
            # sit queued forever (every member reserves its full worst
            # case — sharing never loosens the exhaustion guarantee)
            raise ValueError(
                f"group of {n} needs {n * self._worst_pages} worst-case "
                f"pages but the pool caps at {self.allocator.capacity}"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        m = int(prompt_length) if prompt_length is not None else len(prompt)
        if m < 1 or m > self.config.max_prompt_len:
            raise ValueError(
                f"prompt length {m} outside [1, {self.config.max_prompt_len}]"
            )
        return self._batcher.submit(
            ServingRequest(
                conn=None,
                req_id=None,
                lanes=n,
                payload={
                    "prompt": prompt[:m].copy(),
                    "len": m,
                    "n": n,
                    "tag": tag,
                },
            )
        )

    @property
    def pending(self) -> int:
        """Queued-but-unadmitted LANES (a group of n counts n)."""
        return self._batcher.stats()["pending_lanes"]

    @property
    def live_lanes(self) -> int:
        return sum(l.busy for l in self._lanes)

    @property
    def prefix_saved_ratio(self) -> float:
        """Fraction of admitted full-page prefix tokens whose prefill was
        skipped (cache hits + CoW group shares)."""
        return self.prefix_tokens_saved / max(self.prefix_tokens_total, 1)

    def _admit(self) -> None:
        """Admit queued prompts into free lanes via the batcher's
        flush-on-size-or-deadline predicate.  All table math is host-side
        numpy; the device sees one batched upload per prefill group plus
        one for the CoW fork — never a per-lane transfer."""
        free_ids = [i for i, l in enumerate(self._lanes) if not l.busy]
        if not free_ids:
            return
        if len(free_ids) < self.config.min_free_lanes and len(
            free_ids
        ) < self.config.lanes:
            # admission batching: wait for more lanes to free so the
            # prefill dispatch amortizes (a fully idle pool always admits)
            return
        # admission never over-commits the page pool: cap the flush at the
        # number of worst-case sequences the allocator can still reserve
        # (shared pages count against every holder's reservation, so the
        # cap is exact with or without the prefix cache)
        affordable = (
            self.allocator.capacity - self.allocator.reserved
        ) // self._worst_pages
        limit = min(len(free_ids), affordable)
        batch = self._batcher.poll_batch(max_lanes=limit)
        if not batch:
            return
        now = time.monotonic()
        ps = self.config.page_size
        params, gen = self._snapshot_params()
        local: Dict[int, List[Tuple]] = {}
        prefix: Dict[int, List[Tuple]] = {}
        forks: List[Tuple[int, int, int, int]] = []
        inserts: List[Tuple[np.ndarray, int, List[int]]] = []
        admitted = 0
        for req in batch:
            prompt = req.payload["prompt"]
            m = req.payload["len"]
            n = req.payload.get("n", 1)
            lane_ids = [free_ids.pop(0) for _ in range(n)]
            leader = lane_ids[0]
            # longest cached full-page prefix — capped at m-1 tokens so
            # the uncached tail always holds the token whose forward
            # produces the lane's first decode logits
            cached: List[int] = []
            if self._prefix_cache is not None:
                cached = self._prefix_cache.lookup(prompt, m - 1)
            ck = len(cached) * ps
            worst = self.allocator.pages_for_tokens(
                m + self._response_budget
            )
            full_tokens = (m // ps) * ps  # full-page prefix tokens
            ok = self.allocator.try_reserve(worst)
            assert ok, "admission cap should have prevented over-reserve"
            holder = f"lane[{leader}]"
            if cached:
                self.allocator.share(cached, holder=holder)
                self._shared_counter.inc(len(cached))
            tail_pages = self.allocator.alloc(
                self.allocator.pages_for_tokens(m) - len(cached),
                holder=holder,
            )
            pages = cached + tail_pages
            self._occupy(leader, req, prompt, m, pages, worst, gen, now)
            t_len = m - ck
            row = (leader, prompt, m, ck, pages)
            if ck == 0:
                P = bucket_for(m, self.config.resolved_prompt_buckets())
                local.setdefault(P, []).append(row)
            else:
                T = bucket_for(
                    t_len, self.config.resolved_prompt_buckets()
                )
                prefix.setdefault(T, []).append(row)
            self.prefix_tokens_total += full_tokens
            self.prefix_tokens_saved += min(ck, full_tokens)
            self.prefill_tokens += t_len
            self._prompt_meter.mark(t_len)
            # group members fork off the leader copy-on-write: shared full
            # prompt pages, one physical copy of the partial page
            n_full = m // ps
            partial = pages[n_full] if m % ps else None
            for member in lane_ids[1:]:
                ok = self.allocator.try_reserve(worst)
                assert ok, "admission cap should have prevented over-reserve"
                mh = f"lane[{member}]"
                mpages = list(pages[:n_full])
                if n_full:
                    self.allocator.share(mpages, holder=mh)
                    self._shared_counter.inc(n_full)
                if partial is not None:
                    copy = self.allocator.alloc(1, holder=mh)[0]
                    mpages.append(copy)
                    forks.append((leader, member, partial, copy))
                else:
                    forks.append((leader, member, 0, 0))
                self._occupy(member, req, prompt, m, mpages, worst, gen, now)
                self.prefix_tokens_total += full_tokens
                self.prefix_tokens_saved += full_tokens
            admitted += n
            self._admit_hist.observe(now - req.t_enqueue)
            if self._prefix_cache is not None and n_full:
                inserts.append((prompt, m, pages[:n_full]))
        self._admitted_counter.inc(admitted)
        for P, rows in local.items():
            self._dispatch_local_prefill(P, rows, params)
        for T, rows in prefix.items():
            self._dispatch_prefix_prefill(T, rows, params)
        if forks:
            self._dispatch_fork(forks)
        # register the freshly-written chains AFTER the prefill dispatches
        # (device programs are ordered, so any later reader through a
        # shared table sees the completed writes)
        for prompt, m, full_pages in inserts:
            self._prefix_cache.insert(prompt, m, full_pages)

    def _occupy(
        self,
        lane_id: int,
        req: ServingRequest,
        prompt: np.ndarray,
        m: int,
        pages: List[int],
        reserved: int,
        gen: int,
        now: float,
    ) -> None:
        lane = self._lanes[lane_id]
        lane.busy = True
        lane.prompt = prompt
        lane.prompt_len = m
        lane.context_len = m
        lane.pages = pages
        lane.reserved = reserved
        lane.tokens, lane.logps, lane.values = [], [], []
        lane.generation = gen
        lane.submit_time = req.t_enqueue
        lane.admit_time = now
        lane.tag = req.payload.get("tag")
        lane.admit_macro = self.macro_steps
        self._table[lane_id] = 0
        self._table[lane_id, : len(pages)] = pages
        if self._drafter is not None:
            # a recycled lane id starts a fresh draft table over the new
            # prompt, and any banned-token carry from the previous
            # occupant dies with it
            self._drafter.start(lane_id, prompt[:m])
            self._banned[lane_id] = -1

    # -- prefill dispatch ------------------------------------------------
    def _dispatch_local_prefill(
        self, P: int, rows: List[Tuple], params: Any
    ) -> None:
        """Cold prompts (no cached prefix): causal local-attention prefill
        over the compact batch, K/V written straight into the lanes'
        fresh pages — ONE batched upload, no read."""
        ps = self.config.page_size
        A = bucket_for(len(rows), self._admit_buckets)
        L = self.config.lanes
        tokens = np.full((A, P), self.config.pad_token, np.int32)
        lengths = np.ones((A,), np.int32)
        lane_ids = np.full((A,), L, np.int32)  # pad rows scatter-drop
        page_ids = np.zeros((A, P), np.int32)  # pad writes -> null page
        offsets = np.zeros((A, P), np.int32)
        for r, (lane_id, prompt, m, _ck, pages) in enumerate(rows):
            tokens[r, :m] = prompt
            lengths[r] = m
            lane_ids[r] = lane_id
            pos = np.arange(m)
            page_ids[r, :m] = np.asarray(pages, np.int32)[pos // ps]
            offsets[r, :m] = pos % ps
        fn = self._prefill_fn(("local", P, A))
        with self._dispatch_guard():
            # ONE explicit batched host->device upload per prefill dispatch
            up = _device_put((tokens, lengths, lane_ids, page_ids, offsets))
            (
                self._pools,
                self._logits_st,
                self._value_st,
                self._cl,
                self._done,
                self._resp,
            ) = fn(
                params,
                self._pools,
                self._logits_st,
                self._value_st,
                self._cl,
                self._done,
                self._resp,
                *up,
            )

    def _dispatch_prefix_prefill(
        self, T: int, rows: List[Tuple], params: Any
    ) -> None:
        """Cache-hit prompts: prefill ONLY the uncached tail.  The tail's
        K/V scatters into lane-owned pages; attention gathers the whole
        context (shared prefix + tail) through the page table — sharing
        is purely a page-table fact."""
        ps = self.config.page_size
        A = bucket_for(len(rows), self._admit_buckets)
        L = self.config.lanes
        Mp = self._pages_per_lane
        tokens = np.full((A, T), self.config.pad_token, np.int32)
        tail_lengths = np.ones((A,), np.int32)
        lane_ids = np.full((A,), L, np.int32)
        page_ids = np.zeros((A, T), np.int32)
        offsets = np.zeros((A, T), np.int32)
        table = np.zeros((A, Mp), np.int32)
        starts = np.zeros((A,), np.int32)
        for r, (lane_id, prompt, m, ck, pages) in enumerate(rows):
            t_len = m - ck
            tokens[r, :t_len] = prompt[ck:m]
            tail_lengths[r] = t_len
            lane_ids[r] = lane_id
            gpos = ck + np.arange(t_len)
            page_ids[r, :t_len] = np.asarray(pages, np.int32)[gpos // ps]
            offsets[r, :t_len] = gpos % ps
            table[r, : len(pages)] = pages
            starts[r] = ck
        fn = self._prefill_fn(("prefix", T, A))
        with self._dispatch_guard():
            up = _device_put(
                (tokens, tail_lengths, lane_ids, page_ids, offsets,
                 table, starts)
            )
            (
                self._pools,
                self._logits_st,
                self._value_st,
                self._cl,
                self._done,
                self._resp,
            ) = fn(
                params,
                self._pools,
                self._logits_st,
                self._value_st,
                self._cl,
                self._done,
                self._resp,
                *up,
            )

    def _dispatch_fork(self, forks: List[Tuple[int, int, int, int]]) -> None:
        """One jitted page-copy + lane-state fork for EVERY group member
        admitted this cycle: copies the leader's partial prompt page into
        the member's private page and replicates the leader's post-prefill
        decode carry — one small upload, no read."""
        F = bucket_for(len(forks), self._admit_buckets)
        L = self.config.lanes
        src_lane = np.zeros((F,), np.int32)
        dst_lane = np.full((F,), L, np.int32)  # pad rows scatter-drop
        src_page = np.zeros((F,), np.int32)  # pad copies null -> null
        dst_page = np.zeros((F,), np.int32)
        for i, (sl, dl, sp, dp) in enumerate(forks):
            src_lane[i] = sl
            dst_lane[i] = dl
            src_page[i] = sp
            dst_page[i] = dp
        fn = self._fork_fn(F)
        with self._dispatch_guard():
            up = _device_put((src_lane, dst_lane, src_page, dst_page))
            (
                self._pools,
                self._logits_st,
                self._value_st,
                self._cl,
                self._done,
                self._resp,
            ) = fn(
                self._pools,
                self._logits_st,
                self._value_st,
                self._cl,
                self._done,
                self._resp,
                *up,
            )

    # -- program construction -------------------------------------------
    def _prefill_fn(self, key: Tuple) -> Callable:
        fn = self._prefill_fns.get(key)
        if fn is None:
            kind, a, b = key
            fn = (
                self._build_prefill(a, b)
                if kind == "local"
                else self._build_prefix_prefill(a, b)
            )
            self._prefill_fns[key] = fn
        return fn

    def _fork_fn(self, F: int) -> Callable:
        fn = self._fork_fns.get(F)
        if fn is None:
            fn = self._build_fork(F)
            self._fork_fns[F] = fn
        return fn

    def _build_prefill(self, P: int, A: int) -> Callable:
        """Prefill ``A`` admitted prompts at bucket ``P``: causal forward
        over the compact (right-padded) prompts, K/V written straight into
        the newly-allocated pages, last-position logits/value + cursor +
        flags scattered into the lane state — all device-side, no read."""
        model = self.model

        def prefill(
            params, pools, logits_st, value_st, cl, done, resp,
            tokens, lengths, lane_ids, page_ids, page_offsets,
        ):
            self._prefill_traces += 1
            positions = jnp.broadcast_to(jnp.arange(P), (A, P))
            mask = prompt_attention_mask(lengths, P)
            out, pools = model.apply(
                params,
                tokens,
                positions=positions,
                attn_mask=mask,
                paged_cache=pools,
                page_ids=page_ids,
                page_offsets=page_offsets,
            )
            rows = jnp.arange(A)
            last = lengths - 1
            logits_last = out.policy_logits[rows, last]
            value_last = out.baseline[rows, last]
            # pad rows carry lane_id == lanes: out-of-bounds scatters drop
            logits_st = logits_st.at[lane_ids].set(logits_last, mode="drop")
            value_st = value_st.at[lane_ids].set(value_last, mode="drop")
            cl = cl.at[lane_ids].set(lengths, mode="drop")
            done = done.at[lane_ids].set(False, mode="drop")
            resp = resp.at[lane_ids].set(0, mode="drop")
            return pools, logits_st, value_st, cl, done, resp

        return jax.jit(prefill, donate_argnums=(1, 2, 3, 4, 5, 6))

    def _build_prefix_prefill(self, T: int, A: int) -> Callable:
        """Chunked tail prefill over a shared cached prefix: the ``T``
        tail tokens of ``A`` lanes scatter K/V into lane-owned pages and
        attend through the page table (cached prefix + tail) with a
        causal-from-start mask; last-position logits/value + cursor
        scattered exactly like the local prefill."""
        model = self.model

        def prefill(
            params, pools, logits_st, value_st, cl, done, resp,
            tokens, tail_lengths, lane_ids, page_ids, page_offsets,
            table, starts,
        ):
            self._prefill_traces += 1
            positions = jnp.clip(
                starts[:, None] + jnp.arange(T)[None, :],
                0,
                model.max_len - 1,
            )
            out, pools = model.apply(
                params,
                tokens,
                positions=positions,
                paged_cache=pools,
                page_ids=page_ids,
                page_offsets=page_offsets,
                page_table=table,
                prefix_starts=starts,
            )
            rows = jnp.arange(A)
            last = tail_lengths - 1
            logits_last = out.policy_logits[rows, last]
            value_last = out.baseline[rows, last]
            logits_st = logits_st.at[lane_ids].set(logits_last, mode="drop")
            value_st = value_st.at[lane_ids].set(value_last, mode="drop")
            cl = cl.at[lane_ids].set(starts + tail_lengths, mode="drop")
            done = done.at[lane_ids].set(False, mode="drop")
            resp = resp.at[lane_ids].set(0, mode="drop")
            return pools, logits_st, value_st, cl, done, resp

        return jax.jit(prefill, donate_argnums=(1, 2, 3, 4, 5, 6))

    def _build_fork(self, F: int) -> Callable:
        """The CoW fork program at admit bucket ``F``: batched pool-page
        copy (``pools[dst] = pools[src]`` per layer — only partial prompt
        pages ever ride here) plus leader -> member lane-state
        replication.  Pad rows copy null -> null and scatter-drop."""

        def fork(
            pools, logits_st, value_st, cl, done, resp,
            src_lane, dst_lane, src_page, dst_page,
        ):
            self._fork_traces += 1
            new_k = tuple(
                kp.at[dst_page].set(kp[src_page]) for kp in pools.k
            )
            new_v = tuple(
                vp.at[dst_page].set(vp[src_page]) for vp in pools.v
            )
            pools = PagedKVCache(k=new_k, v=new_v)
            logits_st = logits_st.at[dst_lane].set(
                logits_st[src_lane], mode="drop"
            )
            value_st = value_st.at[dst_lane].set(
                value_st[src_lane], mode="drop"
            )
            cl = cl.at[dst_lane].set(cl[src_lane], mode="drop")
            done = done.at[dst_lane].set(done[src_lane], mode="drop")
            resp = resp.at[dst_lane].set(resp[src_lane], mode="drop")
            return pools, logits_st, value_st, cl, done, resp

        return jax.jit(fork, donate_argnums=(0, 1, 2, 3, 4, 5))

    def _build_decode(self) -> Callable:
        """The ONE macro-step program: ``steps_per_macro`` fused substeps
        of sample -> latch -> paged write -> paged attention -> carry."""
        model = self.model
        cfg = self.config
        ps = cfg.page_size
        steps = cfg.steps_per_macro
        budget = self._response_budget
        use_scan = self.iter_mode == "scan"

        def substep(params, table, carry, _t):
            pools, logits, value, cl, done, resp, key = carry
            key, sub = jax.random.split(key)
            adj = adjust_logits(
                logits, cfg.temperature, cfg.top_k, cfg.vocab_size
            )
            token = sample_tokens(sub, adj, cfg.temperature)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(adj, axis=-1), token[:, None], axis=-1
            )[:, 0]
            alive = jnp.logical_not(done)
            resp2 = resp + alive.astype(jnp.int32)
            finished = resp2 >= budget
            if cfg.eos_token >= 0:
                finished = jnp.logical_or(finished, token == cfg.eos_token)
            done2 = jnp.logical_or(done, finished)
            emit = jnp.where(
                alive, token, jnp.int32(max(cfg.eos_token, cfg.pad_token))
            ).astype(jnp.int32)
            out_t = (emit, logp, value, alive.astype(jnp.float32))
            # feed the sampled token back through the paged model: write
            # K/V at flat position cl (dead lanes route to the null page)
            page_idx = jnp.take_along_axis(
                table, (cl // ps)[:, None], axis=1
            )[:, 0]
            page_idx = jnp.where(alive, page_idx, 0)
            offs = jnp.where(alive, cl % ps, 0)
            att_len = jnp.where(alive, cl + 1, 1)
            out, pools = model.apply(
                params,
                token[:, None].astype(jnp.int32),
                positions=cl[:, None],
                paged_cache=pools,
                page_ids=page_idx[:, None],
                page_offsets=offs[:, None],
                page_table=table,
                attn_lengths=att_len,
            )
            cl2 = cl + alive.astype(jnp.int32)
            new_carry = (
                pools,
                out.policy_logits[:, 0],
                out.baseline[:, 0],
                cl2,
                done2,
                resp2,
                key,
            )
            return new_carry, out_t

        def decode(params, pools, logits_st, value_st, cl, done, resp,
                   table, key):
            self._decode_traces += 1
            carry = (pools, logits_st, value_st, cl, done, resp, key)
            if use_scan:
                carry, outs = jax.lax.scan(
                    lambda c, t: substep(params, table, c, t),
                    carry,
                    jnp.arange(steps),
                )
                toks, logps, values, alive = (
                    jnp.swapaxes(o, 0, 1) for o in outs
                )
            else:
                cols = []
                for t in range(steps):
                    carry, out_t = substep(params, table, carry, t)
                    cols.append(out_t)
                toks = jnp.stack([c[0] for c in cols], axis=1)
                logps = jnp.stack([c[1] for c in cols], axis=1)
                values = jnp.stack([c[2] for c in cols], axis=1)
                alive = jnp.stack([c[3] for c in cols], axis=1)
            pools, logits_st, value_st, cl, done, resp, _key = carry
            outputs = {
                "tokens": toks.astype(jnp.int32),
                "logp": logps.astype(jnp.float32),
                "value": values.astype(jnp.float32),
                "mask": alive,
                "cl": cl,
                "done": done,
                "resp": resp,
            }
            return pools, logits_st, value_st, cl, done, resp, outputs

        return jax.jit(decode, donate_argnums=(1, 2, 3, 4, 5, 6))

    def _build_verify(self, k_eff: int) -> Callable:
        """One speculative verify program at draft width ``k_eff``
        (ISSUE 16): sample the bonus token from the carried logits, feed
        ``[t0, d1..dk]`` through the shared-table tail-prefill path in a
        single forward, accept the longest draft prefix, and carry the
        state at the last accepted position.

        Lane count and ``k_eff`` are both static, so each ladder bucket
        compiles exactly once (``_verify_traces`` pins the total at
        <= len(buckets)); ``_spec_step`` routes every pass to the
        smallest bucket that fits its longest draft, so short-draft
        passes — the ramp, and lanes the AIMD cap has clamped — never
        pay ``spec_k`` computed positions.  ``k_eff`` may be 0: the
        bonus-only program, one position per lane, the spec-mode twin of
        a single decode substep.  The carried-logits
        invariant survives untouched: ``logits_st`` is always the
        distribution for the token at cursor ``cl``, computed from an
        all-accepted context — output slot ``a`` qualifies because slots
        ``0..a`` fed exactly the emitted tokens.  K/V written for
        rejected slots is garbage past the cursor: never attended (the
        tail path masks ``pos <= qpos``) and overwritten by the next
        pass's writes, so the device needs no rollback — rollback is
        purely the host-side page rewind.

        Distribution correctness at temperature > 0 is the standard
        speculative-sampling argument for a point-mass (deterministic)
        drafter: draft ``d_j`` is accepted with probability
        ``pi_j(d_j)``; on an accept-test rejection the replacement token
        must come from the residual ``pi(x) / (1 - pi(d))`` over
        ``x != d``, which is exactly next pass's bonus sampling with
        ``d`` masked out (the ``banned`` carry).  The STORED behavior
        logp is always from the unmasked distribution — marginally the
        output token is ``pi``-distributed, which is what the learner's
        ratios need.  At temperature 0 both rules collapse to greedy
        argmax equality and ``banned`` stays -1.
        """
        model = self.model
        cfg = self.config
        k = k_eff
        T = k + 1
        V = cfg.vocab_size
        budget = self._response_budget
        greedy = cfg.temperature == 0.0
        pad = jnp.int32(max(cfg.eos_token, cfg.pad_token))

        def verify(
            params, pools, logits_st, value_st, cl, done, resp,
            drafts, draft_len, page_ids, page_offsets, table, banned, key,
        ):
            self._verify_traces += 1
            L = cl.shape[0]
            rows = jnp.arange(L)
            alive = jnp.logical_not(done)
            k0, kacc = jax.random.split(key)
            # bonus token: sampled from the carried logits exactly like a
            # decode substep — except at temperature > 0 a banned token
            # (last pass's accept-test rejection) is masked from the
            # SAMPLING distribution only (the residual rule)
            adj0 = adjust_logits(
                logits_st, cfg.temperature, cfg.top_k, V
            )
            if greedy:
                samp0 = adj0
            else:
                ban_pen = jnp.zeros((L, V), jnp.float32)
                ban_pen = ban_pen.at[rows, jnp.clip(banned, 0, V - 1)].set(
                    jnp.where(banned >= 0, -1e9, 0.0)
                )
                samp0 = adj0 + ban_pen
            t0 = sample_tokens(k0, samp0, cfg.temperature)
            logp0 = jnp.take_along_axis(
                jax.nn.log_softmax(adj0, axis=-1), t0[:, None], axis=-1
            )[:, 0]
            # one forward over [t0, d1..dk] at positions cl..cl+k through
            # the shared-table tail path; slot j's output is the policy
            # distribution for position cl+j+1
            X = jnp.concatenate([t0[:, None], drafts], axis=1)
            positions = jnp.clip(
                cl[:, None] + jnp.arange(T)[None, :], 0, model.max_len - 1
            )
            out, pools = model.apply(
                params,
                X,
                positions=positions,
                paged_cache=pools,
                page_ids=page_ids,
                page_offsets=page_offsets,
                page_table=table,
                prefix_starts=cl,
            )
            o_logits = out.policy_logits  # [L, T, V]
            o_value = out.baseline  # [L, T]
            adj = adjust_logits(
                o_logits.reshape(L * T, V), cfg.temperature, cfg.top_k, V
            ).reshape(L, T, V)
            # accept test per draft j (against the distribution AFTER slot
            # j-1): greedy equality at temperature 0, accept-with-prob
            # pi(d) otherwise; gated on the host's draft_len clamp and on
            # no EOS having been emitted earlier in this pass
            prev = adj[:, :k]
            logp_d = jnp.take_along_axis(
                jax.nn.log_softmax(prev, axis=-1),
                drafts[:, :, None], axis=-1,
            )[:, :, 0]
            if greedy:
                accept = drafts == jnp.argmax(prev, axis=-1)
            else:
                u = jax.random.uniform(
                    kacc, (L, k), minval=1e-20, maxval=1.0
                )
                accept = jnp.log(u) < logp_d
            valid = jnp.arange(1, k + 1)[None, :] <= draft_len[:, None]
            ok = accept & valid
            if cfg.eos_token >= 0:
                ok = ok & (X[:, :k] != cfg.eos_token)
            chain = jnp.cumprod(ok.astype(jnp.int32), axis=1)
            a = chain.sum(axis=1)  # accepted drafts per lane, in [0, k]
            # emitted stream: t0 plus the accepted prefix — the outputs
            # mirror the decode macro's (prefix-contiguous mask), so the
            # host harvest path is shared verbatim
            slot = jnp.arange(T)[None, :]
            mask = (slot <= a[:, None]) & alive[:, None]
            emit = jnp.where(mask, X, pad).astype(jnp.int32)
            logps = jnp.concatenate([logp0[:, None], logp_d], axis=1)
            values = jnp.concatenate(
                [value_st[:, None], o_value[:, :k]], axis=1
            )
            n_emit = (1 + a) * alive.astype(jnp.int32)
            resp2 = resp + n_emit
            cl2 = cl + n_emit
            last_tok = jnp.take_along_axis(X, a[:, None], axis=1)[:, 0]
            finished = resp2 >= budget
            if cfg.eos_token >= 0:
                finished = jnp.logical_or(
                    finished, last_tok == cfg.eos_token
                )
            done2 = jnp.logical_or(done, alive & finished)
            # carry the state at the LAST ACCEPTED slot: its output is the
            # distribution for the token at the new cursor
            new_logits = jnp.take_along_axis(
                o_logits, a[:, None, None], axis=1
            )[:, 0]
            new_value = jnp.take_along_axis(o_value, a[:, None], axis=1)[
                :, 0
            ]
            logits_st2 = jnp.where(alive[:, None], new_logits, logits_st)
            value_st2 = jnp.where(alive, new_value, value_st)
            if greedy or k == 0:
                # no draft positions -> nothing the accept test could
                # have rejected; the residual carry stays clear
                banned2 = jnp.full((L,), -1, jnp.int32)
            else:
                # ban only on a genuine accept-test rejection (not mere
                # draft/budget exhaustion) of a still-live lane
                j1 = jnp.clip(a, 0, k - 1)
                hit = jnp.take_along_axis(accept, j1[:, None], axis=1)[
                    :, 0
                ]
                d1 = jnp.take_along_axis(drafts, j1[:, None], axis=1)[
                    :, 0
                ]
                rej = (
                    (a < k)
                    & jnp.take_along_axis(valid, j1[:, None], axis=1)[:, 0]
                    & jnp.logical_not(hit)
                    & alive
                    & jnp.logical_not(done2)
                )
                if cfg.eos_token >= 0:
                    no_eos = jnp.take_along_axis(
                        X[:, :k] != cfg.eos_token, j1[:, None], axis=1
                    )[:, 0]
                    rej = rej & no_eos
                banned2 = jnp.where(rej, d1, -1).astype(jnp.int32)
            outputs = {
                "tokens": emit,
                "logp": logps.astype(jnp.float32),
                "value": values.astype(jnp.float32),
                "mask": mask.astype(jnp.float32),
                "cl": cl2,
                "done": done2,
                "resp": resp2,
                "banned": banned2,
            }
            return (
                pools, logits_st2, value_st2, cl2, done2, resp2, outputs
            )

        return jax.jit(verify, donate_argnums=(1, 2, 3, 4, 5, 6))

    # -- param plane -----------------------------------------------------
    def push_params(
        self,
        params: Any,
        learner_step: Optional[int] = None,
        quantize: Optional[str] = None,
    ) -> int:
        """Publish fresh params AND flush the prefix cache: cached K/V was
        computed under the previous generation, and reusing it would break
        the temperature-0 token-identity contract.  Live lanes keep their
        shared pages (their own refs) until harvest — only the cache's
        index drops."""
        gen = super().push_params(params, learner_step, quantize)
        if self._prefix_cache is not None:
            self._prefix_cache.flush()
        return gen

    # -- the macro-step --------------------------------------------------
    def _ensure_pages(self) -> None:
        """Pre-extend each live lane's page list to cover the in-flight
        decode horizon's worst case (all allocation stays within the
        lane's admission-time reservation, so it can never fail
        mid-flight).  With K macros in flight the host's ``context_len``
        is stale by up to K-1 macros, so the horizon covers the pending
        dispatches plus the one about to go out."""
        ps = self.config.page_size
        if self._spec_k:
            # spec mode is synchronous: the horizon is one verify pass's
            # worst case — the bonus token plus k accepted drafts
            steps = self._spec_k + 1
        else:
            steps = self.config.steps_per_macro * (len(self._inflight) + 1)
        for lane_id, lane in enumerate(self._lanes):
            if not lane.busy:
                continue
            horizon = min(
                lane.context_len + steps,
                lane.prompt_len + self._response_budget,
            )
            need = min(
                self.allocator.pages_for_tokens(horizon), lane.reserved
            )
            delta = need - len(lane.pages)
            if delta > 0:
                new_pages = self.allocator.alloc(
                    delta, holder=f"lane[{lane_id}]"
                )
                start = len(lane.pages)
                lane.pages.extend(new_pages)
                self._table[
                    lane_id, start : start + len(new_pages)
                ] = new_pages

    def step(self) -> List[CompletedSequence]:
        """One engine cycle: admit -> dispatch the next decode macro-step
        (ONE upload) -> read the OLDEST in-flight macro once
        ``steps_in_flight`` are pending (ONE batched read, lagging
        dispatch by K-1) -> harvest.  Returns the sequences that
        completed in the macro(s) read this cycle.

        With ``spec_k > 0`` the cycle is the draft -> verify -> rewind
        loop instead (:meth:`_spec_step`) — same admission, same harvest,
        same one-upload-one-read transfer discipline, but synchronous by
        construction (next pass's drafts need this pass's tokens)."""
        if self._spec_k:
            return self._spec_step()
        t_step0 = time.monotonic()
        self._admit()
        dispatched = False
        occ = 0.0
        if self.live_lanes > 0:
            self._ensure_pages()
            params, _gen = self._snapshot_params()
            occ = self.live_lanes / self.config.lanes
            self._occupancy_gauge.set(occ)
            self._occupancy_sum += occ
            guard = steady_state_guard() if self._warm else nullcontext()
            with guard:
                with self._dispatch_guard():
                    self._key, sub = jax.random.split(self._key)
                    # ONE explicit batched host->device upload per macro
                    table_dev = _device_put(self._table)
                    (
                        self._pools,
                        self._logits_st,
                        self._value_st,
                        self._cl,
                        self._done,
                        self._resp,
                        outputs,
                    ) = self._decode_fn(
                        params,
                        self._pools,
                        self._logits_st,
                        self._value_st,
                        self._cl,
                        self._done,
                        self._resp,
                        table_dev,
                        sub,
                    )
            self._inflight.append((self.macro_steps, outputs))
            self.macro_steps += 1
            self._warm = True
            dispatched = True
        completions: List[CompletedSequence] = []
        # read the oldest in-flight macro once K are pending (reads lag
        # dispatch by K-1); with nothing dispatched this cycle, drain —
        # outputs are loop OUTPUTS (never donated), so holding device
        # references to K of them while later macros run is safe by
        # construction (the MetricsPipeline argument)
        while self._inflight and (
            len(self._inflight) >= self.config.steps_in_flight
            or not dispatched
        ):
            macro_idx, outputs = self._inflight.popleft()
            guard = steady_state_guard() if self._warm else nullcontext()
            with guard:
                # ... and ONE explicit batched device->host read
                host = _device_get(outputs)
            completions.extend(self._harvest(host, macro_idx))
            if dispatched:
                break  # steady state: exactly one read per step
        if tracing.sampling_enabled():
            # ONE head-sampled span per macro-step/harvest — never per
            # token, never per lane; stamps are the host monotonic reads
            # this method already pays (graftlint JG001 good twin)
            tracing.record_span(
                "genrl.macro_step", None, t_step0, time.monotonic(),
                kind="genrl", completed=len(completions),
                live_lanes=self.live_lanes, occupancy=round(occ, 4),
                in_flight=len(self._inflight),
            )
        return completions

    def _spec_step(self) -> List[CompletedSequence]:
        """One speculative cycle (ISSUE 16): admit -> draft (host-side
        n-gram lookups, jax-free) -> ONE batched upload + verify dispatch
        -> ONE batched read -> feed the drafter, harvest, and rewind the
        page cursor of every rejected tail.

        The transfer shape matches the plain macro-step exactly — one
        upload, one read — so graftlint's decode discipline holds; the
        read is synchronous (``steps_in_flight`` is ignored here) because
        pass ``m+1``'s drafts are functions of pass ``m``'s emitted
        tokens."""
        t_step0 = time.monotonic()
        self._admit()
        completions: List[CompletedSequence] = []
        occ = 0.0
        draft_s = verify_s = 0.0
        if self.live_lanes > 0:
            self._ensure_pages()
            params, _gen = self._snapshot_params()
            occ = self.live_lanes / self.config.lanes
            self._occupancy_gauge.set(occ)
            self._occupancy_sum += occ
            cfg = self.config
            ps = cfg.page_size
            k = self._spec_k
            L = cfg.lanes
            # -- draft: per-lane n-gram proposals + page routing, all
            # host numpy (the gap between read and dispatch the device
            # decodes through in plain mode is spent drafting here)
            t_draft0 = time.monotonic()
            drafts = np.zeros((L, k), np.int32)
            draft_len = np.zeros((L,), np.int32)
            busy = np.zeros((L,), bool)
            cl_host = np.zeros((L,), np.int64)
            proposed = 0
            for lane_id, lane in enumerate(self._lanes):
                if not lane.busy:
                    continue
                busy[lane_id] = True
                cl_host[lane_id] = lane.context_len
                # the bonus token always fits (a live lane has budget
                # room by the done latch); drafts are clamped so the
                # whole accepted run stays within the response budget
                room = (
                    lane.prompt_len
                    + self._response_budget
                    - lane.context_len
                    - 1
                )
                if room > 0:
                    d = self._drafter.propose(lane_id)
                    if d is not None:
                        dl = min(len(d), room, k)
                        if dl:
                            drafts[lane_id, :dl] = d[:dl]
                            draft_len[lane_id] = dl
                            proposed += dl
            # bucket the pass to the smallest ladder width that fits its
            # longest draft: a ramp pass whose best proposal is 1 token
            # verifies through the 2-wide program, not the k-wide one —
            # on a compute-bound substrate the unused slots of a too-wide
            # program are pure wall-clock waste.  Each bucket is its own
            # compiled program (shape-static), so this never retraces
            dmax = int(draft_len.max())
            kb = next(b for b in self._spec_buckets if b >= dmax)
            fn = self._verify_fns.get(kb)
            if fn is None:
                fn = self._verify_fns[kb] = self._build_verify(kb)
            T = kb + 1
            drafts = drafts[:, :kb]
            # slot j writes K/V at flat position cl + j; slots past the
            # draft length (and the whole row of a dead lane) route to
            # the null page.  ``self._table[lane, pos // ps]`` already
            # IS the padded page matrix (0 where unheld), so routing is
            # one vectorized [L, T] gather — no per-lane numpy traffic
            # in the host gap the device sits idle through
            slot = np.arange(T)
            gpos = cl_host[:, None] + slot[None, :]
            page_idx = np.minimum(gpos // ps, self._table.shape[1] - 1)
            writable = (slot[None, :] <= draft_len[:, None]) & busy[:, None]
            rows = np.arange(L)[:, None]
            page_ids = np.where(
                writable, self._table[rows, page_idx], 0
            ).astype(np.int32)
            offsets = np.where(writable, gpos % ps, 0).astype(np.int32)
            draft_s = time.monotonic() - t_draft0
            # -- verify: ONE batched upload, ONE dispatch, ONE read
            t_verify0 = time.monotonic()
            # per-BUCKET warmth: a first dispatch at a new ladder width
            # compiles (materializing host constants), which the
            # steady-state transfer guard would flag — every later pass
            # through that bucket runs guarded
            guard = (
                steady_state_guard()
                if kb in self._spec_warm
                else nullcontext()
            )
            with guard:
                with self._dispatch_guard():
                    self._key, sub = jax.random.split(self._key)
                    up = _device_put(
                        (drafts, draft_len, page_ids, offsets,
                         self._table, self._banned)
                    )
                    (
                        self._pools,
                        self._logits_st,
                        self._value_st,
                        self._cl,
                        self._done,
                        self._resp,
                        outputs,
                    ) = fn(
                        params,
                        self._pools,
                        self._logits_st,
                        self._value_st,
                        self._cl,
                        self._done,
                        self._resp,
                        *up,
                        sub,
                    )
                host = _device_get(outputs)
            verify_s = time.monotonic() - t_verify0
            macro_idx = self.macro_steps
            self.macro_steps += 1
            self._warm = True
            self._spec_warm.add(kb)
            self._banned = np.array(host["banned"], np.int32)  # writable copy
            # -- drafter maintenance from the already-read outputs (no
            # extra transfer): live lanes learn their emitted tokens,
            # finished lanes drop their tables before the id recycles
            mask = np.asarray(host["mask"], np.float32)
            tokens = np.asarray(host["tokens"], np.int32)
            done = np.asarray(host["done"], bool)
            accepted = 0
            for lane_id, lane in enumerate(self._lanes):
                if not lane.busy:
                    continue
                count = int(mask[lane_id].sum())
                accepted += max(count - 1, 0)
                self._drafter.observe(
                    lane_id, int(draft_len[lane_id]), max(count - 1, 0)
                )
                if count:
                    self._drafter.extend(
                        lane_id, tokens[lane_id, :count]
                    )
                if done[lane_id]:
                    self._drafter.release(lane_id)
            completions = self._harvest(host, macro_idx)
            # -- page-cursor rewind: every live lane frees the whole
            # pages past its post-verify cursor (the rejected tail's
            # pre-extension) — refcount decrements only, so CoW-shared
            # pages another holder still needs are never touched
            freed = 0
            for lane_id, lane in enumerate(self._lanes):
                if not lane.busy:
                    continue
                keep = self.allocator.pages_for_tokens(lane.context_len)
                n = rewind_pages(
                    self.allocator, lane.pages, keep,
                    holder=f"lane[{lane_id}]",
                )
                if n:
                    self._table[lane_id, keep : keep + n] = 0
                    freed += n
            self.spec_proposed_total += proposed
            self.spec_accepted_total += accepted
            self.spec_rollback_pages_total += freed
            self._spec_draft_s += draft_s
            self._spec_verify_s += verify_s
            if proposed:
                self._spec_proposed_counter.inc(proposed)
            if accepted:
                self._spec_accepted_counter.inc(accepted)
            if freed:
                self._spec_rollback_counter.inc(freed)
            self._spec_accept_gauge.set(self.spec_acceptance_rate)
        if tracing.sampling_enabled():
            # ONE head-sampled span per pass with draft/verify child
            # spans — never per token, never per lane (stamps are host
            # monotonic reads this method already pays)
            t_end = time.monotonic()
            ctx = tracing.record_span(
                "genrl.macro_step", None, t_step0, t_end,
                kind="genrl-spec", completed=len(completions),
                live_lanes=self.live_lanes, occupancy=round(occ, 4),
                acceptance_rate=round(self.spec_acceptance_rate, 4),
            )
            if draft_s or verify_s:
                t_d0 = t_step0
                tracing.record_span(
                    "seq.draft", ctx, t_d0, t_d0 + draft_s,
                    kind="genrl-spec",
                )
                tracing.record_span(
                    "seq.verify", ctx, t_d0 + draft_s,
                    t_d0 + draft_s + verify_s, kind="genrl-spec",
                )
        return completions

    @property
    def spec_acceptance_rate(self) -> float:
        """Fraction of proposed draft tokens the verify pass accepted."""
        return self.spec_accepted_total / max(self.spec_proposed_total, 1)

    def spec_timers(self) -> Optional[Tuple[float, float]]:
        """Cumulative host (draft_s, verify_s) across all spec passes, or
        None with speculation compiled out — the disagg host's seq.draft /
        seq.verify trace edges are deltas of this."""
        if not self._spec_k:
            return None
        return (self._spec_draft_s, self._spec_verify_s)

    def stats(self) -> Dict[str, Any]:
        """Engine-lifetime counters, batched from host state that already
        crossed the device boundary — reading this never adds a
        transfer."""
        return {
            "macro_steps": self.macro_steps,
            "completed": self.completed_total,
            "live_lanes": self.live_lanes,
            "mean_occupancy": self.mean_occupancy,
            "prefill_tokens": self.prefill_tokens,
            "prefix_saved_ratio": self.prefix_saved_ratio,
            "spec_k": self._spec_k,
            "spec_proposed": self.spec_proposed_total,
            "spec_accepted": self.spec_accepted_total,
            "spec_rollback_pages": self.spec_rollback_pages_total,
            "spec_acceptance_rate": self.spec_acceptance_rate,
            "spec_draft_s": self._spec_draft_s,
            "spec_verify_s": self._spec_verify_s,
        }

    def _harvest(
        self, host: Dict[str, np.ndarray], macro_idx: int
    ) -> List[CompletedSequence]:
        mask = np.asarray(host["mask"], np.float32)
        tokens = np.asarray(host["tokens"], np.int32)
        logp = np.asarray(host["logp"], np.float32)
        value = np.asarray(host["value"], np.float32)
        done = np.asarray(host["done"], bool)
        cl = np.asarray(host["cl"], np.int32)
        finish = time.monotonic()
        completions: List[CompletedSequence] = []
        decode_tokens = 0
        for lane_id, lane in enumerate(self._lanes):
            if not lane.busy:
                continue
            if lane.admit_macro > macro_idx:
                # this read predates the lane's current occupancy (the id
                # was recycled while this macro was in flight): the row
                # belongs to the finished previous occupant, already
                # harvested — never apply it to the new one
                continue
            count = int(mask[lane_id].sum())
            decode_tokens += count
            if count > 0:
                lane.tokens.append(tokens[lane_id, :count])
                lane.logps.append(logp[lane_id, :count])
                lane.values.append(value[lane_id, :count])
            lane.context_len = int(cl[lane_id])
            if done[lane_id]:
                completions.append(
                    CompletedSequence(
                        prompt=lane.prompt,
                        prompt_len=lane.prompt_len,
                        response_tokens=np.concatenate(lane.tokens)
                        if lane.tokens
                        else np.zeros((0,), np.int32),
                        behavior_logp=np.concatenate(lane.logps)
                        if lane.logps
                        else np.zeros((0,), np.float32),
                        values=np.concatenate(lane.values)
                        if lane.values
                        else np.zeros((0,), np.float32),
                        generation=lane.generation,
                        submit_time=lane.submit_time,
                        admit_time=lane.admit_time,
                        finish_time=finish,
                        tag=lane.tag,
                    )
                )
                # release the lane: every page hold returns to the pool
                # (shared prefix pages just drop one ref; exclusively
                # owned pages go back to the free list immediately — the
                # memory-scales-with-live-tokens half)
                self.allocator.free(lane.pages, holder=f"lane[{lane_id}]")
                self.allocator.release(lane.reserved)
                self._table[lane_id] = 0
                self._lanes[lane_id] = _Lane()
        self._decode_meter.mark(decode_tokens)
        self.completed_total += len(completions)
        if completions:
            self._completed_counter.inc(len(completions))
        return completions

    @property
    def mean_occupancy(self) -> float:
        """Mean live-lane fraction over all dispatched macro-steps
        (sampled post-admission, the occupancy the decode program saw)."""
        return self._occupancy_sum / max(self.macro_steps, 1)

    def run_until(
        self, n_completions: int, max_macro_steps: int = 10_000
    ) -> List[CompletedSequence]:
        """Drive macro-steps until ``n_completions`` sequences finished
        (requires enough prompts submitted/submittable to get there)."""
        out: List[CompletedSequence] = []
        for _ in range(max_macro_steps):
            if len(out) >= n_completions:
                return out
            if (
                self.live_lanes == 0
                and self.pending == 0
                and not self._inflight
            ):
                raise RuntimeError(
                    f"engine drained at {len(out)}/{n_completions} "
                    "completions (no live lanes, empty queue)"
                )
            out.extend(self.step())
        raise RuntimeError(
            f"run_until({n_completions}) exceeded {max_macro_steps} "
            "macro-steps"
        )
