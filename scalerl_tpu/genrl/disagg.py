"""Disaggregated sequence-RL dataflow: generation fleet -> sharded learner.

MindSpeed RL's core argument (PAPERS.md, arxiv 2507.19017) is that
generation and training want different hardware shapes and must scale as
separate tiers; SEED RL showed the learner is just one client of a serving
plane.  This module composes the ingredients the repo already has — the
elastic fleet's drain/exactly-once machinery (``fleet/cluster.py``), the
KV-cached generation engines (``genrl/engine.py`` / ``continuous.py``), and
the dp×mp learner — into that topology: N generation hosts each running an
engine behind a jax-free :class:`GenerationHost` shell, streaming completed
generation-tagged sequences over the codec-v2 fleet wire into the learner's
sequence replay, with param snapshots flowing back as quantized
generation-tagged pushes.

Wire protocol (dicts over ``fleet.transport.Connection``, codec v2 — the
CRC / ``ProtocolError``-drops-the-link semantics of the data plane apply
as-is):

    host→learner    {"kind": "gen_hello", "host_id": h, "host_epoch": e,
                     "lanes": n}           membership announce (on connect
                                           AND after every reconnect)
                    {"kind": "lease", "n": k, "have_gen": g}
                                           request k prompt leases; the
                                           reply piggybacks the newest
                                           snapshot generation
                    {"kind": "params", "have": g}
                                           fetch the quantized snapshot if
                                           stale
                    {"kind": "seq_batch", "v": [seq...], "seq": s}
                                           completed sequence chunks,
                                           RETAINED by the host until acked
                    {"kind": "lease_return", "v": [lease...]}
                                           unstarted/abandoned leases handed
                                           back (drain, or give-up) for
                                           reissue — no prompt is lost
                    {"kind": "drain_done", "host_id": h}
    learner→host    {"kind": "gen_welcome", "epoch": e, "gen": g}
                                           hello reply: the LEARNER epoch
                                           (bumped on every ledger resume)
                                           and the current snapshot
                                           generation a (re)joining host
                                           must adopt before admitting work
                    {"kind": "lease", "v": [lease...], "gen": g, "epoch": e}
                                           lease None = prompt source done
                    {"kind": "params", "generation": g, "weights": tree,
                     "epoch": e}
                                           int8-quantized wire snapshot
                                           (``quantize_wire_tree``)
                    {"kind": "seq_ack", "seq": s}
                    {"kind": "drain"}      stop admitting prompts, finish
                                           (or return) live lanes, flush +
                                           await acks, exit 0

Robustness is the PR 4/9 machinery applied at sequence granularity:

- every completed sequence carries the at-least-once dedup key
  ``(host_id, host_epoch, seq_id)`` — un-acked uploads are resent after a
  reconnect and absorbed by the learner's bounded per-host epoch table;
- every prompt lease is stamped with a monotonic ``_task_id`` tracked per
  link: a host killed mid-decode has its in-flight leases requeued for the
  surviving/backfilled fleet, and a racing duplicate completion (the corpse
  finished it too) counts exactly once (``disagg.duplicate_leases``);
- the drain protocol extends PR 9's: a draining generation host stops
  admitting prompts, finishes (or returns) its live lanes, flushes and
  awaits acks, then exits 0 — zero sequences lost to a deliberate
  scale-down;
- ``mass_kill`` chaos waves ride :func:`fleet.cluster.apply_mass_kill`
  under the ``disagg`` site, and the autoscaler's floor rule backfills
  through :class:`GenerationTierExecutor`;
- the learner itself is preemptible: a SIGTERM'd learner saves its whole
  accounting plane (lease table, dedup keys, accepted-but-unconsumed
  sequences, snapshot generation) into a durable ledger
  (``genrl/ledger.py``) and a restart resumes it under a bumped **learner
  epoch** — hosts park in-flight work, redial with capped backoff, and the
  ``gen_welcome`` handshake re-synchronizes epoch + snapshot generation so
  pre-restart uploads dedup exactly (docs/DISTRIBUTED.md "Preemption &
  elastic membership").

jax-free by design: the shells, the learner endpoint, and the scripted
engine run in processes that never import jax (the soak's whole point);
real engines arrive through a picklable ``engine_factory`` and only THAT
callable touches jax.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional, Set, Tuple

import numpy as np

from scalerl_tpu.fleet.hub import QueueHub
from scalerl_tpu.fleet.transport import Connection, PipeConnection
from scalerl_tpu.genrl import ledger as ledger_store
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.runtime.autoscaler import FleetSignals
from scalerl_tpu.runtime.param_server import ParamSnapshotPlane
from scalerl_tpu.runtime.supervisor import (
    DRAIN,
    DRAIN_DONE,
    exp_backoff,
    is_heartbeat,
    make_drain,
    make_pong,
)
from scalerl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# EngineFactory: (host params (dequantized wire tree), wire generation) ->
# an engine shell (see ScriptedSequenceEngine for the duck-typed surface).
# Must be picklable (module-level class/function) for spawn-mode fleets.
EngineFactory = Callable[[Any, int], Any]


# ---------------------------------------------------------------------------
# wire snapshot format: host-side quantization (numpy twin of
# runtime/quantize.py, so shells that never import jax can decode it)

WIRE_QUANT_MODES = ("int8", "none")
_QKEY = "__q__"


def _native_float(arr: np.ndarray) -> np.ndarray:
    """Non-native float dtypes (bf16 params arriving via device_get as
    ml_dtypes arrays) widen to float32 for the wire — the codec only
    frames native numpy dtypes."""
    if arr.dtype.kind not in "fiub?":
        return arr.astype(np.float32)
    return arr


def quantize_wire_tree(tree: Any, mode: str) -> Any:
    """Compress a HOST weight pytree for the snapshot wire.

    ``"int8"`` mirrors ``runtime/quantize.py``'s semantics in numpy: per
    leaf symmetric quantization (one f32 scale = max|x| / 127) for float
    leaves with ``ndim >= 2``; 1-D f32-sensitive leaves (biases, norms)
    pass through untouched.  ``"none"`` passes every leaf through (still
    normalizing non-native float dtypes).  The output is a plain
    dict/list/tuple/ndarray pytree the codec frames as-is.
    """
    if mode not in WIRE_QUANT_MODES:
        raise ValueError(
            f"wire quantize mode must be one of {WIRE_QUANT_MODES}, got "
            f"{mode!r}"
        )

    def enc(node: Any) -> Any:
        if isinstance(node, dict):
            return {k: enc(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(enc(v) for v in node)
        if isinstance(node, np.ndarray) or np.isscalar(node) or hasattr(
            node, "dtype"
        ):
            arr = _native_float(np.asarray(node))
            if (
                mode == "int8"
                and arr.ndim >= 2
                and np.issubdtype(arr.dtype, np.floating)
            ):
                amax = float(np.max(np.abs(arr.astype(np.float32))))
                scale = max(amax / 127.0, 1e-12)
                q = np.clip(
                    np.round(arr.astype(np.float32) / scale), -127, 127
                ).astype(np.int8)
                return {
                    _QKEY: 1,
                    "q": q,
                    "scale": float(scale),
                    "dtype": arr.dtype.name,
                }
            return arr
        return node

    return enc(tree)


def dequantize_wire_tree(tree: Any) -> Any:
    """Reconstruct a :func:`quantize_wire_tree` snapshot (original numpy
    dtypes; lossless for passthrough leaves)."""

    def dec(node: Any) -> Any:
        if isinstance(node, dict):
            if node.get(_QKEY) == 1:
                return (
                    node["q"].astype(np.float32) * np.float32(node["scale"])
                ).astype(np.dtype(node["dtype"]))
            return {k: dec(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(dec(v) for v in node)
        return node

    return dec(tree)


def wire_tree_bytes(tree: Any) -> int:
    """Snapshot payload size in bytes — the broadcast-bandwidth number the
    int8 wire format exists to shrink (the ``bench --mode disagg`` row)."""
    total = 0

    def walk(node: Any) -> None:
        nonlocal total
        if isinstance(node, dict):
            if node.get(_QKEY) == 1:
                total += node["q"].nbytes + 4
                return
            for v in node.values():
                walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v)
        elif isinstance(node, np.ndarray):
            total += node.nbytes

    walk(tree)
    return total


# ---------------------------------------------------------------------------
# config


@dataclass
class DisaggConfig:
    """Knobs for the disaggregated dataflow (both tiers adopt the
    learner's copy — the generation-host processes receive it at spawn)."""

    num_hosts: int = 2
    lanes_per_host: int = 4          # engine shell admission capacity
    lease_prefetch: int = 0          # leases fetched per RPC; 0 -> lanes + 1
    upload_batch: int = 4            # completed sequences per uplink frame
    compress_uplink: bool = True
    heartbeat_interval_s: float = 5.0
    heartbeat_timeout_s: float = 0.0
    max_pending: int = 0             # learner hub bounded admission
    seq_maxsize: int = 4096          # learner-side accepted-sequence queue
    snapshot_quantize: str = "int8"  # int8 | none (wire snapshot format)
    # a draining host may spend this many engine steps finishing live
    # lanes before abandoning the rest back to the learner for reissue
    drain_step_budget: int = 2000
    ack_timeout_s: float = 30.0      # drain/exit wait for retained uploads
    # learner-loss recovery: a host that loses its uplink parks in-flight
    # work and redials with capped exponential backoff before giving up
    reconnect_backoff_s: float = 0.05
    reconnect_backoff_cap_s: float = 2.0
    reconnect_max_tries: int = 40

    @property
    def heartbeat_timeout(self) -> float:
        return self.heartbeat_timeout_s or 2.0 * self.heartbeat_interval_s

    @property
    def prefetch(self) -> int:
        return self.lease_prefetch or self.lanes_per_host + 1

    def validate(self) -> None:
        if self.num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {self.num_hosts}")
        if self.lanes_per_host < 1:
            raise ValueError(
                f"lanes_per_host must be >= 1, got {self.lanes_per_host}"
            )
        if self.snapshot_quantize not in WIRE_QUANT_MODES:
            raise ValueError(
                f"snapshot_quantize must be one of {WIRE_QUANT_MODES}, got "
                f"{self.snapshot_quantize!r}"
            )
        if self.upload_batch < 1:
            raise ValueError(
                f"upload_batch must be >= 1, got {self.upload_batch}"
            )
        if self.reconnect_max_tries < 1:
            raise ValueError(
                "reconnect_max_tries must be >= 1, got "
                f"{self.reconnect_max_tries}"
            )


def _device_ready(params: Any) -> Any:
    """One EXPLICIT batched host->device upload of a wire snapshot before
    it reaches a jax engine — the engine's steady-state transfer guard
    (JG001's runtime twin) rightly rejects numpy params sneaking an
    implicit transfer into every warm round.  jax-referenced only when
    already loaded: scripted shells in jax-free children pass through.
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return params
    return jax.device_put(params)


# ---------------------------------------------------------------------------
# tracing helpers: the sequence lifecycle is ONE trace — root opened by the
# learner at lease issue, every edge a retroactive span off host monotonic
# stamps (docs/OBSERVABILITY.md "Distributed tracing" has the taxonomy)

# private host-side stamps riding the lease through the engine shells;
# stripped before a payload goes on the wire
_T_SUBMIT = "_t_submit"
_T_RECV = "_t_recv"
# speculative-decode timer mark at submit (cumulative engine draft/verify
# seconds): lets the harvest edge apportion draft vs verify time into
# child spans under seq.decode (ISSUE 16)
_T_SPEC = "_t_spec"


def _inherit_trace(payload: Dict[str, Any], lease: Mapping[str, Any]) -> None:
    """Copy the lease's propagated context (and the submit stamp) onto its
    completion payload, so the host shell can emit the decode edge and the
    learner/trainer can keep extending the same trace."""
    ctx = lease.get(tracing.TRACE_KEY)
    if ctx is not None:
        payload[tracing.TRACE_KEY] = ctx
        t_sub = lease.get(_T_SUBMIT)
        if t_sub is not None:
            payload[_T_SUBMIT] = t_sub
        spec = lease.get(_T_SPEC)
        if spec is not None:
            payload[_T_SPEC] = spec


def record_consumption_trace(
    payloads: List[Dict[str, Any]],
    t_drain: float,
    t_add0: float,
    t_add1: float,
    t_learn0: float,
    t_learn1: float,
    learn_step: int,
) -> int:
    """Extend every traced wire payload with the learner-side edges —
    ``seq.replay_wait`` (accepted-queue dwell), ``seq.seq_add`` (replay
    insert) and ``seq.learn_step`` (the learn step that consumed it).  All
    arguments are ``time.monotonic()`` stamps the caller already took
    around work it already does; returns the number of traces extended.
    Shared by :class:`~scalerl_tpu.trainer.sequence_rl.
    DisaggSequenceRLTrainer` and the jax-free soak's consumption loop."""
    n = 0
    for p in payloads:
        ctx = tracing.extract(p)
        if ctx is None:
            continue
        n += 1
        t_q = p.get("_t_q")
        if isinstance(t_q, (int, float)):
            tracing.record_span(
                "seq.replay_wait", parent=ctx, t_start=float(t_q),
                t_end=t_drain, kind="disagg",
            )
        tracing.record_span(
            "seq.seq_add", parent=ctx, t_start=t_add0, t_end=t_add1,
            kind="disagg", step=learn_step,
        )
        tracing.record_span(
            "seq.learn_step", parent=ctx, t_start=t_learn0, t_end=t_learn1,
            kind="disagg", step=learn_step,
        )
    return n


# ---------------------------------------------------------------------------
# engine shells: the duck-typed surface GenerationHost drives
#
#   generation: int                      wire generation currently loaded
#   push_params(params, generation)      adopt a dequantized wire snapshot
#   capacity() -> int                    leases admissible right now
#   submit(lease: dict) -> None          admit one lease
#   step() -> List[dict]                 advance; completed payloads
#   live() -> int                        leases in flight
#   abandon() -> List[dict]              give up in-flight leases (drain)


def scripted_sequence_payload(
    seed: int, response_len: int, vocab: int, generation: int,
    sample: int = 0,
) -> Dict[str, Any]:
    """The deterministic completion a :class:`ScriptedSequenceEngine`
    produces for lease ``seed`` — a pure function of the lease (and the
    ``sample`` index within a fanned-out group), NEVER of the host that
    ran it, so chaos tests can assert bit-exact payloads across kills,
    requeues, and racing duplicate executions."""
    rng = (
        np.random.default_rng(int(seed))
        if sample == 0
        else np.random.default_rng((int(seed), int(sample)))
    )
    n = int(rng.integers(1, 5))
    r = int(rng.integers(1, response_len + 1))
    return {
        "seed": int(seed),
        "prompt": rng.integers(2, vocab, size=n).astype(np.int32),
        "prompt_len": n,
        "response_tokens": rng.integers(2, vocab, size=r).astype(np.int32),
        "behavior_logp": -rng.random(r).astype(np.float32),
        "values": rng.standard_normal(r).astype(np.float32),
        "generation": int(generation),
    }


class ScriptedSequenceEngine:
    """jax-free deterministic engine shell for soaks and chaos tests.

    "Decodes" ``tokens_per_step`` tokens per :meth:`step` per live lease
    (so a preemption wave genuinely lands MID-DECODE), then emits the
    scripted payload — a pure function of the lease seed, host-independent,
    so exact-unique accounting can also verify every byte.
    """

    def __init__(
        self,
        lanes: int = 4,
        response_len: int = 8,
        tokens_per_step: int = 2,
        step_sleep_s: float = 0.0,
        vocab: int = 32,
    ) -> None:
        self.lanes = lanes
        self.response_len = response_len
        self.tokens_per_step = max(int(tokens_per_step), 1)
        self.step_sleep_s = step_sleep_s
        self.vocab = vocab
        self.generation = 0
        self._live: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()

    def push_params(self, params: Any, generation: int) -> None:
        self.generation = int(generation)

    def capacity(self) -> int:
        return self.lanes - len(self._live)

    def live(self) -> int:
        return len(self._live)

    def submit(self, lease: Dict[str, Any]) -> None:
        seed = int(lease.get("seed", 0))
        samples = int(lease.get("samples", 1))
        # a fanned-out lease occupies one scripted lane per sample —
        # every sample is its own deterministic payload, so kills landing
        # between sibling completions still account exactly
        for k in range(samples):
            payload = scripted_sequence_payload(
                seed, self.response_len, self.vocab, self.generation,
                sample=k,
            )
            self._live[(id(lease), k)] = {
                "lease": lease,
                "sample": k,
                "samples": samples,
                "payload": payload,
                "remaining": len(payload["response_tokens"]),
            }

    def step(self) -> List[Dict[str, Any]]:
        if self.step_sleep_s:
            time.sleep(self.step_sleep_s)
        done: List[Dict[str, Any]] = []
        for key in list(self._live):
            entry = self._live[key]
            entry["remaining"] -= self.tokens_per_step
            if entry["remaining"] <= 0:
                payload = dict(entry["payload"])
                tid = entry["lease"].get("_task_id")
                if tid is not None:
                    payload["_task_id"] = tid
                if entry["samples"] > 1:
                    payload["_sample_idx"] = entry["sample"]
                    payload["_samples_total"] = entry["samples"]
                _inherit_trace(payload, entry["lease"])
                done.append(payload)
                del self._live[key]
        return done

    def abandon(self) -> List[Dict[str, Any]]:
        leases: List[Dict[str, Any]] = []
        seen: Set[int] = set()
        for e in self._live.values():
            if id(e["lease"]) not in seen:
                seen.add(id(e["lease"]))
                leases.append(e["lease"])
        self._live.clear()
        return leases


class ScriptedEngineFactory:
    """Picklable factory for spawn-mode fleets (the soak's engine)."""

    def __init__(
        self,
        lanes: int = 4,
        response_len: int = 8,
        tokens_per_step: int = 2,
        step_sleep_s: float = 0.0,
        vocab: int = 32,
    ) -> None:
        self.lanes = lanes
        self.response_len = response_len
        self.tokens_per_step = tokens_per_step
        self.step_sleep_s = step_sleep_s
        self.vocab = vocab

    def __call__(self, params: Any, generation: int) -> ScriptedSequenceEngine:
        eng = ScriptedSequenceEngine(
            lanes=self.lanes,
            response_len=self.response_len,
            tokens_per_step=self.tokens_per_step,
            step_sleep_s=self.step_sleep_s,
            vocab=self.vocab,
        )
        eng.push_params(params, generation)
        return eng


class CohortEngineShell:
    """Drive a fixed-cohort :class:`~scalerl_tpu.genrl.engine.
    GenerationEngine` as a disagg shell: buffered leases flush as one
    bucket-pair round per :meth:`step` (the engine's whole-round program),
    and each lease's true-length slice becomes its wire payload.

    The engine's internal generation counter is mapped to the WIRE
    generation the learner published (``push_params`` records the pair),
    so payload tags speak the learner's id space.
    """

    def __init__(
        self, engine: Any, round_batch: int, initial_generation: int = 0
    ) -> None:
        self.engine = engine
        self.round_batch = max(int(round_batch), 1)
        self.generation = int(initial_generation)
        self._pending: List[Dict[str, Any]] = []
        # the engine's internal counter at construction maps to the WIRE
        # generation its construction params carried
        self._gen_map: Dict[int, int] = {
            int(engine.generation): int(initial_generation)
        }

    def push_params(self, params: Any, generation: int) -> None:
        self._gen_map[
            self.engine.push_params(_device_ready(params))
        ] = int(generation)
        while len(self._gen_map) > 64:
            self._gen_map.pop(min(self._gen_map))
        self.generation = int(generation)

    def capacity(self) -> int:
        return self.round_batch - len(self._pending)

    def live(self) -> int:
        return len(self._pending)

    def submit(self, lease: Dict[str, Any]) -> None:
        # a fanned-out lease occupies one cohort lane per sample (the
        # GRPO tiled layout; the prefix-CoW savings live on the
        # continuous engine — here fan-out is a data-layout feature)
        samples = int(lease.get("samples", 1)) if isinstance(
            lease, dict
        ) else 1
        for k in range(samples):
            self._pending.append((lease, k, samples))

    def abandon(self) -> List[Dict[str, Any]]:
        leases: List[Dict[str, Any]] = []
        seen = set()
        for lease, _k, _n in self._pending:
            if id(lease) not in seen:
                seen.add(id(lease))
                leases.append(lease)
        self._pending = []
        return leases

    def step(self) -> List[Dict[str, Any]]:
        if not self._pending:
            return []
        # flush at most one fixed round's worth of lanes; a group whose
        # tail overflows the round rides the next one
        batch = self._pending[: self.round_batch]
        self._pending = self._pending[self.round_batch :]
        lengths = np.ones((self.round_batch,), np.int32)
        for i, (t, _k, _n) in enumerate(batch):
            lengths[i] = int(t["length"])
        L = int(lengths.max())
        # partial rounds pad with inert lanes up to the FIXED round batch
        # (batch size is a jit shape: a ragged round would retrace), and
        # the pad lanes' outputs are simply dropped below
        prompts = np.full((self.round_batch, L), 2, np.int32)
        for i, (t, _k, _n) in enumerate(batch):
            prompts[i, : lengths[i]] = np.asarray(
                t["prompt"], np.int32
            )[: lengths[i]]
        result = self.engine.generate(prompts, lengths)
        wire_gen = self._gen_map.get(result.generation, result.generation)
        out = []
        for i, (t, k, n) in enumerate(batch):
            r = max(int(result.response_len[i]), 1)
            payload = {
                "prompt": prompts[i, : lengths[i]].copy(),
                "prompt_len": int(lengths[i]),
                "response_tokens": result.response_tokens[i, :r].copy(),
                "behavior_logp": result.behavior_logp[i, :r].copy(),
                "values": result.values[i, :r].copy(),
                "generation": int(wire_gen),
            }
            tid = t.get("_task_id")
            if tid is not None:
                payload["_task_id"] = tid
            if n > 1:
                payload["_sample_idx"] = k
                payload["_samples_total"] = n
            _inherit_trace(payload, t)
            out.append(payload)
        return out


class ContinuousEngineShell:
    """Drive a :class:`~scalerl_tpu.genrl.continuous.ContinuousEngine` as
    a disagg shell: leases ride the engine's admission queue with their
    lease id as the lane ``tag``, so out-of-order completions still close
    the lease that admitted them."""

    def __init__(self, engine: Any, initial_generation: int = 0) -> None:
        self.engine = engine
        self.generation = int(initial_generation)
        self._live: Dict[int, Dict[str, Any]] = {}
        self._next = 0
        self._gen_map: Dict[int, int] = {
            int(engine.generation): int(initial_generation)
        }

    def push_params(self, params: Any, generation: int) -> None:
        self._gen_map[
            self.engine.push_params(_device_ready(params))
        ] = int(generation)
        while len(self._gen_map) > 64:
            self._gen_map.pop(min(self._gen_map))
        self.generation = int(generation)

    def capacity(self) -> int:
        return (
            self.engine.config.lanes
            - self.engine.live_lanes
            - self.engine.pending
        )

    def live(self) -> int:
        return len(self._live)

    def spec_timers(self) -> Optional[Tuple[float, float]]:
        """Cumulative (draft_s, verify_s) when the wrapped engine decodes
        speculatively, else None — the host's trace edges use deltas of
        this to attribute draft vs verify time under seq.decode."""
        timers = getattr(self.engine, "spec_timers", None)
        return timers() if timers is not None else None

    def submit(self, lease: Dict[str, Any]) -> None:
        key = self._next
        self._next += 1
        samples = int(lease.get("samples", 1)) if isinstance(
            lease, dict
        ) else 1
        self._live[key] = {"lease": lease, "n": samples, "arrived": 0}
        # a fanned-out lease rides submit_group: the engine admits all
        # n lanes over ONE shared prompt prefix (CoW fork) — the perf
        # half of the GRPO group shape
        self.engine.submit_group(
            np.asarray(lease["prompt"], np.int32),
            samples,
            int(lease["length"]),
            tag=key,
        )

    def abandon(self) -> List[Dict[str, Any]]:
        """Give up leases still in flight (their lanes cannot be evicted
        mid-decode); the learner reissues them, and the eventual straggler
        completion is absorbed by lease-level dedup."""
        leases = [e["lease"] for e in self._live.values()]
        self._live.clear()
        return leases

    def step(self) -> List[Dict[str, Any]]:
        out = []
        for c in self.engine.step():
            entry = self._live.get(c.tag)
            if entry is None:
                continue  # abandoned during a drain: the reissue owns it
            lease = entry["lease"]
            sample_idx = entry["arrived"]
            entry["arrived"] += 1
            if entry["arrived"] >= entry["n"]:
                self._live.pop(c.tag, None)
            payload = {
                "prompt": np.asarray(c.prompt, np.int32),
                "prompt_len": int(c.prompt_len),
                "response_tokens": np.asarray(c.response_tokens, np.int32),
                "behavior_logp": np.asarray(c.behavior_logp, np.float32),
                "values": np.asarray(c.values, np.float32),
                "generation": int(
                    self._gen_map.get(c.generation, c.generation)
                ),
            }
            tid = lease.get("_task_id")
            if tid is not None:
                payload["_task_id"] = tid
            if entry["n"] > 1:
                payload["_sample_idx"] = sample_idx
                payload["_samples_total"] = entry["n"]
            _inherit_trace(payload, lease)
            out.append(payload)
        return out


# ---------------------------------------------------------------------------
# the generation-host shell


class GenerationHost:
    """One generation host's jax-free protocol shell.

    Owns the learner link and the robustness machinery — lease prefetch,
    retained-until-acked uploads with resend-after-reconnect, heartbeat
    answering, and the drain protocol — while the actual token generation
    lives behind the duck-typed engine shell built by ``engine_factory``
    from the first fetched param snapshot.  Everything here is host numpy;
    the factory is the only seam that may touch jax.
    """

    def __init__(
        self,
        conn: Connection,
        config: DisaggConfig,
        engine_factory: EngineFactory,
        host_id: int,
        reconnect: Optional[Callable[[], Connection]] = None,
    ) -> None:
        self.conn = conn
        self.config = config
        self.engine_factory = engine_factory
        self.host_id = int(host_id)
        self.reconnect = reconnect
        self.host_epoch = int.from_bytes(os.urandom(4), "big")
        # the learner's incarnation, adopted from gen_welcome (and every
        # lease/params reply): uploads are stamped with it so a restarted
        # learner can attribute redeliveries to its predecessor exactly
        self.learner_epoch = 0
        self.engine: Any = None
        self._have_gen = -1
        self._latest_gen = 0
        self._queued: Deque[Dict[str, Any]] = deque()
        self._completed: List[Dict[str, Any]] = []
        self._seq_id = 0
        self._upload_seq = 0
        self._unacked: Dict[int, List[Dict[str, Any]]] = {}
        # per-upload trace metadata: [(ctx, t_flush), ...] so the ack can
        # close each sequence's seq.upload edge (flush -> ack, wire + wait)
        self._unacked_trace: Dict[int, List[Tuple[Any, float]]] = {}
        self._exhausted = False
        self._draining = False
        reg = telemetry.get_registry()
        self._seq_counter = reg.counter("disagg_host.sequences")
        self._upload_counter = reg.counter("disagg_host.uploads")
        self._fetch_counter = reg.counter("disagg_host.param_fetches")
        self._reconnect_counter = reg.counter("disagg_host.reconnects")
        self._send_hello()

    # -- link -----------------------------------------------------------
    def _send_hello(self) -> None:
        self.conn.send(
            {
                "kind": "gen_hello",
                "host_id": self.host_id,
                "host_epoch": self.host_epoch,
                "lanes": self.config.lanes_per_host,
            }
        )

    def _replace_conn(self, why: Exception) -> None:
        if self.reconnect is None:
            raise why
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001 — link already broken
            pass
        # learner loss: everything in flight stays PARKED by construction
        # (queued leases, live lanes, retained un-acked uploads) while we
        # redial with capped exponential backoff — a restarting learner
        # takes a while to come back, and a dead one ends the host only
        # after the full budget
        for attempt in range(self.config.reconnect_max_tries):
            try:
                self.conn = self.reconnect()
                break
            except (ConnectionError, EOFError, OSError):
                if attempt + 1 >= self.config.reconnect_max_tries:
                    raise why
                time.sleep(
                    exp_backoff(
                        attempt,
                        base=self.config.reconnect_backoff_s,
                        cap=self.config.reconnect_backoff_cap_s,
                    )
                )
        self._reconnect_counter.inc()
        telemetry.record_event(
            "gen_host_reconnect", host=self.host_id,
            retained_uploads=len(self._unacked),
        )
        # membership first (the learner requeued our leases when the old
        # link dropped), then every retained upload on the fresh link
        self._send_hello()
        for seq in sorted(self._unacked):
            self.conn.send(
                {"kind": "seq_batch", "v": self._unacked[seq], "seq": seq},
                compress=self.config.compress_uplink,
            )

    def _send(self, msg: Dict[str, Any], compress: bool = False) -> None:
        while True:
            try:
                self.conn.send(msg, compress=compress)
                return
            except (ConnectionError, BrokenPipeError, OSError) as e:
                self._replace_conn(e)

    def _absorb(self, msg: Any) -> bool:
        """Handle an unsolicited frame; True when it was consumed."""
        if is_heartbeat(msg):
            if msg.get("kind") == "ping":
                self.conn.send(make_pong(msg))
            return True
        if isinstance(msg, dict) and msg.get("kind") == "seq_ack":
            seq = int(msg.get("seq", -1))
            self._unacked.pop(seq, None)
            now = time.monotonic()
            for ctx, t_flush in self._unacked_trace.pop(seq, ()):
                # the upload edge closes at the ACK, so a reconnect
                # retransmit shows up as a long seq.upload span — exactly
                # the causality the critical-path report exists to surface
                tracing.record_span(
                    "seq.upload", parent=ctx, t_start=t_flush, t_end=now,
                    kind="disagg", host=self.host_id,
                )
            return True
        if isinstance(msg, dict) and msg.get("kind") == "gen_welcome":
            self._adopt_epoch(msg)
            # a (re)joining host adopts the learner's CURRENT snapshot
            # generation before admitting work: lifting _latest_gen makes
            # the run loop refetch params ahead of the next lease
            self._latest_gen = max(self._latest_gen, int(msg.get("gen", 0)))
            return True
        if isinstance(msg, dict) and msg.get("kind") == DRAIN:
            self._draining = True
            return True
        return False

    def _adopt_epoch(self, msg: Mapping[str, Any]) -> None:
        epoch = int(msg.get("epoch", self.learner_epoch))
        if epoch != self.learner_epoch:
            telemetry.record_event(
                "learner_epoch_adopted", host=self.host_id,
                epoch=epoch, prev=self.learner_epoch,
            )
            self.learner_epoch = epoch

    def _rpc(self, msg: Dict[str, Any]) -> Any:
        """send + recv with unsolicited-frame filtering and reconnect."""
        while True:
            try:
                self.conn.send(msg)
                while True:
                    reply = self.conn.recv()
                    if not self._absorb(reply):
                        return reply
            except (ConnectionError, EOFError, OSError, TimeoutError) as e:
                self._replace_conn(e)

    def _pump(self) -> None:
        try:
            while self.conn.poll(0):
                self._absorb(self.conn.recv())
        except (ConnectionError, EOFError, OSError) as e:
            self._replace_conn(e)

    # -- dataflow --------------------------------------------------------
    def _fetch_params(self) -> None:
        t0 = time.monotonic()
        reply = self._rpc({"kind": "params", "have": self._have_gen})
        if not isinstance(reply, dict):
            return
        self._adopt_epoch(reply)
        if "weights" not in reply:
            return
        gen = int(reply["generation"])
        params = dequantize_wire_tree(reply["weights"])
        self._fetch_counter.inc()
        if self.engine is None:
            self.engine = self.engine_factory(params, gen)
        else:
            self.engine.push_params(params, gen)
        self._have_gen = gen
        self._latest_gen = max(self._latest_gen, gen)
        ctx = tracing.extract(reply)
        if ctx is not None:
            # child of the learner's snapshot_publish span: fetch + decode
            # + engine adoption, one edge per host per generation
            tracing.record_span(
                "snapshot.fetch", parent=ctx, t_start=t0,
                t_end=time.monotonic(), kind="disagg",
                generation=gen, host=self.host_id,
            )

    def _request_leases(self) -> None:
        want = min(
            self.config.prefetch,
            max(self.engine.capacity() - len(self._queued), 0)
            if self.engine is not None
            else self.config.prefetch,
        )
        if want <= 0:
            return
        reply = self._rpc(
            {"kind": "lease", "n": want, "have_gen": self._have_gen}
        )
        self._adopt_epoch(reply)
        self._latest_gen = max(self._latest_gen, int(reply.get("gen", 0)))
        now = time.monotonic()
        for lease in reply.get("v", []):
            if lease is None:
                self._exhausted = True
            else:
                if isinstance(lease, dict) and tracing.TRACE_KEY in lease:
                    # the queue-wait edge opens here: lease in hand, not
                    # yet admitted to a lane
                    lease[_T_RECV] = now
                self._queued.append(lease)

    def _trace_submit(self, lease: Any) -> Any:
        """Close the queue-wait edge and stamp the submit time the decode
        edge starts from (host monotonic stamps only)."""
        if isinstance(lease, dict):
            ctx = tracing.extract(lease)
            if ctx is not None:
                now = time.monotonic()
                tracing.record_span(
                    "seq.queue_wait", parent=ctx,
                    t_start=float(lease.pop(_T_RECV, now)), t_end=now,
                    kind="disagg", host=self.host_id,
                )
                lease[_T_SUBMIT] = now
                spec = getattr(self.engine, "spec_timers", None)
                if spec is not None:
                    mark = spec()
                    if mark is not None:
                        lease[_T_SPEC] = mark
        return lease

    def _trace_harvest(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Close the decode edge (engine submit -> harvested completion;
        one span per harvested sequence, never per token).  When the
        engine decodes speculatively, two child spans under seq.decode
        apportion the engine's draft vs verify seconds that elapsed over
        this sequence's decode window (engine-wide aggregates — the
        per-pass truth lives in the engine's own genrl.macro_step spans;
        this gives the critical-path analyzer named draft/verify edges on
        the SEQUENCE trace without any per-token work)."""
        ctx = tracing.extract(payload)
        if ctx is not None:
            t_sub = payload.pop(_T_SUBMIT, None)
            mark = payload.pop(_T_SPEC, None)
            if t_sub is not None:
                t_sub = float(t_sub)
                span = tracing.record_span(
                    "seq.decode", parent=ctx, t_start=t_sub,
                    t_end=time.monotonic(), kind="disagg",
                    host=self.host_id,
                    tokens=int(np.size(payload.get("response_tokens", ()))),
                )
                spec = getattr(self.engine, "spec_timers", None)
                if mark is not None and spec is not None:
                    now_mark = spec()
                    if now_mark is not None:
                        dd = max(now_mark[0] - float(mark[0]), 0.0)
                        dv = max(now_mark[1] - float(mark[1]), 0.0)
                        if dd > 0.0:
                            tracing.record_span(
                                "seq.draft", parent=span, t_start=t_sub,
                                t_end=t_sub + dd, kind="disagg",
                                host=self.host_id,
                            )
                        if dv > 0.0:
                            tracing.record_span(
                                "seq.verify", parent=span,
                                t_start=t_sub + dd, t_end=t_sub + dd + dv,
                                kind="disagg", host=self.host_id,
                            )
        return payload

    def _flush(self, force: bool = False) -> None:
        if not self._completed:
            return
        if not force and len(self._completed) < self.config.upload_batch:
            return
        batch, self._completed = self._completed, []
        self._upload_seq += 1
        self._unacked[self._upload_seq] = batch
        now = time.monotonic()
        traced = [
            (tracing.extract(p), now) for p in batch
            if tracing.extract(p) is not None
        ]
        if traced:
            self._unacked_trace[self._upload_seq] = traced
        self._upload_counter.inc()
        self._send(
            {"kind": "seq_batch", "v": batch, "seq": self._upload_seq},
            compress=self.config.compress_uplink,
        )

    def _stamp(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        payload["host_id"] = self.host_id
        payload["host_epoch"] = self.host_epoch
        payload["seq_id"] = self._seq_id
        # the epoch dimension of the at-least-once key: a redelivery that
        # was generated under a previous learner incarnation is attributed
        # to the resume, not to ordinary wire duplication
        payload["learner_epoch"] = self.learner_epoch
        self._seq_id += 1
        return payload

    def _await_acks(self) -> bool:
        deadline = time.monotonic() + self.config.ack_timeout_s
        while self._unacked and time.monotonic() < deadline:
            try:
                if self.conn.poll(0.1):
                    self._absorb(self.conn.recv())
            except (ConnectionError, EOFError, OSError) as e:
                try:
                    self._replace_conn(e)
                except (ConnectionError, EOFError, OSError):
                    return False
        return not self._unacked

    # -- the host loop ---------------------------------------------------
    def run(self) -> None:
        """The host lifecycle: lease -> generate -> upload until drained
        (clean exit 0), the prompt source runs dry, or the link dies
        past the reconnect budget."""
        try:
            while True:
                self._pump()
                if self._draining:
                    self._run_drain()
                    return
                # params before leases: the first lease must decode on a
                # real snapshot (the factory needs one to build the engine)
                if self.engine is None or self._latest_gen > self._have_gen:
                    self._fetch_params()
                    if self.engine is None:
                        time.sleep(0.05)
                        continue
                if not self._exhausted and self.engine.capacity() > 0 and (
                    len(self._queued) < self.config.prefetch
                ):
                    self._request_leases()
                while self._queued and self.engine.capacity() > 0:
                    self.engine.submit(
                        self._trace_submit(self._queued.popleft())
                    )
                if self.engine.live() > 0:
                    for payload in self.engine.step():
                        self._seq_counter.inc()
                        self._completed.append(
                            self._stamp(self._trace_harvest(payload))
                        )
                    self._flush()
                elif self._exhausted and not self._queued:
                    # source dry, everything decoded: final flush + acks,
                    # then a clean exit (the Gather end-of-source shape)
                    self._flush(force=True)
                    self._await_acks()
                    return
                else:
                    time.sleep(0.005)
        except (KeyboardInterrupt, ConnectionError, EOFError, OSError):
            pass
        finally:
            try:
                self.conn.close()
            except Exception:  # noqa: BLE001 — already gone
                pass

    def _run_drain(self) -> None:
        """The drain protocol at sequence granularity: stop admitting,
        return unstarted leases, finish live lanes within the step budget
        (abandoning the remainder for reissue), flush + await acks, then
        announce ``drain_done`` and exit 0."""
        telemetry.record_event("drain_begin", host=self.host_id)
        returned = list(self._queued)
        self._queued.clear()
        if self.engine is not None:
            for _ in range(self.config.drain_step_budget):
                if self.engine.live() == 0:
                    break
                for payload in self.engine.step():
                    self._completed.append(
                        self._stamp(self._trace_harvest(payload))
                    )
            returned.extend(self.engine.abandon())
        for lease in returned:
            if isinstance(lease, dict):
                # host-local monotonic stamps are meaningless on the host
                # that gets the reissue — it re-stamps its own edges
                lease.pop(_T_RECV, None)
                lease.pop(_T_SUBMIT, None)
        if returned:
            self._send({"kind": "lease_return", "v": returned})
        self._flush(force=True)
        acked = self._await_acks()
        telemetry.record_event(
            "drain_done", host=self.host_id, acked=acked
        )
        self._send({"kind": DRAIN_DONE, "host_id": self.host_id})


def generation_host_main(
    conn: Connection,
    config: DisaggConfig,
    engine_factory: EngineFactory,
    host_id: int,
    reconnect: Optional[Callable[[], Connection]] = None,
) -> None:
    """Process/thread entry point (``open_worker_pipes``-compatible)."""
    try:
        GenerationHost(
            conn, config, engine_factory, host_id, reconnect=reconnect
        ).run()
    except (KeyboardInterrupt, ConnectionError, EOFError, OSError):
        pass


# ---------------------------------------------------------------------------
# the learner-side endpoint


class SequenceLearner(ParamSnapshotPlane):
    """Learner-side endpoint of the disaggregated dataflow.

    Owns the hub the generation hosts connect to, the prompt-lease
    accounting (monotonic ``_task_id`` per lease, tracked per link,
    requeued on ANY link removal, completions deduped at lease level), the
    per-(host, epoch, seq) at-least-once dedup for the retained-upload
    protocol, the accepted-sequence queue the trainer drains, and the
    quantized snapshot plane the hosts pull from — the
    :class:`ParamSnapshotPlane` idiom with the WIRE tree as the stored
    snapshot (generation ids and the gen -> learner-step map back the
    unified staleness gauge).
    """

    def __init__(
        self,
        config: DisaggConfig,
        prompt_source: Callable[[], Optional[Dict[str, Any]]],
        ledger_path: Optional[str] = None,
    ) -> None:
        config.validate()
        self.config = config
        self.prompt_source = prompt_source
        self.ledger_path = ledger_path
        self._init_param_plane(None)
        self.hub = QueueHub(
            heartbeat_interval=config.heartbeat_interval_s,
            heartbeat_timeout=config.heartbeat_timeout
            if config.heartbeat_interval_s > 0
            else 0.0,
            max_pending=config.max_pending,
            on_disconnect=self._on_disconnect,
        )
        self.sequences: "queue.Queue[Dict[str, Any]]" = queue.Queue(
            config.seq_maxsize
        )
        # elastic membership roster (scale decisions, targeted drains)
        self.host_links: Dict[Connection, Dict[str, Any]] = {}
        self._roster_lock = threading.Lock()
        self.hosts_joined = 0
        self.hosts_drained = 0
        # exactly-once lease accounting across churn
        self._lease_lock = threading.Lock()
        self._next_task_id = 0
        self._outstanding: Dict[int, Tuple[Connection, Any]] = {}
        self._conn_leases: Dict[Connection, Set[int]] = {}
        self._completed_leases: "OrderedDict[int, None]" = OrderedDict()
        self._completed_cap = 65536
        # group fan-out (ISSUE 14): a lease issued with samples=n closes
        # only when n distinct sample indices arrived; per-(lease, sample)
        # dedup keeps a reissue racing its original at exactly n samples
        self._completed_samples: "OrderedDict[Tuple[int, int], None]" = (
            OrderedDict()
        )
        self._sample_counts: Dict[int, int] = {}
        # open root spans per lease (head-sampled at issue time; closed at
        # ingest); bounded like the completed-lease table so a lease the
        # fleet never completes cannot leak a span forever
        self._trace_roots: "OrderedDict[int, Any]" = OrderedDict()
        self._snapshot_trace: Optional[Any] = None
        self._returned: Deque[Any] = deque()
        self.requeued_leases = 0
        self.duplicate_leases = 0
        # at-least-once upload dedup: per host, per epoch, newest seq_id
        self._dedup_seen: Dict[int, "OrderedDict[int, int]"] = {}
        self._dedup_epochs_per_host = 4
        self.duplicate_sequences = 0
        self.total_sequences = 0
        self.dropped_sequences = 0
        self.snapshot_wire_bytes = 0
        # preemption/resume plane: the learner's incarnation counter (1 on
        # a fresh start, predecessor+1 after a ledger restore) plus the
        # markers that let the resumed epoch attribute drops to the resume
        self.learner_epoch = 1
        self.restored_extra: Optional[Dict[str, Any]] = None
        self._restored_completed: Set[int] = set()
        self._restored_dedup: Dict[int, Dict[int, int]] = {}
        self.resumed_sequences_reissued = 0
        self.resumed_duplicates_dropped = 0
        reg = telemetry.get_registry()
        self._epoch_gauge = reg.gauge("learner.epoch")
        self._reissued_counter = reg.counter("resume.sequences_reissued")
        self._resume_dup_counter = reg.counter("resume.duplicates_dropped")
        self._seq_meter = reg.meter("disagg.sequences_per_s")
        self._stale_gauge = reg.gauge("disagg.staleness")
        reg.bind(
            "disagg.learner",
            lambda: {
                "generation": self.generation,
                "total_sequences": self.total_sequences,
                "duplicate_sequences": self.duplicate_sequences,
                "duplicate_leases": self.duplicate_leases,
                "requeued_leases": self.requeued_leases,
                "dropped_sequences": self.dropped_sequences,
                "sequences_queued": self.sequences.qsize(),
                "outstanding_leases": len(self._outstanding),
                "live_hosts": self.live_host_count(),
                "live_lanes": self.live_lane_count(),
                "hosts_joined": self.hosts_joined,
                "hosts_drained": self.hosts_drained,
                "snapshot_wire_bytes": self.snapshot_wire_bytes,
                "learner_epoch": self.learner_epoch,
                "resumed_sequences_reissued": self.resumed_sequences_reissued,
                "resumed_duplicates_dropped": (
                    self.resumed_duplicates_dropped
                ),
            },
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if ledger_path is not None and ledger_store.ledger_exists(
            ledger_path
        ):
            self._restore_ledger(ledger_path)
        self._epoch_gauge.set(self.learner_epoch)

    # -- param plane -----------------------------------------------------
    def publish(
        self, host_weights: Any, learner_step: Optional[int] = None
    ) -> int:
        """Publish a fresh snapshot to the generation tier: one host-side
        quantization per publish (``snapshot_quantize`` wire format), a
        monotonic generation bump, and the gen -> learner-step record the
        unified staleness definition reads.  Hosts pull lazily (the lease
        reply advertises the newest generation), so N hosts cost one
        quantization, not N."""
        span = tracing.start_span("snapshot_publish", kind="disagg")
        wire = quantize_wire_tree(host_weights, self.config.snapshot_quantize)
        self.snapshot_wire_bytes = wire_tree_bytes(wire)
        with self._param_lock:
            self.generation += 1
            gen = self.generation
            self._params = wire
            self._quantized = None
            self._record_step(gen, learner_step)
            # the generation's trace rides every params reply, so each
            # host's snapshot.fetch span parents back to this publish
            self._snapshot_trace = span.context if span.sampled else None
        span.end(generation=gen, wire_bytes=self.snapshot_wire_bytes)
        return gen

    def observe_consumed(self, served_generation: int) -> float:
        """The trainer consumed sequences tagged ``served_generation``:
        report the unified staleness (learner steps behind the newest
        generation) on both the plane-local and the unified gauge."""
        lag = self.staleness_steps(served_generation)
        self._stale_gauge.set(lag)
        telemetry.observe_staleness(lag, plane="disagg")
        return lag

    # -- membership ------------------------------------------------------
    def live_host_count(self) -> int:
        with self._roster_lock:
            return sum(
                1
                for info in self.host_links.values()
                if not info.get("draining")
            )

    def live_lane_count(self) -> int:
        with self._roster_lock:
            return sum(
                info["lanes"]
                for info in self.host_links.values()
                if not info.get("draining")
            )

    def drain_hosts(self, n_hosts: int) -> int:
        """Scale-down: ask the newest-joined ``n_hosts`` generation hosts
        to drain (stop admitting, finish/return live lanes, flush + await
        acks, exit 0).  Returns the host count actually asked."""
        with self._roster_lock:
            candidates = sorted(
                (
                    (conn, info)
                    for conn, info in self.host_links.items()
                    if not info.get("draining")
                ),
                key=lambda item: item[1].get("joined_t", 0.0),
                reverse=True,
            )
            picked = []
            for conn, info in candidates[: max(int(n_hosts), 0)]:
                info["draining"] = True
                picked.append((conn, info))
        for conn, info in picked:
            telemetry.record_event(
                "drain_request", host=info["host_id"], tier="generation"
            )
            telemetry.get_registry().counter("disagg.drain_requests").inc()
            self.hub.send(conn, make_drain())
        return len(picked)

    # -- trainer API -----------------------------------------------------
    def get_sequence(
        self, timeout: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        try:
            return self.sequences.get(timeout=timeout)
        except queue.Empty:
            return None

    def queue_occupancy(self) -> float:
        return self.sequences.qsize() / (self.sequences.maxsize or 1)

    # -- bring-up --------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._serve_loop, name="disagg-learner", daemon=True
            )
            self._thread.start()

    def add_host_connection(self, conn: Connection) -> None:
        self.hub.add_connection(conn)

    def stop(self) -> None:
        self._stop.set()
        self.hub.close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    # -- durable ledger (preemption tolerance) ---------------------------
    def ledger_state(
        self, extra: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        """Snapshot the learner's whole accounting plane as one codec-v2
        encodable tree: open + returned leases (reissued verbatim on
        restart), the completed-lease / completed-sample / dedup tables
        (so pre-restart redeliveries drop exactly), the accepted-but-
        unconsumed sequence queue (drained here — losing it would lose
        those sequences forever, their leases already closed), the param
        plane (wire snapshot, generation, gen -> learner-step map), and
        the churn counters.  ``extra`` carries trainer-owned state (replay
        contents, learn step, lease RNG) through the same frame.

        Call with the serve loop stopped (:meth:`stop`): the snapshot
        CONSUMES the accepted queue, so it is a save-and-exit primitive,
        not a live backup.
        """
        queued: List[Dict[str, Any]] = []
        while True:
            try:
                queued.append(self.sequences.get_nowait())
            except queue.Empty:
                break
        with self._lease_lock:
            open_leases = [
                lease
                for _tid, (_conn, lease) in sorted(self._outstanding.items())
                if isinstance(lease, dict)
            ]
            returned = list(self._returned)
            state: Dict[str, Any] = {
                "format": 1,
                "learner_epoch": self.learner_epoch,
                "next_task_id": self._next_task_id,
                "open_leases": open_leases,
                "returned_leases": returned,
                "completed_leases": list(self._completed_leases.keys()),
                "completed_samples": list(self._completed_samples.keys()),
                "sample_counts": dict(self._sample_counts),
                "dedup_seen": {
                    hid: dict(epochs)
                    for hid, epochs in self._dedup_seen.items()
                },
            }
        with self._param_lock:
            state.update(
                generation=self.generation,
                gen_steps=dict(self._gen_steps),
                latest_learner_step=self._latest_learner_step,
                params=self._params,
            )
        state["queued_sequences"] = queued
        state["counters"] = {
            "total_sequences": self.total_sequences,
            "duplicate_sequences": self.duplicate_sequences,
            "duplicate_leases": self.duplicate_leases,
            "requeued_leases": self.requeued_leases,
            "dropped_sequences": self.dropped_sequences,
            "hosts_joined": self.hosts_joined,
            "hosts_drained": self.hosts_drained,
        }
        state["extra"] = extra if extra is not None else {}
        return state

    def save_ledger(
        self,
        path: Optional[str] = None,
        extra: Optional[Dict[str, Any]] = None,
        keep_last: int = 2,
    ) -> str:
        """Persist :meth:`ledger_state` durably (write-new-then-rotate +
        sha256 manifest + ``.prev`` fallback — ``genrl/ledger.py``).  The
        PreemptionGuard safe-point calls this between rounds, so the saved
        frame is always a consistent inter-step cut."""
        p = path if path is not None else self.ledger_path
        if p is None:
            raise ValueError(
                "SequenceLearner has no ledger path (pass one here or at "
                "construction)"
            )
        state = self.ledger_state(extra=extra)
        out = ledger_store.save_ledger(p, state, keep_last=keep_last)
        logger.info(
            "disagg ledger saved: epoch=%d open_leases=%d queued=%d gen=%d",
            self.learner_epoch, len(state["open_leases"]),
            len(state["queued_sequences"]), state["generation"],
        )
        return out

    def _restore_ledger(self, path: str) -> None:
        state = ledger_store.load_ledger(path)
        self.learner_epoch = int(state.get("learner_epoch", 0)) + 1
        with self._lease_lock:
            self._next_task_id = int(state.get("next_task_id", 0))
            for tid in state.get("completed_leases", []):
                self._completed_leases[int(tid)] = None
                self._restored_completed.add(int(tid))
            for tid, k in state.get("completed_samples", []):
                self._completed_samples[(int(tid), int(k))] = None
            for tid, got in state.get("sample_counts", {}).items():
                self._sample_counts[int(tid)] = int(got)
            for hid, epochs in state.get("dedup_seen", {}).items():
                self._dedup_seen[int(hid)] = OrderedDict(
                    (int(e), int(s)) for e, s in epochs.items()
                )
                self._restored_dedup[int(hid)] = {
                    int(e): int(s) for e, s in epochs.items()
                }
            # re-issue every lease that was open (on a host's lanes) or
            # parked for reissue at save time — they keep their _task_id,
            # so a pre-restart completion racing the reissue still counts
            # exactly once through the restored completed-lease table
            reissue = [
                lease
                for lease in (
                    list(state.get("open_leases", []))
                    + list(state.get("returned_leases", []))
                )
                if lease is not None
            ]
            self._returned.extend(reissue)
            self.resumed_sequences_reissued = len(reissue)
        with self._param_lock:
            self.generation = int(state.get("generation", 0))
            self._params = state.get("params")
            self._quantized = None
            gen_steps = {
                int(g): int(s)
                for g, s in state.get("gen_steps", {}).items()
            }
            self._gen_steps = gen_steps if gen_steps else {0: 0}
            self._latest_learner_step = int(
                state.get("latest_learner_step", 0)
            )
        requeued_seqs = 0
        for seq in state.get("queued_sequences", []):
            if isinstance(seq, dict) and "_t_q" in seq:
                # the replay-wait stamp is a pre-restart monotonic reading;
                # restart the dwell clock at restore
                seq["_t_q"] = time.monotonic()
            try:
                self.sequences.put_nowait(seq)
                requeued_seqs += 1
            except queue.Full:
                self.dropped_sequences += 1
        counters = state.get("counters", {})
        self.total_sequences = int(counters.get("total_sequences", 0))
        self.duplicate_sequences = int(
            counters.get("duplicate_sequences", 0)
        )
        self.duplicate_leases = int(counters.get("duplicate_leases", 0))
        self.requeued_leases = int(counters.get("requeued_leases", 0))
        self.dropped_sequences += int(counters.get("dropped_sequences", 0))
        self.hosts_joined = int(counters.get("hosts_joined", 0))
        self.hosts_drained = int(counters.get("hosts_drained", 0))
        self.restored_extra = dict(state.get("extra", {}))
        self._reissued_counter.inc(self.resumed_sequences_reissued)
        telemetry.record_event(
            "preemption_resume",
            epoch=self.learner_epoch,
            reissued=self.resumed_sequences_reissued,
            queued=requeued_seqs,
            generation=self.generation,
            learner_step=self._latest_learner_step,
        )
        logger.info(
            "disagg ledger restored: epoch=%d reissued=%d queued=%d gen=%d "
            "step=%d",
            self.learner_epoch, self.resumed_sequences_reissued,
            requeued_seqs, self.generation, self._latest_learner_step,
        )

    # -- lease accounting ------------------------------------------------
    def _next_lease(self) -> Optional[Any]:
        with self._lease_lock:
            while self._returned:
                lease = self._returned.popleft()
                tid = (
                    lease.get("_task_id") if isinstance(lease, dict) else None
                )
                if tid is not None and tid in self._completed_leases:
                    # the original (or a retained-upload resend) closed
                    # this lease while the reissue waited — handing it out
                    # again would only decode a guaranteed duplicate
                    continue
                return lease
        return None if self._stop.is_set() else self.prompt_source()

    def _record_outstanding(self, conn: Connection, lease: Any) -> Any:
        if not isinstance(lease, dict):
            return lease
        lease = dict(lease)
        with self._lease_lock:
            if "_task_id" not in lease:
                lease["_task_id"] = self._next_task_id
                self._next_task_id += 1
                # head sampling happens HERE, once per sequence lifecycle:
                # the root span rides the lease (and every requeue of it)
                # as the "trace" wire key; rate 0 keeps this a no-op
                root = tracing.start_span(
                    "sequence", kind="disagg", lease=lease["_task_id"]
                )
                if root.sampled:
                    self._trace_roots[lease["_task_id"]] = root
                    while len(self._trace_roots) > self._completed_cap:
                        _tid, stale = self._trace_roots.popitem(last=False)
                        stale.end(outcome="abandoned")
                    tracing.inject(lease, root)
            tid = lease["_task_id"]
            self._outstanding[tid] = (conn, lease)
            self._conn_leases.setdefault(conn, set()).add(tid)
        return lease

    def _on_disconnect(self, conn: Connection) -> None:
        """ANY removal of a host link (EOF, corrupt frame, liveness
        verdict, preempted node): drop the roster entry and requeue its
        outstanding leases — an in-flight generation on a killed host is
        reissued, and the racing duplicate completion counts once."""
        with self._roster_lock:
            self.host_links.pop(conn, None)
        requeued = []
        with self._lease_lock:
            for tid in self._conn_leases.pop(conn, set()):
                entry = self._outstanding.pop(tid, None)
                if entry is not None and tid not in self._completed_leases:
                    requeued.append(entry[1])
            self._returned.extend(requeued)
            self.requeued_leases += len(requeued)
        if requeued:
            telemetry.get_registry().counter("disagg.requeued_leases").inc(
                len(requeued)
            )
            telemetry.record_event(
                "leases_requeued", count=len(requeued), why="disconnect"
            )
            logger.warning(
                "disagg: requeued %d in-flight leases from a dropped "
                "generation host", len(requeued),
            )

    def _is_duplicate(self, seq: Dict[str, Any]) -> bool:
        """Per-(host_id, host_epoch, seq_id) at-least-once dedup — the
        WorkerServer episode rule at sequence granularity, with the same
        bounded per-host epoch history so a slow duplicate from a corpse
        host stays recognizable after its replacement registered."""
        hid = seq.get("host_id")
        sid = seq.get("seq_id")
        if hid is None or sid is None:
            return False
        epoch = int(seq.get("host_epoch", 0))
        sid = int(sid)
        epochs = self._dedup_seen.setdefault(int(hid), OrderedDict())
        last = epochs.get(epoch)
        if last is not None and sid <= last:
            restored = self._restored_dedup.get(int(hid), {}).get(epoch)
            if restored is not None and sid <= restored:
                # dropped by a RESTORED key — a pre-restart upload
                # redelivered to the resumed incarnation (the epoch
                # dimension of the at-least-once key doing its job)
                self.resumed_duplicates_dropped += 1
                self._resume_dup_counter.inc()
            return True
        epochs[epoch] = sid if last is None else max(last, sid)
        epochs.move_to_end(epoch)
        while len(epochs) > self._dedup_epochs_per_host:
            epochs.popitem(last=False)
        return False

    # -- serve loop ------------------------------------------------------
    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, msg = self.hub.recv(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._handle(conn, msg)
            except Exception:  # noqa: BLE001 — one bad frame must not kill the loop
                logger.exception(
                    "disagg learner: failed handling %r",
                    msg.get("kind") if isinstance(msg, dict) else msg,
                )

    def _handle(self, conn: Connection, msg: Dict[str, Any]) -> None:
        kind = msg.get("kind")
        if kind == "lease":
            n = int(msg.get("n", 1))
            leases: List[Any] = []
            for _ in range(n):
                lease = self._next_lease()
                if lease is not None:
                    lease = self._record_outstanding(conn, lease)
                leases.append(lease)
                if lease is None:
                    break
            with self._param_lock:
                gen = self.generation
            self.hub.send(
                conn,
                {
                    "kind": "lease",
                    "v": leases,
                    "gen": gen,
                    "epoch": self.learner_epoch,
                },
            )
        elif kind == "params":
            with self._param_lock:
                wire, gen = self._params, self.generation
                snap_trace = self._snapshot_trace
            if wire is None or int(msg.get("have", -1)) == gen:
                self.hub.send(
                    conn,
                    {
                        "kind": "params",
                        "generation": gen,
                        "epoch": self.learner_epoch,
                    },
                )
            else:
                reply = {
                    "kind": "params",
                    "generation": gen,
                    "weights": wire,
                    "epoch": self.learner_epoch,
                }
                tracing.inject(reply, snap_trace)
                self.hub.send(conn, reply, compress=True)
        elif kind == "seq_batch":
            # ack FIRST: the host retains the batch until this lands;
            # dedup below absorbs any redelivery
            if "seq" in msg:
                self.hub.send(conn, {"kind": "seq_ack", "seq": msg["seq"]})
            self._ingest(msg.get("v", []))
        elif kind == "gen_hello":
            with self._roster_lock:
                self.host_links[conn] = {
                    "host_id": int(msg.get("host_id", -1)),
                    "host_epoch": int(msg.get("host_epoch", 0)),
                    "lanes": int(msg.get("lanes", 0)),
                    "draining": False,
                    "joined_t": time.monotonic(),
                }
                self.hosts_joined += 1
            telemetry.get_registry().counter("disagg.hosts_joined").inc()
            telemetry.record_event(
                "gen_host_join",
                host=msg.get("host_id"),
                lanes=msg.get("lanes"),
            )
            # the epoch handshake: a (re)joining host learns the learner's
            # incarnation AND the current snapshot generation it must adopt
            # before admitting work (a host that outlived a learner restart
            # re-hellos here and re-synchronizes both)
            with self._param_lock:
                gen = self.generation
            self.hub.send(
                conn,
                {
                    "kind": "gen_welcome",
                    "epoch": self.learner_epoch,
                    "gen": gen,
                },
            )
        elif kind == "lease_return":
            requeued = 0
            with self._lease_lock:
                for lease in msg.get("v", []):
                    tid = (
                        lease.get("_task_id")
                        if isinstance(lease, dict)
                        else None
                    )
                    if tid is not None:
                        entry = self._outstanding.pop(tid, None)
                        if entry is not None:
                            self._conn_leases.get(entry[0], set()).discard(
                                tid
                            )
                        if tid in self._completed_leases:
                            continue  # raced its completion: done already
                    self._returned.append(lease)
                    requeued += 1
                self.requeued_leases += requeued
            if requeued:
                telemetry.get_registry().counter(
                    "disagg.requeued_leases"
                ).inc(requeued)
                telemetry.record_event(
                    "leases_requeued", count=requeued, why="drain"
                )
        elif kind == DRAIN_DONE:
            with self._roster_lock:
                self.host_links.pop(conn, None)
                self.hosts_drained += 1
            telemetry.get_registry().counter("disagg.hosts_drained").inc()
            telemetry.record_event(
                "gen_host_drained", host=msg.get("host_id")
            )
            logger.info(
                "disagg: generation host %s drained cleanly",
                msg.get("host_id"),
            )
        else:
            logger.warning("disagg learner: unknown message kind %r", kind)

    def _ingest(self, batch: List[Dict[str, Any]]) -> None:
        reg = telemetry.get_registry()
        for seq in batch:
            if self._is_duplicate(seq):
                self.duplicate_sequences += 1
                reg.counter("disagg.duplicate_sequences").inc()
                continue
            # lease-level exactly-once: a lease orphaned by a killed host
            # was reissued and may complete TWICE — the second completion
            # is dropped here, keeping the sequence count exact.  A
            # fanned-out lease (samples=n) dedups per (lease, sample) and
            # closes only once all n samples landed.
            tid = seq.pop("_task_id", None) if isinstance(seq, dict) else None
            if tid is not None:
                k = int(seq.pop("_sample_idx", 0))
                total = int(seq.pop("_samples_total", 1))
                closed = False
                with self._lease_lock:
                    if tid in self._completed_leases or (
                        (tid, k) in self._completed_samples
                    ):
                        self.duplicate_leases += 1
                        dup = True
                        # a reissue that raced past the close re-recorded
                        # itself as outstanding — drop that zombie entry
                        # so the lease table closes exactly (orphans == 0)
                        entry = self._outstanding.pop(tid, None)
                        if entry is not None:
                            self._conn_leases.get(entry[0], set()).discard(
                                tid
                            )
                    else:
                        dup = False
                        self._completed_samples[(tid, k)] = None
                        while (
                            len(self._completed_samples) > self._completed_cap
                        ):
                            self._completed_samples.popitem(last=False)
                        got = self._sample_counts.get(tid, 0) + 1
                        if got >= total:
                            closed = True
                            self._sample_counts.pop(tid, None)
                            self._completed_leases[tid] = None
                            while (
                                len(self._completed_leases)
                                > self._completed_cap
                            ):
                                self._completed_leases.popitem(last=False)
                            entry = self._outstanding.pop(tid, None)
                            if entry is not None:
                                self._conn_leases.get(
                                    entry[0], set()
                                ).discard(tid)
                        else:
                            self._sample_counts[tid] = got
                if dup:
                    reg.counter("disagg.duplicate_leases").inc()
                    if tid in self._restored_completed:
                        # a lease closed before the restart completing
                        # again after it (straggler host, reissue race)
                        self.resumed_duplicates_dropped += 1
                        self._resume_dup_counter.inc()
                    continue
                seq["lease_id"] = tid
                if total > 1:
                    seq["sample_idx"] = k
                if closed:
                    root = self._trace_roots.pop(tid, None)
                    if root is not None:
                        # the root span covers lease issue -> accepted
                        # ingest (of the LAST group sample); the trainer's
                        # seq_add/learn_step edges extend the trace
                        # afterwards (record_consumption_trace)
                        root.end(host=seq.get("host_id"))
            if tracing.TRACE_KEY in seq:
                seq["_t_q"] = time.monotonic()  # replay-wait edge opens
            self.total_sequences += 1
            self._seq_meter.mark()
            try:
                self.sequences.put_nowait(seq)
            except queue.Full:
                # backpressure: evict the stalest queued sequence so the
                # freshest generations survive (off-policy freshness)
                try:
                    self.sequences.get_nowait()
                    self.dropped_sequences += 1
                except queue.Empty:
                    pass
                try:
                    self.sequences.put_nowait(seq)
                except queue.Full:
                    self.dropped_sequences += 1


# ---------------------------------------------------------------------------
# the generation-host fleet (pipe processes or in-process threads)


class LocalGenerationFleet:
    """Generation hosts as local children over pipes — the process shape
    the soak/chaos tests kill, or (``use_threads=True``) in-process threads
    for single-process integration/bench runs where the wire still flows
    but nothing needs SIGTERMing.

    Mirrors ``LocalCluster``: ``scale_up`` admits fresh hosts mid-run with
    FRESH host ids, ``chaos_poll`` applies one seeded ``mass_kill`` draw
    (site ``"disagg"``), and a supervisor thread drives the waves
    automatically when the active chaos plan arms them — backfilling is the
    AUTOSCALER's job (floor rule), never a respawn budget here.
    """

    def __init__(
        self,
        learner: SequenceLearner,
        config: DisaggConfig,
        engine_factory: EngineFactory,
        mp_context: Optional[str] = None,
        use_threads: bool = False,
        chaos_poll_interval_s: float = 0.5,
        auto_chaos: bool = True,
    ) -> None:
        self.learner = learner
        self.config = config
        self.engine_factory = engine_factory
        self.mp_context = mp_context
        self.use_threads = use_threads
        self.chaos_poll_interval_s = chaos_poll_interval_s
        # auto_chaos=False leaves the seeded wave to an explicit
        # chaos_poll() call — tests that must land the wave MID-DECODE
        # (after warmup) own the timing themselves
        self.auto_chaos = auto_chaos
        self.procs: List[Any] = []
        self._next_host_id = 0
        self._ctx: Any = None
        self._scale_lock = threading.Lock()
        self._stopping = threading.Event()
        self._supervisor: Optional[threading.Thread] = None

    def _assign_host_id(self) -> int:
        with self._scale_lock:
            hid = self._next_host_id
            self._next_host_id += 1
            return hid

    def spawned_host_count(self) -> int:
        with self._scale_lock:
            return sum(1 for p in self.procs if p.is_alive())

    def _spawn(self, host_id: int) -> None:
        import multiprocessing as mp

        if self.use_threads:
            parent, child = mp.Pipe(duplex=True)
            proc = threading.Thread(
                target=generation_host_main,
                args=(
                    PipeConnection(child),
                    self.config,
                    self.engine_factory,
                    host_id,
                ),
                kwargs={"reconnect": self._dial},
                name=f"gen-host-{host_id}",
                daemon=True,
            )
            proc.start()
        else:
            parent, child = self._ctx.Pipe(duplex=True)
            proc = self._ctx.Process(
                target=_host_proc_main,
                args=(child, self.config, self.engine_factory, host_id),
                daemon=True,
            )
            proc.start()
            child.close()
        self.learner.add_host_connection(PipeConnection(parent))
        with self._scale_lock:
            self.procs.append(proc)

    def start(self) -> None:
        if not self.use_threads:
            import multiprocessing as mp

            from scalerl_tpu.utils.platform import safe_mp_context

            self._ctx = mp.get_context(safe_mp_context(self.mp_context))
        for _ in range(self.config.num_hosts):
            self._spawn(self._assign_host_id())
        from scalerl_tpu.runtime import chaos

        inj = chaos.active()
        armed = inj is not None and (
            inj.plan.rates.get("mass_kill", 0.0) > 0
            or inj.plan.rates.get("preempt", 0.0) > 0
        )
        if armed and self.auto_chaos and not self.use_threads:
            self._supervisor = threading.Thread(
                target=self._supervise, name="disagg-supervisor", daemon=True
            )
            self._supervisor.start()

    def scale_up(self, n_hosts: int) -> int:
        """Dynamic admission: backfill with FRESH host ids (never a reuse
        of a dead id — fresh ids keep the dedup tables legible)."""
        added = 0
        for _ in range(max(int(n_hosts), 0)):
            self._spawn(self._assign_host_id())
            added += 1
        return added

    def _dial(self) -> Connection:
        """Thread-mode reconnect seam: a host that lost its uplink redials
        the CURRENT learner — which a preemption harness may have swapped
        for a restarted one via :meth:`adopt_learner`.  Raises
        ``ConnectionError`` while no learner is accepting; the host's
        capped backoff owns the retry cadence."""
        import multiprocessing as mp

        with self._scale_lock:
            learner = self.learner
        if learner is None or learner.stopped:
            raise ConnectionError("no live learner to dial")
        parent, child = mp.Pipe(duplex=True)
        learner.add_host_connection(PipeConnection(parent))
        return PipeConnection(child)

    def adopt_learner(self, learner: SequenceLearner) -> None:
        """Point the reconnect seam at a restarted learner: surviving
        hosts redial into it, the ``gen_welcome`` handshake hands them the
        new epoch + snapshot generation, and their retained uploads resend
        into the restored dedup tables."""
        with self._scale_lock:
            self.learner = learner

    def chaos_poll(self) -> List[int]:
        """One seeded preemption-wave draw against the live host procs:
        a ``mass_kill`` wave plus (independently seeded) one ``preempt``
        single-victim SIGTERM."""
        if self.use_threads:
            return []
        from scalerl_tpu.fleet.cluster import apply_mass_kill, apply_preempt

        killed = apply_mass_kill(self.procs, site="disagg")
        victim = apply_preempt(self.procs, site="disagg")
        if victim is not None and victim not in killed:
            killed.append(victim)
        return killed

    def _supervise(self) -> None:
        while not self._stopping.wait(self.chaos_poll_interval_s):
            self.chaos_poll()

    def join(self, timeout: float = 10.0) -> None:
        self._stopping.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=2.0)
        deadline = time.monotonic() + timeout
        for p in self.procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if not self.use_threads and p.is_alive():
                p.terminate()


def _host_proc_main(child_conn, config, engine_factory, host_id) -> None:
    generation_host_main(
        PipeConnection(child_conn), config, engine_factory, host_id
    )


# ---------------------------------------------------------------------------
# autoscaler wiring: the generation tier as a scalable role


class GenerationTierExecutor:
    """The autoscaler's ``ScaleExecutor`` over the generation tier:
    ``scale_up`` spawns fresh hosts, ``scale_down`` runs the drain
    protocol (a deliberate zero-loss close, never a kill)."""

    def __init__(
        self, learner: SequenceLearner, fleet: LocalGenerationFleet
    ) -> None:
        self.learner = learner
        self.fleet = fleet

    def worker_count(self) -> int:
        return self.fleet.spawned_host_count()

    def scale_up(self, n: int) -> int:
        return self.fleet.scale_up(n)

    def scale_down(self, n: int) -> int:
        return self.learner.drain_hosts(n)


def disagg_signal_source(
    learner: SequenceLearner, registry: Optional[Any] = None
) -> Callable[[], FleetSignals]:
    """Generation-tier signal reader: the IMPALA/Podracer triad applied to
    sequence RL — decode production (``disagg.sequences_per_s``) vs learn
    consumption (``genrl.learn_steps_per_s``) vs replay-feed occupancy —
    plus the unified snapshot-staleness gauge, so the autoscaler can
    rebalance host counts per role off staleness pressure as well as
    queue pressure (``AutoscalerConfig.max_staleness``)."""
    last = {"shed": 0.0}

    def read() -> FleetSignals:
        reg = registry if registry is not None else telemetry.get_registry()
        shed = float(learner.hub.shed_total + learner.dropped_sequences)
        delta, last["shed"] = shed - last["shed"], shed
        return FleetSignals(
            fps=reg.meter("disagg.sequences_per_s").rate(),
            learn_steps_per_s=reg.meter("genrl.learn_steps_per_s").rate(),
            queue_occupancy=learner.queue_occupancy(),
            shed_delta=delta,
            snapshot_staleness=reg.gauge("staleness").value,
            live_workers=learner.live_host_count(),
        )

    return read
