"""Host-side page allocator for the block-paged KV cache.

jax-free by design (the ``serving/batcher.py`` discipline): the allocator
is pure Python bookkeeping over page *ids* — the device only ever sees the
resulting int32 page tables, uploaded inside the continuous engine's one
batched transfer per macro-step.  Two-level accounting:

- **reservations** bound admission: admitting a prompt reserves its
  worst-case page count (``ceil((prompt_len + response_budget) /
  page_size)``) so a live lane can NEVER hit mid-flight exhaustion — when
  the pool can't cover a new sequence's worst case, admission backpressures
  (the prompt stays queued / is shed at the queue bound), it never
  corrupts;
- **allocations** track live tokens: physical pages are drawn lazily as a
  lane's context actually grows, so the allocated-page gauge — the memory
  the continuous plane really uses — scales with live tokens, not with
  ``max_bucket x lanes`` (early-EOS lanes return their pages immediately).

Pages are **refcounted** (ISSUE 14): a full prefix page can back several
lanes at once (group sampling forks n lanes over one prompt's KV, and the
prefix cache keeps hot chains alive between admissions).  :meth:`alloc`
starts a page at refcount 1, :meth:`share` bumps it on behalf of another
holder, and :meth:`free` decrements — the page returns to the free list
only at zero.  Every hold is labelled with its *holder* (``"lane[3]"``,
``"prefix-cache"``), so the double-free / foreign-free guards can name
exactly who held what when the invariant broke.

Page 0 is the **null page**: never handed out, the routing target for
dead-lane and pad writes, never read (reads are masked by true lengths).
Double-free and foreign-free are hard errors — the no-aliasing invariant
the randomized admit/finish test hammers.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional


class PageAllocator:
    """Refcounted free-list page allocator with admission reservations.

    ``num_pages`` includes the null page, so ``capacity = num_pages - 1``
    pages are actually allocatable.  All methods are O(1)/O(k) list ops;
    not thread-safe (the continuous engine drives it from its one host
    loop, like every other host-side queue in the codebase).

    ``reclaim``: optional hook called when :meth:`alloc` finds the free
    list short — the prefix cache registers its LRU evictor here, so
    cached-but-unreferenced chains are reclaimed on demand instead of
    counting against admission.
    """

    def __init__(self, num_pages: int, page_size: int) -> None:
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the null page), got "
                f"{num_pages}"
            )
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        # LIFO free list: recently-freed pages are reused first, so a long
        # churny run naturally fragments lane->page maps — which is why
        # fragmentation-independence is a tested property, not an accident
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs: Dict[int, int] = {}  # live page -> refcount
        self._holders: Dict[int, List[str]] = {}  # live page -> holder labels
        self.reserved = 0
        self._reclaim: Optional[Callable[[int], int]] = None

    # -- capacity ------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        return len(self._refs)

    @property
    def shared_pages(self) -> int:
        """Pages currently held by more than one holder (CoW prefixes)."""
        return sum(1 for r in self._refs.values() if r > 1)

    def refcount(self, page: int) -> int:
        """Current holder count for ``page`` (0 = not live)."""
        return self._refs.get(page, 0)

    def holders(self, page: int) -> List[str]:
        """Holder labels currently registered on ``page`` (diagnostics)."""
        return list(self._holders.get(page, ()))

    def pages_for_tokens(self, tokens: int) -> int:
        return -(-tokens // self.page_size)  # ceil div

    def set_reclaim_hook(self, hook: Optional[Callable[[int], int]]) -> None:
        """Register ``hook(n) -> freed``: asked to return up to ``n`` pages
        to the free list (the prefix cache's LRU evictor)."""
        self._reclaim = hook

    # -- reservations (admission control) ------------------------------
    def try_reserve(self, n_pages: int) -> bool:
        """Reserve worst-case capacity for a new sequence; False =
        backpressure (the pool cannot guarantee the sequence finishes).

        A lane's reservation covers EVERY page in its table — shared
        prefix pages included — so sharing never loosens the exhaustion
        guarantee: the win of the prefix cache is skipped prefill compute
        and fewer *allocated* pages, not a larger admission envelope.
        """
        if self.reserved + n_pages > self.capacity:
            return False
        self.reserved += n_pages
        return True

    def release(self, n_pages: int) -> None:
        """Return a reservation (the lane finished or was never admitted)."""
        if n_pages > self.reserved:
            raise RuntimeError(
                f"release({n_pages}) exceeds outstanding reservation "
                f"{self.reserved}"
            )
        self.reserved -= n_pages

    # -- physical pages ------------------------------------------------
    def alloc(self, n_pages: int, holder: str = "?") -> List[int]:
        """Draw ``n_pages`` fresh physical pages at refcount 1.  Callers
        alloc only within their reservation; when the free list is short
        the reclaim hook (prefix-cache LRU eviction) is asked first, and
        an empty free list after that is a bookkeeping bug (aliasing
        hazard) and raises instead of corrupting."""
        if n_pages > len(self._free) and self._reclaim is not None:
            self._reclaim(n_pages - len(self._free))
        if n_pages > len(self._free):
            raise RuntimeError(
                f"alloc({n_pages}) by {holder!r} with only "
                f"{len(self._free)} free pages (reserved={self.reserved}) "
                "— reservation accounting broken"
            )
        pages = [self._free.pop() for _ in range(n_pages)]
        for p in pages:
            self._refs[p] = 1
            self._holders[p] = [holder]
        return pages

    def share(self, pages: List[int], holder: str = "?") -> None:
        """Bump the refcount of already-live pages on behalf of a new
        holder (a forked group lane or the prefix cache).  Sharing a page
        that is not live is a hard error — it would alias a recycled
        page."""
        for p in pages:
            if p == 0 or p not in self._refs:
                raise RuntimeError(
                    f"share of page {p} by {holder!r}: page is not live "
                    "(never allocated, or already fully freed)"
                )
        for p in pages:
            self._refs[p] += 1
            self._holders[p].append(holder)

    def free(self, pages: List[int], holder: str = "?") -> None:
        """Drop one hold per page; a page returns to the free list only
        when its refcount reaches zero.  Freeing a non-live page
        (double-free) or a page this holder never held (foreign-free)
        raises, naming the page and the holders involved."""
        for p in pages:
            if p == 0 or p not in self._refs:
                raise RuntimeError(
                    f"free of page {p} by {holder!r}: page is not live "
                    "(double free, or never allocated)"
                )
            held = self._holders[p]
            if holder != "?" and holder not in held:
                raise RuntimeError(
                    f"free of page {p} by {holder!r}: foreign free — page "
                    f"is held by {held!r}"
                )
            held.remove(holder if holder in held else held[-1])
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                del self._holders[p]
                self._free.append(p)

    # -- telemetry -----------------------------------------------------
    def stats(self) -> Dict[str, int]:
        return {
            "capacity": self.capacity,
            "free": self.free_pages,
            "allocated": self.allocated_pages,
            "shared": self.shared_pages,
            "reserved": self.reserved,
        }


def rewind_pages(
    allocator: PageAllocator,
    pages: List[int],
    keep_pages: int,
    holder: str = "?",
) -> int:
    """Page-cursor rewind (ISSUE 16): drop ``holder``'s hold on every page
    of ``pages`` past the first ``keep_pages`` entries, truncating the list
    in place.  Returns the number of tail pages rewound.

    This is how a speculative-decode rejection rolls back: the verify pass
    advanced the lane cursor by fewer tokens than the pages pre-extended
    for the draft horizon, so the whole pages past
    ``pages_for_tokens(new_cursor)`` go back through :meth:`PageAllocator
    .free` — a refcount decrement, NEVER a mutation, so a rewound page that
    another lane or the prefix cache still holds stays live for them and
    only this holder's ref drops.  The kept partial page's garbage beyond
    the cursor is harmless by the engine's masking invariant (attention
    never reads past a lane's cursor, and the next accepted tokens
    overwrite those slots).
    """
    if keep_pages < 0:
        raise ValueError(f"keep_pages must be >= 0, got {keep_pages}")
    tail = pages[keep_pages:]
    if tail:
        allocator.free(tail, holder=holder)
        del pages[keep_pages:]
    return len(tail)
