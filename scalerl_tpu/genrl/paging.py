"""Host-side page allocator for the block-paged KV cache.

jax-free by design (the ``serving/batcher.py`` discipline): the allocator
is pure Python bookkeeping over page *ids* — the device only ever sees the
resulting int32 page tables, uploaded inside the continuous engine's one
batched transfer per macro-step.  Two-level accounting:

- **reservations** bound admission: admitting a prompt reserves its
  worst-case page count (``ceil((prompt_len + response_budget) /
  page_size)``) so a live lane can NEVER hit mid-flight exhaustion — when
  the pool can't cover a new sequence's worst case, admission backpressures
  (the prompt stays queued / is shed at the queue bound), it never
  corrupts;
- **allocations** track live tokens: physical pages are drawn lazily as a
  lane's context actually grows, so the allocated-page gauge — the memory
  the continuous plane really uses — scales with live tokens, not with
  ``max_bucket x lanes`` (early-EOS lanes return their pages immediately).

Page 0 is the **null page**: never handed out, the routing target for
dead-lane and pad writes, never read (reads are masked by true lengths).
Double-free and double-alloc are hard errors — the no-aliasing invariant
the randomized admit/finish test hammers.
"""

from __future__ import annotations

from typing import Dict, List, Set


class PageAllocator:
    """Free-list page allocator with admission reservations.

    ``num_pages`` includes the null page, so ``capacity = num_pages - 1``
    pages are actually allocatable.  All methods are O(1)/O(k) list ops;
    not thread-safe (the continuous engine drives it from its one host
    loop, like every other host-side queue in the codebase).
    """

    def __init__(self, num_pages: int, page_size: int) -> None:
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the null page), got "
                f"{num_pages}"
            )
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        # LIFO free list: recently-freed pages are reused first, so a long
        # churny run naturally fragments lane->page maps — which is why
        # fragmentation-independence is a tested property, not an accident
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._live: Set[int] = set()
        self.reserved = 0

    # -- capacity ------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def allocated_pages(self) -> int:
        return len(self._live)

    def pages_for_tokens(self, tokens: int) -> int:
        return -(-tokens // self.page_size)  # ceil div

    # -- reservations (admission control) ------------------------------
    def try_reserve(self, n_pages: int) -> bool:
        """Reserve worst-case capacity for a new sequence; False =
        backpressure (the pool cannot guarantee the sequence finishes)."""
        if self.reserved + n_pages > self.capacity:
            return False
        self.reserved += n_pages
        return True

    def release(self, n_pages: int) -> None:
        """Return a reservation (the lane finished or was never admitted)."""
        if n_pages > self.reserved:
            raise RuntimeError(
                f"release({n_pages}) exceeds outstanding reservation "
                f"{self.reserved}"
            )
        self.reserved -= n_pages

    # -- physical pages ------------------------------------------------
    def alloc(self, n_pages: int) -> List[int]:
        """Draw ``n_pages`` physical pages.  Callers alloc only within
        their reservation, so an empty free list here is a bookkeeping bug
        (aliasing hazard) and raises instead of corrupting."""
        if n_pages > len(self._free):
            raise RuntimeError(
                f"alloc({n_pages}) with only {len(self._free)} free pages "
                f"(reserved={self.reserved}) — reservation accounting broken"
            )
        pages = [self._free.pop() for _ in range(n_pages)]
        self._live.update(pages)
        return pages

    def free(self, pages: List[int]) -> None:
        """Return physical pages.  Double-free (or freeing the null page)
        raises — the invariant that no page is ever owned by two lanes."""
        for p in pages:
            if p == 0 or p not in self._live:
                raise RuntimeError(f"free of page {p} not currently live")
            self._live.remove(p)
            self._free.append(p)

    # -- telemetry -----------------------------------------------------
    def stats(self) -> Dict[str, int]:
        return {
            "capacity": self.capacity,
            "free": self.free_pages,
            "allocated": self.allocated_pages,
            "reserved": self.reserved,
        }
