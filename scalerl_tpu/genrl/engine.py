"""Batched KV-cached generation engine: the acting half of sequence RL.

One jitted program per (prompt bucket, response bucket) pair covers the
WHOLE generation round — prefill over the left-padded prompt batch plus a
``lax.scan`` (TPU/GPU) or Python-unrolled (CPU, the PR 6 ``iter_mode``
verdict) loop of single-token decode steps with temperature/top-k
sampling.  The host dispatches once and reads back once:

- **bucketed static shapes** — prompt lengths pad up a power-of-two ladder
  (``serving/batcher.py``'s ``bucket_for``) and prompts are LEFT-padded
  (right-aligned) inside the bucket, so every lane's decode cursor is the
  same scalar and XLA compiles once per bucket, never retracing on ragged
  prompts (graftlint JG003 designed out);
- **one batched host read per round** — the program returns one pytree
  (tokens, behavior logprobs, values, alive mask, lengths) fetched with a
  single ``_device_get``; after a bucket's first (compiling) round the
  call runs under ``steady_state_guard()``, so a stray implicit transfer
  anywhere in the loop raises at the line that did it (JG001's runtime
  twin, same discipline as the fused drivers and the serving flush loop);
- **generation-tagged parameters** — the learner publishes snapshots via
  :meth:`push_params` (device-side copy + monotonic bump, the
  ``InferenceServer`` idiom); every completed sequence carries the
  generation that produced it, so the learner's importance ratios know
  their off-policy lag.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from dataclasses import dataclass
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scalerl_tpu.models.transformer import (
    TransformerPolicy,
    decode_attention_mask,
    init_kv_cache,
    prefill_attention_mask,
    sequence_positions,
)
from scalerl_tpu.runtime import telemetry, tracing
from scalerl_tpu.runtime.device_loop import resolve_iter_mode
from scalerl_tpu.runtime.dispatch import steady_state_guard
from scalerl_tpu.runtime.param_server import ParamSnapshotPlane
from scalerl_tpu.utils.buckets import bucket_for, default_buckets

# module seams: tests monkeypatch these to count host transfers and assert
# the one-upload-one-read-per-round invariant
_device_put = jax.device_put
_device_get = jax.device_get


def adjust_logits(
    logits: jnp.ndarray, temperature: float, top_k: int, vocab_size: int
) -> jnp.ndarray:
    """Sampling adjustments (top-k mask then temperature) — the behavior
    logprob is computed from THESE logits, so the stored logp is the true
    log-density of the sampling distribution.  ``temperature == 0`` (greedy)
    skips the scale: sampling argmaxes and the logp reads the unscaled
    log-softmax (both engines share this helper, so temperature-0 parity
    across them is exact by construction)."""
    if top_k > 0 and top_k < vocab_size:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits >= kth, logits, jnp.float32(-1e30))
    if temperature > 0:
        logits = logits / jnp.float32(temperature)
    return logits


def sample_tokens(key, adj_logits: jnp.ndarray, temperature: float):
    """Categorical sample from adjusted logits; argmax at temperature 0."""
    if temperature == 0:
        return jnp.argmax(adj_logits, axis=-1)
    return jax.random.categorical(key, adj_logits, axis=-1)


@dataclass
class GenerationConfig:
    """Knobs for the generation engine.

    ``eos_token < 0`` disables early stopping (fixed-length responses, the
    synthetic-task default); with an EOS id, lanes latch done on sampling
    it and their remaining steps emit EOS with a zeroed alive mask.
    ``temperature == 0`` selects greedy (argmax) decoding — the setting the
    fixed-vs-continuous engine parity tests pin token-identical outputs at.
    """

    vocab_size: int
    max_prompt_len: int = 64
    max_new_tokens: int = 64
    temperature: float = 1.0
    top_k: int = 0  # 0 = full distribution
    eos_token: int = -1
    pad_token: int = 0
    prompt_buckets: Tuple[int, ...] = ()  # () -> pow2 ladder
    response_buckets: Tuple[int, ...] = ()
    seed: int = 0

    def resolved_prompt_buckets(self) -> Tuple[int, ...]:
        return tuple(self.prompt_buckets) or default_buckets(self.max_prompt_len)

    def resolved_response_buckets(self) -> Tuple[int, ...]:
        return tuple(self.response_buckets) or default_buckets(self.max_new_tokens)

    def validate(self) -> None:
        if self.vocab_size < 2:
            raise ValueError(f"vocab_size must be >= 2, got {self.vocab_size}")
        if self.max_prompt_len < 1 or self.max_new_tokens < 1:
            raise ValueError(
                "max_prompt_len and max_new_tokens must be >= 1, got "
                f"{self.max_prompt_len}/{self.max_new_tokens}"
            )
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy), got "
                f"{self.temperature}"
            )
        if self.top_k < 0 or self.top_k > self.vocab_size:
            raise ValueError(
                f"top_k must be in [0, vocab_size], got {self.top_k}"
            )
        if self.eos_token >= self.vocab_size:
            raise ValueError(
                f"eos_token {self.eos_token} outside vocab {self.vocab_size}"
            )


class GenerationResult(NamedTuple):
    """One generation round, materialized on the host (post batched read)."""

    sequences: np.ndarray  # [B, P+R] int32 left-padded prompt + response
    response_tokens: np.ndarray  # [B, R] int32
    behavior_logp: np.ndarray  # [B, R] f32 logprob under the SAMPLING dist
    values: np.ndarray  # [B, R] f32 baseline before each sampled token
    mask: np.ndarray  # [B, R] f32 1.0 where the token is real
    response_len: np.ndarray  # [B] int32
    prompt_len: np.ndarray  # [B] int32 true (unpadded) prompt lengths
    prompt_pad: int  # the prompt bucket P this round compiled at
    response_pad: int  # the response bucket R
    generation: int  # param generation that produced the round

    @property
    def decode_tokens(self) -> int:
        return int(self.mask.sum())

    @property
    def prompt_tokens(self) -> int:
        return int(self.prompt_len.sum())


class GenerationEngine(ParamSnapshotPlane):
    """Owns generation-tagged param snapshots + one jitted decode program
    per (prompt, response) bucket pair.

    ``model``: a token-mode :class:`TransformerPolicy` (``vocab_size`` set,
    ``max_len >= prompt_bucket + response_bucket``).  ``params``: the
    initial snapshot (the learner's live params at construction).
    ``dispatch_guard``: zero-arg context-manager factory entered around
    every device dispatch — trainers with a live mesh pass their mesh
    dispatch guard (graftlint JG002).
    """

    def __init__(
        self,
        model: TransformerPolicy,
        params: Any,
        config: GenerationConfig,
        iter_mode: str = "auto",
        dispatch_guard: Optional[Callable[[], Any]] = None,
    ) -> None:
        config.validate()
        if model.vocab_size is None:
            raise ValueError(
                "GenerationEngine needs a token-mode TransformerPolicy "
                "(vocab_size set); got a feature-embedding model"
            )
        max_p = bucket_for(
            config.max_prompt_len, config.resolved_prompt_buckets()
        )
        max_r = bucket_for(
            config.max_new_tokens, config.resolved_response_buckets()
        )
        if model.max_len < max_p + max_r:
            raise ValueError(
                f"model.max_len ({model.max_len}) must cover the largest "
                f"bucket pair (prompt {max_p} + response {max_r})"
            )
        self.model = model
        self.config = config
        self.iter_mode = resolve_iter_mode(iter_mode)
        self._dispatch_guard = dispatch_guard or nullcontext
        self._init_param_plane(params)
        self._key = jax.random.PRNGKey(config.seed)
        self._programs: Dict[Tuple[int, int], Callable] = {}
        self._warm: set = set()
        reg = telemetry.get_registry()
        self._round_counter = reg.counter("genrl.rounds")
        self._prompt_meter = reg.meter("genrl.prompt_tokens_per_s")
        self._decode_meter = reg.meter("genrl.decode_tokens_per_s")
        reg.bind(
            "genrl.engine",
            lambda: {
                "generation": self.generation,
                "warm_buckets": len(self._warm),
                "iter_mode": self.iter_mode,
            },
        )

    # -- program construction ------------------------------------------
    def _adjust_logits(self, logits: jnp.ndarray) -> jnp.ndarray:
        return adjust_logits(
            logits, self.config.temperature, self.config.top_k,
            self.config.vocab_size,
        )

    def _build_program(self, P: int, R: int) -> Callable:
        """Build + jit the whole-round program at one bucket pair.

        The Python ints ``P``/``R`` are closed over (never traced), so the
        returned callable is shape-stable by construction; ``iter_mode``
        picks lax.scan vs a Python-unrolled decode loop inside the SAME
        jitted program (identical math, asserted in tests).
        """
        model = self.model
        cfg = self.config
        S = P + R
        head_dim = model.d_model // model.num_heads
        use_scan = self.iter_mode == "scan"

        def step(params, lengths, carry, t):
            cache, logits, value, done, key = carry
            key, sub = jax.random.split(key)
            adj = self._adjust_logits(logits)
            token = sample_tokens(sub, adj, cfg.temperature)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(adj, axis=-1), token[:, None], axis=-1
            )[:, 0]
            # a token is real if its lane had not finished BEFORE this step
            # (the step that samples EOS still emits a real token)
            alive = jnp.logical_not(done)
            if cfg.eos_token >= 0:
                token = jnp.where(done, cfg.eos_token, token)
                done = jnp.logical_or(done, token == cfg.eos_token)
            out_t = (token, logp, value, alive.astype(jnp.float32))
            # feed the sampled token back through the cached model
            pos = (lengths + t)[:, None]
            mask = decode_attention_mask(lengths, P, t, S)
            out, cache = model.apply(
                params,
                token[:, None],
                positions=pos,
                kv_cache=cache,
                cache_index=P + t,
                attn_mask=mask,
            )
            new_carry = (
                cache,
                out.policy_logits[:, 0],
                out.baseline[:, 0],
                done,
                key,
            )
            return new_carry, out_t

        def generate(params, tokens, lengths, key):
            B = tokens.shape[0]
            cache = init_kv_cache(
                B, S, model.num_layers, model.num_heads, head_dim,
            )
            ppos = sequence_positions(lengths, P, S)[:, :P]
            pmask = prefill_attention_mask(lengths, P, S)
            out, cache = model.apply(
                params,
                tokens,
                positions=ppos,
                kv_cache=cache,
                cache_index=0,
                attn_mask=pmask,
            )
            carry = (
                cache,
                out.policy_logits[:, -1],
                out.baseline[:, -1],
                jnp.zeros((B,), jnp.bool_),
                key,
            )
            if use_scan:
                carry, outs = jax.lax.scan(
                    lambda c, t: step(params, lengths, c, t),
                    carry,
                    jnp.arange(R),
                )
                toks, logps, values, alive = outs
                # scan stacks on axis 0: [R, B] -> [B, R]
                toks = jnp.swapaxes(toks, 0, 1)
                logps = jnp.swapaxes(logps, 0, 1)
                values = jnp.swapaxes(values, 0, 1)
                alive = jnp.swapaxes(alive, 0, 1)
            else:
                cols = []
                for t in range(R):
                    carry, out_t = step(params, lengths, carry, t)
                    cols.append(out_t)
                toks = jnp.stack([c[0] for c in cols], axis=1)
                logps = jnp.stack([c[1] for c in cols], axis=1)
                values = jnp.stack([c[2] for c in cols], axis=1)
                alive = jnp.stack([c[3] for c in cols], axis=1)
            resp_len = jnp.sum(alive, axis=1).astype(jnp.int32)
            return {
                "tokens": toks.astype(jnp.int32),
                "logp": logps.astype(jnp.float32),
                "value": values.astype(jnp.float32),
                "mask": alive,
                "resp_len": resp_len,
            }

        return jax.jit(generate)

    def _program(self, P: int, R: int) -> Callable:
        fn = self._programs.get((P, R))
        if fn is None:
            fn = self._build_program(P, R)
            self._programs[(P, R)] = fn
        return fn

    def prefill_program(self, P: int, R: int) -> Callable:
        """Jitted prefill-only step at a bucket pair — the bench's
        prefill-tokens/s numerator (``generate`` fuses prefill + decode
        into one program, so the split timing needs this twin)."""
        model = self.model
        S = P + R
        head_dim = model.d_model // model.num_heads

        def prefill(params, tokens, lengths):
            B = tokens.shape[0]
            cache = init_kv_cache(
                B, S, model.num_layers, model.num_heads, head_dim,
            )
            ppos = sequence_positions(lengths, P, S)[:, :P]
            pmask = prefill_attention_mask(lengths, P, S)
            out, cache = model.apply(
                params, tokens, positions=ppos, kv_cache=cache,
                cache_index=0, attn_mask=pmask,
            )
            return out.policy_logits[:, -1], out.baseline[:, -1], cache

        return jax.jit(prefill)

    # -- the generation round ------------------------------------------
    def _align_prompts(
        self, prompts: np.ndarray, lengths: np.ndarray, P: int
    ) -> np.ndarray:
        """Right-align (left-pad) host prompts into the ``[B, P]`` bucket."""
        B = prompts.shape[0]
        out = np.full((B, P), self.config.pad_token, np.int32)
        for b in range(B):
            n = int(lengths[b])
            out[b, P - n:] = prompts[b, :n]
        return out

    def generate(
        self,
        prompts: np.ndarray,
        prompt_lengths: Optional[np.ndarray] = None,
        max_new_tokens: Optional[int] = None,
    ) -> GenerationResult:
        """Run one generation round; returns host numpy results.

        ``prompts``: ``[B, L]`` int32, right-padded (token ``b`` real for
        the first ``prompt_lengths[b]`` columns).  The round pads to the
        (prompt, response) bucket pair, dispatches the ONE jitted program,
        and reads the outputs back with a single batched ``_device_get`` —
        armed with ``steady_state_guard()`` once the bucket pair is warm.
        """
        t_round0 = time.monotonic()
        prompts = np.asarray(prompts, np.int32)
        B, L = prompts.shape
        if prompt_lengths is None:
            prompt_lengths = np.full(B, L, np.int32)
        prompt_lengths = np.asarray(prompt_lengths, np.int32)
        if prompt_lengths.max(initial=1) > self.config.max_prompt_len:
            raise ValueError(
                f"prompt length {int(prompt_lengths.max())} exceeds "
                f"max_prompt_len={self.config.max_prompt_len}"
            )
        P = bucket_for(
            int(prompt_lengths.max(initial=1)),
            self.config.resolved_prompt_buckets(),
        )
        R = bucket_for(
            int(max_new_tokens or self.config.max_new_tokens),
            self.config.resolved_response_buckets(),
        )
        aligned = self._align_prompts(prompts, prompt_lengths, P)
        fn = self._program(P, R)
        params, gen = self._snapshot_params()
        warm = (P, R) in self._warm
        guard = steady_state_guard() if warm else nullcontext()
        with guard:
            with self._dispatch_guard():
                self._key, sub = jax.random.split(self._key)
                # ONE explicit batched host->device upload per round ...
                dev_tokens, dev_lengths = _device_put(
                    (aligned, prompt_lengths)
                )
                out = fn(params, dev_tokens, dev_lengths, sub)
                # ... and ONE explicit batched device->host read
                host = _device_get(out)
        self._warm.add((P, R))
        sequences = np.concatenate(
            [aligned, np.asarray(host["tokens"], np.int32)], axis=1
        )
        result = GenerationResult(
            sequences=sequences,
            response_tokens=np.asarray(host["tokens"], np.int32),
            behavior_logp=np.asarray(host["logp"], np.float32),
            values=np.asarray(host["value"], np.float32),
            mask=np.asarray(host["mask"], np.float32),
            response_len=np.asarray(host["resp_len"], np.int32),
            prompt_len=prompt_lengths,
            prompt_pad=P,
            response_pad=R,
            generation=gen,
        )
        self._round_counter.inc()
        self._prompt_meter.mark(result.prompt_tokens)
        self._decode_meter.mark(result.decode_tokens)
        if tracing.sampling_enabled():
            # ONE head-sampled span per generation round (the whole fused
            # prefill+decode dispatch + its single batched read) — never
            # per token; host monotonic stamps only (JG001 good twin)
            tracing.record_span(
                "genrl.generate_round", None, t_round0, time.monotonic(),
                kind="genrl", batch=B, prompt_pad=P, response_pad=R,
                decode_tokens=int(result.decode_tokens), generation=gen,
            )
        return result
