"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` axis.

No counterpart in the reference (SURVEY.md §2.4 lists PP as absent); this
completes the mesh's parallelism families.  Homogeneous stages (same
input/output shape) are stacked on a leading ``[S, ...]`` param axis sharded
over ``pp``; inside ``shard_map`` each device runs its stage and hands
activations to its right neighbor via a non-cyclic ``ppermute`` shift.  The
classic GPipe bubble applies: ``S + M - 1`` steps for ``M`` microbatches.

This is the correctness-first formulation (activations are dense every
step; idle stages compute on zeros).  It exists so ``pp`` is a real,
executable axis — RL-parity models are far too small to need it, which is
why the flagship trainers default to dp/fsdp.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

# stage_fn(stage_params, x[mb, ...]) -> y[mb, ...] (same shape)
StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def make_pipeline_apply(
    stage_fn: StageFn,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = "pp",
):
    """Build ``apply(stacked_params, x) -> y`` running stages in pipeline.

    ``stacked_params``: pytree whose leaves lead with the stage axis
    ``[S, ...]`` (sharded over ``axis_name``).  ``x``: ``[B, ...]`` with
    ``B`` divisible by ``num_microbatches``; output has the same shape.
    """
    M = num_microbatches

    def body(params_blk, x):
        S = jax.lax.psum(1, axis_name)
        stage = jax.lax.axis_index(axis_name)
        params_local = jax.tree_util.tree_map(lambda p: p[0], params_blk)
        B = x.shape[0]
        mb = B // M
        mbs = x.reshape((M, mb) + x.shape[1:])

        out0 = jnp.zeros_like(mbs)
        cur0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)

        def step(t, carry):
            outputs, cur = carry
            k = t - stage  # microbatch index flowing through this stage
            active = jnp.logical_and(k >= 0, k < M)
            k_safe = jnp.clip(k, 0, M - 1)
            # stage 0 pulls fresh microbatches; others take the neighbor's
            x_in = jnp.where(stage == 0, mbs[k_safe], cur)
            y = stage_fn(params_local, x_in)
            y = jnp.where(active, y, jnp.zeros_like(y))
            outputs = jnp.where(
                jnp.logical_and(active, stage == S - 1),
                outputs.at[k_safe].set(y),
                outputs,
            )
            # non-cyclic right shift: stage i -> i+1 (stage 0 receives zeros)
            nxt = jax.lax.ppermute(
                y, axis_name, [(i, i + 1) for i in range(S - 1)]
            )
            return outputs, nxt

        outputs, _ = jax.lax.fori_loop(0, M + S - 1, step, (out0, cur0))
        # only the last stage holds real outputs; psum replicates them
        outputs = jax.lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)),
            axis_name,
        )
        return outputs.reshape(x.shape)

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_rep=False,
    )
    pp = mesh.shape[axis_name]

    def apply(stacked_params, x):
        # One stage per pp device: the body takes p[0] of each device's
        # param block, so S > pp would silently drop the extra stages and
        # S < pp would crash inside shard_map with a shape error.
        for path, leaf in jax.tree_util.tree_flatten_with_path(stacked_params)[0]:
            if leaf.shape[0] != pp:
                raise ValueError(
                    f"stacked stage axis {leaf.shape[0]} != pp={pp} at "
                    f"{jax.tree_util.keystr(path)}; one stage per pp device"
                )
        if x.shape[0] % M != 0:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by num_microbatches={M}"
            )
        return sharded(stacked_params, x)

    return apply


def sequential_apply(stage_fn: StageFn, stacked_params: Any, x: jnp.ndarray):
    """Reference semantics: stages applied one after another (no pipeline)."""
    S = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    for s in range(S):
        params_s = jax.tree_util.tree_map(lambda p: p[s], stacked_params)
        x = stage_fn(params_s, x)
    return x
